"""Live run watch: what is this run doing RIGHT NOW, and when will it end?

``python -m redcliff_tpu.obs watch <run_dir>`` tails a run directory's
telemetry — ``metrics.jsonl`` (rotation chain, torn mid-append tails
tolerated) + ``run_ledger.jsonl`` + the ``dispatch_stats`` snapshot inside
``grid_checkpoint.pkl`` — and renders the operator view the report CLI's
post-mortem join cannot give: lanes live, the current G-bucket, epoch rate,
the stall breakdown (ckpt / barrier / prefetch / compile), numerics skip
counters, heartbeat ages, and the learned cost model's ETA per fit and for
the whole run (``cost_model`` events, obs/costmodel.py).

Follow mode re-snapshots the whole rotation chain every ``--interval``
seconds rather than holding a file offset: a chain re-read is O(run dir)
and therefore cheap at metrics scale, and it is the only approach that is
automatically correct across rotation boundaries (``metrics.jsonl`` ->
``.1``), truncation, a writer SIGKILLed mid-append, and a supervisor
restart swapping the writing pid — every case a byte-offset tail gets
wrong. The snapshot also carries the device-memory view (obs/memory.py):
the newest measured HBM watermark + the analytical prediction, rendered
as a live ``hbm:`` line (``n/a (backend)`` where ``memory_stats()`` is
unsupported).

``--once`` prints a single snapshot and exits; ``--once --json`` prints the
snapshot as one strict-JSON object that validates against the registered
``watch`` event schema (:mod:`redcliff_tpu.obs.schema`) — the scriptable /
testable contract. A missing or telemetry-less run dir exits with code 2
and a one-line diagnosis (never a traceback).

"Heartbeat ages" here are the OUTSIDE view: seconds since each telemetry
source (metrics file mtime, newest record, newest ``epoch`` event, newest
emitted span per component, ledger) last moved. The in-process watchdog
(runtime/watchdog.py) owns the authoritative in-memory heartbeat registry;
a watcher on another host only sees what reached disk.
"""
from __future__ import annotations

import json
import os
import sys
import time

from redcliff_tpu.obs import schema as _schema
from redcliff_tpu.obs.logging import jsonl_files, read_jsonl

__all__ = ["build_snapshot", "render_text", "diagnose_run_dir", "run_watch",
           "is_fleet_root"]


def is_fleet_root(path):
    """Whether ``path`` is a fleet sweep-service root (fleet/queue.py
    layout) rather than a single-run directory — flips the watch into
    FLEET mode (queue depth, per-tenant in-flight, planner decisions)."""
    return (os.path.exists(os.path.join(path, "requests.jsonl"))
            or os.path.isdir(os.path.join(path, "leases")))


def diagnose_run_dir(run_dir):
    """One-line diagnosis for an unwatchable run dir, or None when it holds
    telemetry (shared by the report CLI's exit-2 contract)."""
    if not os.path.exists(run_dir):
        return f"run dir does not exist: {run_dir}"
    if not os.path.isdir(run_dir):
        return f"not a directory: {run_dir}"
    if (not jsonl_files(os.path.join(run_dir, "metrics.jsonl"))
            and not os.path.exists(os.path.join(run_dir,
                                                "run_ledger.jsonl"))
            and not is_fleet_root(run_dir)):
        return (f"no telemetry in {run_dir}: neither metrics.jsonl (or its "
                f"rotation chain) nor run_ledger.jsonl nor a fleet queue "
                f"(requests.jsonl) — is this a run directory?")
    return None


def _fit_view(rec):
    shape = rec.get("shape")
    return {
        "model": rec.get("model"),
        "shape": _schema.shape_key(shape),
        "grid_size": rec.get("grid_size"),
        "grid_width": rec.get("grid_width"),   # updated by compaction/remesh
        "stream_mode": rec.get("stream_mode"),
        "max_iter": rec.get("max_iter"),
        "started_wall": rec.get("wall_time"),
        "resumed_from_epoch": rec.get("resumed_from_epoch"),
        "last_epoch": None, "lanes_live": None, "num_quarantined": 0,
        "guarded_steps_skipped": 0, "epoch_ms_last": None,
        "epochs_seen": 0, "first_epoch": None, "first_epoch_wall": None,
        "last_epoch_wall": None, "epoch_rate_per_min": None,
        "eta": None, "done": False,
        # a later fit_start in the same metrics chain (a supervisor
        # re-attempt / resume) supersedes this one: it is no longer live
        # even though it never wrote a fit_end (it crashed/was killed)
        "superseded": False,
    }


def _fit_eta(fit, now):
    """Remaining-work estimate for one fit: the newest ``cost_model``
    event's ETA discounted by the time since it was computed; fallback —
    extrapolate the observed check-window epoch rate to ``max_iter``."""
    cm = fit.pop("_cost_model_last", None)
    if cm is not None and isinstance(cm.get("eta_s"), (int, float)):
        age = max(now - (cm.get("wall_time") or now), 0.0)
        return {"eta_s": round(max(cm["eta_s"] - age, 0.0), 3),
                "source": f"cost_model:{cm.get('source') or '?'}",
                "predicted_epoch_ms": cm.get("predicted_epoch_ms"),
                "epochs_remaining": cm.get("epochs_remaining"),
                "as_of_epoch": cm.get("epoch")}
    rate = fit.get("epoch_rate_per_min")
    if (rate and fit.get("max_iter") is not None
            and fit.get("last_epoch") is not None):
        remaining = max(fit["max_iter"] - fit["last_epoch"] - 1, 0)
        # discount by time already elapsed since the last observed epoch —
        # symmetrical with the cost_model branch; a wedged run's eta decays
        # to 0 instead of promising the same remaining work forever
        age = max(now - (fit.get("last_epoch_wall") or now), 0.0)
        return {"eta_s": round(max(remaining / rate * 60.0 - age, 0.0), 3),
                "source": "epoch_rate",
                "predicted_epoch_ms": round(60e3 / rate, 3),
                "epochs_remaining": remaining,
                "as_of_epoch": fit["last_epoch"]}
    return None


# follow-mode cache for the checkpointed stall breakdown: the grid
# checkpoint pickles EVERY lane's params (hundreds of MB on real sweeps),
# so unpickling it each refresh tick would burn the fit host's disk/CPU to
# extract a handful of scalars — re-read only when the file changes
_ckpt_stall_cache = {}


def _checkpoint_stalls(run_dir):
    """Stall/compile breakdown from the newest checkpointed dispatch_stats
    (the only mid-run source: fit_end has not happened yet). Cached on the
    checkpoint file's (mtime, size) signature."""
    from redcliff_tpu.obs import report as _report

    path = os.path.join(run_dir, "grid_checkpoint.pkl")
    try:
        st = os.stat(path)
        sig = (st.st_mtime_ns, st.st_size)
    except OSError:
        sig = None
    cached = _ckpt_stall_cache.get(run_dir)
    if cached is not None and cached[0] == sig:
        return cached[1]
    ck = _report._checkpoint_stats(run_dir)
    if not isinstance(ck, dict):
        _ckpt_stall_cache[run_dir] = (sig, None)
        return None
    out = {k: (round(v, 3) if isinstance(v, float) else v)
           for k in ("ckpt_stall_ms", "ckpt_barrier_stall_ms",
                     "prefetch_stall_ms", "compile_ms", "train_time_ms",
                     "val_time_ms", "epochs", "lanes_live", "grid_width")
           for v in (ck.get(k),)}
    out["source"] = "grid_checkpoint.pkl"
    _ckpt_stall_cache[run_dir] = (sig, out)
    return out


# follow-mode cache for the fleet-SLO view: slo_for_root re-reads and
# re-aggregates the WHOLE lifecycle ledger, which only grows — so a busy
# root would pay an ever-larger parse on every refresh tick even when no
# request moved. Cached on the ledger head file's (mtime, size) signature
# (appends grow it, rotation replaces it — either invalidates).
_fleet_slo_cache = {}


def _fleet_slo(root):
    from redcliff_tpu.fleet import history as _history
    from redcliff_tpu.obs import slo as _slo

    try:
        st = os.stat(_history.history_path(root))
        sig = (st.st_mtime_ns, st.st_size)
    except OSError:
        sig = None
    cached = _fleet_slo_cache.get(str(root))
    if cached is not None and cached[0] == sig:
        return cached[1]
    out = _slo.slo_for_root(root)
    _fleet_slo_cache[str(root)] = (sig, out)
    return out


def build_snapshot(run_dir, now=None):
    """One watch snapshot as a plain dict (``event="watch"`` — validates
    against the registered schema; importable for services and tests)."""
    now = time.time() if now is None else now
    mstats = {}
    try:
        records = read_jsonl(run_dir, stats=mstats)
    except FileNotFoundError:
        records, mstats = [], {"files": [], "records": 0, "torn_lines": 0}
    lstats = {}
    ledger_path = os.path.join(run_dir, "run_ledger.jsonl")
    ledger = (read_jsonl(ledger_path, stats=lstats)
              if os.path.exists(ledger_path) else [])

    fits, incidents = [], []
    cur = None
    fleet_last_plan = None   # newest planner packing decision (fleet event)
    fleet_workers = {}       # worker id -> last fleet-event wall time
    last_autoscale = None    # newest autoscaler decision (ISSUE 16)
    last_qos = {}            # tenant -> newest qos demote/restore event
    last_backpressure = None  # newest admission-gate reject
    backpressure_rejects = 0
    mem_pred = mem_meas = None  # newest memory events (obs/memory.py)
    last_quality = None      # newest quality event (obs/quality.py)
    last_policy = None       # newest predictive-policy decision (ISSUE 15)
    last_preempt = None      # newest deadline-aware preemption event
    last_serve = None        # newest serve-plane event (ISSUE 17)
    serve_counts = {}        # newest non-None value per serve counter
    serve_quarantines = 0    # session quarantine verdicts seen
    last_pack_plan = None    # newest packing kind=plan verdict (ISSUE 18)
    last_pack_event = None   # newest packing event of any kind
    pack_claims = pack_frees = 0  # slot lifecycle counters
    partial_points = 0       # partial_result rows streamed so far
    last_partial = None      # newest partial_result row
    anomalies = rollbacks = aborts = 0
    last_span_by_component = {}
    last_wall = last_epoch_wall = None
    for rec in records:
        wt = rec.get("wall_time")
        if isinstance(wt, (int, float)):
            last_wall = wt if last_wall is None else max(last_wall, wt)
        ev = rec.get("event")
        if ev == "fit_start":
            # a run dir's fits are sequential (attempts/resumes append to
            # one chain): any earlier fit still "live" at this point died
            # without a fit_end — mark it superseded, not LIVE
            for f in fits:
                if not f["done"]:
                    f["superseded"] = True
            cur = _fit_view(rec)
            fits.append(cur)
        elif ev == "epoch" and cur is not None:
            e = rec.get("epoch")
            cur["last_epoch"] = e
            cur["epochs_seen"] += 1
            if cur["first_epoch"] is None:
                cur["first_epoch"], cur["first_epoch_wall"] = e, wt
            cur["last_epoch_wall"] = wt
            last_epoch_wall = wt
            for k_rec, k_fit in (("lanes_live", "lanes_live"),
                                 ("num_quarantined", "num_quarantined"),
                                 ("guarded_steps_skipped",
                                  "guarded_steps_skipped"),
                                 ("epoch_ms", "epoch_ms_last"),
                                 ("grid_width", "grid_width")):
                if rec.get(k_rec) is not None:
                    cur[k_fit] = rec[k_rec]
        elif ev == "cost_model" and cur is not None:
            cur["_cost_model_last"] = rec
        elif ev == "memory":
            if rec.get("kind") == "measured":
                mem_meas = rec
            elif rec.get("kind") == "predicted":
                mem_pred = rec
        elif ev == "quality":
            # model-quality observatory (obs/quality.py): the newest
            # check-window summary becomes the `quality:` headline; absent
            # on pre-quality runs (section simply omitted)
            last_quality = rec
        elif ev == "policy":
            # predictive scheduling (ISSUE 15, parallel/policy.py): the
            # newest decision — chosen rung / compact-vs-hold pricing in a
            # run dir, compile ordering / preemption pricing in a fleet
            # root — becomes the `policy:` headline
            last_policy = rec
        elif ev == "preempt":
            last_preempt = rec
        elif ev == "serve":
            # serving-plane headline (ISSUE 17): counters are cumulative
            # but scattered across kinds (drain has no capacity, stop no
            # streams) — fold the newest non-None value per field
            last_serve = rec
            for k in ("capacity", "streams", "free_slots", "ticks",
                      "samples_in", "samples_out", "rejects", "dropped",
                      "p50_ms", "p99_ms", "n", "width", "live",
                      "fused_samples", "mode", "fuse", "precision_mode"):
                if rec.get(k) is not None:
                    serve_counts[k] = rec[k]
        elif ev == "session":
            serve_quarantines += rec.get("kind") == "quarantine"
        elif ev in ("compaction", "remesh") and cur is not None:
            if rec.get("to_width") is not None:
                cur["grid_width"] = rec["to_width"]
        elif ev == "fleet":
            if rec.get("kind") == "plan":
                fleet_last_plan = rec
            w = rec.get("worker")
            if w and isinstance(wt, (int, float)):
                fleet_workers[str(w)] = wt
        elif ev == "packing":
            # spatial mesh packing (ISSUE 18): the newest priced
            # packed-vs-serial verdict + slot lifecycle counters become
            # the `packing:` headline
            last_pack_event = rec
            kind = rec.get("kind")
            if kind == "plan":
                last_pack_plan = rec
            pack_claims += kind == "slot_claim"
            pack_frees += kind == "slot_free"
        elif ev == "partial_result":
            partial_points += 1
            last_partial = rec
        elif ev == "autoscale":
            # the SLO-driven control loop's decision stream (ISSUE 16):
            # the newest decision becomes the fleet section's headline
            last_autoscale = rec
        elif ev == "qos":
            if rec.get("tenant") is not None:
                last_qos[str(rec["tenant"])] = rec
        elif ev == "backpressure":
            last_backpressure = rec
            backpressure_rejects += rec.get("kind") == "reject"
        elif ev == "anomaly":
            anomalies += 1
        elif ev == "numerics":
            kind = rec.get("kind")
            rollbacks += kind == "rollback"
            aborts += kind == "abort"
        elif ev == "fit_end" and cur is not None:
            cur["done"] = True
        elif ev in ("hang", "host_lost", "hang_exit", "host_lost_exit"):
            incidents.append({"event": ev, "wall_time": wt,
                              "components": sorted(
                                  rec.get("components") or {})})
        elif ev == "span":
            comp = (rec.get("component")
                    or str(rec.get("name", "")).partition(".")[0])
            if comp and isinstance(wt, (int, float)):
                last_span_by_component[comp] = wt

    for fit in fits:
        if fit["superseded"]:
            # dead attempt: no rate extrapolation, no eta contribution
            fit.pop("_cost_model_last", None)
            continue
        n_e, t0, t1 = (fit["epochs_seen"], fit["first_epoch_wall"],
                       fit["last_epoch_wall"])
        if (n_e > 1 and isinstance(t0, (int, float))
                and isinstance(t1, (int, float)) and t1 > t0
                and fit["last_epoch"] is not None
                and fit["first_epoch"] is not None
                and fit["last_epoch"] > fit["first_epoch"]):
            # epochs advanced per wall minute, from the check-window cadence
            # (exact even when check_every > 1: the epoch NUMBERS advance)
            fit["epoch_rate_per_min"] = round(
                (fit["last_epoch"] - fit["first_epoch"]) / (t1 - t0) * 60.0,
                3)
        fit["eta"] = None if fit["done"] else _fit_eta(fit, now)
        fit.pop("_cost_model_last", None)

    live = [f for f in fits if not f["done"] and not f["superseded"]]
    etas = [f["eta"]["eta_s"] for f in live
            if f.get("eta") and isinstance(f["eta"].get("eta_s"),
                                           (int, float))]
    attempts = [r for r in ledger if r.get("event") == "attempt"]
    final = next((r for r in reversed(ledger) if r.get("event") == "final"),
                 None)

    files = mstats.get("files") or []
    try:
        newest_mtime = max(os.path.getmtime(p) for p in files) \
            if files else None
    except OSError:
        newest_mtime = None
    heartbeats = {
        "metrics_file_age_s": (round(now - newest_mtime, 3)
                               if newest_mtime is not None else None),
        "last_record_age_s": (round(now - last_wall, 3)
                              if last_wall is not None else None),
        "last_epoch_age_s": (round(now - last_epoch_wall, 3)
                             if last_epoch_wall is not None else None),
        "span_age_s": {c: round(now - t, 3)
                       for c, t in sorted(last_span_by_component.items())},
    }
    # the numerics skip counter of the run as it stands NOW: live (or
    # completed) fits only — a crashed superseded attempt's stale counter
    # must not shadow the restarted attempt's
    current_fits = [f for f in fits if not f["superseded"]] or fits
    last_skipped = max((f["guarded_steps_skipped"] or 0
                        for f in current_fits), default=0)
    # live HBM view (obs/memory.py): the newest measured watermark poll +
    # the newest analytical prediction; measured stays None on backends
    # without memory_stats (render shows an explicit "n/a (backend)")
    memory = None
    if mem_pred is not None or mem_meas is not None:
        memory = {
            "predicted_bytes": (mem_pred or {}).get("predicted_bytes"),
            "g_bucket": (mem_pred or {}).get("g_bucket"),
            "backend": (mem_pred or {}).get("backend"),
            "bytes_in_use": (mem_meas or {}).get("bytes_in_use"),
            "peak_bytes": (mem_meas or {}).get("peak_bytes"),
            "bytes_limit": ((mem_meas or {}).get("bytes_limit")
                            or (mem_pred or {}).get("bytes_limit")),
            "measured_age_s": (
                round(now - mem_meas["wall_time"], 3)
                if mem_meas and isinstance(mem_meas.get("wall_time"),
                                           (int, float)) else None),
        }
    # model-quality headline (obs/quality.py): the newest check-window
    # graph summary — lanes covered, plateau count, edge-set stability,
    # live AUROC when ground truth is in hand. None (section omitted) on
    # runs that never emitted a quality event, pre-quality runs included
    quality = None
    if last_quality is not None:
        qwt = last_quality.get("wall_time")
        quality = {
            "epoch": last_quality.get("epoch"),
            "lanes": len(last_quality.get("lanes") or []),
            "plateaued_count": last_quality.get("plateaued_count"),
            "stability": last_quality.get("mean_jaccard"),
            "auroc": last_quality.get("mean_auroc"),
            "aupr": last_quality.get("mean_aupr"),
            "age_s": (round(now - qwt, 3)
                      if isinstance(qwt, (int, float)) else None),
        }
    # predictive-scheduling headlines (ISSUE 15): the newest policy
    # decision and preemption event, age-stamped — None (sections omitted)
    # on runs/roots that never decided predictively
    policy = None
    if last_policy is not None:
        pwt = last_policy.get("wall_time")
        policy = {k: last_policy.get(k) for k in
                  ("kind", "action", "fallback", "epoch", "from_width",
                   "to_width", "chosen_width", "heuristic_width",
                   "saving_ms", "compile_ms", "heuristic_ms", "total_ms",
                   "epochs_remaining", "beneficiary", "request_id",
                   "batch_id", "reason")}
        policy["age_s"] = (round(now - pwt, 3)
                          if isinstance(pwt, (int, float)) else None)
    preempt = None
    if last_preempt is not None:
        pwt = last_preempt.get("wall_time")
        preempt = {k: last_preempt.get(k) for k in
                   ("kind", "batch_id", "requests", "beneficiary", "tenant",
                    "queued_eta_s", "running_rem_s", "deadline_at",
                    "grace_s")}
        preempt["age_s"] = (round(now - pwt, 3)
                            if isinstance(pwt, (int, float)) else None)
    # streaming-inference section (ISSUE 17): the serve plane's live
    # counters + the newest latency view — None (section omitted) on run
    # dirs that never served
    serve = None
    if last_serve is not None:
        swt = last_serve.get("wall_time")
        serve = dict(serve_counts)
        # elastic data plane (ISSUE 20): the engine's current pow2 rung —
        # the dispatched slot-table width, <= capacity under the occupancy
        # ladder — surfaces as `rung` (watch.serve.rung)
        serve["rung"] = serve.pop("width", None)
        serve["last_kind"] = last_serve.get("kind")
        serve["quarantines"] = serve_quarantines
        serve["age_s"] = (round(now - swt, 3)
                          if isinstance(swt, (int, float)) else None)
    # fleet mode (fleet/queue.py roots): queue depth + per-tenant counts
    # from the authoritative file queue, live in-flight claims from the
    # lease files, and the planner's newest packing decision from the
    # rotation-chain-tailed `fleet` events above
    # spatial-packing section (ISSUE 18): the worker-published occupancy
    # state file is authoritative (it outlives the metrics tail); the
    # tailed packing/partial_result events supply the newest verdict and
    # streaming progress. None (section omitted) on roots that never packed
    packing_sec = None
    pack_state = None
    if is_fleet_root(run_dir):
        from redcliff_tpu.parallel import packing as _fpacking
        pack_state = _fpacking.load_state(run_dir, now=now)
        # partial_result rows stream into the per-batch run-dir chains,
        # not the root chain — count the durable partial files instead
        # (bounded: tiny one-line-per-point files, capped at 256)
        import glob as _glob
        for path in _glob.glob(os.path.join(
                run_dir, "work", "*", "results",
                "*.partial.jsonl"))[:256]:
            try:
                with open(path, encoding="utf-8") as fh:
                    partial_points += sum(1 for _ in fh)
            except OSError:
                continue
    if (pack_state is not None or last_pack_event is not None
            or partial_points):
        packing_sec = {
            "state": pack_state,
            "slot_claims": pack_claims,
            "slot_frees": pack_frees,
            "partial_points": partial_points,
            "last_partial": ({k: last_partial.get(k) for k in
                              ("request_id", "batch_id", "point", "epoch",
                               "final")}
                             if last_partial else None),
            "last_plan": ({k: last_pack_plan.get(k) for k in
                           ("decision", "reason", "makespan_ratio",
                            "makespan_s", "serial_s", "n_devices", "pool",
                            "headroom_violations")}
                          if last_pack_plan else None),
            "last_event": ({k: last_pack_event.get(k) for k in
                            ("kind", "batch_id", "slot", "worker")}
                           if last_pack_event else None),
        }
        pwt = (last_pack_event or {}).get("wall_time")
        packing_sec["age_s"] = (round(now - pwt, 3)
                                if isinstance(pwt, (int, float)) else None)
    fleet = None
    if is_fleet_root(run_dir):
        fleet = _fleet_section(
            run_dir, fleet_last_plan, fleet_workers, now,
            last_autoscale=last_autoscale, last_qos=last_qos,
            last_backpressure=last_backpressure,
            backpressure_rejects=backpressure_rejects)
    return {
        "event": "watch",
        "wall_time": now,
        "schema_version": _schema.SCHEMA_VERSION,
        "run_dir": os.path.abspath(run_dir),
        "ok": bool(records or ledger or fleet is not None),
        "fleet": fleet,
        "fits": fits,
        "grid_eta_s": round(sum(etas), 3) if etas else None,
        "stalls": _checkpoint_stalls(run_dir),
        "numerics": {"anomaly_events": anomalies, "rollbacks": rollbacks,
                     "aborts": aborts,
                     "guarded_steps_skipped": int(last_skipped)},
        "memory": memory,
        "quality": quality,
        "policy": policy,
        "preempt": preempt,
        "serve": serve,
        "packing": packing_sec,
        "heartbeats": heartbeats,
        "incidents": incidents,
        "attempts": {"n": len(attempts),
                     "last_classification": (attempts[-1].get(
                         "classification") if attempts else None),
                     "last_eta": (attempts[-1].get("eta")
                                  if attempts else None),
                     "final": (final or {}).get("classification")},
        "read_audit": {"records": mstats.get("records", 0),
                       "torn_lines": (mstats.get("torn_lines", 0)
                                      + lstats.get("torn_lines", 0)),
                       "files": [os.path.basename(p) for p in files]},
    }


def _fleet_section(root, last_plan, workers, now, last_autoscale=None,
                   last_qos=None, last_backpressure=None,
                   backpressure_rejects=0):
    """The fleet-mode snapshot body: queue/tenant counts (file queue =
    authoritative), live in-flight claims (lease files), the planner's
    newest packing decision, worker liveness ages, and the autoscaler's
    control state (published ``autoscale.json`` = authoritative pool view;
    the tailed ``autoscale``/``qos``/``backpressure`` events supply the
    newest decisions)."""
    from redcliff_tpu.fleet import autoscale as _as
    from redcliff_tpu.fleet.queue import FleetQueue

    # create=False: a watcher is a pure reader — it must neither mkdir
    # under the service root nor crash on a read-only/archived one
    q = FleetQueue(root, create=False)
    st = q.status(now=now)
    in_flight = [{
        "request_id": l.get("request_id"),
        "tenant": l.get("tenant"),
        "worker": l.get("worker"),
        "batch_id": l.get("batch_id"),
        "expires_in_s": round(float(l.get("expires_at") or 0.0) - now, 3),
    } for l in q.live_leases(now=now)]
    plan = None
    if last_plan is not None:
        plan = {k: last_plan.get(k) for k in
                ("queue_depth", "batches", "unschedulable", "plan_ms",
                 "utilization_pct", "decisions", "worker")}
        wt = last_plan.get("wall_time")
        plan["age_s"] = (round(now - wt, 3)
                         if isinstance(wt, (int, float)) else None)
    # containment view (ISSUE 11): dead-letter depth + dossier headlines,
    # and every request's durable attempt/reclaim counts (the retry-budget
    # state a release/reclaim updates). One terminal_ids() batch view for
    # the whole tick — a follow-mode watcher re-renders this every tick,
    # so no per-request stat probes and only the rendered dossiers read
    term = q.terminal_ids()
    terminal_rids = set().union(*term.values())
    deadletters = []
    for rid in sorted(term["deadletter"])[:16]:
        rec = q.deadletter_record(rid)
        if rec is None:
            continue  # raced a requeue; depth still counts the listing
        deadletters.append({
            "request_id": rec.get("request_id"),
            "tenant": (rec.get("dossier") or {}).get("tenant"),
            "reason": (rec.get("dossier") or {}).get("reason"),
            "attempts": (rec.get("dossier") or {}).get("attempts"),
            "last_classification": (rec.get("dossier") or {}).get(
                "last_classification"),
        })
    # live requests only (a terminal request's budget lives in its
    # dossier), bounded like the dead-letter list so snapshot size never
    # grows with root history
    attempts = {}
    for rec in q.attempt_records():
        rid = rec.get("request_id")
        if not rid or rid in terminal_rids:
            continue
        if not (rec.get("attempts") or rec.get("reclaims")
                or rec.get("suspect")):
            continue
        attempts[rid] = {
            "attempts": int(rec.get("attempts") or 0),
            "reclaims": int(rec.get("reclaims") or 0),
            "last": (rec.get("last") or {}).get("classification"),
        }
        if len(attempts) >= 64:
            break
    # fleet-SLO headline (ISSUE 12, obs/slo.py): per-tenant queue-wait
    # percentiles / deadline hit-rate / dead-letter rate from the durable
    # lifecycle ledger, with REDCLIFF_SLO_* threshold breach flags — the
    # service-level numbers a follow-mode operator steers by
    slo = _fleet_slo(root)
    # autoscale view (ISSUE 16): durable state file + qos rung files are
    # authoritative (they outlive the metrics tail); the tailed events
    # carry the newest decision/reject headline
    auto_state = _as.load_state(root)
    qos_rungs = _as.active_qos(root)
    autoscale = None
    if auto_state is not None or qos_rungs or last_autoscale is not None \
            or last_backpressure is not None or last_qos:
        last_dec = (auto_state or {}).get("last_decision") or last_autoscale
        awt = (auto_state or {}).get("wall_time")
        autoscale = {
            "workers": (auto_state or {}).get("workers"),
            "target": (auto_state or {}).get("target"),
            "max_workers": (auto_state or {}).get("max_workers"),
            "pending": (auto_state or {}).get("pending"),
            "drain_eta_s": (auto_state or {}).get("drain_eta_s"),
            "state_age_s": (round(now - awt, 3)
                            if isinstance(awt, (int, float)) else None),
            "last_decision": ({k: last_dec.get(k) for k in
                               ("kind", "reason", "workers", "target",
                                "queue_depth", "drain_eta_s", "breaches")}
                              if last_dec else None),
            "qos": {t: {"rung": r.get("rung"), "reason": r.get("reason")}
                    for t, r in sorted(qos_rungs.items())},
            "last_qos_events": {t: {k: e.get(k) for k in
                                    ("kind", "rung", "from_rung", "reason")}
                                for t, e in sorted((last_qos or {}).items())},
            "backpressure": {
                "rejects": int(backpressure_rejects),
                "last": ({k: last_backpressure.get(k) for k in
                          ("tenant", "eta_s", "threshold_s", "queue_depth",
                           "workers")}
                         if last_backpressure else None),
            },
        }
    return {
        "counts": st["counts"],
        "by_tenant": st["by_tenant"],
        "torn_spool_lines": st["torn_spool_lines"],
        "in_flight": in_flight,
        "last_plan": plan,
        "deadletter": {"depth": len(term["deadletter"]),
                       "requests": deadletters},
        "attempts": attempts,
        "slo": slo,
        "autoscale": autoscale,
        "worker_age_s": {w: round(now - t, 3)
                         for w, t in sorted(workers.items())},
    }


def _fmt_age(s):
    if s is None:
        return "-"
    if s >= 3600:
        return f"{s / 3600:.1f}h"
    if s >= 60:
        return f"{s / 60:.1f}m"
    return f"{s:.1f}s"


def _fmt_eta(eta):
    if not eta or eta.get("eta_s") is None:
        return "-"
    return f"{_fmt_age(eta['eta_s'])} ({eta['source']})"


def render_text(snap):
    """Terminal rendering of one :func:`build_snapshot` dict."""
    out = [f"watch: {snap['run_dir']}  "
           f"(records {snap['read_audit']['records']}, torn "
           f"{snap['read_audit']['torn_lines']})"]
    fl = snap.get("fleet")
    if fl:
        c = fl["counts"]
        out.append(f"  fleet queue: {c['queued']} queued | {c['running']} "
                   f"running | {c['done']} done | {c['failed']} failed | "
                   f"{c.get('deadletter', 0)} dead-lettered | "
                   f"{c.get('canceled', 0)} canceled "
                   f"(of {c['submitted']} submitted"
                   + (f"; {c['expired_claims']} expired claim(s)"
                      if c["expired_claims"] else "") + ")")
        for tenant, t in sorted(fl["by_tenant"].items()):
            out.append(f"    tenant {tenant}: {t['queued']}q "
                       f"{t['running']}r {t['done']}d {t['failed']}f"
                       + (f" {t['deadletter']}dl"
                          if t.get("deadletter") else "")
                       + (f" {t['canceled']}c" if t.get("canceled") else ""))
        dl = fl.get("deadletter") or {}
        if dl.get("depth"):
            out.append(f"    dead-letter depth: {dl['depth']}")
            for d in dl.get("requests") or []:
                out.append(f"      {d['request_id']} [{d['tenant']}] "
                           f"{d['reason']} after {d['attempts']} attempt(s)"
                           + (f" (last {d['last_classification']})"
                              if d.get("last_classification") else ""))
        att = fl.get("attempts") or {}
        if att:
            out.append("    attempt budgets: " + "  ".join(
                f"{rid}={a['attempts']}f/{a['reclaims']}r"
                for rid, a in sorted(att.items())))
        slo = fl.get("slo")
        if slo:
            ov = slo["overall"]

            def _slo_s(v):
                return f"{v:.2f}s" if isinstance(v, (int, float)) else "-"

            qw, tt = ov.get("queue_wait_s") or {}, ov.get("ttfa_s") or {}
            dl = ov.get("deadline") or {}
            dlp = ov.get("deadletter_pct")
            att_pr = ov.get("attempts_per_request")
            out.append(
                f"    slo: qwait p50/p99 {_slo_s(qw.get('p50'))}/"
                f"{_slo_s(qw.get('p99'))} | ttfa p99 "
                f"{_slo_s(tt.get('p99'))} | deadline "
                + (f"{dl['hit_pct']:.0f}%" if dl.get("hit_pct") is not None
                   else "-")
                + f" | attempts/req "
                + (f"{att_pr:.2f}" if att_pr is not None else "-")
                + f" | dead-letter "
                + (f"{dlp:.1f}%" if dlp is not None else "-")
                + f" ({ov['settled']}/{ov['requests']} settled)")
            for br in slo.get("breaches") or []:
                out.append(f"    SLO BREACH [{br['scope']}] {br['slo']}: "
                           f"{br['value']:.3f} vs {br['threshold']:.3f}")
        for inf in fl["in_flight"]:
            out.append(f"    in-flight {inf['request_id']} "
                       f"[{inf['tenant']}] on {inf['worker']} "
                       f"batch={inf['batch_id']} lease "
                       f"{_fmt_age(max(inf['expires_in_s'], 0.0))} left")
        lp = fl.get("last_plan")
        if lp:
            out.append(f"    last plan ({_fmt_age(lp['age_s'])} ago): "
                       f"depth={lp['queue_depth']} -> "
                       f"{lp['batches']} batch(es), "
                       f"{lp['unschedulable']} unschedulable, "
                       f"util={lp['utilization_pct']}%, "
                       f"plan={lp['plan_ms']}ms")
            for d in (lp.get("decisions") or [])[:4]:
                out.append(f"      {d.get('batch_id')}: "
                           f"{d.get('n_points')} pt -> "
                           f"bucket {d.get('g_bucket')}, tenants "
                           f"{','.join(d.get('tenants') or [])}"
                           + (f", eta {_fmt_age(d['eta_s'])}"
                              if d.get("eta_s") is not None else ""))
        if fl["worker_age_s"]:
            out.append("    workers: " + "  ".join(
                f"{w}={_fmt_age(a)}"
                for w, a in fl["worker_age_s"].items()))
        auto = fl.get("autoscale")
        if auto:
            out.append(
                f"    autoscale: {auto.get('workers')}/"
                f"{auto.get('max_workers')} worker(s), target "
                f"{auto.get('target')}, pending {auto.get('pending')}, "
                f"drain eta {_fmt_age(auto.get('drain_eta_s'))}"
                + (f" (state {_fmt_age(auto['state_age_s'])} old)"
                   if auto.get("state_age_s") is not None else ""))
            ld = auto.get("last_decision")
            if ld:
                out.append(f"      last decision: {ld.get('kind')} "
                           f"({ld.get('reason')})")
            for tenant, r in sorted((auto.get("qos") or {}).items()):
                out.append(f"      qos tenant {tenant}: rung "
                           f"{r.get('rung')} ({r.get('reason')})")
            bp = auto.get("backpressure") or {}
            if bp.get("rejects"):
                last = bp.get("last") or {}
                out.append(
                    f"      backpressure: {bp['rejects']} reject(s)"
                    + (f", last [{last.get('tenant')}] eta "
                       f"{_fmt_age(last.get('eta_s'))} vs slo "
                       f"{_fmt_age(last.get('threshold_s'))}"
                       if last else ""))
    pk = snap.get("packing")
    if pk:
        st_p = pk.get("state") or {}
        lp_p = pk.get("last_plan") or {}
        out.append(
            "  packing: "
            + (f"{st_p.get('busy_devices', 0)}/{st_p.get('pool', '?')} "
               f"device(s) busy, {st_p.get('concurrent_batches', 0)} "
               f"co-resident, util {st_p.get('utilization_pct', 0)}%"
               if st_p else "no live occupancy state")
            + f" | {pk.get('slot_claims', 0)} claim(s) / "
              f"{pk.get('slot_frees', 0)} free(s)"
            + (f" ({_fmt_age(pk['age_s'])} old)"
               if pk.get("age_s") is not None else ""))
        if lp_p:
            ratio = lp_p.get("makespan_ratio")
            out.append(
                f"    last packing plan: {lp_p.get('decision')} "
                f"({lp_p.get('reason')})"
                + (f", makespan ratio {ratio:.3f}"
                   if isinstance(ratio, (int, float)) else "")
                + f", headroom violations "
                  f"{lp_p.get('headroom_violations', 0)}")
        if pk.get("partial_points"):
            last_pr = pk.get("last_partial") or {}
            out.append(
                f"    partial results: {pk['partial_points']} point(s) "
                f"streamed"
                + (f", last {last_pr.get('request_id')}#"
                   f"{last_pr.get('point')} epoch {last_pr.get('epoch')}"
                   + (" (final)" if last_pr.get("final") else "")
                   if last_pr else ""))
    sv = snap.get("serve")
    if sv:
        def _ms(v):
            return f"{v:.2f}ms" if isinstance(v, (int, float)) else "-"

        out.append(
            f"  serve [{sv.get('last_kind')}]: "
            f"{sv.get('streams', 0)} stream(s) / "
            f"{sv.get('capacity', '?')} slot(s), "
            f"{sv.get('samples_out', 0)}/{sv.get('samples_in', 0)} "
            f"answered, lat p50/p99 {_ms(sv.get('p50_ms'))}/"
            f"{_ms(sv.get('p99_ms'))}"
            + (f", rung:{sv['rung']}/{sv.get('capacity', '?')}"
               + (f" [{sv['mode']}]" if sv.get("mode") else "")
               if sv.get("rung") is not None else "")
            + (f", fused:{sv['fused_samples']}"
               + (f" (depth<={sv['fuse']})" if sv.get("fuse") else "")
               if sv.get("fused_samples") else "")
            + (f", precision:{sv['precision_mode']}"
               if sv.get("precision_mode")
               and sv.get("precision_mode") != "f32" else "")
            + (f", {sv['rejects']} reject(s)" if sv.get("rejects") else "")
            + (f", {sv['dropped']} dropped" if sv.get("dropped") else "")
            + (f", {sv['quarantines']} quarantine(s)"
               if sv.get("quarantines") else "")
            + (f" ({_fmt_age(sv['age_s'])} old)"
               if sv.get("age_s") is not None else ""))
    hb = snap["heartbeats"]
    out.append(f"  ages: metrics file {_fmt_age(hb['metrics_file_age_s'])} |"
               f" last record {_fmt_age(hb['last_record_age_s'])} | last "
               f"epoch {_fmt_age(hb['last_epoch_age_s'])}")
    if hb["span_age_s"]:
        out.append("  span ages: " + "  ".join(
            f"{c}={_fmt_age(a)}" for c, a in hb["span_age_s"].items()))
    at = snap["attempts"]
    if at["n"]:
        out.append(f"  supervisor: {at['n']} attempt(s), last "
                   f"{at['last_classification']}"
                   + (f", final {at['final']}" if at["final"] else "")
                   + (f", eta-at-exit {_fmt_age(at['last_eta']['eta_s'])}"
                      if at.get("last_eta")
                      and at["last_eta"].get("eta_s") is not None else ""))
    for i, f in enumerate(snap["fits"]):
        state = ("done" if f["done"]
                 else "dead" if f.get("superseded") else "LIVE")
        width = f.get("grid_width")
        out.append(
            f"  fit {i} [{state}] {f['model']} G={f['grid_size']} "
            f"bucket={width} mode={f['stream_mode'] or '?'} epoch "
            f"{f['last_epoch']}"
            + (f"/{f['max_iter']}" if f.get("max_iter") is not None else "")
            + f" lanes_live={f['lanes_live']} "
            f"quarantined={f['num_quarantined']} "
            f"skipped={f['guarded_steps_skipped']}")
        out.append(
            f"         rate={f['epoch_rate_per_min'] or '-'} epoch/min  "
            f"last_epoch_ms={f['epoch_ms_last'] or '-'}  "
            f"eta={_fmt_eta(f['eta'])}")
    if not snap["fits"]:
        out.append("  (no fit_start recorded yet)")
    if snap["grid_eta_s"] is not None:
        out.append(f"  whole-run ETA: {_fmt_age(snap['grid_eta_s'])}")
    st = snap["stalls"]
    if st:
        out.append(
            f"  stalls (from {st['source']}, epoch {st.get('epochs')}): "
            f"ckpt={st.get('ckpt_stall_ms')}ms "
            f"barrier={st.get('ckpt_barrier_stall_ms')}ms "
            f"prefetch={st.get('prefetch_stall_ms')}ms "
            f"compile={st.get('compile_ms')}ms")
    n = snap["numerics"]
    out.append(f"  numerics: {n['anomaly_events']} anomaly, "
               f"{n['rollbacks']} rollback, {n['aborts']} abort, "
               f"{n['guarded_steps_skipped']} guarded step(s) skipped")
    q = snap.get("quality")
    if q:
        fs = lambda v: (f"{v:.3f}" if isinstance(v, (int, float)) else "-")
        out.append(
            f"  quality: epoch {q.get('epoch')} lanes={q.get('lanes')} "
            f"plateaued={q.get('plateaued_count')} "
            f"stability={fs(q.get('stability'))} "
            f"auroc={fs(q.get('auroc'))} "
            f"(age {_fmt_age(q.get('age_s'))})")
    pol = snap.get("policy")
    if pol:
        fms = lambda v: (f"{v:.0f}ms" if isinstance(v, (int, float))
                         else "-")
        kind = pol.get("kind")
        if kind == "compaction":
            body = (f"{pol.get('action')} {pol.get('from_width')}->"
                    f"{pol.get('to_width')} saving {fms(pol.get('saving_ms'))}"
                    f" vs compile {fms(pol.get('compile_ms'))} "
                    f"({pol.get('epochs_remaining')} epochs left)")
        elif kind == "initial_width":
            body = (f"{pol.get('action')} rung {pol.get('chosen_width')} "
                    f"(heuristic {pol.get('heuristic_width')}, "
                    f"saving {fms(pol.get('saving_ms'))})")
        elif kind == "preempt_price":
            body = (f"{pol.get('action')} "
                    f"{pol.get('request_id') or pol.get('beneficiary') or ''}"
                    + (f" ({pol['reason']})" if pol.get("reason") else ""))
        else:
            body = f"{kind} {pol.get('action') or ''}".strip()
        out.append(f"  policy: {body}"
                   + (" [fallback]" if pol.get("fallback") else "")
                   + f" (age {_fmt_age(pol.get('age_s'))})")
    pre = snap.get("preempt")
    if pre:
        out.append(
            f"  preempt: {pre.get('kind')} batch {pre.get('batch_id')} -> "
            f"{pre.get('beneficiary')}"
            + (f" [{pre['tenant']}]" if pre.get("tenant") else "")
            + (f" queued eta {_fmt_age(pre['queued_eta_s'])}"
               if pre.get("queued_eta_s") is not None else "")
            + (f", running rem {_fmt_age(pre['running_rem_s'])}"
               if pre.get("running_rem_s") is not None else "")
            + f" (age {_fmt_age(pre.get('age_s'))})")
    mem = snap.get("memory")
    if mem:
        fb = lambda b: (f"{b / (1 << 20):.1f}MB"
                        if isinstance(b, (int, float)) else "-")
        if mem.get("bytes_in_use") is not None \
                or mem.get("peak_bytes") is not None:
            out.append(
                f"  hbm: in_use {fb(mem['bytes_in_use'])} | peak "
                f"{fb(mem['peak_bytes'])} | limit {fb(mem['bytes_limit'])} "
                f"(age {_fmt_age(mem['measured_age_s'])}; predicted "
                f"{fb(mem['predicted_bytes'])})")
        else:
            out.append(
                f"  hbm: n/a ({mem.get('backend') or 'backend'}) — "
                f"predicted {fb(mem['predicted_bytes'])} at bucket "
                f"{mem.get('g_bucket')}")
    if snap["incidents"]:
        out.append(f"  incidents: " + "; ".join(
            f"{i['event']}({','.join(i['components'])})"
            for i in snap["incidents"]))
    return "\n".join(out)


def run_watch(run_dir, once=False, as_json=False, interval=2.0,
              max_ticks=None, out=None):
    """CLI body. ``max_ticks`` bounds the follow loop (tests); returns the
    exit code."""
    out = out if out is not None else sys.stdout
    diag = diagnose_run_dir(run_dir)
    if diag is not None:
        print(f"obs watch: {diag}", file=sys.stderr)
        return 2
    ticks = 0
    while True:
        snap = build_snapshot(run_dir)
        if as_json:
            json.dump(snap, out, indent=2, allow_nan=False)
            out.write("\n")
        else:
            if not once and out.isatty():
                out.write("\x1b[H\x1b[2J")  # home + clear: live refresh
            out.write(render_text(snap) + "\n")
        out.flush()
        ticks += 1
        if once or (max_ticks is not None and ticks >= max_ticks):
            return 0
        try:
            time.sleep(interval)
        except KeyboardInterrupt:
            return 0
