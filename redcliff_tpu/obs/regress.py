"""Cross-round bench regression sentinel.

Every PR round leaves a ``BENCH_r*.json`` artifact (driver-captured
``bench.py`` output). Nothing compared them: a 2x headline slowdown would
ship unnoticed until a human eyeballed the trajectory. This module compares
the CURRENT round's payload against the prior rounds' per metric family and
emits a machine-readable ``regressions`` block (empty list = clean) that
bench.py embeds into every round's artifact — the trajectory audits
itself. Runnable standalone: ``python -m redcliff_tpu.obs regress``.

Noise model (the measured caveats this repo documents, see
docs/ARCHITECTURE.md "Performance observatory"):

* this container's per-dispatch step timing wobbles run-to-run by ~±25 %
  (measured while building the ``obs_overhead_pct`` probe), so throughput
  bands default to ±35 % and PER-BATCH throughput families (``wps`` /
  ``per_step_wps`` — non-headline, dominated by dispatch noise) are
  deliberately NOT tracked; the scanned/epoch-engine families are the
  production path and the stable signal;
* the XLA thunk-runtime ~1 ulp per-grid-width rounding is a numerics
  caveat, not a cost one — it never moves a timing family, and the one
  numeric family tracked (the Pallas prox TPU parity error) uses a 10x
  band so ulp-level jitter can't page anyone;
* a family is only judged against ≥ :data:`MIN_PRIOR_SAMPLES` prior
  samples from the SAME backend platform (and the same headline grid size
  for G-dependent families), and the band widens to the priors' own
  min-max spread when history is noisier than the default band;
* timing families carry an absolute floor (``abs_floor``): a "regression"
  from 3 ms to 6 ms is measurement dust, not a finding.

Verdicts: ``regressions`` (current worse than the prior median beyond the
band), ``improvements`` (better beyond the band — reported, never fatal).
The sentinel never raises on malformed artifacts; unusable rounds are
skipped and counted.

stdlib only — bench.py's backend-free parent imports this path.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time

from redcliff_tpu.obs import schema as _schema

__all__ = ["Family", "FAMILIES", "MIN_PRIOR_SAMPLES", "load_trajectory",
           "payload_samples", "run_sentinel", "render_text", "main",
           "repo_root", "load_tpu_cache_provenance"]

MIN_PRIOR_SAMPLES = 2

# default relative noise bands by family character
_BAND_THROUGHPUT = 0.35   # container step noise measured at ~±25 %
_BAND_TIMING = 0.50       # compile/stall/plan latencies are spikier still


class Family:
    """One tracked metric family: where it lives in the payload, which
    direction is good, and how much noise to forgive."""

    def __init__(self, key, path=None, better="higher",
                 band=_BAND_THROUGHPUT, abs_floor=None, g_dependent=True,
                 contract_max=None, contract_min=None):
        self.key = key
        self.path = path or key
        self.better = better
        self.band = band
        # regressions below this absolute value are ignored (timing dust)
        self.abs_floor = abs_floor
        # compare only against priors at the same headline grid size
        self.g_dependent = g_dependent
        # absolute ceiling that flags REGARDLESS of the prior trajectory:
        # a documented contract breach is a finding even when every prior
        # round was already in breach (relative bands would hide the drift)
        self.contract_max = contract_max
        # absolute FLOOR for higher-is-better scientific families
        # (obs/quality.py): a model-quality metric dropping below it flags
        # regardless of the trajectory — a perf PR that silently degrades
        # graph recovery fails exactly like a throughput regression
        self.contract_min = contract_min

    def extract(self, payload):
        cur = payload
        for part in self.path.split("."):
            if not isinstance(cur, dict):
                return None
            cur = cur.get(part)
        return cur if isinstance(cur, (int, float)) \
            and not isinstance(cur, bool) else None


FAMILIES = [
    # production-path throughput (scanned / epoch-engine dispatches)
    Family("value"),
    Family("epoch_scan_wps"),
    Family("vs_baseline"),
    Family("mfu_pct"),
    Family("bf16.ratio_vs_f32"),
    # the promoted mixed-precision probe (ISSUE 14): the production
    # precision_mode="mixed" wps ratio vs f32 at the same grid point —
    # a kernel/precision regression fails the round like a throughput one.
    # sentinel_skips is judged as lower-is-better with an absolute floor:
    # an occasional skip is the guard working, a growing count is a cliff
    Family("mixed_precision.wps_ratio_vs_f32"),
    Family("mixed_precision.sentinel_skips", better="lower",
           band=_BAND_TIMING, abs_floor=3.0, g_dependent=False),
    # kernel-tiling autotune (ops/autotune.py): the winner's measured edge
    # over the default tile must not erode, and the per-round fresh search
    # must stay cheap (it runs on every fit's first encounter of a shape)
    Family("autotune.speedup_vs_default", band=_BAND_TIMING,
           g_dependent=False),
    Family("autotune.search_ms", better="lower", band=_BAND_TIMING,
           abs_floor=2000.0, g_dependent=False),
    Family("dead_lane_flops_saved_pct", band=_BAND_TIMING),
    # cost probes: lower is better, with absolute floors for timing dust
    Family("ckpt_stall_ms.async_ms", better="lower", band=_BAND_TIMING,
           abs_floor=50.0),
    Family("compile_cache.warm_compile_ms", better="lower",
           band=_BAND_TIMING, abs_floor=100.0),
    Family("compile_cache.warm_vs_cold_speedup", band=_BAND_TIMING),
    Family("remesh.plan_ms", better="lower", band=_BAND_TIMING,
           abs_floor=50.0, g_dependent=False),
    # the telemetry-spine contract (<= 2 %): wobble below the ceiling never
    # flags (abs_floor), a breach past it ALWAYS does (contract_max) — even
    # when the prior rounds were already in breach
    Family("obs_overhead_pct", better="lower", band=_BAND_TIMING,
           abs_floor=2.0, g_dependent=False, contract_max=2.0),
    # real-TPU Pallas prox parity error (rides the bench cache provenance):
    # 10x band — ulp-level jitter is documented, an order of magnitude is a
    # kernel bug
    Family("pallas_prox_max_abs_err", path="pallas_prox_check.max_abs_err",
           better="lower", band=9.0, abs_floor=1e-5, g_dependent=False),
    # device-memory observatory (ISSUE 9, obs/memory.py): the analytical
    # HBM model's error vs the measured watermark — null (skipped) on
    # backends without memory_stats; the ±20% acceptance contract is the
    # absolute ceiling, judged even when priors were already in breach
    Family("mem_model_err_pct", path="mem_model.abs_err_pct",
           better="lower", band=_BAND_TIMING, abs_floor=20.0,
           g_dependent=False, contract_max=20.0),
    # span -> Perfetto round-trip cost (obs/trace_export.py): a post-mortem
    # tool, but an O(n^2) regression in the exporter would make real run
    # dirs unexportable — keep it on the trajectory
    Family("trace_export.export_ms", better="lower", band=_BAND_TIMING,
           abs_floor=250.0, g_dependent=False),
    # fleet admission planner (redcliff_tpu/fleet): the packed-vs-FIFO
    # mesh-slot utilization gain on the synthetic heterogeneous request mix
    # must not erode (the packing IS the service's perf claim), and the
    # host-only planning latency must stay queue-scan cheap
    Family("fleet.packed_utilization_pct", band=_BAND_TIMING,
           g_dependent=False),
    Family("fleet.utilization_gain", band=_BAND_TIMING, g_dependent=False),
    Family("fleet.plan_ms", better="lower", band=_BAND_TIMING,
           abs_floor=50.0, g_dependent=False),
    # fleet failure containment (ISSUE 11): healthy-sibling completion
    # latency with a poison co-tenant over without one, end-to-end through
    # real drains at the same bucket width. ~1.0 means the poison tenant
    # costs its siblings nothing; a creeping ratio means containment is
    # leaking wall-clock back into healthy requests
    Family("fleet_containment.latency_ratio", better="lower",
           band=_BAND_TIMING, g_dependent=False),
    # fleet trace export (ISSUE 12, obs/trace_export.py --fleet): the
    # ledger-join cost on a synthetic 50-request history — the whole-fleet
    # post-mortem must stay cheap enough to run on every incident
    Family("fleet_trace.export_ms", better="lower", band=_BAND_TIMING,
           abs_floor=250.0, g_dependent=False),
    # predictive scheduling policy (ISSUE 15, parallel/policy.py): the
    # simulated mixed-shape sweep makespan under the predictive policy over
    # the heuristic ladder — < 1.0 is the win the policy exists for, and
    # the absolute ceiling (contract_max) pins the acceptance bound even on
    # a trajectory whose priors were already in breach. decide_ms keeps the
    # pure-host decision pricing queue-scan cheap (it runs at every check
    # window and every worker claim cycle)
    Family("predictive_policy.makespan_ratio", better="lower",
           band=_BAND_TIMING, g_dependent=False, contract_max=1.0),
    Family("predictive_policy.decide_ms", better="lower", band=_BAND_TIMING,
           abs_floor=50.0, g_dependent=False),
    # SLO-driven autoscaling (ISSUE 16, fleet/autoscale.py): wall time
    # from the first windowed breach detection of a seeded submit storm to
    # the queue fully drained with the pool grown — the breach-absorption
    # latency the subsystem exists to bound. Storms drain real tiny fits,
    # so the floor forgives scheduler/compile jitter on small absolutes.
    # reject_eta_err_pct tracks the backpressure gate's reject-with-ETA
    # accuracy (|predicted wait - observed drain| as % of observed): a
    # creeping error means tenants are told wrong retry times
    # spatial mesh packing (ISSUE 18, parallel/packing.py + the worker's
    # gang loop): packed/serial wall-clock of two heterogeneous batches on
    # a simulated 4-device pool — the contract_max flags any round where
    # co-residency stops beating serial outright — and the busy
    # device-seconds pool utilization the packer achieved. Both legs run
    # real tiny drains, so the timing band forgives process-spawn jitter.
    Family("packing.makespan_ratio", better="lower", band=_BAND_TIMING,
           g_dependent=False, contract_max=1.0),
    Family("packing.utilization_pct", band=_BAND_TIMING,
           g_dependent=False),
    Family("autoscale.breach_to_recovery_s", better="lower",
           band=_BAND_TIMING, abs_floor=30.0, g_dependent=False),
    Family("autoscale.reject_eta_err_pct", better="lower",
           band=_BAND_TIMING, abs_floor=50.0, g_dependent=False),
    # scientific regression families (ISSUE 13, obs/quality.py): the
    # quality probe's graph-recovery score on the deterministic synthetic
    # sVAR grid fit, the top-k edge-set stability at the end of that fit,
    # and the per-check-window readout cost. The AUROC floor is absolute
    # (contract_min): a perf PR that silently degrades graph recovery
    # fails the sentinel exactly like a throughput regression, even on a
    # trajectory with no quality-bearing priors yet. Rounds predating the
    # probe simply lack the fields (skipped, never noise)
    Family("quality.synthetic_auroc", path="quality.final_auroc",
           band=_BAND_TIMING, g_dependent=False, contract_min=0.65),
    Family("quality.edge_stability", path="quality.edge_stability",
           band=_BAND_TIMING, g_dependent=False),
    Family("quality.overhead_pct", path="quality.overhead_pct",
           better="lower", band=_BAND_TIMING, abs_floor=2.0,
           g_dependent=False, contract_max=2.0),
    # streaming inference service (ISSUE 17, redcliff_tpu/serve): the
    # saturated-slot-table per-sample dispatch p99 (ingest->answer wall
    # clock; the abs_floor forgives sub-5ms scheduler dust), sustained
    # samples/s at full stream occupancy, and the churn-isolation pin —
    # isolation_ok is 1.0 iff co-resident lanes are byte-identical with
    # vs without a chaos storm; contract_min pins it as an acceptance
    # bound even on trajectories whose priors were already green
    Family("serve.p99_ms", path="serve.p99_ms", better="lower",
           band=_BAND_TIMING, abs_floor=5.0, g_dependent=False),
    Family("serve.samples_per_s", path="serve.samples_per_s",
           band=_BAND_TIMING, g_dependent=False),
    Family("serve.isolation_ok", path="serve.isolation_ok",
           band=_BAND_TIMING, g_dependent=False, contract_min=1.0),
    # elastic serve data plane (ISSUE 20): the 25%-occupancy leg's
    # structural dead-lane saving (forced occupancy ladder riding the min
    # rung — contract_min pins that the ladder actually shrinks; 10% is
    # far below the ~50% a healthy min-rung ride yields at capacity//4
    # streams, so it trips only on a ladder that stopped engaging), the
    # backlogged single-scan fusion drain throughput, and the
    # mixed-vs-f32 throughput ratio (<1 under CPU bf16 EMULATION — the
    # MXU speedup only shows on TPU hardware; the family tracks the
    # trajectory so a silently broken mixed path shows as a cliff, it is
    # not a speedup floor)
    Family("serve.dead_lane_flops_saved_pct",
           path="serve.dead_lane_flops_saved_pct", band=_BAND_TIMING,
           g_dependent=False, contract_min=10.0),
    Family("serve.fused_samples_per_s", path="serve.fused_samples_per_s",
           band=_BAND_TIMING, g_dependent=False),
    Family("serve.mixed_ratio_vs_f32", path="serve.mixed_ratio_vs_f32",
           band=_BAND_TIMING, g_dependent=False),
]


def _g_scaling_families(payload):
    """Dynamic per-G families for the scanned dispatch (wps_scan /
    epoch_scan only — see the module docstring's noise model for why the
    per-batch wps entries are exempt)."""
    out = []
    for g, entry in ((payload or {}).get("g_scaling") or {}).items():
        if isinstance(entry, dict):
            for field in ("wps_scan", "epoch_scan"):
                if isinstance(entry.get(field), (int, float)):
                    out.append(Family(f"g_scaling.{g}.{field}",
                                      g_dependent=False))
    return out


def repo_root():
    """The checkout root (where BENCH_r*.json and experiments/ live)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def _payload_from_artifact(art):
    """The emitted bench payload inside one driver artifact: the ``parsed``
    field, else the last parseable ``{"metric": ...}`` line recovered from
    ``tail`` (the driver truncates tails, so recovery can fail — that
    round is then skipped, not fatal)."""
    if isinstance(art.get("parsed"), dict):
        return art["parsed"]
    for line in reversed(str(art.get("tail") or "").splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            payload = json.loads(line)
        except ValueError:
            continue
        if isinstance(payload, dict) and payload.get("metric"):
            return payload
    return None


def payload_samples(payload):
    """Comparable samples inside one round's payload: the headline, plus
    the CPU ``live_fallback`` leg a cached-TPU headline carries (so the CPU
    trajectory stays comparable across rounds where the real-TPU cache was
    the headline)."""
    if not isinstance(payload, dict):
        return []
    samples = [payload]
    fb = payload.get("live_fallback")
    if isinstance(fb, dict) and fb.get("metric"):
        samples.append(fb)
    return samples


def load_trajectory(bench_dir=None):
    """All BENCH_r*.json rounds under ``bench_dir`` (default: the repo
    root), round-ordered: ``[{"round", "path", "payload"}]``; rounds whose
    payload cannot be recovered carry ``payload=None``."""
    bench_dir = bench_dir or repo_root()
    rounds = []
    for path in glob.glob(os.path.join(bench_dir, "BENCH_r*.json")):
        m = _ROUND_RE.search(os.path.basename(path))
        if not m:
            continue
        try:
            with open(path) as f:
                art = json.load(f)
        except (OSError, ValueError):
            art = {}
        rounds.append({"round": int(m.group(1)), "path": path,
                       "payload": _payload_from_artifact(art)})
    rounds.sort(key=lambda r: r["round"])
    return rounds


def load_tpu_cache_provenance(bench_dir=None):
    """Provenance of the cached real-TPU evidence
    (``experiments/TPU_BENCH_CACHE.json``, falling back to the tracked
    seed file): measured_at, source, value, and the Pallas prox parity
    error — surfaced so cached TPU measurements join the trajectory
    instead of being invisible. None when neither file parses."""
    bench_dir = bench_dir or repo_root()
    for name in ("TPU_BENCH_CACHE.json", "TPU_BENCH_CACHE_SEED.json"):
        path = os.path.join(bench_dir, "experiments", name)
        try:
            with open(path) as f:
                cache = json.load(f)
        except (OSError, ValueError):
            continue
        result = cache.get("result") or {}
        if not isinstance(result, dict) or not result.get("value"):
            continue
        prox = cache.get("pallas_prox_check") \
            or result.get("pallas_prox_check") or {}
        return {
            "file": name,
            "measured_at": cache.get("measured_at"),
            "source": cache.get("source"),
            "git_commit": cache.get("git_commit"),
            "value": result.get("value"),
            "platform": result.get("platform"),
            "device": result.get("device"),
            "pallas_prox_max_abs_err": prox.get("max_abs_err"),
        }
    return None


def _median(vals):
    vals = sorted(vals)
    n = len(vals)
    mid = n // 2
    return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])


def _comparable(fam, current, sample):
    if sample.get("platform") != current.get("platform"):
        return False
    if fam.g_dependent and current.get("grid_points") is not None \
            and sample.get("grid_points") is not None \
            and sample["grid_points"] != current["grid_points"]:
        return False
    return True


def run_sentinel(current, trajectory=None, bench_dir=None, now=None):
    """Judge ``current`` (one bench payload dict) against the prior rounds.

    Returns the machine-readable sentinel block (``event="regression"``,
    validates against the registered schema). ``trajectory`` defaults to
    :func:`load_trajectory`; the current round (matched by identical
    payload identity or the highest round whose payload IS ``current``) is
    never compared against itself.
    """
    now = time.time() if now is None else now
    trajectory = (load_trajectory(bench_dir) if trajectory is None
                  else trajectory)
    current_round = None
    prior_rounds = []
    for r in trajectory:
        if r["payload"] is current or (
                r["payload"] is not None and current is not None
                and r["payload"] == current):
            current_round = r["round"]
            continue
        prior_rounds.append(r)
    regressions, improvements, skipped = [], [], []
    checked = 0
    notes = [
        "bands absorb the documented ~±25% container dispatch noise "
        "(per-batch wps families exempt entirely); the ~1 ulp XLA "
        "width-rounding caveat is numerics-only and cannot move a timing "
        "family",
    ]
    if not isinstance(current, dict) or not current.get("metric"):
        notes.append("no usable current payload — nothing to judge")
        current = {}
    # judge EVERY leg of the current round: the headline, and — when the
    # headline is a replayed cached-TPU measurement — the fresh CPU
    # live_fallback leg too (otherwise a slowdown in the only measurement
    # this round actually ran would ship behind a byte-identical cache)
    legs = [("headline", current)]
    fb = current.get("live_fallback")
    if isinstance(fb, dict) and fb.get("metric"):
        legs.append(("live_fallback", fb))
    for leg_name, leg in legs:
        for fam in FAMILIES + _g_scaling_families(leg):
            cur = fam.extract(leg)
            if cur is None:
                continue
            if fam.contract_max is not None and cur > fam.contract_max:
                # absolute contract breach: judged against the documented
                # ceiling, not the (possibly already-breached) trajectory
                checked += 1
                regressions.append({
                    "metric": fam.key, "direction": fam.better,
                    "sample": leg_name, "current": cur,
                    "baseline_median": fam.contract_max,
                    "change_pct": round(
                        100.0 * (cur - fam.contract_max)
                        / fam.contract_max, 1),
                    "band_pct": 0.0, "contract": True, "priors": {}})
                continue
            if fam.contract_min is not None and cur < fam.contract_min:
                # scientific floor breach (obs/quality.py families): a
                # quality score under the documented floor is a finding
                # even with no prior trajectory to compare against
                checked += 1
                regressions.append({
                    "metric": fam.key, "direction": fam.better,
                    "sample": leg_name, "current": cur,
                    "baseline_median": fam.contract_min,
                    "change_pct": round(
                        100.0 * (cur - fam.contract_min)
                        / fam.contract_min, 1),
                    "band_pct": 0.0, "contract": True, "priors": {}})
                continue
            priors = {}
            for r in prior_rounds:
                for sample in payload_samples(r["payload"]):
                    if not _comparable(fam, leg, sample):
                        continue
                    v = fam.extract(sample)
                    if v is not None:
                        priors.setdefault(f"r{r['round']:02d}", v)
            if len(priors) < MIN_PRIOR_SAMPLES:
                skipped.append({"metric": fam.key, "sample": leg_name,
                                "reason":
                                f"{len(priors)} prior sample(s) "
                                f"< {MIN_PRIOR_SAMPLES}"})
                continue
            checked += 1
            vals = list(priors.values())
            med = _median(vals)
            if med == 0:
                skipped.append({"metric": fam.key, "sample": leg_name,
                                "reason": "zero baseline"})
                continue
            # widen the band to the priors' own spread: history noisier
            # than the default band raises the bar for a finding
            spread = (max(vals) - min(vals)) / abs(med)
            band = max(fam.band, spread)
            change = (cur - med) / abs(med)
            worse = (change < -band if fam.better == "higher"
                     else change > band)
            better = (change > band if fam.better == "higher"
                      else change < -band)
            if worse and fam.abs_floor is not None:
                # timing dust / contract floors: tiny values never flag
                bad_side = cur if fam.better == "lower" else med
                if bad_side < fam.abs_floor:
                    worse = False
            entry = {
                "metric": fam.key, "direction": fam.better,
                "sample": leg_name,
                "current": cur, "baseline_median": round(med, 6),
                "change_pct": round(100.0 * change, 1),
                "band_pct": round(100.0 * band, 1),
                "priors": priors,
            }
            if worse:
                regressions.append(entry)
            elif better:
                improvements.append(entry)
    block = {
        "event": "regression",
        "wall_time": now,
        "schema_version": _schema.SCHEMA_VERSION,
        "current_round": current_round,
        "rounds_compared": [f"r{r['round']:02d}" for r in prior_rounds
                            if r["payload"] is not None],
        "families_checked": checked,
        "regressions": regressions,
        "improvements": improvements,
        "skipped": skipped,
        "notes": notes,
        "tpu_cache": load_tpu_cache_provenance(bench_dir),
    }
    return block


def render_text(block):
    out = [f"regression sentinel: {block['families_checked']} family(ies) "
           f"judged against rounds "
           f"[{', '.join(block['rounds_compared']) or 'none'}]"]
    leg = lambda r: (f" [{r['sample']}]"
                     if r.get("sample") not in (None, "headline") else "")
    for r in block["regressions"]:
        out.append(f"  REGRESSION {r['metric']}{leg(r)}: {r['current']} vs "
                   f"median {r['baseline_median']} ({r['change_pct']:+.1f}% "
                   f"past the ±{r['band_pct']:.0f}% band)")
    for r in block["improvements"]:
        out.append(f"  improvement {r['metric']}{leg(r)}: {r['current']} vs "
                   f"median {r['baseline_median']} ({r['change_pct']:+.1f}%)")
    if not block["regressions"]:
        out.append("  clean: no family outside its noise band")
    tc = block.get("tpu_cache")
    if tc:
        out.append(f"  cached TPU evidence: {tc['value']} w/s on "
                   f"{tc.get('device')} measured {tc.get('measured_at')} "
                   f"({tc['file']}; pallas prox max err "
                   f"{tc.get('pallas_prox_max_abs_err')})")
    return "\n".join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m redcliff_tpu.obs regress",
        description="Compare the newest BENCH_r*.json round against the "
                    "prior trajectory per metric family with noise bands.")
    ap.add_argument("--bench-dir", default=None,
                    help="directory holding BENCH_r*.json (default: the "
                         "repo root)")
    ap.add_argument("--current", default=None,
                    help="payload JSON to judge (a bench payload or a "
                         "driver artifact with a 'parsed' field; default: "
                         "the highest round in --bench-dir)")
    ap.add_argument("--json", action="store_true",
                    help="print the sentinel block as JSON")
    args = ap.parse_args(argv)
    trajectory = load_trajectory(args.bench_dir)
    if args.current:
        try:
            with open(args.current) as f:
                cur = json.load(f)
        except (OSError, ValueError) as e:
            print(f"obs regress: cannot read --current: {e}",
                  file=sys.stderr)
            return 2
        if isinstance(cur, dict) and not cur.get("metric"):
            cur = _payload_from_artifact(cur)
        if not (isinstance(cur, dict) and cur.get("metric")):
            # exiting 0 here would make a CI gate pass forever while
            # judging nothing — unusable input is a hard error, like the
            # no---current path below
            print(f"obs regress: no bench payload recoverable from "
                  f"--current {args.current} (expected an emitted payload "
                  f"or a driver artifact with a 'parsed' field)",
                  file=sys.stderr)
            return 2
    else:
        usable = [r for r in trajectory if r["payload"] is not None]
        if not usable:
            print("obs regress: no BENCH_r*.json round with a recoverable "
                  "payload — nothing to judge", file=sys.stderr)
            return 2
        cur = usable[-1]["payload"]
    block = run_sentinel(cur, trajectory=trajectory,
                         bench_dir=args.bench_dir)
    if args.json:
        json.dump(block, sys.stdout, indent=2, allow_nan=False)
        sys.stdout.write("\n")
    else:
        print(render_text(block))
    return 3 if block["regressions"] else 0
