"""Lifecycle trace spans: the timing half of the telemetry spine.

A *span* wraps one operation — a dispatch, a checkpoint write, a shard load,
a compaction — and records how long it took, against both clocks (monotonic
for durations, wall for cross-process alignment), with process/host identity
and a propagated parent id so nested spans reconstruct the call tree in a
post-mortem. Every finished span lands in the in-memory flight-recorder ring
(:mod:`redcliff_tpu.obs.flight`); spans opened with ``emit=True`` and a live
:class:`~redcliff_tpu.obs.logging.MetricLogger` additionally write one
``span`` event to ``metrics.jsonl`` (schema: :mod:`redcliff_tpu.obs.schema`).

Cost discipline (the spine's contract, pinned by bench.py's
``obs_overhead_pct`` and the tier-1 identity tripwire):

* **zero-cost when disabled** — :func:`span` returns one shared no-op
  context after a single module-global flag check (``REDCLIFF_TRACE=0``);
* **never a host sync** — a span measures host wall time around the
  operation it wraps. Around an asynchronously-dispatched XLA program that
  is *enqueue* time, by design: no ``.block_until_ready()``, no transfer,
  ever happens inside span bookkeeping (device time stays attributable via
  the engines' dispatch counters);
* hot-path spans (per-dispatch) are ring-only: a dict build + deque append,
  no I/O.

Side-band counters (:class:`Counters`) accumulate cross-thread totals that
have no natural span emission point — prefetch stall milliseconds, async
checkpoint submit-barrier stalls — which the grid engine folds into its
per-fit ``dispatch_stats``.

**Trace context** (the fleet's cross-process request identity,
docs/ARCHITECTURE.md "Request lifecycle tracing & SLOs"): a fleet worker
runs each batch under a context ``{"batch_id": ..., "trace_ids":
{request_id: trace_id}}`` — set in-process via :func:`set_trace_ctx` and
handed to the supervised run_batch child through the ``REDCLIFF_TRACE_CTX``
env var (parsed once at import). While a context is live (and tracing is
on), every finished span — and, via :class:`~redcliff_tpu.obs.logging
.MetricLogger`, every metrics record — carries it as a ``trace`` field, so
a post-mortem join can attribute any span in any process to the fleet
requests it was serving. One ``is not None`` check on the hot path; no
context, no cost.

stdlib only — no numpy, no jax: the watchdog and the backend-free bench
parent import this path.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time

from redcliff_tpu.obs import flight as _flight

__all__ = ["span", "record_span", "enabled", "set_enabled", "Span", "NOOP",
           "Counters", "COUNTERS", "ENV_TRACE", "ENV_TRACE_CTX",
           "trace_ctx", "set_trace_ctx", "PID", "HOST"]

ENV_TRACE = "REDCLIFF_TRACE"
ENV_TRACE_CTX = "REDCLIFF_TRACE_CTX"

# tracing defaults ON: the spine's steady-state cost is ring appends and a
# handful of jsonl lines per check window (bench pins it <= 2% of wps);
# REDCLIFF_TRACE=0 drops it to one flag check per span() call
_enabled = os.environ.get(ENV_TRACE, "1").strip().lower() not in (
    "0", "off", "false")

PID = os.getpid()
try:
    HOST = os.uname().nodename
except (AttributeError, OSError):  # non-posix
    import socket

    HOST = socket.gethostname()

# process-wide span ids: unique within a process; (pid, span_id) is unique
# across the run's processes (both ride every span record)
_ids = itertools.count(1)
_tls = threading.local()  # per-thread open-span stack (parent propagation)


# cross-process trace context: a fleet worker exports REDCLIFF_TRACE_CTX
# (JSON {"batch_id", "trace_ids": {request_id: trace_id}}) into its
# supervised run_batch child; a non-dict / unparseable value is ignored —
# identity stamping must never crash the process it identifies
def _ctx_from_env():
    raw = os.environ.get(ENV_TRACE_CTX)
    if not raw:
        return None
    try:
        ctx = json.loads(raw)
    except ValueError:
        return None
    return ctx if isinstance(ctx, dict) and ctx else None


_trace_ctx = _ctx_from_env()


def trace_ctx():
    """The live trace context dict, or None: a thread-scoped context (a
    packed fleet worker's gang threads each bracket their own batch —
    ISSUE 18) wins over the process-wide one."""
    return getattr(_tls, "trace_ctx", None) or _trace_ctx


def set_trace_ctx(ctx):
    """Set (or clear, with None) the trace context; returns the PREVIOUS
    context so callers can scope it (the fleet worker brackets each batch).
    From the main thread this is the process-wide context (unchanged
    pre-packing behavior); from any other thread it is a THREAD-scoped
    override — concurrent gang-scheduled batches must never stamp each
    other's spans with the wrong batch id."""
    global _trace_ctx
    ctx = ctx if isinstance(ctx, dict) and ctx else None
    if threading.current_thread() is threading.main_thread():
        prev = _trace_ctx
        _trace_ctx = ctx
        return prev
    prev = getattr(_tls, "trace_ctx", None)
    _tls.trace_ctx = ctx
    return prev


def enabled():
    """Whether tracing is live (module-global flag; one attribute read)."""
    return _enabled


def set_enabled(flag):
    """Flip tracing at runtime (bench.py's on/off overhead probe; tests).
    Returns the new state."""
    global _enabled
    _enabled = bool(flag)
    return _enabled


class _NoopSpan:
    """The shared disabled-tracing span: enter/exit do nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


NOOP = _NoopSpan()


class Span:
    """One traced operation. Use via :func:`span` as a context manager."""

    __slots__ = ("name", "component", "logger", "emit", "attrs",
                 "span_id", "parent_id", "t_wall", "t_mono", "dur_ms")

    def __init__(self, name, component, logger, emit, attrs):
        self.name = name
        self.component = component or name.partition(".")[0]
        self.logger = logger
        self.emit = emit
        self.attrs = attrs
        self.span_id = None
        self.parent_id = None
        self.t_wall = None
        self.t_mono = None
        self.dur_ms = None

    def set(self, **attrs):
        """Attach/overwrite attributes mid-span (recorded at exit)."""
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        self.parent_id = stack[-1].span_id if stack else None
        self.span_id = next(_ids)
        stack.append(self)
        self.t_wall = time.time()
        self.t_mono = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.dur_ms = (time.perf_counter() - self.t_mono) * 1e3
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1] is self:
            stack.pop()
        rec = {
            "event": "span", "name": self.name,
            "span_id": self.span_id, "parent_id": self.parent_id,
            "t_wall": self.t_wall, "t_mono": self.t_mono,
            "dur_ms": round(self.dur_ms, 3), "pid": PID, "host": HOST,
        }
        if exc_type is not None:
            rec["error"] = exc_type.__name__
        if self.attrs:
            rec["attrs"] = dict(self.attrs)
        if _trace_ctx is not None:
            rec["trace"] = _trace_ctx
        _flight.record(self.component, rec)
        if self.emit and self.logger is not None \
                and getattr(self.logger, "active", False):
            emit_rec = {k: v for k, v in rec.items()
                        if k not in ("event", "pid", "host")}
            self.logger.log("span", **emit_rec)
        return False


def span(name, *, component=None, logger=None, emit=False, **attrs):
    """Open a trace span named ``name`` (convention:
    ``"<component>.<operation>"``, e.g. ``"grid.dispatch"``,
    ``"ckpt.write"`` — see docs/ARCHITECTURE.md "Telemetry spine").

    ``component`` keys the flight-recorder ring the finished span lands in
    (defaults to the name's dotted head). ``emit=True`` + a live ``logger``
    additionally writes a ``span`` event to metrics.jsonl — reserve it for
    low-frequency lifecycle spans (check windows, compactions, remeshes);
    hot-path spans stay ring-only. ``**attrs`` must be plain JSON-able
    scalars/short lists. Returns the shared no-op when tracing is disabled.
    """
    if not _enabled:
        return NOOP
    return Span(name, component, logger, emit, attrs)


def record_span(name, dur_ms, *, component=None, logger=None, emit=False,
                t_wall=None, **attrs):
    """Record an already-measured operation as a finished span — for call
    sites where wrapping the block in a context manager would be awkward
    (e.g. long engine sections timed with ``perf_counter``). Same record
    shape and destinations as :class:`Span`; returns the record, or None
    when tracing is disabled."""
    if not _enabled:
        return None
    stack = getattr(_tls, "stack", None)
    rec = {
        "event": "span", "name": name,
        "span_id": next(_ids),
        "parent_id": stack[-1].span_id if stack else None,
        "t_wall": t_wall if t_wall is not None else time.time(),
        "dur_ms": round(dur_ms, 3), "pid": PID, "host": HOST,
    }
    if attrs:
        rec["attrs"] = dict(attrs)
    if _trace_ctx is not None:
        rec["trace"] = _trace_ctx
    _flight.record(component or name.partition(".")[0], rec)
    if emit and logger is not None and getattr(logger, "active", False):
        logger.log("span", **{k: v for k, v in rec.items()
                              if k not in ("event", "pid", "host")})
    return rec


class Counters:
    """Thread-safe additive counters for cross-thread time accounting that
    has no single span emission point (prefetch stall, ckpt barrier stall).
    Engines snapshot at fit start and fold the delta into their stats."""

    def __init__(self):
        self._lock = threading.Lock()
        self._c = {}

    def add(self, key, value=1.0):
        with self._lock:
            self._c[key] = self._c.get(key, 0.0) + value

    def snapshot(self):
        with self._lock:
            return dict(self._c)

    def delta(self, before):
        """``now - before`` for every key present now (missing = 0)."""
        now = self.snapshot()
        return {k: round(v - before.get(k, 0.0), 3) for k, v in now.items()}


COUNTERS = Counters()
