"""Crash flight recorder: bounded in-memory rings of recent telemetry.

Post-mortems of a ``hang`` / ``host_lost`` / numerics-abort escalation used
to depend on whatever metrics.jsonl happened to have flushed — and the hot
path deliberately does NOT emit per-dispatch events, so the most relevant
evidence (what each component was doing in its last seconds) was never on
disk at all. This module keeps that evidence in memory: every finished span
and any explicitly recorded event lands in a per-component ring of the last
``capacity`` records (``REDCLIFF_FLIGHT_N``, default 64). On escalation the
watchdog (:mod:`redcliff_tpu.runtime.watchdog`) and the trainers'
DivergenceMonitor abort path :func:`dump` the rings as one structured
``flight_record.json`` artifact next to the run's metrics.jsonl — strict
JSON, atomically written, latest incident wins.

Ring appends are a dict build + ``deque.append`` under a lock — cheap enough
for per-dispatch recording (bench.py's ``obs_overhead_pct`` pins the total).

stdlib only — no numpy, no jax: the watchdog and the supervisor-side
tooling import this safely.
"""
from __future__ import annotations

import collections
import json
import math
import os
import threading
import time

__all__ = ["FlightRecorder", "RECORDER", "record", "snapshot", "clear",
           "dump", "dump_for_logger", "FLIGHT_RECORD_NAME", "ENV_CAPACITY",
           "DEFAULT_CAPACITY"]

FLIGHT_RECORD_NAME = "flight_record.json"
ENV_CAPACITY = "REDCLIFF_FLIGHT_N"
DEFAULT_CAPACITY = 64


def _capacity_from_env():
    try:
        return max(int(os.environ.get(ENV_CAPACITY, DEFAULT_CAPACITY)), 1)
    except ValueError:
        return DEFAULT_CAPACITY


class FlightRecorder:
    """Per-component bounded rings of the most recent telemetry records."""

    def __init__(self, capacity=None):
        self.capacity = capacity or _capacity_from_env()
        self._lock = threading.Lock()
        self._rings = {}

    def record(self, component, rec):
        with self._lock:
            ring = self._rings.get(component)
            if ring is None:
                ring = self._rings[component] = collections.deque(
                    maxlen=self.capacity)
            ring.append(rec)

    def snapshot(self):
        """{component: [oldest .. newest]} — copies, safe to mutate."""
        with self._lock:
            return {c: list(r) for c, r in self._rings.items()}

    def clear(self):
        with self._lock:
            self._rings.clear()


# process-global recorder: spans and engines record without plumbing a
# handle; the watchdog dumps it on escalation
RECORDER = FlightRecorder()


def record(component, rec):
    """Record ``rec`` (a dict) into ``component``'s global ring."""
    RECORDER.record(component, rec)


def snapshot():
    return RECORDER.snapshot()


def clear():
    RECORDER.clear()


def _plain(v):
    """Best-effort strict-JSON coercion without numpy: non-finite floats
    become null, unknown objects become their ``str``."""
    if v is None or isinstance(v, (bool, int, str)):
        return v
    if isinstance(v, float):
        return v if math.isfinite(v) else None
    if isinstance(v, dict):
        return {str(k): _plain(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_plain(x) for x in v]
    return str(v)


def dump(target_dir, reason, extra=None, recorder=None,
         filename=FLIGHT_RECORD_NAME):
    """Write the flight record as ``<target_dir>/flight_record.json``
    (atomic tmp+replace; the latest incident wins) and return its path.

    The artifact is one strict-JSON object::

        {"event": "flight_record", "schema_version": ..., "reason": ...,
         "wall_time": ..., "pid": ..., "host": ...,
         "extra": <caller context, e.g. the watchdog's incident record>,
         "components": {component: [last-N span/event records]}}
    """
    from redcliff_tpu.obs import schema as _schema
    from redcliff_tpu.obs import spans as _spans

    recorder = recorder if recorder is not None else RECORDER
    os.makedirs(target_dir, exist_ok=True)
    path = os.path.join(target_dir, filename)
    rec = {
        "event": "flight_record",
        "schema_version": _schema.SCHEMA_VERSION,
        "reason": reason,
        "wall_time": time.time(),
        "pid": os.getpid(),
        "host": _spans.HOST,
        "extra": _plain(extra),
        "components": _plain(recorder.snapshot()),
    }
    tmp = f"{path}.tmp{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        json.dump(rec, f, allow_nan=False)
        f.write("\n")
    os.replace(tmp, path)
    return path


def dump_for_logger(logger, reason, extra=None):
    """Dump next to a bound :class:`MetricLogger`'s jsonl file (the run
    directory); no-op returning None when the logger is inactive/unbound —
    escalation paths call this unconditionally."""
    path = getattr(logger, "path", None) if logger is not None else None
    if not path:
        return None
    return dump(os.path.dirname(path) or ".", reason, extra=extra)
