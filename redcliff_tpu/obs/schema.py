"""Versioned event-schema registry for the telemetry spine.

Every line this repo writes to ``metrics.jsonl`` (and every record in the
supervisor's ``run_ledger.jsonl``) is one of the event types registered
here. The registry is the *contract*: an emitter adding an event type or a
field must register it — the tier-1 schema tripwire
(tests/test_obs_report.py) runs a faulted supervised grid fit and validates
every emitted record, so undocumented drift fails CI, not a 3am post-mortem.
The full taxonomy table lives in docs/ARCHITECTURE.md "Telemetry spine".

Validation is CLOSED: an unknown event name, a missing required field, or a
field that is neither registered nor matched by one of the event's
``patterns`` (dynamic metric families like the GC-tracker's
``f1_t0.5_factor2``) is an error. Records from older writers may lack the
``seq``/``pid``/``host`` identity fields (added in schema version 1) —
readers stay backfill-tolerant, so those are optional everywhere.

stdlib only.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

__all__ = ["SCHEMA_VERSION", "EventSchema", "EVENTS", "LEDGER_EVENTS",
           "validate_record", "validate_records", "SHAPE_KEYS", "shape_desc",
           "shape_key", "SPAN_NAMES", "check_sources", "main"]

SCHEMA_VERSION = 1

# model-config fields that key a compiled program family: with the grid
# width they form the (shape, G-bucket) axis of the obs report's cost table
# (the input ROADMAP item 4's learned cost model trains on). Emitters stamp
# the subset their model config defines into fit_start's "shape" field.
SHAPE_KEYS = ("num_chans", "gen_lag", "embed_lag", "max_lag", "num_factors",
              "num_supervised_factors", "gen_hidden", "embed_hidden_sizes",
              "input_length", "num_sims")


def shape_desc(config):
    """The ``fit_start.shape`` dict for a model config: every
    :data:`SHAPE_KEYS` field the config defines (non-None)."""
    return {k: getattr(config, k) for k in SHAPE_KEYS
            if getattr(config, k, None) is not None}


def _shape_val(v):
    # normalize through the same tuple->list coercion the jsonl round trip
    # applies, so a key computed live (config tuples) and one computed from
    # re-read metrics (JSON lists) are IDENTICAL — the cost-model store
    # merges on this string
    if isinstance(v, tuple):
        v = list(v)
    if isinstance(v, list):
        return "[" + ", ".join(str(_shape_val(x)) for x in v) + "]"
    return v


def shape_key(shape):
    """Canonical string key for a ``fit_start.shape`` dict — the shape half
    of the (shape, G-bucket) cost axis shared by the obs report's cost
    table and the learned cost model's store (obs/costmodel.py). Stable
    across the metrics round trip (tuples serialize as JSON lists)."""
    if not isinstance(shape, dict) or not shape:
        return "unknown"
    return ",".join(f"{k}={_shape_val(shape[k])}" for k in sorted(shape))


# the CLOSED span-name registry: every `obs.span(...)` / `record_span(...)`
# name literal in redcliff_tpu/ must appear here (and in the
# docs/ARCHITECTURE.md span table) — enforced by the AST source tripwire in
# tests/test_observability.py, the span analog of the event registry below
SPAN_NAMES = frozenset({
    "grid.dispatch", "grid.check_window", "grid.compaction", "grid.remesh",
    "grid.ckpt_save",
    "ckpt.write", "ckpt.async_write", "ckpt.submit_barrier",
    "prefetch.fill", "prefetch.stall", "shard.load",
    "fleet.plan", "fleet.batch",
    "serve.dispatch",
})

# identity fields the MetricLogger stamps on every record (schema v1);
# optional on read: pre-v1 files and third-party writers lack them.
# ``trace`` is the cross-process half (ISSUE 12): the fleet trace context
# ({"batch_id", "trace_ids": {request_id: trace_id}}) stamped on every
# record — and every emitted span — a process writes while serving a fleet
# batch (spans.set_trace_ctx / REDCLIFF_TRACE_CTX), so post-mortem joins
# can attribute any record to the requests it was serving
_IDENTITY = ("seq", "pid", "host", "trace")

# numerics-sentinel summary fields (runtime/numerics.py numerics_summary),
# splatted into anomaly/numerics events by the trainers
_NUMERICS_SUMMARY = ("skipped", "consecutive", "checked", "grad_norm_last",
                     "grad_norm_mean", "grad_norm_std", "grad_norm_max")

# hang/host-loss incident body (runtime/watchdog.py _record)
_INCIDENT = ("components", "ages_s", "grace_s", "stacks", "host")


@dataclass(frozen=True)
class EventSchema:
    """One registered event type. ``required``/``optional`` are field names
    beyond the registry-wide core fields; ``patterns`` are regexes that
    admit dynamic field families."""

    emitter: str
    required: frozenset = frozenset()
    optional: frozenset = frozenset()
    patterns: tuple = ()
    version: int = SCHEMA_VERSION
    _compiled: tuple = field(default=None, compare=False, repr=False)

    def allows(self, name):
        if name in self.required or name in self.optional:
            return True
        compiled = self._compiled
        if compiled is None:
            compiled = tuple(re.compile(p) for p in self.patterns)
            object.__setattr__(self, "_compiled", compiled)
        return any(p.match(name) for p in compiled)


def _ev(emitter, required=(), optional=(), patterns=()):
    return EventSchema(emitter=emitter, required=frozenset(required),
                       optional=frozenset(optional), patterns=tuple(patterns))


# ---------------------------------------------------------------------------
# metrics.jsonl events. Core fields: event + wall_time required (the
# MetricLogger stamps both), seq/pid/host optional-on-read.
# ---------------------------------------------------------------------------
EVENTS = {
    "fit_start": _ev(
        "trainers + grid engine",
        required=("model",),
        optional=("train_config", "resume_epoch", "training_mode", "shape",
                  "grid_size", "grid_width", "lanes_padded", "stream_mode",
                  "mesh", "compile_cache_dir", "resumed_from_epoch",
                  "resumed_from", "points", "max_iter", "precision_mode")),
    "epoch": _ev(
        "trainers + grid engine",
        required=("epoch",),
        optional=("phases", "criteria", "epoch_ms",
                  # grid per-check-window fields
                  "val_combo_loss", "best_criteria", "num_active",
                  "lanes_live", "grid_width", "lanes_padded",
                  "num_quarantined", "guarded_steps_skipped"),
        patterns=(
            # the trainers splat validate() loss parts and the GC tracker's
            # per-threshold/per-factor oracle metrics into the record
            r".*_loss$", r".*_penalty$", r".*_sim$",
            r"^(f1|roc_auc|accuracy|precision|recall|deltacon0|"
            r"deltaffinity|gc_l1|cosine_sim|confusion)_[A-Za-z0-9._\-]+$")),
    "anomaly": _ev(
        "numerics sentinel (trainers)",
        required=("epoch", "cause"),
        optional=("epoch_skipped_steps",) + _NUMERICS_SUMMARY),
    "numerics": _ev(
        "DivergenceMonitor (trainers)",
        required=("epoch", "kind", "cause"),
        optional=("restored_epoch", "lr_scale", "learning_rates",
                  "rollbacks", "flight_record") + _NUMERICS_SUMMARY),
    "fit_end": _ev(
        "trainers + grid engine",
        optional=("best_it", "best_loss", "final_val_loss", "aborted",
                  "best_epoch", "best_criteria", "num_active", "compactions",
                  "compile_ms", "failures", "dispatch_stats",
                  # model-quality snapshot (obs/quality.py): the trainers
                  # stamp it directly; the grid engine carries it inside
                  # dispatch_stats["quality"]
                  "quality")),
    "precision": _ev(
        "trainers + grid engine + serve (mixed-precision production path, "
        "ISSUE 14: kind=demote — the numerics sentinel caught a "
        "skip/rollback storm under precision_mode='mixed' and the fit "
        "rebuilt every step at f32; kind=resume_demoted — a resumed fit "
        "honored the checkpointed demotion instead of re-promoting. ISSUE "
        "20 scopes the same pair to the serve table — scope='serve', "
        "tick-indexed instead of epoch-indexed: a poisoned-lane storm "
        "inside the sentinel window demotes the whole slot table to f32)",
        required=("kind",),
        optional=("epoch", "cause", "mode_from", "mode_to", "lanes",
                  "grid_width", "rollbacks", "scope", "ticks",
                  "lanes_poisoned", "window_ticks") + _NUMERICS_SUMMARY),
    "autotune": _ev(
        "trainers + grid engine (ops/autotune.py kernel-tiling search/"
        "lookup records: kind=search — a measured candidate-ladder search "
        "ran and persisted a winner beside the compile cache; kind=reuse "
        "— a persisted winner was loaded with zero search steps)",
        required=("kernel",),
        optional=("kind", "platform", "shape", "g_bucket", "tile",
                  "candidates", "search_ms", "search_steps",
                  "speedup_vs_default")),
    "compile": _ev(
        "grid engine (runtime/compileobs.py counters)",
        required=("epoch", "programs", "compile_ms"),
        optional=("cache_hits", "cache_misses", "grid_width")),
    "compaction": _ev(
        "grid engine (parallel/compaction.py)",
        required=("epoch", "from_width", "to_width"),
        optional=("lanes_live", "retired", "mesh_devices")),
    "remesh": _ev(
        "grid engine (parallel/remesh.py)",
        required=("epoch",),
        optional=("from_width", "to_width", "from_devices", "to_devices",
                  "lanes_migrated", "lanes_retired", "plan_ms")),
    "deadline_evicted": _ev(
        "grid engine (GridSpec.fit_deadline_s)",
        required=("epoch", "lanes"),
        optional=("elapsed_s", "num_evicted")),
    "early_exit_all_inactive": _ev("grid engine", required=("epoch",)),
    "preempted_final_checkpoint": _ev(
        "grid engine (PreemptionGuard)",
        required=("epoch",), optional=("signum",)),
    "grid_deadline_final_checkpoint": _ev(
        "grid engine (GridSpec.grid_deadline_s)",
        required=("epoch",),
        optional=("elapsed_s", "deadline_s", "checkpointed")),
    "hang": _ev("watchdog", required=("components",), optional=_INCIDENT),
    "hang_exit": _ev(
        "watchdog", required=("exit_code",), optional=_INCIDENT),
    "host_lost": _ev(
        "watchdog", required=("components",), optional=_INCIDENT),
    "host_lost_exit": _ev(
        "watchdog", required=("exit_code",), optional=_INCIDENT),
    "span": _ev(
        "obs.spans (emit=True call sites)",
        required=("name", "dur_ms"),
        optional=("span_id", "parent_id", "t_wall", "t_mono", "component",
                  "attrs", "error")),
    "flight_record": _ev(
        "obs.flight (artifact file, not a jsonl line)",
        required=("reason", "components"),
        optional=("schema_version", "extra")),
    "cost_model": _ev(
        "grid engine (obs/costmodel.py prediction-vs-actual scoring, one "
        "per check window once a prediction exists)",
        required=("epoch", "predicted_epoch_ms", "actual_epoch_ms"),
        optional=("residual_pct", "grid_width", "source", "eta_s",
                  "epochs_remaining", "samples", "mape_pct",
                  "predicted_compile_ms")),
    "policy": _ev(
        "predictive scheduling policy (ISSUE 15, parallel/policy.py "
        "decisions consulted from the learned cost model, logged by the "
        "grid engine and the fleet worker; kind=initial_width — the priced "
        "starting-rung choice at fit start; kind=compaction — the "
        "compact/hold/fallback pricing of one check window's ladder move; "
        "kind=compile_order — the worker's cold-compile claim ordering "
        "over one admission plan; kind=preempt_price — the worker's "
        "deadline-aware hold/preempt pricing of a queued tenant against "
        "the running batch)",
        required=("kind",),
        optional=("epoch", "grid_width", "action", "fallback",
                  "from_width", "to_width", "chosen_width",
                  "heuristic_width", "saving_ms", "compile_ms", "gather_ms",
                  "total_ms", "heuristic_ms", "epochs", "epochs_remaining",
                  "order", "batch_id", "request_id", "beneficiary",
                  "deadline_at", "eta_s", "queued_eta_s", "running_rem_s",
                  "grace_s", "slack_s", "priority", "worker", "reason")),
    "preempt": _ev(
        "fleet worker deadline-aware preemption (ISSUE 15: "
        "kind=signal — the worker decided a queued higher-priority "
        "tenant's deadline would be missed and SIGTERMed the supervised "
        "batch child after its checkpoint landed; kind=preempted — the "
        "batch settled as a zero-charge reclaim: leases released, "
        "composition pinned to resume bit-identically after the "
        "beneficiary runs)",
        required=("kind",),
        optional=("batch_id", "requests", "tenants", "beneficiary",
                  "tenant", "priority", "deadline_at", "eta_s",
                  "queued_eta_s", "running_rem_s", "slack_s", "grace_s",
                  "worker", "run_dir", "reason", "epoch")),
    "memory": _ev(
        "grid engine + trainers (obs/memory.py: kind=predicted — the "
        "analytical HBM footprint at fit start; kind=measured — a "
        "device.memory_stats() watermark poll, check-window cadence, only "
        "on backends that report)",
        required=("kind",),
        optional=("epoch", "g_bucket", "grid_width", "predicted_bytes",
                  "params_bytes", "opt_bytes", "best_bytes",
                  "per_lane_bytes", "dataset_bytes", "epoch_gather_bytes",
                  "bytes_in_use", "peak_bytes", "bytes_limit",
                  "budget_bytes", "headroom_bytes", "fits", "backend",
                  "device_kind", "n_devices", "note")),
    "quality": _ev(
        "grid engine + trainers (obs/quality.py: one per check window when "
        "REDCLIFF_QUALITY=1 — per-lane Granger-graph summaries keyed by "
        "original point id, convergence diagnostics, and live AUROC/AUPR "
        "when the dataset carries ground-truth graphs)",
        required=("epoch", "lanes"),
        optional=("grid_width", "mode", "topk_k", "edge_energy", "sparsity",
                  "entropy", "topk_hash", "jaccard", "plateaued", "auroc",
                  "aupr", "mean_jaccard", "mean_auroc", "mean_aupr",
                  "plateaued_count")),
    "profile": _ev(
        "obs/profiling.py capture windows (announces the jax.profiler "
        "artifact a bounded window wrote under the run dir)",
        required=("path",),
        optional=("spec", "first_epoch", "last_epoch", "dur_ms",
                  "truncated")),
    "watch": _ev(
        "obs.watch (snapshot artifact / --once --json output, not a jsonl "
        "line; the serve block carries the elastic-data-plane posture — "
        "watch.serve.rung is the resident rung width vs capacity, "
        "watch.serve.fused_samples the cumulative fusion credit)",
        required=("run_dir", "fits"),
        optional=("schema_version", "ok", "grid_eta_s", "stalls", "numerics",
                  "heartbeats", "attempts", "incidents", "read_audit",
                  "memory", "fleet", "quality", "policy", "preempt",
                  "serve", "packing")),
    "fleet": _ev(
        "fleet sweep service (redcliff_tpu/fleet: submit CLI, planner, "
        "worker loop, run_batch driver, containment layer; kind=submit | "
        "plan | claim | reclaim | batch_start | batch_end | complete | "
        "lease_lost | renew_error | deadletter | bisect | cancel | requeue "
        "| manifest | worker_start | worker_stop | worker_crash)",
        required=("kind",),
        optional=("batch_id", "requests", "tenants", "n_points", "g_bucket",
                  "queue_depth", "batches", "unschedulable", "plan_ms",
                  "utilization_pct", "decisions", "eta_s",
                  "predicted_bytes", "run_dir", "worker", "classification",
                  "rc", "attempts", "wall_s", "done", "failed", "released",
                  "priority", "n_devices", "budget_bytes", "lease_s",
                  # containment fields (ISSUE 11): retry budgets, bisection,
                  # dead-letter routing, heartbeat renewal escalation,
                  # suspect-solo planning
                  "reason", "halves", "error", "consecutive", "suspects",
                  "deadlettered", "bisected", "max_attempts", "preempted",
                  # worker_crash (ISSUE 12): the uncaught-exception record
                  # + the flight-record artifact dumped before exit
                  "flight_record",
                  # spatial packing fields (ISSUE 18): the sub-mesh slot a
                  # batch ran on, the plan's priced packed-vs-serial
                  # verdict, and the fair-share deferrals the planner made
                  "slot", "packing", "quota_deferred")),
    "packing": _ev(
        "fleet spatial mesh packing (ISSUE 18, fleet/worker.py gang loop "
        "over parallel/packing.py's slot table; kind=plan — the priced "
        "packed-vs-serial verdict for the current queue; kind=slot_claim "
        "| slot_free — a sub-mesh slot occupied/returned at a "
        "check-window boundary; kind=slot_wait — a reclaim whose recorded "
        "slot is still busy; kind=cancel_stop — the cancel watch SIGTERMed "
        "a batch whose every member went terminal; kind=slot_canceled — "
        "that batch settled with its slot freed and no requeue)",
        required=("kind",),
        optional=("batch_id", "slot", "requests", "tenants",
                  "predicted_bytes", "worker", "decision", "reason",
                  "makespan_s", "serial_s", "makespan_ratio", "n_devices",
                  "pool", "headroom_violations")),
    "partial_result": _ev(
        "fleet per-point result streaming (ISSUE 18, fleet/run_batch.py — "
        "one line per grid point appended to results/<id>.partial.jsonl "
        "as lanes retire at check windows; final=True rows are the "
        "settle-time completion sweep, at-least-once so consumers keep "
        "the last row per point)",
        required=("request_id", "batch_id", "point", "final"),
        optional=("tenant", "merged_point", "epoch", "best_criterion",
                  "best_epoch", "failed")),
    "fleet_lifecycle": _ev(
        "fleet history ledger (fleet/history.py — the durable per-request "
        "lifecycle transitions obs/slo.py and the fleet trace export join; "
        "kind=submitted | planned | claimed | attempt | released | "
        "bisected | settled | requeued | preempted — the zero-charge "
        "checkpoint-and-yield transition ISSUE 15's deadline-aware "
        "preemption records — | autoscale | qos — ISSUE 16's durable "
        "pool-scaling and QoS-rung transitions, what `obs trace --fleet` "
        "joins scaling decisions from)",
        required=("kind",),
        optional=("request_id", "trace_id", "batch_id", "tenant", "worker",
                  "state", "classification", "attempt", "attempts",
                  "started_at", "requests", "trace_ids", "halves", "reason",
                  "priority", "deadline_s", "n_points", "submitted_at",
                  "g_bucket", "reclaim", "run_dir", "parent_batch_id",
                  "beneficiary", "workers", "target", "rung")),
    "autoscale": _ev(
        "fleet autoscaler (fleet/autoscale.py — the SLO-driven control "
        "loop's decision stream in the fleet root's metrics chain; "
        "kind=start | scale_up | respawn | scale_down | hold | stop)",
        required=("kind",),
        optional=("workers", "target", "max_workers", "min_workers",
                  "reason", "queue_depth", "drain_eta_s", "target_drain_s",
                  "window_s", "breaches", "spawned", "retired", "worker",
                  "classification", "restarts", "pending", "ticks")),
    "qos": _ev(
        "fleet autoscaler degraded-QoS ladder (fleet/autoscale.py — a "
        "breaching tenant demoted to cheaper settings instead of "
        "dead-lining; kind=demote | restore)",
        required=("kind", "tenant"),
        optional=("rung", "from_rung", "reason", "precision_mode",
                  "check_every_factor", "window_s", "worker")),
    "backpressure": _ev(
        "fleet queue admission gate (fleet/queue.py submit — the "
        "structured reject-with-ETA when predicted queue wait would "
        "breach the tenant's armed queue-wait SLO; kind=reject)",
        required=("kind", "tenant"),
        optional=("eta_s", "threshold_s", "queue_depth", "workers",
                  "n_points", "priority", "reason")),
    "serve": _ev(
        "streaming inference service (redcliff_tpu/serve/service.py — the "
        "slot-table serving loop's operational stream; kind=start | resume "
        "| tick | qos | reject | overflow | drain | stop. qos is the "
        "per-STREAM degraded graph-readout cadence ladder — the serve twin "
        "of the fleet's per-tenant qos event; reject is the SlotsExhausted "
        "admission refusal with lease-expiry ETA)",
        required=("kind",),
        optional=("capacity", "streams", "free_slots", "ticks",
                  "samples_in", "samples_out", "rejects", "dropped",
                  "p50_ms", "p99_ms", "n", "eta_s", "reason", "sid",
                  "trace_id", "rung", "from_rung", "cadence", "backlog",
                  "checkpoint", "resumed", "undelivered", "model_class",
                  # elastic data plane (ISSUE 20): resident rung width,
                  # live high-water mark, fusion + precision posture
                  "width", "live", "fused_samples", "mode", "fuse",
                  "precision_mode")),
    "serve_ladder": _ev(
        "serve occupancy ladder (redcliff_tpu/serve/service.py ServeLadder "
        "— the slot table's pow2 rung decisions at tick boundaries; "
        "kind=grow | shrink | hold | fallback | repack. grow is mandatory "
        "(a leased slot beyond the rung would never dispatch), shrink is "
        "priced through the PR-8 cost store (predicted dead-lane saving "
        "over the horizon vs cold-compile cost), hold/fallback record a "
        "declined or unpriceable shrink once per hysteresis episode, and "
        "repack is the cross-geometry resume that re-packs lanes instead "
        "of failing the shape check)",
        required=("kind",),
        optional=("from_width", "to_width", "live", "capacity", "mode",
                  "cold", "saving_ms", "compile_ms", "horizon_ticks",
                  "reason", "ticks", "streams", "from_capacity")),
    "serve_fuse": _ev(
        "serve micro-batched tick fusion (redcliff_tpu/serve/service.py — "
        "periodic fusion stats at the tick-event cadence when "
        "REDCLIFF_SERVE_FUSE > 1; kind=stats. hist maps per-stream fused "
        "take -> dispatch count — the fuse depth distribution obs report "
        "renders)",
        required=("kind",),
        optional=("depth", "fused_samples", "hist", "ticks", "width")),
    "session": _ev(
        "serve session lifecycle (redcliff_tpu/serve/service.py over "
        "serve/session.py's lease/heartbeat registry; kind=connect | "
        "disconnect | expire | quarantine | recycle | resume — expire is "
        "the lease reaper, quarantine the per-stream input-contract "
        "verdict, recycle the lane reset that returns a slot to the pool)",
        required=("kind", "sid"),
        optional=("slot", "trace_id", "reason", "samples_in", "samples_out",
                  "lease_s", "state", "undelivered")),
    "regression": _ev(
        "obs.regress (bench-artifact sentinel block, not a jsonl line)",
        required=("regressions",),
        optional=("schema_version", "current_round", "rounds_compared",
                  "families_checked", "improvements", "skipped", "notes",
                  "tpu_cache")),
}

# ---------------------------------------------------------------------------
# run_ledger.jsonl events (runtime/supervisor.py): stdlib writer, no
# wall_time core field (attempts carry started_at instead)
# ---------------------------------------------------------------------------
LEDGER_EVENTS = {
    "attempt": _ev(
        "supervisor",
        required=("attempt", "cmd", "rc", "classification", "action"),
        optional=("backoff_s", "started_at", "duration_s", "mesh", "eta")),
    "remesh": _ev(
        "supervisor",
        required=("from_devices", "to_devices"),
        optional=("from_hosts", "to_hosts")),
    "final": _ev(
        "supervisor",
        required=("classification",), optional=("rc", "attempts")),
    "fleet": _ev(
        "fleet worker (tenant manifest: request id -> merged point range, "
        "the per-tenant attribution map obs report joins on)",
        required=("kind",),
        optional=("batch_id", "requests", "worker", "tenants")),
}


def _registry_for(kind):
    if kind == "metrics":
        return EVENTS, ("event", "wall_time")
    if kind == "ledger":
        return LEDGER_EVENTS, ("event",)
    raise ValueError(f"unknown registry kind {kind!r}")


def validate_record(rec, kind="metrics"):
    """Validate one record against the registry; returns a list of error
    strings (empty = valid)."""
    registry, core_required = _registry_for(kind)
    if not isinstance(rec, dict):
        return [f"record is not an object: {type(rec).__name__}"]
    errors = []
    name = rec.get("event")
    if name is None:
        return ["missing 'event' field"]
    schema = registry.get(name)
    if schema is None:
        return [f"unknown event type {name!r} (register it in "
                f"redcliff_tpu/obs/schema.py and document it in "
                f"docs/ARCHITECTURE.md)"]
    for f_ in core_required:
        if f_ not in rec:
            errors.append(f"{name}: missing core field {f_!r}")
    for f_ in sorted(schema.required):
        if f_ not in rec:
            errors.append(f"{name}: missing required field {f_!r}")
    known_core = set(core_required) | set(_IDENTITY)
    for f_ in rec:
        if f_ in known_core:
            continue
        if not schema.allows(f_):
            errors.append(
                f"{name}: unregistered field {f_!r} (add it to the event's "
                f"schema in redcliff_tpu/obs/schema.py)")
    return errors


def validate_records(records, kind="metrics"):
    """Validate a sequence of records; returns ``[(index, [errors...])]``
    for every invalid record (empty list = all valid)."""
    out = []
    for i, rec in enumerate(records):
        errs = validate_record(rec, kind=kind)
        if errs:
            out.append((i, errs))
    return out


# ---------------------------------------------------------------------------
# standalone source tripwires: ``python -m redcliff_tpu.obs.schema --check``
# runs the AST-level registry/no-host-sync scans as a lint entry point (CI's
# lint job and tests/test_observability.py both drive these). stdlib only —
# this must run on a box with no jax backend at all.
# ---------------------------------------------------------------------------

# observability + fleet-control modules under the no-host-sync discipline.
# "no-jax": jax may not be imported AT ALL — the span/flight hot path, the
# post-mortem trace exporter, and the fleet CONTROL plane (queue scans,
# admission planning, the worker loop must never initialize a backend; only
# the supervised run_batch child does); "lazy-jax": jax only inside function
# bodies (memory polls and profiler start/stop need the API but must not
# drag jax into stdlib-only importers). block_until_ready is banned in
# every one of them — a device sync inside the observability layer would
# serialize what it observes.
NO_JAX_MODULES = ("obs/spans.py", "obs/flight.py", "obs/trace_export.py",
                  "obs/slo.py",
                  # spatial packing (ISSUE 18): the slot table and the
                  # packed-vs-serial pricer run inside the worker loop
                  "parallel/packing.py",
                  "fleet/queue.py", "fleet/planner.py", "fleet/worker.py",
                  "fleet/chaos.py", "fleet/__main__.py",
                  "fleet/history.py", "fleet/autoscale.py",
                  # serve control plane (ISSUE 17): admission, session
                  # supervision, and the chaos harness drive a service
                  # object without ever touching the backend themselves
                  "runtime/admission.py", "serve/session.py",
                  "serve/chaos.py")
# ops/autotune.py joins the lazy set (ISSUE 14): its store half must stay
# importable by backend-free processes, and its measurement half must sync
# via jax.device_get — a block_until_ready inside the tuner would be a
# banned device sync on what is effectively an observability path
LAZY_JAX_MODULES = ("obs/memory.py", "obs/profiling.py", "obs/quality.py",
                    "ops/autotune.py",
                    # serve data plane (ISSUE 17): jax only once an engine
                    # actually spins up — tests construct/inspect services
                    # and the session layer without a backend, and a
                    # device sync inside the serving loop outside the
                    # engine's own output read would serialize the
                    # double-buffered dispatch
                    "serve/engine.py", "serve/service.py")


def _pkg_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _iter_sources(pkg_root):
    for dirpath, _dirs, files in os.walk(pkg_root):
        if "__pycache__" in dirpath:
            continue
        for name in files:
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def _check_name_literals(tree, path, events, errors):
    """Every event/span name LITERAL must be registered: ``log("<event>")``
    -> EVENTS u LEDGER_EVENTS, ``span``/``record_span`` -> SPAN_NAMES, and
    dict literals carrying ``"event": "<name>"`` (the stdlib writers)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            fname = (fn.id if isinstance(fn, ast.Name)
                     else fn.attr if isinstance(fn, ast.Attribute)
                     else None)
            if not (fname in ("span", "record_span", "log") and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            name = node.args[0].value
            if fname == "log":
                if name not in events:
                    errors.append(f"{path}:{node.lineno}: unregistered "
                                  f"event literal {name!r}")
            elif name not in SPAN_NAMES:
                errors.append(f"{path}:{node.lineno}: unregistered span "
                              f"literal {name!r}")
        elif isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if (isinstance(k, ast.Constant) and k.value == "event"
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)
                        and v.value not in events):
                    errors.append(f"{path}:{node.lineno}: unregistered "
                                  f"event literal {v.value!r}")


def _check_host_sync(tree, path, rel, errors):
    """The no-host-sync discipline for the observability modules: no
    ``block_until_ready`` anywhere; jax imports banned entirely
    (:data:`NO_JAX_MODULES`) or confined to function bodies
    (:data:`LAZY_JAX_MODULES`)."""
    no_jax = rel.endswith(NO_JAX_MODULES)
    lazy_jax = rel.endswith(LAZY_JAX_MODULES)
    if not (no_jax or lazy_jax):
        return
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) \
                and node.attr == "block_until_ready":
            errors.append(f"{path}:{node.lineno}: block_until_ready in an "
                          f"observability module (device sync)")
    if no_jax:
        banned = ast.walk(tree)
    else:
        # lazy-jax: EVERY import outside a function body is module scope —
        # including ones nested in try:/if: blocks, which a plain
        # tree.body walk would miss
        in_func = set()
        for fn in ast.walk(tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(fn):
                    in_func.add(id(sub))
        banned = (n for n in ast.walk(tree) if id(n) not in in_func)
    for node in banned:
        if isinstance(node, ast.Import):
            names = [a.name for a in node.names]
        elif isinstance(node, ast.ImportFrom):
            names = [node.module or ""]
        else:
            continue
        if any(n.split(".")[0] == "jax" for n in names):
            where = "at all" if no_jax else "at module scope (lazy only)"
            errors.append(f"{path}:{node.lineno}: jax imported {where}")


def check_sources(pkg_root=None):
    """Run every source tripwire over ``redcliff_tpu/``; returns a list of
    ``"path:line: message"`` violations (empty = clean)."""
    pkg_root = pkg_root or _pkg_root()
    events = set(EVENTS) | set(LEDGER_EVENTS)
    errors = []
    for path in sorted(_iter_sources(pkg_root)):
        with open(path) as f:
            try:
                tree = ast.parse(f.read(), filename=path)
            except SyntaxError as e:
                errors.append(f"{path}: syntax error: {e}")
                continue
        rel = os.path.relpath(path, pkg_root).replace(os.sep, "/")
        _check_name_literals(tree, path, events, errors)
        _check_host_sync(tree, path, rel, errors)
    return errors


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m redcliff_tpu.obs.schema",
        description="Event-schema registry tools: --check runs the AST "
                    "source tripwires (event/span literal registration + "
                    "observability no-host-sync discipline) as a lint "
                    "step; exits 1 on any violation.")
    ap.add_argument("--check", action="store_true",
                    help="scan redcliff_tpu/ sources for unregistered "
                         "event/span literals and host-sync violations")
    args = ap.parse_args(argv)
    if not args.check:
        ap.print_help()
        return 2
    errors = check_sources()
    for e in errors:
        print(e)
    print(f"schema --check: {len(errors)} violation(s); "
          f"{len(EVENTS)} metric + {len(LEDGER_EVENTS)} ledger event "
          f"type(s), {len(SPAN_NAMES)} span name(s) registered "
          f"(schema v{SCHEMA_VERSION})")
    return 1 if errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
