"""redcliff_tpu.fleet — the grid-fleet sweep service (ROADMAP item 1).

REDCLIFF-S model selection is a grid sweep by construction (per-factor cMLP
forecasters swept over regularization/shape coefficients), but a grid fit
used to be one process launched by a driver. This package turns sweep
fitting into a long-lived, multi-tenant SERVICE — the "heavy traffic from
millions of users" shape of large-scale ML systems (arXiv:1605.08695)
applied to sweep serving:

* :mod:`.queue` — a durable, crash-safe request queue: an append-only JSONL
  spool plus atomic claim/lease files with lease expiry, so a SIGKILLed
  worker's claim is reclaimed by the next worker and the fit resumes from
  its durable checkpoint — a request is never lost and never run twice;
* :mod:`.planner` — the cost/memory-aware admission planner: packs
  heterogeneous requests (shapes, priorities, deadlines) into the elastic
  scheduler's G-buckets by predicted wall-clock
  (obs/costmodel.py ``predict_fit_eta``) under an HBM budget
  (obs/memory.py ``per_lane_bytes``/``check_headroom``), batching
  same-shape requests into ONE grid fit so the mesh stays full and the
  persistent compile cache amortizes across tenants;
* :mod:`.worker` — the worker loop: claims a planned batch, runs it under
  the crash-loop supervisor (runtime/supervisor.py ``supervise``), renews
  leases while the fit runs, stamps tenant ids into ``run_ledger.jsonl``
  and metrics events, and marks requests complete from the batch's
  per-request results;
* :mod:`.run_batch` — the jax-side batch driver the worker supervises: one
  merged grid fit per batch (checkpointed + resumable, content-derived
  per-lane seeds so a request fits identically whatever batch it lands
  in), split back into per-request result records plus the merged-grid
  ``failures.json`` attribution artifact;
* :mod:`.chaos` — the fleet chaos harness (ISSUE 11): poison request
  specs, worker SIGKILL storms, lease-expiry races, torn/corrupt durable
  state — seeded schedules for the containment soak;
* CLI — ``python -m redcliff_tpu.fleet submit|work|status|cancel|requeue``.

Blast-radius containment (docs/ARCHITECTURE.md "Fleet failure
containment"): per-request retry budgets persisted in ``attempts/``,
poison attribution from the grid engine's per-lane quarantine causes,
blind-failure batch bisection over pinned compositions, suspect-solo
admission planning, and a durable ``deadletter/`` with failure dossiers —
so one poison tenant can never fail a healthy co-tenant's request or
crash-loop a worker fleet forever.

Import discipline: ``queue``/``planner``/``worker`` are under the
observability no-host-sync discipline (obs/schema.py ``--check``): no jax
import at all — a fleet control process must never initialize a backend
(that is ``run_batch``'s job, in the supervised child).
"""
from __future__ import annotations

__all__ = ["queue", "planner", "worker"]
