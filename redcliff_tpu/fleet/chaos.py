"""Fleet chaos harness: seeded fleet-level faults for the containment soak.

The PR-1/PR-4 fault grammar (runtime/faultinject.py) breaks ONE supervised
fit — kills, NaN batches, hangs, torn checkpoint writes. This module extends
the same philosophy one level up, to the fleet SERVICE: the faults a
multi-tenant sweep queue meets in production, composed into seeded schedules
so the chaos soak (tests/test_fleet_containment.py) is deterministic and
replayable. The invariant every schedule must leave intact: every submitted
request ends in exactly ONE of ``done/``, ``failed/``, ``deadletter/``,
``canceled/`` — never lost, never duplicated — and healthy requests always
complete, bit-identical to a fault-free run.

Fault classes:

- **poison request specs** (:func:`poison_point`): grid points that
  deterministically ruin the batch they are merged into. ``"nan"`` is an
  ATTRIBUTABLE poison — an absurd learning rate drives the lane non-finite
  and the grid engine's per-lane quarantine names the culprit. The
  ``__chaos__`` sentinel modes (``"sigkill"`` / ``"exit:N"`` /
  ``"hang:S"``) are BLIND poisons — the batch driver dies before any
  attribution exists, so the worker must corner the culprit by bisection.
  Sentinels are inert unless the fault grammar arms ``fleet_poison``
  (:func:`redcliff_tpu.runtime.faultinject.fleet_poison_armed`), and the
  driver strips them from points before the fit either way;
- **worker SIGKILL storms** (:class:`WorkerFleet`): real worker processes
  (own process groups, so the supervised child dies with them) killed on a
  seeded schedule and respawned — the lease-expiry/reclaim/resume path
  under sustained infrastructure failure;
- **lease-expiry races** (:func:`expire_random_lease`): a live lease's
  ``expires_at`` is forced into the past, so another worker reclaims a
  batch whose original owner may still be running — the claim token
  protocol must keep exactly one publisher;
- **torn/corrupt durable state** (:func:`tear_spool_tail`,
  :func:`corrupt_random_lease`): a submitter killed mid-append, a lease
  file half-written by a dying claimant — every reader must skip-and-count,
  never crash, never lose a healthy request.

stdlib only, no jax (obs/schema.py ``--check`` enforces it): chaos drives
CONTROL processes; only the supervised batch driver it torments initializes
a backend.
"""
from __future__ import annotations

import json
import os
import random
import signal
import subprocess
import sys
import time

__all__ = ["CHAOS_KEY", "poison_point", "strip_chaos", "detonate",
           "tear_spool_tail", "corrupt_random_lease", "expire_random_lease",
           "WorkerFleet", "FLEET_FAULT_KINDS", "random_fleet_fault_schedule",
           "apply_fault", "submit_storm"]

# the sentinel key a poison request spec rides in on; the batch driver
# strips it from every point before the fit and acts on it only when the
# fault grammar arms `fleet_poison`
CHAOS_KEY = "__chaos__"

# a learning rate past sqrt(f32 max): Adam-normalized updates bound steps to
# ~lr, so the poisoned lane's squared forecast error overflows to inf within
# an epoch and the numerics guard quarantines it (same constant the PR-1
# bad-point harness uses — the attributable poison)
_NAN_LR = 1e20


def poison_point(mode, base=None):
    """One poison grid point. ``mode``:

    - ``"nan"`` — attributable: quarantined in-engine, named in
      ``failures.json``;
    - ``"sigkill"`` — blind: the batch driver SIGKILLs itself pre-fit;
    - ``"exit:N"`` — blind: the driver exits with code N (e.g. ``exit:19``
      simulates a watchdog-hard-exited hang without the wait);
    - ``"hang:S"`` — blind: the driver sleeps S seconds, then exits 19
      (a hang long enough to look wedged, short enough to soak-test).
    """
    if mode == "nan":
        return {"gen_lr": _NAN_LR, "embed_lr": _NAN_LR}
    return dict(base or {"gen_lr": 1e-3}, **{CHAOS_KEY: str(mode)})


def strip_chaos(point, sink=None):
    """A copy of ``point`` without the chaos sentinel; when the point
    carried one, its spec is appended to ``sink``. The batch driver runs
    every point through this so an UNARMED replay of a chaos spool fits the
    underlying healthy point instead of crash-looping."""
    if CHAOS_KEY not in point:
        return dict(point)
    out = {k: v for k, v in point.items() if k != CHAOS_KEY}
    if sink is not None:
        sink.append(str(point[CHAOS_KEY]))
    return out


def detonate(spec):
    """Die the way a poison sentinel says (called by the batch driver,
    pre-fit, only when ``fleet_poison`` is armed)."""
    if spec == "sigkill":
        os.kill(os.getpid(), signal.SIGKILL)
    name, _, arg = spec.partition(":")
    if name == "exit":
        raise SystemExit(int(arg or 1))
    if name == "hang":
        time.sleep(float(arg or 1.0))
        raise SystemExit(19)  # watchdog EXIT_HANG: a wedged child hard-exit
    raise SystemExit(f"unknown fleet poison spec {spec!r}")


# ---------------------------------------------------------------------------
# durable-state faults
# ---------------------------------------------------------------------------
def tear_spool_tail(root, garbage=b'{"request_id": "req-chaos-torn", "ten'):
    """Append a torn (newline-less, truncated-JSON) tail to the spool — a
    submitter SIGKILLed mid-append. Readers must skip-and-count it; the next
    real submit must heal the line boundary."""
    path = os.path.join(str(root), "requests.jsonl")
    fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        os.write(fd, garbage)
        os.fsync(fd)
    finally:
        os.close(fd)


def _lease_files(root):
    d = os.path.join(str(root), "leases")
    try:
        return sorted(n for n in os.listdir(d)
                      if n.endswith(".json") and ".tmp." not in n
                      and ".expired." not in n)
    except OSError:
        return []


def corrupt_random_lease(root, rng):
    """Overwrite one lease file with garbage bytes (a claimant that died
    mid-create / media corruption). The claim protocol treats a torn lease
    as expired, so the request is reclaimable — never wedged, never lost.
    Returns the corrupted file name, or None when no lease exists."""
    names = _lease_files(root)
    if not names:
        return None
    name = names[rng.randrange(len(names))]
    with open(os.path.join(str(root), "leases", name), "wb") as f:
        f.write(b"\x00{torn-lease-garbage")
    return name


def expire_random_lease(root, rng, now=None):
    """Force one live lease's ``expires_at`` into the past — the
    lease-expiry RACE: a reclaimer takes the batch while the recorded owner
    may still be running; the owner's next renew must see LeaseLost and
    stand down. Returns the expired request id, or None."""
    names = _lease_files(root)
    if not names:
        return None
    name = names[rng.randrange(len(names))]
    path = os.path.join(str(root), "leases", name)
    try:
        with open(path) as f:
            lease = json.load(f)
    except (OSError, ValueError):
        return None
    lease["expires_at"] = 0.0
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(lease, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return lease.get("request_id")


# ---------------------------------------------------------------------------
# load faults
# ---------------------------------------------------------------------------
def submit_storm(root, n_requests, tenant="storm", seed=0, spec=None,
                 points_per_request=1, epochs=None, priority=0,
                 deadline_s=None, distinct=True, now=None):
    """A seeded burst of N requests against a fleet root — the LOAD fault:
    more work than the current pool can drain inside its SLO. Used by the
    autoscale acceptance soak and bench probe (ISSUE 16): at fixed worker
    count the storm breaches queue-wait p99; with the autoscaler +
    backpressure armed it must settle with SLOs restored and zero
    dead-letters.

    ``spec`` is the per-request fit spec (defaults to the CLI's tiny
    synthetic spec). ``distinct=True`` (the default) varies each request's
    data seed deterministically in ``seed`` — CRITICAL for a storm: N
    byte-identical specs share one ``planner.batch_key`` and merge into a
    single batch, which is a merge benchmark, not a load storm.

    Submissions ride the normal admission gate: a
    :class:`~redcliff_tpu.fleet.queue.BackpressureReject` is counted, not
    raised. Returns ``{"submitted": [rids...], "rejected": [
    {"eta_s", "threshold_s"}...], "tenant", "seed"}``."""
    from redcliff_tpu.fleet.queue import BackpressureReject, FleetQueue

    rng = random.Random(seed)
    q = FleetQueue(str(root))
    if spec is None:
        from redcliff_tpu.fleet.__main__ import TINY_SPEC

        spec = TINY_SPEC
    submitted, rejected = [], []
    for i in range(int(n_requests)):
        s = json.loads(json.dumps(spec))
        if distinct:
            data = s.setdefault("data", {})
            data["seed"] = int(data.get("seed") or 0) + 1 + rng.randrange(
                1 << 20)
        points = [{"gen_lr": round(1e-3 * (1 + rng.random()), 8)}
                  for _ in range(int(points_per_request))]
        try:
            rid = q.submit(tenant, points, spec=s, epochs=epochs,
                           priority=priority, deadline_s=deadline_s,
                           now=now)
        except BackpressureReject as rej:
            rejected.append({"eta_s": rej.eta_s,
                             "threshold_s": rej.threshold_s,
                             "queue_depth": rej.queue_depth,
                             "workers": rej.workers})
            continue
        submitted.append(rid)
    return {"submitted": submitted, "rejected": rejected,
            "tenant": str(tenant), "seed": int(seed)}


# ---------------------------------------------------------------------------
# worker fleet + SIGKILL storms
# ---------------------------------------------------------------------------
class WorkerFleet:
    """N real fleet workers as subprocesses in their own process groups (a
    SIGKILL to the group takes the supervised batch child down too — the
    whole-host-death the reclaim path exists for).

    ``env`` should carry the chaos arming (``REDCLIFF_FAULT_INJECT=
    fleet_poison``) and any runtime pinning the soak's bit-identity legs
    need. Workers run ``--drain``: a worker exits on an empty queue, and
    :meth:`respawn` keeps the fleet at strength until the queue settles.
    """

    def __init__(self, root, n_workers=2, lease_s=4.0, poll_s=0.2,
                 max_attempts=2, max_restarts=0, env=None, python=None,
                 extra_args=()):
        self.root = str(root)
        self.n_workers = int(n_workers)
        self.lease_s = float(lease_s)
        self.poll_s = float(poll_s)
        self.max_attempts = int(max_attempts)
        self.max_restarts = int(max_restarts)
        self.env = dict(env) if env is not None else None
        self.python = python or sys.executable
        self.extra_args = list(extra_args)
        self.procs = []
        self.kills = 0
        self.spawned = 0

    def _cmd(self):
        return [self.python, "-m", "redcliff_tpu.fleet", "work",
                "--root", self.root, "--drain",
                "--lease-s", str(self.lease_s),
                "--poll-s", str(self.poll_s),
                "--max-attempts", str(self.max_attempts),
                "--max-restarts", str(self.max_restarts),
                "--base-delay-s", "0.05", "--max-delay-s", "0.05",
                ] + self.extra_args

    def spawn_one(self):
        proc = subprocess.Popen(self._cmd(), env=self.env,
                                start_new_session=True,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
        self.procs.append(proc)
        self.spawned += 1
        return proc

    def __enter__(self):
        for _ in range(self.n_workers):
            self.spawn_one()
        return self

    def live(self):
        return [p for p in self.procs if p.poll() is None]

    def kill_one(self, rng):
        """SIGKILL a random live worker's whole process group (worker +
        supervised child). Returns the killed pid, or None."""
        live = self.live()
        if not live:
            return None
        proc = live[rng.randrange(len(live))]
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            return None
        proc.wait()
        self.kills += 1
        return proc.pid

    def respawn(self):
        """Top the fleet back up to ``n_workers`` live processes."""
        for _ in range(self.n_workers - len(self.live())):
            self.spawn_one()

    def __exit__(self, *exc):
        for proc in self.procs:
            if proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except OSError:
                    pass
            proc.wait()


# the fleet-level chaos grammar the seeded schedule fuzzer draws from;
# every op must leave the containment invariant reachable (kills respawn,
# torn state is skip-and-count, races resolve through the claim token)
FLEET_FAULT_KINDS = ("kill_worker", "expire_lease", "corrupt_lease",
                     "tear_spool")


def random_fleet_fault_schedule(seed, n_ops=6):
    """A seeded list of fleet-fault ops for the chaos soak — applied between
    polls while the worker fleet drains. Deterministic in ``seed``; kills
    lead the distribution (the dominant production fault)."""
    r = random.Random(seed)
    weighted = ("kill_worker", "kill_worker", "expire_lease",
                "corrupt_lease", "tear_spool")
    return [weighted[r.randrange(len(weighted))] for _ in range(int(n_ops))]


def apply_fault(op, root, rng, fleet=None):
    """Apply one schedule op; returns a short description for the soak log.
    ``kill_worker`` needs ``fleet`` (it also respawns to strength)."""
    if op == "kill_worker":
        if fleet is None:
            return "kill_worker: no fleet"
        pid = fleet.kill_one(rng)
        fleet.respawn()
        return f"kill_worker: pid={pid}"
    if op == "expire_lease":
        return f"expire_lease: {expire_random_lease(root, rng)}"
    if op == "corrupt_lease":
        return f"corrupt_lease: {corrupt_random_lease(root, rng)}"
    if op == "tear_spool":
        tear_spool_tail(root)
        return "tear_spool"
    raise ValueError(f"unknown fleet fault op {op!r}")
