"""Durable multi-tenant request queue: JSONL spool + atomic claim leases.

The fleet's persistence layer. Everything is plain files under one root so
the queue survives any process death and needs no daemon, no database, and
no locks held across crashes::

    <root>/requests.jsonl        append-only submission spool (one JSON line
                                 per request; O_APPEND + fsync — a torn tail
                                 from a killed submitter is skipped+counted)
    <root>/leases/<id>.json      live claim: created O_CREAT|O_EXCL (the
                                 atomic claim), renewed by tmp+rename,
                                 carries an absolute ``expires_at``
    <root>/done/<id>.json        terminal result record (atomic tmp+rename;
                                 first writer wins — the never-run-twice
                                 half of the contract)
    <root>/failed/<id>.json      terminal failure record (same discipline)
    <root>/work/<batch_id>/      batch run directories (worker-owned:
                                 grid checkpoints, metrics, ledger, results)

**Crash safety.** A worker that dies holding a lease simply stops renewing
it; once ``expires_at`` passes, any worker may RECLAIM the request:
``os.rename`` the expired lease to a unique tombstone (exactly one racer's
rename succeeds — rename of a vanished source fails), then re-claim through
the same ``O_EXCL`` create every fresh claim uses. The lease records the
batch it was claimed under (``batch_id`` + the batch's ordered request ids),
so the reclaiming worker re-runs the SAME batch composition in the same
run directory — the grid fit resumes from its durable checkpoint
(runtime/checkpoint.py) and the final results are bit-identical to an
uninterrupted run (pinned by tests/test_fleet.py).

**Exactly-once results.** ``complete()`` writes ``done/<id>.json``
atomically and refuses to overwrite an existing record; a request with a
done (or failed) record is never pending and never claimable again. The
lease protocol guarantees single-claimant only while claimants are LIVE —
a worker that outlives its own lease (e.g. a multi-minute GC pause) could
race a reclaimer, which is why ``lease_s`` must comfortably exceed the
renewal cadence; the first ``complete()`` still wins either way.

stdlib only, no jax (obs/schema.py ``--check`` enforces it): queue scans
run in control processes that must never initialize a backend.
"""
from __future__ import annotations

import json
import os
import socket
import time
import uuid

__all__ = ["FleetQueue", "Lease", "LeaseLost", "SPOOL_NAME"]

SPOOL_NAME = "requests.jsonl"
_LEASES = "leases"
_DONE = "done"
_FAILED = "failed"
_WORK = "work"


class LeaseLost(RuntimeError):
    """The lease file no longer belongs to this claimant (it expired and
    another worker reclaimed the request)."""


def _read_json(path):
    """Parse one JSON file; None on missing/torn (a reader must never crash
    on a half-written artifact)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _write_json_atomic(path, payload, overwrite=True):
    """tmp + fsync + rename. With ``overwrite=False`` an existing file wins
    (os.link is atomic-fail-if-exists on POSIX); returns False then."""
    tmp = f"{path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
    with open(tmp, "w") as f:
        json.dump(payload, f, allow_nan=False)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    try:
        if overwrite:
            os.replace(tmp, path)
            return True
        try:
            os.link(tmp, path)
            return True
        except FileExistsError:
            return False
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass


class Lease:
    """One live claim on one request. ``renew`` extends ``expires_at``
    (tmp+rename keeps the file continuously present); ``release`` deletes
    the lease so the request becomes claimable again. Both verify the
    on-disk lease still carries this claimant's token — a reclaimed lease
    raises :class:`LeaseLost` instead of clobbering the new owner."""

    def __init__(self, queue, request_id, data):
        self._q = queue
        self.request_id = request_id
        self.data = data

    @property
    def path(self):
        return self._q._lease_path(self.request_id)

    def _check_owner(self):
        cur = _read_json(self.path)
        if cur is None or cur.get("token") != self.data["token"]:
            raise LeaseLost(
                f"lease on {self.request_id} now belongs to "
                f"{(cur or {}).get('worker')!r} (expired and reclaimed?)")

    def renew(self, lease_s, now=None):
        now = time.time() if now is None else now
        self._check_owner()
        self.data = dict(self.data, renewed_at=now,
                         expires_at=now + float(lease_s),
                         renewals=int(self.data.get("renewals") or 0) + 1)
        _write_json_atomic(self.path, self.data)

    def release(self):
        try:
            self._check_owner()
        except LeaseLost:
            return  # not ours anymore: nothing to release
        try:
            os.unlink(self.path)
        except OSError:
            pass


class FleetQueue:
    """File-backed fleet queue rooted at ``root`` (created on first use).

    ``create=False`` opens the root READ-ONLY for observers (the watch
    CLI): nothing is mkdir'd, and the scan methods tolerate missing
    subdirectories — a pure reader must never mutate the service root (or
    crash on an archived/read-only mount)."""

    def __init__(self, root, create=True):
        self.root = str(root)
        if create:
            os.makedirs(self.root, exist_ok=True)
            for d in (_LEASES, _DONE, _FAILED, _WORK):
                os.makedirs(os.path.join(self.root, d), exist_ok=True)
        self.spool_path = os.path.join(self.root, SPOOL_NAME)

    # ------------------------------------------------------------------
    # paths
    # ------------------------------------------------------------------
    def _lease_path(self, request_id):
        return os.path.join(self.root, _LEASES, f"{request_id}.json")

    def _done_path(self, request_id):
        return os.path.join(self.root, _DONE, f"{request_id}.json")

    def _failed_path(self, request_id):
        return os.path.join(self.root, _FAILED, f"{request_id}.json")

    def batch_dir(self, batch_id):
        return os.path.join(self.root, _WORK, str(batch_id))

    # ------------------------------------------------------------------
    # submit / read the spool
    # ------------------------------------------------------------------
    def submit(self, tenant, points, spec=None, shape=None, priority=0,
               deadline_s=None, epochs=None, per_lane_bytes=None,
               fixed_bytes=None, request_id=None, now=None):
        """Append one fit request to the spool; returns its ``request_id``.

        ``points``: the grid points this tenant wants fitted (list of hparam
        dicts — the unit the planner merges across same-shape requests).
        ``spec``: what to fit — ``{"model_config", "train_config", "data",
        "epochs"}`` consumed by :mod:`redcliff_tpu.fleet.run_batch`;
        requests batch together only when their non-point spec is identical.
        ``shape``: the (shape-key) dict for the cost/memory models (derived
        from ``spec["model_config"]`` when omitted). ``per_lane_bytes`` /
        ``fixed_bytes``: HBM hints for the admission planner (from
        obs/memory.py ``grid_footprint``/``per_lane_bytes``)."""
        now = time.time() if now is None else now
        spec = dict(spec or {})
        if epochs is None:
            epochs = spec.get("epochs")
        if shape is None:
            shape = _shape_from_model_config(spec.get("model_config") or {})
        rid = request_id or (
            f"req-{int(now * 1000):013d}-{os.getpid()}-"
            f"{uuid.uuid4().hex[:8]}")
        rec = {
            "request_id": rid,
            "tenant": str(tenant),
            "submitted_at": now,
            "priority": int(priority),
            "deadline_s": (float(deadline_s) if deadline_s is not None
                           else None),
            "shape": shape,
            "points": list(points),
            "epochs": (int(epochs) if epochs is not None else None),
            "per_lane_bytes": per_lane_bytes,
            "fixed_bytes": fixed_bytes,
            "spec": spec,
        }
        line = json.dumps(rec, allow_nan=False).encode("utf-8") + b"\n"
        # one O_APPEND write + fsync: concurrent submitters interleave whole
        # lines; a submitter killed mid-write leaves one torn tail line the
        # tolerant reader skips and counts. A torn tail has no newline, so
        # the NEXT submitter starts with one — otherwise its record would
        # fuse into the garbage and be lost too (two healers racing just
        # produce a blank line, which the reader skips)
        fd = os.open(self.spool_path,
                     os.O_RDWR | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            size = os.fstat(fd).st_size
            if size and os.pread(fd, 1, size - 1) != b"\n":
                line = b"\n" + line
            os.write(fd, line)
            os.fsync(fd)
        finally:
            os.close(fd)
        return rid

    def requests(self, stats=None):
        """Every spooled request in submission order (first record wins on a
        duplicated id). ``stats`` (optional dict out-param) gets
        ``{"records", "torn_lines"}``."""
        out, seen = [], set()
        torn = 0
        try:
            with open(self.spool_path, "rb") as f:
                raw = f.read()
        except OSError:
            raw = b""
        for line in raw.splitlines():
            if not line.strip():
                continue
            try:
                rec = json.loads(line.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                torn += 1
                continue
            rid = rec.get("request_id")
            if not rid or rid in seen:
                continue
            seen.add(rid)
            out.append(rec)
        if stats is not None:
            stats["records"] = len(out)
            stats["torn_lines"] = torn
        return out

    # ------------------------------------------------------------------
    # claim protocol
    # ------------------------------------------------------------------
    def lease_of(self, request_id):
        """The current lease record (live or expired), or None."""
        return _read_json(self._lease_path(request_id))

    def is_terminal(self, request_id):
        return (os.path.exists(self._done_path(request_id))
                or os.path.exists(self._failed_path(request_id)))

    def claim(self, request_id, worker, lease_s, batch_id=None,
              batch_request_ids=None, tenant=None, now=None):
        """Atomically claim ``request_id``; returns a :class:`Lease` or
        None (already done/failed, or live-leased by someone else, or lost
        the reclaim race).

        ``batch_id``/``batch_request_ids`` record the batch this claim
        belongs to, so a worker reclaiming an expired lease re-runs the
        SAME batch composition (and therefore resumes the same grid
        checkpoint) instead of re-planning a different one."""
        now = time.time() if now is None else now
        if self.is_terminal(request_id):
            return None
        path = self._lease_path(request_id)
        data = {
            "request_id": request_id,
            "worker": str(worker),
            "token": uuid.uuid4().hex,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "tenant": tenant,
            "claimed_at": now,
            "expires_at": now + float(lease_s),
            "renewals": 0,
            "batch_id": batch_id,
            "batch_request_ids": (list(batch_request_ids)
                                  if batch_request_ids else None),
            "reclaimed_from": None,
        }
        existing = _read_json(path)
        if existing is None and os.path.exists(path):
            # torn lease (claimant died mid-create): treat as expired
            existing = {"expires_at": 0.0}
        if existing is not None:
            if float(existing.get("expires_at") or 0.0) > now:
                return None  # live claim
            # expired: exactly one racer wins the tombstone rename
            tomb = (f"{path}.expired.{os.getpid()}."
                    f"{uuid.uuid4().hex[:8]}")
            try:
                os.rename(path, tomb)
            except OSError:
                return None  # someone else reclaimed first
            data["reclaimed_from"] = {
                "worker": existing.get("worker"),
                "expires_at": existing.get("expires_at"),
                "batch_id": existing.get("batch_id"),
            }
            # a reclaim inherits the dead worker's batch composition unless
            # the caller pinned its own
            if batch_id is None:
                data["batch_id"] = existing.get("batch_id")
                data["batch_request_ids"] = existing.get("batch_request_ids")
        if not _write_json_atomic(path, data, overwrite=False):
            return None  # another claimant slipped in after the tombstone
        return Lease(self, request_id, data)

    # ------------------------------------------------------------------
    # terminal records
    # ------------------------------------------------------------------
    def complete(self, request_id, result=None, now=None):
        """Record the request as done (atomic; FIRST writer wins — the
        never-run-twice half of the durability contract) and drop any lease
        file. Returns True when this call wrote the record."""
        now = time.time() if now is None else now
        rec = {"request_id": request_id, "completed_at": now,
               "result": result}
        wrote = _write_json_atomic(self._done_path(request_id), rec,
                                   overwrite=False)
        try:
            os.unlink(self._lease_path(request_id))
        except OSError:
            pass
        return wrote

    def fail(self, request_id, reason, now=None):
        """Record a terminal failure (deterministic classifications the
        supervisor will not restart: numerics_abort, deadline, giving_up)."""
        now = time.time() if now is None else now
        rec = {"request_id": request_id, "failed_at": now,
               "reason": str(reason)}
        wrote = _write_json_atomic(self._failed_path(request_id), rec,
                                   overwrite=False)
        try:
            os.unlink(self._lease_path(request_id))
        except OSError:
            pass
        return wrote

    def result(self, request_id):
        """The done record, or None."""
        return _read_json(self._done_path(request_id))

    # ------------------------------------------------------------------
    # queue views
    # ------------------------------------------------------------------
    def pending(self, now=None, include_leased=False):
        """Requests with no terminal record (and, by default, no LIVE
        lease), in submission order — the planner's input."""
        now = time.time() if now is None else now
        out = []
        for rec in self.requests():
            rid = rec["request_id"]
            if self.is_terminal(rid):
                continue
            if not include_leased:
                lease = self.lease_of(rid)
                if lease is not None \
                        and float(lease.get("expires_at") or 0.0) > now:
                    continue
            out.append(rec)
        return out

    def live_leases(self, now=None):
        """Current LIVE claims (unexpired, non-terminal) — the watch CLI's
        per-tenant in-flight view. Sorted by request id."""
        now = time.time() if now is None else now
        out = []
        for lease in self._scan_leases():
            rid = lease.get("request_id")
            if not rid or self.is_terminal(rid):
                continue
            if float(lease.get("expires_at") or 0.0) > now:
                out.append(lease)
        return out

    def _scan_leases(self):
        lease_dir = os.path.join(self.root, _LEASES)
        try:
            names = sorted(os.listdir(lease_dir))
        except OSError:
            return  # read-only observer of a root with no leases dir yet
        for name in names:
            if not name.endswith(".json") or ".tmp." in name \
                    or ".expired." in name:
                continue
            lease = _read_json(os.path.join(lease_dir, name))
            if lease is not None:
                yield lease

    def expired_claims(self, now=None):
        """Expired (unrenewed) leases of non-terminal requests, grouped by
        recorded batch id: ``{batch_id_or_None: [lease_record, ...]}`` — the
        reclaim-first work a scanning worker prefers over fresh planning."""
        now = time.time() if now is None else now
        groups = {}
        for lease in self._scan_leases():
            rid = lease.get("request_id")
            if not rid or self.is_terminal(rid):
                continue
            if float(lease.get("expires_at") or 0.0) > now:
                continue
            groups.setdefault(lease.get("batch_id"), []).append(lease)
        return groups

    def status(self, now=None):
        """Queue-wide counts: total/queued/running/done/failed plus the
        per-tenant breakdown — the ``fleet status`` CLI body and the watch
        CLI's fleet section."""
        now = time.time() if now is None else now
        stats = {}
        reqs = self.requests(stats=stats)
        by_tenant = {}
        counts = {"submitted": len(reqs), "queued": 0, "running": 0,
                  "done": 0, "failed": 0, "expired_claims": 0}

        def tbucket(tenant):
            return by_tenant.setdefault(str(tenant), {
                "submitted": 0, "queued": 0, "running": 0, "done": 0,
                "failed": 0})

        for rec in reqs:
            rid = rec["request_id"]
            t = tbucket(rec.get("tenant"))
            t["submitted"] += 1
            if os.path.exists(self._done_path(rid)):
                counts["done"] += 1
                t["done"] += 1
                continue
            if os.path.exists(self._failed_path(rid)):
                counts["failed"] += 1
                t["failed"] += 1
                continue
            lease = self.lease_of(rid)
            if lease is not None \
                    and float(lease.get("expires_at") or 0.0) > now:
                counts["running"] += 1
                t["running"] += 1
            else:
                if lease is not None:
                    counts["expired_claims"] += 1
                counts["queued"] += 1
                t["queued"] += 1
        return {"root": os.path.abspath(self.root), "counts": counts,
                "by_tenant": by_tenant,
                "torn_spool_lines": stats.get("torn_lines", 0)}


# shape-key fields mirrored from obs/schema.py SHAPE_KEYS; kept as a literal
# so this module stays importable with zero package dependencies (the
# supervisor-style control processes must stay jax-free)
_SHAPE_KEYS = ("num_chans", "gen_lag", "embed_lag", "max_lag", "num_factors",
               "num_supervised_factors", "gen_hidden", "embed_hidden_sizes",
               "input_length", "num_sims")


def _shape_from_model_config(model_config):
    return {k: model_config[k] for k in _SHAPE_KEYS
            if model_config.get(k) is not None}
