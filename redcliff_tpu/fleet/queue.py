"""Durable multi-tenant request queue: JSONL spool + atomic claim leases.

The fleet's persistence layer. Everything is plain files under one root so
the queue survives any process death and needs no daemon, no database, and
no locks held across crashes::

    <root>/requests.jsonl        append-only submission spool (one JSON line
                                 per request; O_APPEND + fsync — a torn tail
                                 from a killed submitter is skipped+counted)
    <root>/leases/<id>.json      live claim: created O_CREAT|O_EXCL (the
                                 atomic claim), renewed by tmp+rename,
                                 carries an absolute ``expires_at``
    <root>/done/<id>.json        terminal result record (atomic tmp+rename;
                                 first writer wins — the never-run-twice
                                 half of the contract)
    <root>/failed/<id>.json      terminal failure record (same discipline)
    <root>/deadletter/<id>.json  terminal containment record: the request
                                 exhausted its retry budget or was attributed
                                 as the poison member of a merged batch; the
                                 record carries a failure DOSSIER (attempts,
                                 classifications, run dirs, flight-record
                                 paths) so an operator can judge it without
                                 spelunking run dirs. ``requeue`` resurrects
                                 it with a fresh budget (dossier archived)
    <root>/canceled/<id>.json    terminal cancellation record (first writer
                                 wins; a canceled leased request is never
                                 re-planned and never orphans its lease)
    <root>/attempts/<id>.json    durable per-request attempt ledger: failure
                                 attempt count + reclaim count + a bounded
                                 classification history — the retry-budget
                                 state every release/reclaim updates
    <root>/pinned/<batch_id>.json  pinned batch composition (ordered request
                                 ids): work a bisecting worker requeued as
                                 exact halves — claimed AS THAT COMPOSITION,
                                 bypassing the admission planner
    <root>/work/<batch_id>/      batch run directories (worker-owned:
                                 grid checkpoints, metrics, ledger, results)

**Crash safety.** A worker that dies holding a lease simply stops renewing
it; once ``expires_at`` passes, any worker may RECLAIM the request:
``os.rename`` the expired lease to a unique tombstone (exactly one racer's
rename succeeds — rename of a vanished source fails), then re-claim through
the same ``O_EXCL`` create every fresh claim uses. The lease records the
batch it was claimed under (``batch_id`` + the batch's ordered request ids),
so the reclaiming worker re-runs the SAME batch composition in the same
run directory — the grid fit resumes from its durable checkpoint
(runtime/checkpoint.py) and the final results are bit-identical to an
uninterrupted run (pinned by tests/test_fleet.py).

**Exactly-once results.** ``complete()`` writes ``done/<id>.json``
atomically and refuses to overwrite an existing record; a request with a
done (or failed) record is never pending and never claimable again. The
lease protocol guarantees single-claimant only while claimants are LIVE —
a worker that outlives its own lease (e.g. a multi-minute GC pause) could
race a reclaimer, which is why ``lease_s`` must comfortably exceed the
renewal cadence; the first ``complete()`` still wins either way.

**Trace identity & lifecycle ledger (ISSUE 12).** ``submit`` mints a
durable ``trace_id`` on every spool record — the one identity a request
keeps across the submit CLI, the planner, every worker that claims it, and
every supervised run_batch child that fits it. Each lifecycle transition
the queue itself performs (submitted / claimed / settled / requeued) is
additionally appended to the ``<root>/history.jsonl`` ledger
(fleet/history.py) — best-effort, multi-process-safe — which is what the
SLO layer (obs/slo.py) and the fleet trace export (``obs trace --fleet``)
join after the workers are gone.

stdlib only, no jax (obs/schema.py ``--check`` enforces it): queue scans
run in control processes that must never initialize a backend.
"""
from __future__ import annotations

import json
import os
import socket
import time
import uuid

from redcliff_tpu.fleet import history as _history
# shared admission taxonomy (ISSUE 17): BackpressureReject moved to
# runtime/admission.py so the serve plane raises the same family; this
# re-export keeps every existing `from fleet.queue import BackpressureReject`
# call site and except-clause working unchanged
from redcliff_tpu.runtime.admission import BackpressureReject

__all__ = ["FleetQueue", "Lease", "LeaseLost", "BackpressureReject",
           "SPOOL_NAME", "TERMINAL_STATES"]

SPOOL_NAME = "requests.jsonl"
_LEASES = "leases"
_DONE = "done"
_FAILED = "failed"
_DEADLETTER = "deadletter"
_CANCELED = "canceled"
_ATTEMPTS = "attempts"
_PINNED = "pinned"
_WORK = "work"

# every request ends in EXACTLY one of these (the containment invariant
# tests/test_fleet_containment.py pins under the chaos soak)
TERMINAL_STATES = ("done", "failed", "deadletter", "canceled")

# bounded attempt history: enough to read a crash-loop's shape from the
# dossier without letting a pathological requeue loop grow the file forever
_MAX_HISTORY = 20


class LeaseLost(RuntimeError):
    """The lease file no longer belongs to this claimant (it expired and
    another worker reclaimed the request)."""


def _read_json(path):
    """Parse one JSON file; None on missing/torn (a reader must never crash
    on a half-written artifact)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _write_json_atomic(path, payload, overwrite=True):
    """tmp + fsync + rename. With ``overwrite=False`` an existing file wins
    (os.link is atomic-fail-if-exists on POSIX); returns False then."""
    tmp = f"{path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
    with open(tmp, "w") as f:
        json.dump(payload, f, allow_nan=False)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    try:
        if overwrite:
            os.replace(tmp, path)
            return True
        try:
            os.link(tmp, path)
            return True
        except FileExistsError:
            return False
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass


class Lease:
    """One live claim on one request. ``renew`` extends ``expires_at``
    (tmp+rename keeps the file continuously present); ``release`` deletes
    the lease so the request becomes claimable again. Both verify the
    on-disk lease still carries this claimant's token — a reclaimed lease
    raises :class:`LeaseLost` instead of clobbering the new owner."""

    def __init__(self, queue, request_id, data):
        self._q = queue
        self.request_id = request_id
        self.data = data

    @property
    def path(self):
        return self._q._lease_path(self.request_id)

    def _check_owner(self):
        cur = _read_json(self.path)
        if cur is None or cur.get("token") != self.data["token"]:
            raise LeaseLost(
                f"lease on {self.request_id} now belongs to "
                f"{(cur or {}).get('worker')!r} (expired and reclaimed?)")

    def renew(self, lease_s, now=None):
        now = time.time() if now is None else now
        self._check_owner()
        self.data = dict(self.data, renewed_at=now,
                         expires_at=now + float(lease_s),
                         renewals=int(self.data.get("renewals") or 0) + 1)
        _write_json_atomic(self.path, self.data)

    def release(self, now=None):
        try:
            self._check_owner()
        except LeaseLost:
            return  # not ours anymore: nothing to release
        try:
            os.unlink(self.path)
        except OSError:
            return  # lease file stuck: the claim is still visibly live
        # the request is back in the queue: without this transition the
        # SLO layer would end its queue wait at the aborted claim and the
        # trace export's in-flight counter would stay high through exactly
        # the crash-loop incidents the timeline exists to diagnose
        _history.append_event(
            self._q.root, "released", request_id=self.request_id,
            trace_id=self.data.get("trace_id"),
            batch_id=self.data.get("batch_id"),
            tenant=self.data.get("tenant"),
            worker=self.data.get("worker"), now=now)


class FleetQueue:
    """File-backed fleet queue rooted at ``root`` (created on first use).

    ``create=False`` opens the root READ-ONLY for observers (the watch
    CLI): nothing is mkdir'd, and the scan methods tolerate missing
    subdirectories — a pure reader must never mutate the service root (or
    crash on an archived/read-only mount)."""

    def __init__(self, root, create=True):
        self.root = str(root)
        if create:
            os.makedirs(self.root, exist_ok=True)
            for d in (_LEASES, _DONE, _FAILED, _DEADLETTER, _CANCELED,
                      _ATTEMPTS, _PINNED, _WORK):
                os.makedirs(os.path.join(self.root, d), exist_ok=True)
        self.spool_path = os.path.join(self.root, SPOOL_NAME)

    # ------------------------------------------------------------------
    # paths
    # ------------------------------------------------------------------
    def _lease_path(self, request_id):
        return os.path.join(self.root, _LEASES, f"{request_id}.json")

    def _done_path(self, request_id):
        return os.path.join(self.root, _DONE, f"{request_id}.json")

    def _failed_path(self, request_id):
        return os.path.join(self.root, _FAILED, f"{request_id}.json")

    def _deadletter_path(self, request_id):
        return os.path.join(self.root, _DEADLETTER, f"{request_id}.json")

    def _canceled_path(self, request_id):
        return os.path.join(self.root, _CANCELED, f"{request_id}.json")

    def _attempts_path(self, request_id):
        return os.path.join(self.root, _ATTEMPTS, f"{request_id}.json")

    def _pin_path(self, batch_id):
        return os.path.join(self.root, _PINNED, f"{batch_id}.json")

    def batch_dir(self, batch_id):
        return os.path.join(self.root, _WORK, str(batch_id))

    # ------------------------------------------------------------------
    # submit / read the spool
    # ------------------------------------------------------------------
    def _backpressure_gate(self, tenant, now):
        """Raise :class:`BackpressureReject` when the predicted queue wait
        for a request submitted now would breach the armed queue-wait SLO.
        Inert unless ``REDCLIFF_SLO_QUEUE_P99_S`` is set (and not opted
        out via ``REDCLIFF_BACKPRESSURE=0``) — prediction costs a planner
        pass, so the gate only runs when a tenant actually bought an SLO."""
        from redcliff_tpu.fleet import autoscale as _autoscale
        from redcliff_tpu.obs import slo as _slo

        if not _autoscale.backpressure_enabled():
            return
        threshold = _slo.thresholds_from_env().get("queue_p99_s")
        if threshold is None:
            return
        pred = _autoscale.predict_queue_wait_s(self.root, q=self, now=now)
        if pred["eta_s"] <= threshold:
            return
        from redcliff_tpu.obs.logging import MetricLogger

        with MetricLogger(self.root) as log:
            log.log("backpressure", kind="reject", tenant=str(tenant),
                    eta_s=pred["eta_s"], threshold_s=float(threshold),
                    queue_depth=pred["queue_depth"],
                    workers=pred["workers"], reason="predicted queue wait")
        raise BackpressureReject(tenant, pred["eta_s"], threshold,
                                 pred["queue_depth"], pred["workers"])

    def submit(self, tenant, points, spec=None, shape=None, priority=0,
               deadline_s=None, epochs=None, per_lane_bytes=None,
               fixed_bytes=None, request_id=None, now=None):
        """Append one fit request to the spool; returns its ``request_id``.

        ``points``: the grid points this tenant wants fitted (list of hparam
        dicts — the unit the planner merges across same-shape requests).
        ``spec``: what to fit — ``{"model_config", "train_config", "data",
        "epochs"}`` consumed by :mod:`redcliff_tpu.fleet.run_batch`;
        requests batch together only when their non-point spec is identical.
        ``shape``: the (shape-key) dict for the cost/memory models (derived
        from ``spec["model_config"]`` when omitted). ``per_lane_bytes`` /
        ``fixed_bytes``: HBM hints for the admission planner (from
        obs/memory.py ``grid_footprint``/``per_lane_bytes``).

        Mints the request's durable ``trace_id`` — the identity every
        lifecycle event, span, and metrics record downstream joins on —
        and appends the ``submitted`` lifecycle transition to the history
        ledger.

        **Admission backpressure (ISSUE 16).** When the tenant queue-wait
        SLO is armed (``REDCLIFF_SLO_QUEUE_P99_S`` set) and
        ``REDCLIFF_BACKPRESSURE`` is not ``0``, submission first consults
        the autoscaler's queue-wait prediction
        (fleet/autoscale.py:predict_queue_wait_s — cost-model-priced drain
        estimate over the live worker count) and raises
        :class:`BackpressureReject` — structured, with the predicted ETA —
        instead of spooling work that is predicted to breach. With no SLO
        armed the gate is inert and submit behaves exactly as before."""
        now = time.time() if now is None else now
        self._backpressure_gate(tenant, now)
        spec = dict(spec or {})
        if epochs is None:
            epochs = spec.get("epochs")
        if shape is None:
            shape = _shape_from_model_config(spec.get("model_config") or {})
        rid = request_id or (
            f"req-{int(now * 1000):013d}-{os.getpid()}-"
            f"{uuid.uuid4().hex[:8]}")
        trace_id = f"tr-{uuid.uuid4().hex[:16]}"
        rec = {
            "request_id": rid,
            "trace_id": trace_id,
            "tenant": str(tenant),
            "submitted_at": now,
            "priority": int(priority),
            "deadline_s": (float(deadline_s) if deadline_s is not None
                           else None),
            "shape": shape,
            "points": list(points),
            "epochs": (int(epochs) if epochs is not None else None),
            "per_lane_bytes": per_lane_bytes,
            "fixed_bytes": fixed_bytes,
            "spec": spec,
        }
        # one guarded O_APPEND write + fsync (fleet/history.py append_line,
        # the shared torn-tail-healing invariant): concurrent submitters
        # interleave whole lines; a submitter killed mid-write leaves one
        # torn tail line the tolerant reader skips and counts. Raises on
        # failure — the spool IS the durability contract
        _history.append_line(
            self.spool_path,
            json.dumps(rec, allow_nan=False).encode("utf-8") + b"\n")
        _history.append_event(self.root, "submitted", request_id=rid,
                              trace_id=trace_id, tenant=tenant, now=now,
                              priority=int(priority),
                              deadline_s=rec["deadline_s"],
                              n_points=len(rec["points"]),
                              submitted_at=now)
        return rid

    def requests(self, stats=None):
        """Every spooled request in submission order (first record wins on a
        duplicated id). ``stats`` (optional dict out-param) gets
        ``{"records", "torn_lines"}``."""
        out, seen = [], set()
        torn = 0
        try:
            with open(self.spool_path, "rb") as f:
                raw = f.read()
        except OSError:
            raw = b""
        for line in raw.splitlines():
            if not line.strip():
                continue
            try:
                rec = json.loads(line.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                torn += 1
                continue
            rid = rec.get("request_id")
            if not rid or rid in seen:
                continue
            seen.add(rid)
            out.append(rec)
        if stats is not None:
            stats["records"] = len(out)
            stats["torn_lines"] = torn
        return out

    # ------------------------------------------------------------------
    # claim protocol
    # ------------------------------------------------------------------
    def lease_of(self, request_id):
        """The current lease record (live or expired), or None."""
        return _read_json(self._lease_path(request_id))

    def terminal_state(self, request_id):
        """Which terminal record exists — one of :data:`TERMINAL_STATES` —
        or None while the request is still live. Checked in a fixed order so
        racing writers (e.g. cancel vs complete) always read ONE winner."""
        for state, path_of in (("done", self._done_path),
                               ("failed", self._failed_path),
                               ("deadletter", self._deadletter_path),
                               ("canceled", self._canceled_path)):
            if os.path.exists(path_of(request_id)):
                return state
        return None

    def terminal_ids(self):
        """``{state: set(request_ids)}`` in ONE listdir per state — the
        batch view the whole-queue scans (status/pending) use instead of
        4 stat calls per request (the watch CLI re-runs status every
        tick)."""
        dirs = {"done": _DONE, "failed": _FAILED,
                "deadletter": _DEADLETTER, "canceled": _CANCELED}
        out = {}
        for state in TERMINAL_STATES:
            try:
                names = os.listdir(os.path.join(self.root, dirs[state]))
            except OSError:
                names = []
            out[state] = {n[:-len(".json")] for n in names
                          if n.endswith(".json")}
        return out

    def is_terminal(self, request_id):
        return self.terminal_state(request_id) is not None

    def claim(self, request_id, worker, lease_s, batch_id=None,
              batch_request_ids=None, tenant=None, trace_id=None, now=None):
        """Atomically claim ``request_id``; returns a :class:`Lease` or
        None (already done/failed, or live-leased by someone else, or lost
        the reclaim race).

        ``batch_id``/``batch_request_ids`` record the batch this claim
        belongs to, so a worker reclaiming an expired lease re-runs the
        SAME batch composition (and therefore resumes the same grid
        checkpoint) instead of re-planning a different one. ``trace_id``
        (from the spool record) rides the ``claimed`` lifecycle event —
        the queue-wait endpoint the SLO layer measures."""
        now = time.time() if now is None else now
        if self.is_terminal(request_id):
            return None
        path = self._lease_path(request_id)
        data = {
            "request_id": request_id,
            "worker": str(worker),
            "token": uuid.uuid4().hex,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "tenant": tenant,
            "trace_id": trace_id,
            "claimed_at": now,
            "expires_at": now + float(lease_s),
            "renewals": 0,
            "batch_id": batch_id,
            "batch_request_ids": (list(batch_request_ids)
                                  if batch_request_ids else None),
            "reclaimed_from": None,
        }
        existing = _read_json(path)
        if existing is None and os.path.exists(path):
            # torn lease (claimant died mid-create): treat as expired
            existing = {"expires_at": 0.0}
        if existing is not None:
            if float(existing.get("expires_at") or 0.0) > now:
                return None  # live claim
            # expired: exactly one racer wins the tombstone rename
            tomb = (f"{path}.expired.{os.getpid()}."
                    f"{uuid.uuid4().hex[:8]}")
            try:
                os.rename(path, tomb)
            except OSError:
                return None  # someone else reclaimed first
            data["reclaimed_from"] = {
                "worker": existing.get("worker"),
                "expires_at": existing.get("expires_at"),
                "batch_id": existing.get("batch_id"),
            }
            # a reclaim inherits the dead worker's batch composition unless
            # the caller pinned its own
            if batch_id is None:
                data["batch_id"] = existing.get("batch_id")
                data["batch_request_ids"] = existing.get("batch_request_ids")
        if not _write_json_atomic(path, data, overwrite=False):
            return None  # another claimant slipped in after the tombstone
        _history.append_event(
            self.root, "claimed", request_id=request_id, trace_id=trace_id,
            batch_id=data["batch_id"], tenant=tenant, now=now,
            worker=str(worker),
            reclaim=(True if data["reclaimed_from"] is not None else None))
        return Lease(self, request_id, data)

    # ------------------------------------------------------------------
    # terminal records
    # ------------------------------------------------------------------
    def _settle(self, request_id, state, rec, trace_id=None, now=None):
        """Write one terminal record (first writer wins within a state) and
        drop any lease file so a settled request never orphans its claim.

        Cross-STATE exclusivity (a request terminal in exactly ONE of
        done/failed/deadletter/canceled) cannot ride the pre-write
        ``is_terminal`` check alone: two racers aiming at DIFFERENT states
        (cancel vs complete) can both pass it. So after a successful write
        each writer re-scans in the fixed :data:`TERMINAL_STATES` priority
        order and CONVERGES: it deletes any lower-priority record its own
        outranks, and deletes its own (returning False) when a
        higher-priority record exists. Whichever write lands last sees the
        other's record, so every interleaving ends with exactly the
        highest-priority state on disk (done > failed > deadletter >
        canceled: finished work outranks a racing cancel)."""
        paths = {"done": self._done_path, "failed": self._failed_path,
                 "deadletter": self._deadletter_path,
                 "canceled": self._canceled_path}
        path = paths[state](request_id)
        wrote = (not self.is_terminal(request_id)
                 and _write_json_atomic(path, rec, overwrite=False))
        if wrote:
            idx = TERMINAL_STATES.index(state)
            if any(os.path.exists(paths[s](request_id))
                   for s in TERMINAL_STATES[:idx]):
                # a higher-priority racer landed between our check and our
                # write: defer to it
                try:
                    os.unlink(path)
                except OSError:
                    pass
                wrote = False
            else:
                for s in TERMINAL_STATES[idx + 1:]:
                    try:
                        os.unlink(paths[s](request_id))
                    except OSError:
                        pass
        try:
            os.unlink(self._lease_path(request_id))
        except OSError:
            pass
        if wrote:
            # the terminal lifecycle transition the SLO layer keys on
            # (settled-at minus submitted-at = end-to-end latency; state
            # splits the deadline-hit / dead-letter-rate numerators). `now`
            # is the caller's clock — the SAME timestamp the terminal
            # record carries, so an injected-time settle (tests, replays)
            # stays synthetic-timing-exact in the ledger too
            _history.append_event(self.root, "settled",
                                  request_id=request_id, trace_id=trace_id,
                                  state=state, now=now,
                                  reason=rec.get("reason"))
        return wrote

    def complete(self, request_id, result=None, trace_id=None, now=None):
        """Record the request as done (atomic; FIRST writer wins — the
        never-run-twice half of the durability contract) and drop any lease
        file. Returns True when this call wrote the record."""
        now = time.time() if now is None else now
        return self._settle(request_id, "done",
                            {"request_id": request_id, "completed_at": now,
                             "result": result}, trace_id=trace_id, now=now)

    def fail(self, request_id, reason, trace_id=None, now=None):
        """Record a terminal failure (deterministic classifications the
        supervisor will not restart: numerics_abort, deadline,
        mesh_exhausted)."""
        now = time.time() if now is None else now
        return self._settle(request_id, "failed",
                            {"request_id": request_id, "failed_at": now,
                             "reason": str(reason)}, trace_id=trace_id,
                            now=now)

    def deadletter(self, request_id, dossier=None, trace_id=None, now=None):
        """Route the request to the durable dead-letter directory instead of
        re-planning it (retry budget exhausted, or attributed as the poison
        member of a merged batch). ``dossier`` is the failure dossier the
        worker assembled: attempts, classifications, run dirs, flight-record
        paths, quarantine causes."""
        now = time.time() if now is None else now
        return self._settle(request_id, "deadletter",
                            {"request_id": request_id,
                             "deadlettered_at": now,
                             "dossier": dossier}, trace_id=trace_id,
                            now=now)

    def cancel(self, request_id, reason=None, now=None):
        """Cancel a request: first-writer-wins ``canceled`` terminal record
        riding the same settle discipline as complete/fail. A canceled
        request is never claimable or re-plannable again; if a worker is
        mid-batch on it, the worker's own settle finds the terminal record
        and skips publishing (its lease is unlinked here and by the settle).
        Returns True when this call canceled it (False: already terminal)."""
        now = time.time() if now is None else now
        known = {r["request_id"]: r for r in self.requests()}
        if request_id not in known:
            return False
        return self._settle(request_id, "canceled",
                            {"request_id": request_id, "canceled_at": now,
                             "reason": (str(reason) if reason is not None
                                        else None)},
                            trace_id=known[request_id].get("trace_id"),
                            now=now)

    def requeue(self, request_id, now=None):
        """Resurrect a dead-letter request with a FRESH retry budget: the
        dead-letter record is archived beside itself (audit trail, no longer
        terminal) and the attempt ledger reset, so the request is pending
        again and plannable — but SOLO: the fresh ledger carries a
        ``suspect`` marker so the planner keeps quarantining it away from
        healthy tenants until it proves clean (a zeroed budget alone would
        let a known-poison request re-merge). Returns True when resurrected
        (False: no dead-letter record to resurrect)."""
        now = time.time() if now is None else now
        path = self._deadletter_path(request_id)
        # archive name does not end in .json, so terminal scans skip it
        archive = f"{path}.requeued.{int(now)}.{uuid.uuid4().hex[:6]}"
        try:
            os.rename(path, archive)
        except OSError:
            return False  # no dossier (or a racing requeue won)
        _write_json_atomic(self._attempts_path(request_id), {
            "request_id": request_id, "attempts": 0, "reclaims": 0,
            "last": None, "history": [], "suspect": True,
            "requeued_at": now})
        # the resurrected request keeps its submit-minted identity: look the
        # spool record back up so the `requeued` transition carries the same
        # join keys every other queue-written transition does
        spool = next((r for r in self.requests()
                      if r["request_id"] == request_id), {})
        _history.append_event(self.root, "requeued", request_id=request_id,
                              trace_id=spool.get("trace_id"),
                              tenant=spool.get("tenant"), now=now)
        return True

    def result(self, request_id):
        """The done record, or None."""
        return _read_json(self._done_path(request_id))

    def deadletter_record(self, request_id):
        """The dead-letter record (with its dossier), or None."""
        return _read_json(self._deadletter_path(request_id))

    def deadletters(self):
        """Every dead-letter record, sorted by request id — the containment
        view obs watch/report render."""
        d = os.path.join(self.root, _DEADLETTER)
        try:
            names = sorted(os.listdir(d))
        except OSError:
            return []
        out = []
        for name in names:
            if not name.endswith(".json"):
                continue  # .requeued archives, .tmp droppings
            rec = _read_json(os.path.join(d, name))
            if rec is not None:
                out.append(rec)
        return out

    # ------------------------------------------------------------------
    # per-request attempt ledger (the retry-budget state)
    # ------------------------------------------------------------------
    def attempt_record(self, request_id):
        """The durable attempt ledger for one request, or None (never
        failed/reclaimed). ``{"attempts", "reclaims", "last", "history"}``:
        ``attempts`` counts FAILURE attempts (what the retry budget bounds),
        ``reclaims`` counts lease-expiry reclaims (recorded for the dossier;
        infra faults like a worker SIGKILL storm must not eat a healthy
        tenant's budget)."""
        return _read_json(self._attempts_path(request_id))

    def record_attempt(self, request_id, classification, batch_id=None,
                       run_dir=None, kind="failure", now=None):
        """Append one attempt to the request's durable ledger and return the
        updated record. ``kind="failure"`` increments the budgeted attempt
        count; ``kind="reclaim"`` increments the reclaim count only. Last
        writer wins on a racing update (atomic tmp+rename): attempt counts
        are containment accounting, not the exactly-once surface — that is
        the terminal records'."""
        now = time.time() if now is None else now
        rec = self.attempt_record(request_id) or {
            "request_id": request_id, "attempts": 0, "reclaims": 0,
            "last": None, "history": []}
        entry = {"at": now, "kind": kind,
                 "classification": str(classification),
                 "batch_id": batch_id, "run_dir": run_dir}
        if kind == "failure":
            rec["attempts"] = int(rec.get("attempts") or 0) + 1
        else:
            rec["reclaims"] = int(rec.get("reclaims") or 0) + 1
        rec["last"] = entry
        rec["history"] = (list(rec.get("history") or [])
                          + [entry])[-_MAX_HISTORY:]
        _write_json_atomic(self._attempts_path(request_id), rec)
        return rec

    def attempt_records(self):
        """Every request's attempt ledger, sorted by request id — the
        per-request attempt-count view obs watch/report render."""
        d = os.path.join(self.root, _ATTEMPTS)
        try:
            names = sorted(os.listdir(d))
        except OSError:
            return []
        out = []
        for name in names:
            if not name.endswith(".json") or ".tmp." in name:
                continue
            rec = _read_json(os.path.join(d, name))
            if rec is not None:
                out.append(rec)
        return out

    def reset_attempts(self, request_id):
        try:
            os.unlink(self._attempts_path(request_id))
        except OSError:
            pass

    # ------------------------------------------------------------------
    # pinned batches (bisection halves: exact compositions, planner-bypass)
    # ------------------------------------------------------------------
    def pin_batch(self, batch_id, request_ids, parent_batch_id=None,
                  after_request=None, now=None):
        """Durably pin an exact batch composition for the next claiming
        worker (the bisection requeue path: halves must run AS HALVES, not
        be re-merged by the admission planner).

        ``after_request`` (deadline-aware preemption, ISSUE 15): the
        beneficiary request this composition yielded the mesh to — workers
        defer claiming the pin while that request is still pending (no
        terminal record, no live lease), so the preempted batch resumes
        only once the tenant it was preempted FOR has been served (or has
        settled some other way)."""
        now = time.time() if now is None else now
        _write_json_atomic(self._pin_path(batch_id), {
            "batch_id": batch_id, "requests": list(request_ids),
            "parent_batch_id": parent_batch_id,
            "after_request": after_request, "pinned_at": now})

    def unpin_batch(self, batch_id):
        try:
            os.unlink(self._pin_path(batch_id))
        except OSError:
            pass

    def pinned_batches(self):
        """Every pinned composition, sorted by batch id (deterministic claim
        order across workers)."""
        d = os.path.join(self.root, _PINNED)
        try:
            names = sorted(os.listdir(d))
        except OSError:
            return []
        out = []
        for name in names:
            if not name.endswith(".json") or ".tmp." in name:
                continue
            rec = _read_json(os.path.join(d, name))
            if rec is not None and rec.get("batch_id") \
                    and rec.get("requests"):
                out.append(rec)
        return out

    # ------------------------------------------------------------------
    # queue views
    # ------------------------------------------------------------------
    def pending(self, now=None, include_leased=False):
        """Requests with no terminal record (and, by default, no LIVE
        lease), in submission order — the planner's input."""
        now = time.time() if now is None else now
        out = []
        terminal = set().union(*self.terminal_ids().values())
        for rec in self.requests():
            rid = rec["request_id"]
            if rid in terminal:
                continue
            if not include_leased:
                lease = self.lease_of(rid)
                if lease is not None \
                        and float(lease.get("expires_at") or 0.0) > now:
                    continue
            out.append(rec)
        return out

    def live_leases(self, now=None):
        """Current LIVE claims (unexpired, non-terminal) — the watch CLI's
        per-tenant in-flight view. Sorted by request id."""
        now = time.time() if now is None else now
        out = []
        for lease in self._scan_leases():
            rid = lease.get("request_id")
            if not rid or self.is_terminal(rid):
                continue
            if float(lease.get("expires_at") or 0.0) > now:
                out.append(lease)
        return out

    def _scan_leases(self):
        lease_dir = os.path.join(self.root, _LEASES)
        try:
            names = sorted(os.listdir(lease_dir))
        except OSError:
            return  # read-only observer of a root with no leases dir yet
        for name in names:
            if not name.endswith(".json") or ".tmp." in name \
                    or ".expired." in name:
                continue
            lease = _read_json(os.path.join(lease_dir, name))
            if lease is not None:
                yield lease

    def expired_claims(self, now=None):
        """Expired (unrenewed) leases of non-terminal requests, grouped by
        recorded batch id: ``{batch_id_or_None: [lease_record, ...]}`` — the
        reclaim-first work a scanning worker prefers over fresh planning."""
        now = time.time() if now is None else now
        groups = {}
        for lease in self._scan_leases():
            rid = lease.get("request_id")
            if not rid:
                continue
            expired = float(lease.get("expires_at") or 0.0) <= now
            if self.is_terminal(rid):
                if expired:
                    # GC: the claimant died AFTER the request went terminal
                    # (e.g. canceled out from under a dead worker) — the
                    # stale lease would otherwise sit forever ("never
                    # orphans a lease")
                    try:
                        os.unlink(self._lease_path(rid))
                    except OSError:
                        pass
                continue
            if not expired:
                continue
            groups.setdefault(lease.get("batch_id"), []).append(lease)
        return groups

    # terminal-record timestamp field per state (the terminal-state age the
    # status CLI renders)
    _TERMINAL_AT = {"done": "completed_at", "failed": "failed_at",
                    "deadletter": "deadlettered_at",
                    "canceled": "canceled_at"}

    def status(self, now=None, include_requests=False):
        """Queue-wide counts: total/queued/running/done/failed plus the
        per-tenant breakdown — the ``fleet status`` CLI body and the watch
        CLI's fleet section.

        ``include_requests=True`` adds a per-request ``requests`` list with
        lifecycle ages: ``queue_age_s`` (now − ``submitted_at``) for live
        requests — how long each tenant has been waiting — and
        ``terminal_age_s`` (now − the terminal record's own timestamp) for
        settled ones. Off by default: it reads one terminal record per
        settled request, which a follow-mode watcher re-running status
        every tick must not pay."""
        now = time.time() if now is None else now
        stats = {}
        reqs = self.requests(stats=stats)
        terminal = self.terminal_ids()
        by_tenant = {}
        rows = []
        counts = {"submitted": len(reqs), "queued": 0, "running": 0,
                  "done": 0, "failed": 0, "deadletter": 0, "canceled": 0,
                  "expired_claims": 0}

        def tbucket(tenant):
            return by_tenant.setdefault(str(tenant), {
                "submitted": 0, "queued": 0, "running": 0, "done": 0,
                "failed": 0, "deadletter": 0, "canceled": 0})

        def row(rec, state, terminal_state=None):
            if not include_requests:
                return
            sub = rec.get("submitted_at")
            r = {"request_id": rec["request_id"],
                 "tenant": str(rec.get("tenant")),
                 "trace_id": rec.get("trace_id"),
                 "state": state,
                 "queue_age_s": None, "terminal_age_s": None}
            if terminal_state is None:
                if isinstance(sub, (int, float)):
                    r["queue_age_s"] = round(now - sub, 3)
            else:
                trec = _read_json(
                    {"done": self._done_path,
                     "failed": self._failed_path,
                     "deadletter": self._deadletter_path,
                     "canceled": self._canceled_path}[terminal_state](
                         rec["request_id"])) or {}
                at = trec.get(self._TERMINAL_AT[terminal_state])
                if isinstance(at, (int, float)):
                    r["terminal_age_s"] = round(now - at, 3)
            rows.append(r)

        for rec in reqs:
            rid = rec["request_id"]
            t = tbucket(rec.get("tenant"))
            t["submitted"] += 1
            state = next((s for s in TERMINAL_STATES
                          if rid in terminal[s]), None)
            if state is not None:
                counts[state] += 1
                t[state] += 1
                row(rec, state, terminal_state=state)
                continue
            lease = self.lease_of(rid)
            if lease is not None \
                    and float(lease.get("expires_at") or 0.0) > now:
                counts["running"] += 1
                t["running"] += 1
                row(rec, "running")
            else:
                if lease is not None:
                    counts["expired_claims"] += 1
                counts["queued"] += 1
                t["queued"] += 1
                row(rec, "queued")
        out = {"root": os.path.abspath(self.root), "counts": counts,
               "by_tenant": by_tenant,
               "torn_spool_lines": stats.get("torn_lines", 0)}
        if include_requests:
            out["requests"] = rows
        return out


# shape-key fields mirrored from obs/schema.py SHAPE_KEYS; kept as a literal
# so this module stays importable with zero package dependencies (the
# supervisor-style control processes must stay jax-free)
_SHAPE_KEYS = ("num_chans", "gen_lag", "embed_lag", "max_lag", "num_factors",
               "num_supervised_factors", "gen_hidden", "embed_hidden_sizes",
               "input_length", "num_sims")


def _shape_from_model_config(model_config):
    return {k: model_config[k] for k in _SHAPE_KEYS
            if model_config.get(k) is not None}
