"""Durable request-lifecycle ledger: ``<root>/history.jsonl``.

The fleet's SERVICE-LEVEL memory. The queue's terminal records say *where*
a request ended; the metrics chain says what each process did while it had
the request — but neither survives as one joinable per-request timeline:
the spool never learns a request was claimed, and a worker's metrics die
with its run dir's retention. This ledger records every lifecycle
TRANSITION — append-only, one strict-JSON line per event, multi-process
safe — so queue-wait percentiles, deadline hit-rates, and attempt counts
(obs/slo.py) and the fleet-wide Perfetto export (obs/trace_export.py
``--fleet``) can be computed long after the workers that produced them are
gone, across any number of worker restarts and SIGKILL storms.

Event taxonomy (``fleet_lifecycle`` in the closed obs/schema.py registry;
docs/ARCHITECTURE.md "Request lifecycle tracing & SLOs")::

    submitted   queue.submit — mints the request's durable trace_id
    planned     worker — the admission/merge decision that claimed a batch
    claimed     queue.claim — fresh claim or lease-expiry reclaim
    attempt     worker — one supervised run of a batch holding the request
                (classification + supervisor attempt count + started_at)
    released    queue Lease.release — a claim handed back without a verdict
                (budget-route, bisection, all-or-nothing claim rollback):
                the request is queued again and its queue wait continues
    bisected    worker — a blind-failed merged batch split into pinned
                halves (the halves stay linked to the members' traces)
    settled     queue._settle — the terminal transition
                (state=done|failed|deadletter|canceled)
    requeued    queue.requeue — a dead-letter resurrected (fresh budget)

Every event carries ``wall_time`` + the seq/pid/host identity triple (the
spine's ordering contract) and, where the writer knows them, the request's
``trace_id``/``batch_id``/``tenant`` — the join keys one trace identity
rides from submit to settle across the submit CLI, the worker, and the
supervised run_batch child.

Write discipline: one ``O_APPEND`` write + fsync per event with the same
torn-tail newline-healing guard as the request spool (fleet/queue.py) —
concurrent submitters/workers interleave whole lines, a writer SIGKILLed
mid-append leaves one torn line the tolerant reader skips and counts.
Writes are BEST-EFFORT (an unwritable history must never fail the queue
protocol itself); reads ride the spine's rotation-chain- and
torn-tail-aware :func:`redcliff_tpu.obs.logging.read_jsonl`.

Rotation: ``REDCLIFF_HISTORY_MAX_BYTES`` (0/unset = never rotate, the
default) caps the head file like the metrics spine —
``history.jsonl`` -> ``history.jsonl.1``, shifting backups up and
dropping the oldest past :data:`MAX_BACKUPS`. Unlike the spine's
single-writer logger this ledger has many writer PROCESSES, so exactly
one racer rotates (non-blocking flock on a ``.lock`` sidecar; losers
skip — the next append retries) and a writer mid-append keeps its fd
through the rename, so records land in the rotated segment, never lost.
Under a cap the SLO window is the retained chain: week-long fleets trade
unbounded ledger growth (and the O(ledger) re-parse every ``obs watch``
tick pays on an active root) for windowed service metrics.

stdlib only at module scope, and never jax (obs/schema.py ``--check``
enforces it): the submit CLI and worker control processes write here.
"""
from __future__ import annotations

import fcntl
import itertools
import json
import os
import time

from redcliff_tpu.obs import spans as _spans

__all__ = ["HISTORY_NAME", "LIFECYCLE_EVENT", "ENV_MAX_BYTES",
           "MAX_BACKUPS", "history_path", "append_line", "append_event",
           "read_history"]

HISTORY_NAME = "history.jsonl"
LIFECYCLE_EVENT = "fleet_lifecycle"
ENV_MAX_BYTES = "REDCLIFF_HISTORY_MAX_BYTES"
MAX_BACKUPS = 8

# process-local sequence for history records (the spine's per-process total
# order; independent of obs.logging's counter — (pid, seq) only needs to
# order ONE file's records from one process)
_seq = itertools.count(1)


def history_path(root):
    return os.path.join(str(root), HISTORY_NAME)


def append_line(path, line):
    """One guarded ``O_APPEND`` write + fsync of ``line`` (bytes, newline-
    terminated): concurrent writers interleave whole lines, and a writer
    SIGKILLed mid-append leaves one torn tail the NEXT writer heals by
    leading with a newline — its record never fuses into the garbage (two
    healers racing just produce a blank line the tolerant reader skips).
    The one copy of the crash-safety invariant this ledger and the request
    spool (fleet/queue.py submit) both ride; raises ``OSError`` — each
    caller picks its own durability contract."""
    fd = os.open(str(path), os.O_RDWR | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        size = os.fstat(fd).st_size
        if size and os.pread(fd, 1, size - 1) != b"\n":
            line = b"\n" + line
        os.write(fd, line)
        os.fsync(fd)
    finally:
        os.close(fd)


def append_event(root, kind, request_id=None, trace_id=None, batch_id=None,
                 tenant=None, now=None, **fields):
    """Append one lifecycle transition to ``<root>/history.jsonl``;
    returns the record (written or not — best-effort durability: an
    unwritable ledger is counted against observability, never against the
    queue protocol the caller is in the middle of)."""
    now = time.time() if now is None else now
    rec = {"event": LIFECYCLE_EVENT, "wall_time": now, "seq": next(_seq),
           "pid": os.getpid(), "host": _spans.HOST, "kind": str(kind)}
    for key, val in (("request_id", request_id), ("trace_id", trace_id),
                     ("batch_id", batch_id),
                     ("tenant", str(tenant) if tenant is not None else None)):
        if val is not None:
            rec[key] = val
    for key, val in fields.items():
        if val is not None:
            rec[key] = val
    try:
        path = history_path(root)
        append_line(path,
                    json.dumps(rec, allow_nan=False).encode("utf-8") + b"\n")
        _maybe_rotate(path)
    except OSError:
        pass
    return rec


def _maybe_rotate(path):
    """Rotate ``path`` past the ``REDCLIFF_HISTORY_MAX_BYTES`` cap (0/unset
    = never). Multi-process safe: exactly one racer wins a non-blocking
    flock on the ``.lock`` sidecar and shifts the chain; losers skip — the
    cap is advisory, the NEXT append retries. A concurrent appender's
    O_APPEND fd follows its inode through the rename, so its record lands
    in the rotated segment and the chain reader still sees it. Rotation is
    best-effort like the spine's: a failed rename grows the file past the
    cap but never destroys recorded transitions."""
    try:
        cap = int(os.environ.get(ENV_MAX_BYTES, "0") or 0)
    except ValueError:
        cap = 0
    if cap <= 0:
        return
    try:
        if os.path.getsize(path) <= cap:
            return
    except OSError:
        return
    try:
        lfd = os.open(f"{path}.lock", os.O_CREAT | os.O_RDWR, 0o644)
    except OSError:
        return
    try:
        try:
            fcntl.flock(lfd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            return  # another process is rotating right now
        try:
            if os.path.getsize(path) <= cap:
                return  # it already rotated while we waited on the lock
            oldest = f"{path}.{MAX_BACKUPS}"
            if os.path.exists(oldest):
                os.remove(oldest)
            for i in range(MAX_BACKUPS - 1, 0, -1):
                src = f"{path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{path}.{i + 1}")
            os.replace(path, f"{path}.1")
        except OSError:
            pass
    finally:
        os.close(lfd)


def read_history(root, stats=None):
    """Every parseable lifecycle record, oldest first (rotation-chain- and
    torn-tail-aware via the spine's reader). ``stats`` (optional dict
    out-param) gets ``{"files", "records", "torn_lines"}``. Returns ``[]``
    — never raises — on a root with no history yet (pure readers point
    this at arbitrary directories)."""
    # lazy import: obs.logging pulls numpy, which control-plane writers
    # (queue/worker) never need on the append path
    from redcliff_tpu.obs.logging import read_jsonl

    try:
        records = read_jsonl(history_path(root), stats=stats)
    except FileNotFoundError:
        if stats is not None:
            stats.update(files=[], records=0, torn_lines=0)
        return []
    return [r for r in records if r.get("event") == LIFECYCLE_EVENT]
