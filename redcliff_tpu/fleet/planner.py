"""Cost/memory-aware admission planner: pack fit requests into G-buckets.

The decision layer between the durable queue (fleet/queue.py) and the grid
engine (parallel/grid.py). Given the pending heterogeneous request mix —
shapes, priorities, deadlines, point counts — :func:`plan` produces an
ordered list of BATCHES, each one grid fit:

* **same-shape requests merge into one fit** — their points concatenate
  along the grid axis, so the mesh runs one compiled program family at a
  bucket-ladder width instead of one padded micro-fit per tenant, and the
  persistent compile cache + cost-model store amortize across tenants.
  Requests batch together only when their full non-point spec matches
  (:func:`batch_key`): same model/train config, same data — one merged
  ``GridSpec`` must mean the same math for every tenant in it;
* **widths come from the elastic scheduler's ladder**
  (parallel/compaction.py ``bucket_width`` — the same rungs
  ``footprint_by_bucket`` enumerates), so the planner's packing unit IS the
  engine's execution unit;
* **admission is memory-gated**: with per-request HBM hints
  (``per_lane_bytes``/``fixed_bytes``, from obs/memory.py
  ``grid_footprint``) and a device budget (``budget_bytes``, from
  ``check_headroom``'s ``budget_bytes``), a batch is CLOSED before its
  predicted footprint at the next bucket would exceed the budget, and a
  single request that cannot fit at any width is returned as
  ``unschedulable`` — the planner never admits a batch whose footprint
  estimate exceeds headroom (pinned by tests/test_fleet.py);
* **ordering is cost-aware**: batches sort by priority (desc), then
  earliest tenant deadline, then predicted wall-clock
  (obs/costmodel.py ``predict_fit_eta`` — shortest first; unknown-ETA
  batches after, in submission order rather than hash order so planners
  with different cost-model stores agree — ISSUE 15 satellite), then
  deterministic tie-breaks, so urgent and cheap work drains ahead of long
  sweeps. Batch views also carry ``cold_compile_ms`` (the predicted
  first-touch compile when the program family is cold, 0 when the shared
  persistent cache holds it) — the fleet worker's cold-compile claim
  ordering input (parallel/policy.py ``compile_order``).

:func:`fifo_plan` is the naive one-request-per-fit baseline bench.py's
``fleet`` probe compares against (mesh-slot utilization,
:func:`utilization`).

stdlib + numpy only, no jax (obs/schema.py ``--check`` enforces it):
planning runs in control processes that must never initialize a backend.
All predictions are consumed from persistent stores/hints, never computed
on device.
"""
from __future__ import annotations

import hashlib
import json
import os
import time

from redcliff_tpu.parallel import compaction, packing as _packing
from redcliff_tpu.runtime.admission import TenantQuotaExceeded

__all__ = ["batch_key", "batch_id_for", "plan", "fifo_plan", "utilization",
           "predicted_batch_bytes", "tenant_slot_quota",
           "DEFAULT_MAX_BUCKET", "ENV_TENANT_SLOTS"]

# widest bucket a single batch may occupy without an explicit override: a
# merged sweep past this rides multiple batches (bounded checkpoint size,
# bounded blast radius of one bad batch)
DEFAULT_MAX_BUCKET = 256

# per-tenant fair-share quota (ISSUE 18 satellite): max sub-mesh slots one
# tenant may hold in flight at once. "2" = every tenant, "a=1,b=4" =
# per-tenant overrides, "2,a=1" = default plus override. Unset = unlimited.
ENV_TENANT_SLOTS = "REDCLIFF_FLEET_TENANT_SLOTS"


def tenant_slot_quota(env=None):
    """Parse the ``REDCLIFF_FLEET_TENANT_SLOTS`` fair-share spec into
    ``{tenant_or_"*": max_inflight_slots}`` (None when unset/invalid —
    quotas are an operator knob, never a crash)."""
    raw = (os.environ.get(ENV_TENANT_SLOTS, "") if env is None else env)
    raw = str(raw).strip()
    if not raw:
        return None
    quota = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            tenant, _, n = part.partition("=")
            tenant = tenant.strip()
        else:
            tenant, n = "*", part
        try:
            n = int(n)
        except ValueError:
            return None
        if n < 1 or not tenant:
            return None
        quota[tenant] = n
    return quota or None


def batch_key(request):
    """The mergeability key: requests batch into one grid fit only when
    everything except their points/tenant/priority/deadline is identical
    (same model config, train config, data spec, and horizon). Returns
    ``(shape_json, spec_hash)`` — both deterministic strings."""
    shape = request.get("shape") or {}
    spec = dict(request.get("spec") or {})
    spec.pop("points", None)
    blob = json.dumps({"spec": spec, "epochs": request.get("epochs")},
                      sort_keys=True)
    return (json.dumps(shape, sort_keys=True),
            hashlib.sha1(blob.encode("utf-8")).hexdigest()[:12])


def batch_id_for(request_ids):
    """Deterministic batch id from the ORDERED member request ids — the
    same composition always lands in the same ``work/<batch_id>`` run dir,
    which is what lets a reclaiming worker resume the dead worker's grid
    checkpoint instead of starting a different fit."""
    h = hashlib.sha1("\n".join(request_ids).encode("utf-8")).hexdigest()
    return f"batch-{h[:12]}"


def predicted_batch_bytes(requests, g_bucket):
    """Predicted HBM footprint of a merged batch at execution width
    ``g_bucket``: ``per_lane_bytes * g_bucket + max(fixed_bytes)`` from the
    members' hints (the obs/memory.py ``grid_footprint`` decomposition —
    fixed covers the device-resident dataset + epoch gather, shared across
    lanes). None when no member carries a per-lane hint (no memory
    evidence: admission degrades to ungated, mirroring
    ``check_headroom``'s explicit None on backends without memory stats)."""
    per_lane = [r.get("per_lane_bytes") for r in requests
                if isinstance(r.get("per_lane_bytes"), (int, float))]
    if not per_lane:
        return None
    fixed = max((r.get("fixed_bytes") or 0) for r in requests)
    return int(max(per_lane) * int(g_bucket) + fixed)


def _order_key(request):
    """Deterministic urgency ordering: priority desc, earliest deadline,
    submission order, id."""
    dl = request.get("deadline_s")
    return (-int(request.get("priority") or 0),
            float(dl) if dl is not None else float("inf"),
            float(request.get("submitted_at") or 0.0),
            str(request.get("request_id")))


def _batch_view(members, n_devices, cost_model=None, platform=None,
                suspect=False):
    n_points = sum(len(r.get("points") or ()) for r in members)
    width = compaction.bucket_width(n_points, n_devices)
    ids = [r["request_id"] for r in members]
    shape = members[0].get("shape") or {}
    epochs = max((r.get("epochs") or 0) for r in members)
    # precision half of the cost bucket: a mixed-precision batch must be
    # priced from mixed-epoch evidence, not f32's (the merge key guarantees
    # every member shares one train_config). utils.precision is jax-free at
    # module scope — the planner's no-jax control-plane discipline holds.
    # Defensive: pricing is ADVISORY, so a malformed tenant-supplied spec
    # (non-dict train_config) degrades to the default label instead of
    # crashing the whole worker's plan cycle
    try:
        from redcliff_tpu.utils.precision import precision_label

        tcd = (members[0].get("spec") or {}).get("train_config") or {}
        precision = precision_label(tcd.get("precision_mode") or "f32",
                                    tcd.get("matmul_precision"))
    except Exception:  # noqa: BLE001 — tenant input, advisory output
        precision = "f32"
    eta_s = cold_compile_ms = None
    if cost_model is not None:
        try:
            from redcliff_tpu.obs.schema import shape_key as _sk

            sk = _sk(shape)
            eta_s = cost_model.predict_fit_eta(
                sk, width, epochs, platform=platform,
                cold_programs=1, precision=precision)
            # cold-compile ordering input (ISSUE 15): the predicted cost of
            # this batch's FIRST-TOUCH compile — 0 when the program family
            # has compile evidence (the shared persistent XLA cache holds
            # it), the predicted cold compile otherwise, None unpriceable
            if cost_model.compile_warm(sk, width, platform=platform,
                                       precision=precision):
                cold_compile_ms = 0.0
            else:
                cm = cost_model.predict_compile_ms(sk, width,
                                                   platform=platform,
                                                   precision=precision)
                cold_compile_ms = (round(float(cm), 3)
                                   if cm is not None else None)
        except Exception:  # noqa: BLE001 — predictions are advisory
            eta_s = cold_compile_ms = None
    n_dev = int(n_devices or 1)
    return {
        "batch_id": batch_id_for(ids),
        "requests": ids,
        # the members' durable trace identities (queue.submit) ride every
        # planning decision, so the worker's trace context — and the
        # `planned` lifecycle event — link the merge decision back to each
        # request's submit-to-settle timeline
        "trace_ids": {r["request_id"]: r["trace_id"]
                      for r in members if r.get("trace_id")},
        "tenants": sorted({str(r.get("tenant")) for r in members}),
        "shape": shape,
        "n_points": n_points,
        "g_bucket": width,
        # lane capacity the mesh is tied up for while this fit runs: a
        # sub-bucket fit (G' < n_devices) still occupies the whole mesh
        # serially, so slots round up to the device count — the honest
        # denominator for mesh-slot utilization
        "mesh_slots": max(width, n_dev) if width <= n_dev
        else -(-width // n_dev) * n_dev,
        "epochs": epochs,
        "priority": max((int(r.get("priority") or 0) for r in members),
                        default=0),
        "deadline_s": min((float(r["deadline_s"]) for r in members
                           if r.get("deadline_s") is not None),
                          default=None),
        "predicted_bytes": predicted_batch_bytes(members, width),
        "eta_s": (round(eta_s, 3) if isinstance(eta_s, (int, float))
                  else None),
        # earliest member submission: the deterministic tie-break for
        # unknown-ETA ordering (see _batch_order_key)
        "submitted_at": min((float(r.get("submitted_at") or 0.0)
                             for r in members), default=0.0),
        "precision": precision,
        "cold_compile_ms": cold_compile_ms,
        # containment circuit breaker: this batch was planned SOLO because
        # its request has prior failed attempts (never merged with healthy
        # tenants until it proves clean)
        "suspect": bool(suspect),
    }


def _batch_order_key(batch):
    """Priority desc, earliest deadline, then predicted wall-clock
    shortest-first for KNOWN ETAs — with unknown-ETA batches after them,
    ordered among themselves by earliest member SUBMISSION time (then id).

    The unknown group's internal order deliberately rides submission time,
    not the content-hash batch id (the pre-ISSUE-15 "unknown last" key):
    on a mixed-store fleet — some hosts' cost models price a shape others
    have never seen — the hash order made two planners disagree about
    which unpriced tenant drains first, i.e. queue position depended on
    which worker happened to scan. Submission order is store-independent
    FIFO fairness for every pair of batches unknown to both planners
    (pinned by the two-store planner test)."""
    dl = batch.get("deadline_s")
    eta = batch.get("eta_s")
    return (-batch["priority"],
            dl if dl is not None else float("inf"),
            ((0, float(eta)) if eta is not None
             else (1, float(batch.get("submitted_at") or 0.0))),
            batch["batch_id"])


def plan(requests, n_devices=1, budget_bytes=None, cost_model=None,
         platform=None, max_bucket=DEFAULT_MAX_BUCKET, suspects=None,
         tenant_slots=None, inflight_slots=None):
    """Pack ``requests`` (queue records) into admitted batches.

    Returns ``{"batches": [...], "unschedulable": [...], "quota_deferred":
    [...], "queue_depth", "plan_ms", "utilization", "packing"}``. Every
    admitted batch satisfies ``predicted_bytes is None or predicted_bytes
    <= budget_bytes`` (when a budget is known); requests that cannot fit
    even alone at their smallest bucket are listed under ``unschedulable``
    with a reason instead of being silently admitted.

    ``suspects`` (request-id set): containment circuit breaker — a request
    with prior failed attempts is planned into a SOLO batch, never merged
    with healthy tenants, until it proves clean. One poison tenant can then
    cost at most its own solo fits, not a merged batch's blast radius (the
    ~3x-utilization merge path stays open to everyone else).

    ``tenant_slots`` (None = the ``REDCLIFF_FLEET_TENANT_SLOTS`` env spec,
    see :func:`tenant_slot_quota`): per-tenant fair-share — a batch whose
    tenant already holds its ``max_inflight_slots`` sub-mesh slots
    (``inflight_slots``: {tenant: live slots}, from the packed worker's
    slot table, plus whatever this plan admitted earlier) is DEFERRED to
    ``quota_deferred`` with the structured
    :class:`~redcliff_tpu.runtime.admission.TenantQuotaExceeded` reason —
    still queued, surfaced by ``fleet status``, re-planned next cycle.

    ``packing`` is the spatial packing decision record
    (parallel/packing.py :func:`~redcliff_tpu.parallel.packing
    .price_packing` over the admitted batches): ``decision`` is
    ``"packed"`` only when every batch is cost-model priced AND the
    simulated slot-table makespan beats serial — an empty cost store keeps
    the worker bit-identical to the serial heuristic."""
    t0 = time.perf_counter()
    suspects = frozenset(suspects or ())
    ordered = sorted(requests, key=_order_key)
    groups = {}
    for r in ordered:
        groups.setdefault(batch_key(r), []).append(r)

    batches, unschedulable = [], []
    for key in sorted(groups):
        members = []
        n_points = 0
        for r in groups[key]:
            r_points = len(r.get("points") or ())
            if r_points == 0:
                unschedulable.append({"request_id": r["request_id"],
                                      "reason": "no_points"})
                continue
            if r["request_id"] in suspects:
                solo_width = compaction.bucket_width(r_points, n_devices)
                solo_bytes = predicted_batch_bytes([r], solo_width)
                if (budget_bytes is not None and solo_bytes is not None
                        and solo_bytes > budget_bytes) \
                        or solo_width > int(max_bucket):
                    unschedulable.append({
                        "request_id": r["request_id"],
                        "reason": ("exceeds_headroom"
                                   if solo_width <= int(max_bucket)
                                   else "exceeds_max_bucket"),
                        "predicted_bytes": solo_bytes,
                        "budget_bytes": budget_bytes,
                        "g_bucket": solo_width})
                    continue
                batches.append(_batch_view([r], n_devices, cost_model,
                                           platform, suspect=True))
                continue
            cand_points = n_points + r_points
            cand_width = compaction.bucket_width(cand_points, n_devices)
            cand_bytes = predicted_batch_bytes(members + [r], cand_width)
            over_budget = (budget_bytes is not None
                           and cand_bytes is not None
                           and cand_bytes > budget_bytes)
            over_width = cand_width > int(max_bucket)
            if members and (over_budget or over_width):
                batches.append(_batch_view(members, n_devices,
                                           cost_model, platform))
                members, n_points = [], 0
                cand_width = compaction.bucket_width(r_points, n_devices)
                cand_bytes = predicted_batch_bytes([r], cand_width)
                over_budget = (budget_bytes is not None
                               and cand_bytes is not None
                               and cand_bytes > budget_bytes)
                over_width = cand_width > int(max_bucket)
            if not members and (over_budget or over_width):
                unschedulable.append({
                    "request_id": r["request_id"],
                    "reason": ("exceeds_headroom" if over_budget
                               else "exceeds_max_bucket"),
                    "predicted_bytes": cand_bytes,
                    "budget_bytes": budget_bytes,
                    "g_bucket": cand_width})
                continue
            members.append(r)
            n_points += r_points
        if members:
            batches.append(_batch_view(members, n_devices, cost_model,
                                       platform))
    batches.sort(key=_batch_order_key)
    if tenant_slots is None:
        tenant_slots = tenant_slot_quota()
    batches, quota_deferred = _apply_tenant_quota(batches, tenant_slots,
                                                 inflight_slots)
    return {
        "batches": batches,
        "unschedulable": unschedulable,
        "quota_deferred": quota_deferred,
        "queue_depth": len(ordered),
        "plan_ms": round((time.perf_counter() - t0) * 1e3, 3),
        "utilization": utilization(batches),
        "packing": _packing.price_packing(batches, n_devices, budget_bytes),
    }


def _apply_tenant_quota(batches, tenant_slots, inflight_slots):
    """Fair-share filter over the ordered admitted batches: each batch
    charges one sub-mesh slot to every tenant riding it; a batch that would
    push any of its tenants past quota (live slots + slots admitted earlier
    this cycle) is deferred — stays queued, re-plans next cycle. Deferral
    never reorders the survivors (priority order is the planner's, quota
    only thins it)."""
    if not tenant_slots:
        return batches, []
    held = {str(t): int(n) for t, n in (inflight_slots or {}).items()}
    default = tenant_slots.get("*")
    admitted, deferred = [], []
    for b in batches:
        over = None
        for tenant in b.get("tenants") or ():
            cap = tenant_slots.get(tenant, default)
            if cap is not None and held.get(tenant, 0) >= cap:
                over = (tenant, cap)
                break
        if over is None:
            for tenant in b.get("tenants") or ():
                held[tenant] = held.get(tenant, 0) + 1
            admitted.append(b)
            continue
        tenant, cap = over
        exc = TenantQuotaExceeded(tenant, cap, held.get(tenant, 0),
                                  eta_s=b.get("eta_s"))
        deferred.append({"batch_id": b["batch_id"],
                         "requests": b["requests"],
                         "tenant": exc.tenant,
                         "reason": exc.reason,
                         "max_inflight_slots": exc.max_inflight_slots,
                         "inflight": exc.inflight,
                         "eta_s": exc.eta_s,
                         "detail": str(exc)})
    return admitted, deferred


def fifo_plan(requests, n_devices=1, budget_bytes=None, cost_model=None,
              platform=None):
    """The naive baseline: one request per fit, strict submission order, no
    merging — what the repo did before the fleet service (one driver
    process per sweep). Same admission gate, so the bench comparison
    isolates PACKING, not safety."""
    t0 = time.perf_counter()
    ordered = sorted(requests,
                     key=lambda r: (float(r.get("submitted_at") or 0.0),
                                    str(r.get("request_id"))))
    batches, unschedulable = [], []
    for r in ordered:
        if not r.get("points"):
            unschedulable.append({"request_id": r["request_id"],
                                  "reason": "no_points"})
            continue
        b = _batch_view([r], n_devices, cost_model, platform)
        if budget_bytes is not None and b["predicted_bytes"] is not None \
                and b["predicted_bytes"] > budget_bytes:
            unschedulable.append({
                "request_id": r["request_id"],
                "reason": "exceeds_headroom",
                "predicted_bytes": b["predicted_bytes"],
                "budget_bytes": budget_bytes,
                "g_bucket": b["g_bucket"]})
            continue
        batches.append(b)
    return {
        "batches": batches,
        "unschedulable": unschedulable,
        "queue_depth": len(ordered),
        "plan_ms": round((time.perf_counter() - t0) * 1e3, 3),
        "utilization": utilization(batches),
    }


def utilization(batches):
    """Mesh-slot utilization of a plan: real grid points over the lane
    capacity the mesh is serially tied up for (``mesh_slots`` — bucket
    padding plus per-fit mesh rounding are the waste; a 2-point fit on an
    8-device mesh burns 8 slots). ``{"points", "slots",
    "utilization_pct"}``."""
    points = sum(b["n_points"] for b in batches)
    slots = sum(b.get("mesh_slots", b["g_bucket"]) for b in batches)
    return {"points": points, "slots": slots,
            "utilization_pct": (round(100.0 * points / slots, 1)
                                if slots else None)}
