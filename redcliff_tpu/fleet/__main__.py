"""``python -m redcliff_tpu.fleet {submit,work,autoscale,status,cancel,
requeue}``.

submit — append fit requests to a fleet root's durable queue
    (fleet/queue.py). ``--tiny`` uses the built-in canonical tiny spec
    (the fault-injection harness's small deterministic fit) — the smoke /
    CI path; real sweeps pass ``--spec-file`` + ``--points``. Rides the
    admission backpressure gate: with ``REDCLIFF_SLO_QUEUE_P99_S`` armed,
    a submit whose predicted queue wait would breach it is REJECTED with
    the ETA (exit 3; ``REDCLIFF_BACKPRESSURE=0`` opts out).
work — run the worker loop (fleet/worker.py): reclaim expired claims,
    run pinned bisection halves, plan admission (fleet/planner.py),
    supervise batches, settle results under the containment discipline
    (``--max-attempts`` is the per-request retry budget).
autoscale — run the SLO-driven fleet control loop (fleet/autoscale.py):
    spawn/retire ``work --drain`` workers against the queue's predicted
    drain time (``REDCLIFF_AUTOSCALE_*`` knobs), demote breaching tenants
    down the degraded-QoS ladder at the pool cap, publish
    ``<root>/autoscale.json``.
status — queue-wide and per-tenant counts plus a per-request age table:
    queue age (now − ``submitted_at``) for live requests, terminal-state
    age for settled ones (``--json`` for scripts); plus the autoscaler's
    last published decision and per-tenant QoS/backpressure state when an
    autoscaler has run against the root.
cancel — first-writer-wins ``canceled`` terminal record: the request is
    never re-planned, a running worker's settle stands down, and no lease
    is orphaned (tombstone-reclaim path, docs/ARCHITECTURE.md "Fleet
    failure containment").
requeue — resurrect a dead-lettered request with a fresh retry budget
    (its dossier is archived; the planner treats it as a solo suspect
    until it proves clean).

The CLI (like the queue/planner/worker) never initializes a jax backend;
only the supervised ``run_batch`` child does.
"""
from __future__ import annotations

import argparse
import json
import sys

# the canonical tiny spec: mirrors runtime/faultinject.py's _tiny_runner
# model/train shape so fleet smoke fits warm-start from the same persistent
# compile cache the fault-injection suite already primes
TINY_SPEC = {
    "model": "RedcliffSCMLP",
    "model_config": {
        "num_chans": 4, "gen_lag": 2, "gen_hidden": [8], "embed_lag": 4,
        "embed_hidden_sizes": [8], "num_factors": 2,
        "num_supervised_factors": 2, "factor_weight_l1_coeff": 0.01,
        "adj_l1_reg_coeff": 0.001, "factor_cos_sim_coeff": 0.01,
        "factor_score_embedder_type": "Vanilla_Embedder",
        "primary_gc_est_mode": "fixed_factor_exclusive", "num_sims": 1,
        "training_mode": "combined"},
    "train_config": {"batch_size": 16, "check_every": 1, "seed": 0},
    "data": {"kind": "synthetic", "seed": 0, "n": 48},
    "epochs": 2,
}
TINY_POINTS = [{"gen_lr": 1e-3}, {"gen_lr": 3e-3}]


def _cmd_submit(args):
    from redcliff_tpu.fleet.queue import FleetQueue
    from redcliff_tpu.obs.logging import MetricLogger

    if args.tiny:
        spec = json.loads(json.dumps(TINY_SPEC))  # deep copy
        if args.epochs is not None:
            spec["epochs"] = args.epochs
        points = (json.loads(args.points) if args.points
                  else list(TINY_POINTS))
    else:
        if not args.spec_file:
            print("fleet submit: --spec-file (or --tiny) is required",
                  file=sys.stderr)
            return 2
        with open(args.spec_file) as f:
            spec = json.load(f)
        if args.epochs is not None:
            spec["epochs"] = args.epochs
        if args.points:
            points = json.loads(args.points)
        elif args.points_file:
            with open(args.points_file) as f:
                points = json.load(f)
        else:
            points = spec.pop("points", None)
        if not points:
            print("fleet submit: no grid points (--points / --points-file "
                  "/ spec['points'])", file=sys.stderr)
            return 2
    if getattr(args, "precision_mode", None):
        # tenant-facing mixed-precision knob (ISSUE 14): rides the spec's
        # train_config, so it joins the planner's merge key (requests that
        # disagree on numerics never share a batch) and the batch driver's
        # RedcliffTrainConfig verbatim
        spec.setdefault("train_config", {})["precision_mode"] = \
            args.precision_mode
    from redcliff_tpu.fleet.queue import BackpressureReject

    q = FleetQueue(args.root)
    rids = []
    rc = 0
    with MetricLogger(args.root) as log:
        for _ in range(args.n):
            try:
                rid = q.submit(args.tenant, points, spec=spec,
                               priority=args.priority,
                               deadline_s=args.deadline_s,
                               per_lane_bytes=args.per_lane_bytes,
                               fixed_bytes=args.fixed_bytes)
            except BackpressureReject as rej:
                # the structured reject-with-ETA, not a crash: nothing was
                # spooled; retry after ~eta_s or opt out
                print(f"fleet submit: {rej}", file=sys.stderr)
                rc = 3
                break
            log.log("fleet", kind="submit", requests=[rid],
                    tenants=[args.tenant], n_points=len(points),
                    priority=args.priority)
            rids.append(rid)
    for rid in rids:
        print(rid)
    return rc


def _cmd_work(args):
    from redcliff_tpu.fleet.worker import work
    from redcliff_tpu.runtime.retry import RetryPolicy
    from redcliff_tpu.runtime.supervisor import SupervisorPolicy

    policy = SupervisorPolicy(
        max_restarts=args.max_restarts,
        backoff=RetryPolicy(max_attempts=1_000_000,
                            base_delay_s=args.base_delay_s, multiplier=2.0,
                            max_delay_s=args.max_delay_s))
    n = work(args.root, worker_id=args.worker_id, lease_s=args.lease_s,
             poll_s=args.poll_s, max_batches=args.max_batches,
             drain=args.drain, once=args.once, n_devices=args.n_devices,
             budget_bytes=args.budget_bytes, max_bucket=args.max_bucket,
             checkpoint_every=args.checkpoint_every,
             supervisor_policy=policy, max_attempts=args.max_attempts,
             packing=args.packing)
    print(f"fleet work: ran {n} batch(es)", file=sys.stderr)
    return 0


def _cmd_autoscale(args):
    from redcliff_tpu.fleet import autoscale as _autoscale

    policy = _autoscale.AutoscalePolicy.from_env()
    for name in ("max_workers", "min_workers", "target_drain_s",
                 "hysteresis_s", "window_s"):
        val = getattr(args, name)
        if val is not None:
            setattr(policy, name, val)
    scaler = _autoscale.Autoscaler(
        args.root, policy=policy, n_devices=args.n_devices,
        lease_s=args.lease_s, poll_s=args.poll_s,
        max_attempts=args.max_attempts, max_restarts=args.max_restarts)
    summary = scaler.run(interval_s=args.interval_s,
                         max_ticks=args.max_ticks, drain=args.drain)
    last = summary.get("last_decision") or {}
    print(f"fleet autoscale: {summary['ticks']} tick(s) over "
          f"{summary['wall_s']:.1f}s, {summary['workers']} worker(s) "
          f"live, last decision {last.get('kind')} "
          f"({last.get('reason')})", file=sys.stderr)
    return 0


def _cmd_cancel(args):
    from redcliff_tpu.fleet.queue import FleetQueue
    from redcliff_tpu.obs.logging import MetricLogger

    q = FleetQueue(args.root)
    if q.cancel(args.request_id, reason=args.reason):
        with MetricLogger(args.root) as log:
            log.log("fleet", kind="cancel", requests=[args.request_id],
                    reason=args.reason)
        print(f"canceled {args.request_id}")
        return 0
    state = q.terminal_state(args.request_id)
    print(f"fleet cancel: {args.request_id} not canceled "
          + (f"(already terminal: {state})" if state
             else "(unknown request id)"), file=sys.stderr)
    return 1


def _cmd_requeue(args):
    from redcliff_tpu.fleet.queue import FleetQueue
    from redcliff_tpu.obs.logging import MetricLogger

    q = FleetQueue(args.root)
    if q.requeue(args.request_id):
        with MetricLogger(args.root) as log:
            log.log("fleet", kind="requeue", requests=[args.request_id])
        print(f"requeued {args.request_id} (fresh retry budget)")
        return 0
    print(f"fleet requeue: {args.request_id} has no dead-letter record "
          f"to resurrect", file=sys.stderr)
    return 1


def _cmd_status(args):
    import os

    from redcliff_tpu.fleet.queue import FleetQueue

    if not os.path.exists(args.root):
        print(f"fleet status: no such fleet root: {args.root}",
              file=sys.stderr)
        return 2
    # create=False: status is a pure reader — no mkdir side effects, and
    # archived/read-only roots still report. include_requests: the
    # per-request age view (queue age = now - submitted_at for live
    # requests, terminal-state age for settled ones)
    from redcliff_tpu.fleet import autoscale as _autoscale

    st = FleetQueue(args.root, create=False).status(include_requests=True)
    auto = _autoscale.load_state(args.root)
    qos = _autoscale.active_qos(args.root)
    if auto is not None or qos:
        st["autoscale"] = {
            "state": auto,
            "qos": {t: {"rung": r.get("rung"), "reason": r.get("reason")}
                    for t, r in sorted(qos.items())},
        }
    # spatial-packing view (ISSUE 18): worker-published occupancy state,
    # the newest plan's fair-share quota deferrals (structured reasons from
    # the metrics chain), and per-request partial-result stream progress
    # (results/<id>.partial.jsonl row counts under the batch work dirs)
    import glob as _glob

    from redcliff_tpu.parallel import packing as _packing

    pack_state = _packing.load_state(args.root)
    quota_deferred = None
    try:
        from redcliff_tpu.obs.logging import read_jsonl
        for rec in reversed(read_jsonl(args.root)):
            if rec.get("event") == "fleet" and rec.get("kind") == "plan":
                quota_deferred = rec.get("quota_deferred") or []
                break
    except (OSError, ValueError):
        pass
    partials = {}
    for path in sorted(_glob.glob(os.path.join(
            args.root, "work", "*", "results", "*.partial.jsonl"))):
        rid = os.path.basename(path)[:-len(".partial.jsonl")]
        rows = finals = 0
        try:
            with open(path, encoding="utf-8") as fh:
                for line in fh:
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue
                    rows += 1
                    finals += bool(row.get("final"))
        except OSError:
            continue
        acc = partials.setdefault(rid, {"rows": 0, "final": 0})
        acc["rows"] += rows
        acc["final"] += finals
    if pack_state is not None or quota_deferred or partials:
        st["packing"] = {
            "state": pack_state,
            "quota_deferred": quota_deferred or [],
            "partial_results": partials,
        }
    if args.json:
        json.dump(st, sys.stdout, indent=2, allow_nan=False)
        sys.stdout.write("\n")
        return 0
    c = st["counts"]
    print(f"fleet: {st['root']}")
    print(f"  {c['submitted']} submitted | {c['queued']} queued | "
          f"{c['running']} running | {c['done']} done | "
          f"{c['failed']} failed | {c['deadletter']} dead-lettered | "
          f"{c['canceled']} canceled"
          + (f" | {c['expired_claims']} expired claim(s)"
             if c["expired_claims"] else "")
          + (f" | {st['torn_spool_lines']} torn spool line(s)"
             if st["torn_spool_lines"] else ""))
    for tenant, t in sorted(st["by_tenant"].items()):
        print(f"  tenant {tenant}: {t['submitted']} submitted, "
              f"{t['queued']} queued, {t['running']} running, "
              f"{t['done']} done, {t['failed']} failed, "
              f"{t['deadletter']} dead-lettered, {t['canceled']} canceled")
    auto_st = (st.get("autoscale") or {}).get("state")
    if auto_st:
        last = auto_st.get("last_decision") or {}
        print(f"  autoscale: {auto_st.get('workers')}/"
              f"{auto_st.get('max_workers')} worker(s), target "
              f"{auto_st.get('target')}, {auto_st.get('pending')} pending, "
              f"drain eta {auto_st.get('drain_eta_s')}s")
        if last:
            print(f"    last decision: {last.get('kind')} "
                  f"({last.get('reason')})")
    for tenant, rec in sorted(((st.get("autoscale") or {}).get("qos")
                               or {}).items()):
        print(f"    qos tenant {tenant}: rung {rec.get('rung')} "
              f"({rec.get('reason')})")
    pk = st.get("packing")
    if pk:
        ps = pk.get("state") or {}
        if ps:
            print(f"  packing: {ps.get('busy_devices', 0)}/"
                  f"{ps.get('pool', '?')} device(s) busy, "
                  f"{ps.get('concurrent_batches', 0)} co-resident "
                  f"batch(es), util {ps.get('utilization_pct', 0)}%")
        for d in pk.get("quota_deferred") or []:
            print(f"    quota-deferred {d.get('batch_id')} "
                  f"[{d.get('tenant')}]: {d.get('reason')} — "
                  f"{d.get('inflight')}/{d.get('max_inflight_slots')} "
                  f"slot(s) held"
                  + (f", eta {d.get('eta_s')}s"
                     if d.get("eta_s") is not None else ""))
        for rid, acc in sorted((pk.get("partial_results") or {}).items()):
            print(f"    partial {rid}: {acc['rows']} row(s) streamed, "
                  f"{acc['final']} final")

    def _age(s):
        if s is None:
            return "-"
        if s >= 3600:
            return f"{s / 3600:.1f}h"
        if s >= 60:
            return f"{s / 60:.1f}m"
        return f"{s:.1f}s"

    rows = st.get("requests") or []
    if rows:
        print(f"  {'request':<40} {'tenant':<12} {'state':<10} "
              f"{'queue age':>10} {'settled for':>12}")
        for r in rows:
            print(f"  {r['request_id']:<40} {r['tenant']:<12} "
                  f"{r['state']:<10} {_age(r['queue_age_s']):>10} "
                  f"{_age(r['terminal_age_s']):>12}")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m redcliff_tpu.fleet",
        description="Grid-fleet sweep service: durable multi-tenant queue "
                    "+ cost/memory-aware admission planner "
                    "(docs/ARCHITECTURE.md 'Fleet sweep service').")
    sub = ap.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("submit", help="append fit request(s) to the queue")
    sp.add_argument("--root", required=True, help="fleet root directory")
    sp.add_argument("--tenant", default="default")
    sp.add_argument("--priority", type=int, default=0)
    sp.add_argument("--deadline-s", type=float, default=None)
    sp.add_argument("--epochs", type=int, default=None)
    sp.add_argument("--tiny", action="store_true",
                    help="use the built-in canonical tiny spec (smoke/CI)")
    sp.add_argument("--spec-file", default=None,
                    help="JSON spec: {model, model_config, train_config, "
                         "data, epochs[, points]}")
    sp.add_argument("--points", default=None,
                    help="grid points as a JSON list of hparam dicts")
    sp.add_argument("--points-file", default=None)
    sp.add_argument("--precision-mode", default=None,
                    choices=("f32", "mixed"),
                    help="production precision mode for the fit "
                         "(train_config.precision_mode; 'mixed' = bf16 "
                         "MXU contractions under the numerics sentinel's "
                         "auto-demotion watch)")
    sp.add_argument("--per-lane-bytes", type=int, default=None,
                    help="HBM per-lane hint for the admission planner "
                         "(obs/memory.py per_lane_bytes)")
    sp.add_argument("--fixed-bytes", type=int, default=None)
    sp.add_argument("-n", type=int, default=1, dest="n",
                    help="submit N identical requests")
    sp.set_defaults(fn=_cmd_submit)

    wp = sub.add_parser("work", help="run the worker loop")
    wp.add_argument("--root", required=True)
    wp.add_argument("--worker-id", default=None)
    wp.add_argument("--lease-s", type=float, default=60.0)
    wp.add_argument("--poll-s", type=float, default=2.0)
    wp.add_argument("--max-batches", type=int, default=None)
    wp.add_argument("--drain", action="store_true",
                    help="exit once the queue holds no claimable or "
                         "running work")
    wp.add_argument("--once", action="store_true")
    wp.add_argument("--n-devices", type=int, default=1,
                    help="mesh device count the planner packs buckets for")
    wp.add_argument("--budget-bytes", type=int, default=None,
                    help="admission HBM budget (check_headroom's "
                         "budget_bytes; omit = ungated)")
    wp.add_argument("--max-bucket", type=int, default=256)
    wp.add_argument("--checkpoint-every", type=int, default=1)
    wp.add_argument("--max-restarts", type=int, default=2)
    wp.add_argument("--base-delay-s", type=float, default=0.5)
    wp.add_argument("--max-delay-s", type=float, default=30.0)
    wp.add_argument("--packing", default=None,
                    choices=["off", "auto", "force"],
                    help="spatial mesh packing mode (ISSUE 18): off = "
                         "serial claims (default), auto = co-schedule "
                         "disjoint sub-mesh slots when the priced plan "
                         "says packed beats serial, force = always pack; "
                         "unset defers to REDCLIFF_FLEET_PACKING")
    wp.add_argument("--max-attempts", type=int, default=3,
                    help="per-request retry budget: failure attempts before "
                         "a request is dead-lettered (fleet/worker.py)")
    wp.set_defaults(fn=_cmd_work)

    asp = sub.add_parser(
        "autoscale",
        help="run the SLO-driven fleet control loop (fleet/autoscale.py): "
             "scale drain-workers against predicted drain time, demote "
             "breaching tenants down the degraded-QoS ladder")
    asp.add_argument("--root", required=True)
    asp.add_argument("--interval-s", type=float, default=2.0,
                     help="control-loop tick interval")
    asp.add_argument("--max-ticks", type=int, default=None,
                     help="stop after N ticks (smoke/CI)")
    asp.add_argument("--drain", action="store_true",
                     help="exit once the queue settles and every spawned "
                          "worker has retired")
    asp.add_argument("--max-workers", type=int, default=None,
                     help="pool cap (default REDCLIFF_AUTOSCALE_MAX_WORKERS "
                          "or 4)")
    asp.add_argument("--min-workers", type=int, default=None)
    asp.add_argument("--target-drain-s", type=float, default=None,
                     help="queue drain-time target the pool is sized for")
    asp.add_argument("--hysteresis-s", type=float, default=None,
                     help="cooldown between pool/QoS changes")
    asp.add_argument("--window-s", type=float, default=None,
                     help="rolling SLO window the loop reacts to")
    asp.add_argument("--n-devices", type=int, default=1)
    asp.add_argument("--lease-s", type=float, default=60.0)
    asp.add_argument("--poll-s", type=float, default=2.0)
    asp.add_argument("--max-attempts", type=int, default=3)
    asp.add_argument("--max-restarts", type=int, default=2,
                     help="respawn budget per worker slot (crashed workers "
                          "respawn under the supervised-exit taxonomy)")
    asp.set_defaults(fn=_cmd_autoscale)

    st = sub.add_parser("status", help="queue + per-tenant counts")
    st.add_argument("--root", required=True)
    st.add_argument("--json", action="store_true")
    st.set_defaults(fn=_cmd_status)

    cp = sub.add_parser("cancel",
                        help="terminal 'canceled' record (first writer "
                             "wins; never re-planned, no orphaned lease)")
    cp.add_argument("request_id")
    cp.add_argument("--root", required=True)
    cp.add_argument("--reason", default=None)
    cp.set_defaults(fn=_cmd_cancel)

    rq = sub.add_parser("requeue",
                        help="resurrect a dead-lettered request with a "
                             "fresh retry budget (dossier archived)")
    rq.add_argument("request_id")
    rq.add_argument("--root", required=True)
    rq.set_defaults(fn=_cmd_requeue)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
