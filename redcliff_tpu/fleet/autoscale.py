"""SLO-driven fleet autoscaler: scale, backpressure, and a degraded-QoS
ladder (ROADMAP item 4 — the service-level control loop).

PR 15 closed the *per-fit* loop (cost-model predictions steer scheduling);
this module closes the *service-level* one. ``obs/slo.py`` computes
per-tenant queue-wait/TTFA percentiles and breach flags from the durable
lifecycle ledger, and until now nothing acted on them — a submit storm just
made every tenant silently late. The autoscaler consumes the WINDOWED SLO
view (``compute_slo(..., window_s=...)`` — recent breaches, not all-time
percentiles) plus the learned cost model's fit ETAs
(``obs/costmodel.py:predict_fit_eta`` via the admission planner's batch
views) and reacts three ways, cheapest reaction first:

* **scale** — spawn supervised worker processes (``python -m
  redcliff_tpu.fleet work --drain``, own process groups, exactly the chaos
  harness's :class:`~redcliff_tpu.fleet.chaos.WorkerFleet` mechanics)
  against the queue's predicted drain time, with hysteresis (a cooldown
  between pool changes) and a hard max-worker cap. Scale-DOWN is passive
  by design: workers run ``--drain`` and retire themselves on an empty
  queue — the autoscaler reaps the exit and logs it, so a scale-down can
  never SIGKILL a supervised batch mid-fit. Crashed workers are respawned
  on the supervisor taxonomy (``runtime/supervisor.py
  worker_exit_action``) within a restart budget;
* **backpressure** — :meth:`~redcliff_tpu.fleet.queue.FleetQueue.submit`
  consults :func:`predict_queue_wait_s` and rejects with a structured
  reject-with-ETA error when the predicted wait would breach the tenant's
  queue-wait SLO (``REDCLIFF_SLO_QUEUE_P99_S``). Rejection beats silent
  lateness; ``REDCLIFF_BACKPRESSURE=0`` opts out;
* **degrade** — a priced QoS ladder applied to a BREACHING tenant's queued
  work instead of dead-lining it, pulling the same demotion lever the
  PR-14 numerics sentinel pulls mid-fit: rung 1 demotes the tenant's
  queued requests to ``precision_mode="mixed"`` (cheaper MXU
  contractions), rung 2 additionally coarsens ``check_every`` by
  :data:`QOS_CHECK_EVERY_FACTOR` — fewer eval/quality readouts, which IS
  the lowered quality top-k cadence (obs/quality.py reads at check
  windows). Rungs are durable per-tenant files (``<root>/qos/<tenant>
  .json``) the worker's fresh-admission path applies via
  :func:`apply_qos`; a demoted spec no longer shares a
  ``planner.batch_key`` with undemoted work, so un-breached co-tenants'
  batches — and their decision streams — are bit-identical with the
  autoscaler on or off. Demotion is recorded on the request (``"qos"``)
  and lands in its results manifest (fleet/run_batch.py).

Every decision is logged as a schema-registered ``autoscale``/``qos``
event in the fleet root's metrics chain AND (pool/rung changes) as a
durable ``fleet_lifecycle`` transition in ``history.jsonl`` — traceable in
``obs trace --fleet``, ``obs watch``, ``obs report``, and ``fleet
status``. The control state lives in ``<root>/autoscale.json`` (atomic
tmp+rename) so observers and the submit-side backpressure gate read one
file, never the autoscaler's memory.

Knobs (see docs/ARCHITECTURE.md "SLO-driven autoscaling & degraded QoS")::

    REDCLIFF_AUTOSCALE_MAX_WORKERS     pool cap               (default 4)
    REDCLIFF_AUTOSCALE_MIN_WORKERS     pool floor             (default 0)
    REDCLIFF_AUTOSCALE_TARGET_DRAIN_S  drain-time target      (default 60)
    REDCLIFF_AUTOSCALE_HYSTERESIS_S    pool-change cooldown   (default 10)
    REDCLIFF_AUTOSCALE_WINDOW_S        rolling SLO window     (default 300)
    REDCLIFF_AUTOSCALE_DEFAULT_ETA_S   unpriced-batch ETA     (default 30)
    REDCLIFF_AUTOSCALE_QOS             QoS ladder gate        (default 1)
    REDCLIFF_BACKPRESSURE              submit-gate opt-out    (default 1)

stdlib only, no jax (obs/schema.py ``--check`` enforces it): the
autoscaler is fleet CONTROL plane — it spawns workers, it never initializes
a backend.
"""
from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import time
import uuid
from dataclasses import dataclass

__all__ = ["AutoscalePolicy", "Autoscaler", "QOS_MAX_RUNG",
           "QOS_CHECK_EVERY_FACTOR", "qos_knobs", "apply_qos", "set_qos",
           "active_qos", "load_state", "predict_queue_wait_s",
           "predicted_drain", "STATE_NAME", "QOS_DIR",
           "ENV_MAX_WORKERS", "ENV_MIN_WORKERS", "ENV_TARGET_DRAIN_S",
           "ENV_HYSTERESIS_S", "ENV_WINDOW_S", "ENV_DEFAULT_ETA_S",
           "ENV_QOS", "ENV_BACKPRESSURE", "backpressure_enabled"]

ENV_MAX_WORKERS = "REDCLIFF_AUTOSCALE_MAX_WORKERS"
ENV_MIN_WORKERS = "REDCLIFF_AUTOSCALE_MIN_WORKERS"
ENV_TARGET_DRAIN_S = "REDCLIFF_AUTOSCALE_TARGET_DRAIN_S"
ENV_HYSTERESIS_S = "REDCLIFF_AUTOSCALE_HYSTERESIS_S"
ENV_WINDOW_S = "REDCLIFF_AUTOSCALE_WINDOW_S"
ENV_DEFAULT_ETA_S = "REDCLIFF_AUTOSCALE_DEFAULT_ETA_S"
ENV_QOS = "REDCLIFF_AUTOSCALE_QOS"
ENV_BACKPRESSURE = "REDCLIFF_BACKPRESSURE"

STATE_NAME = "autoscale.json"
QOS_DIR = "qos"

# how stale the autoscale.json worker count may be before the submit-side
# backpressure gate falls back to counting live-lease workers
STATE_FRESH_S = 60.0

QOS_MAX_RUNG = 2
QOS_CHECK_EVERY_FACTOR = 4

# the breached SLOs the ladder reacts to: waiting-time SLOs a cheaper/
# coarser fit can actually fix (a dead-letter-rate breach is a containment
# story, not a capacity one)
_QOS_SLOS = ("queue_p99_s", "ttfa_p99_s")


def _env_float(name, default):
    raw = os.environ.get(name)
    if raw is None or not str(raw).strip():
        return float(default)
    try:
        return float(raw)
    except ValueError:
        return float(default)


def backpressure_enabled():
    """The submit-side admission gate's opt-out knob: on unless
    ``REDCLIFF_BACKPRESSURE=0`` (rejection beats silent lateness)."""
    return os.environ.get(ENV_BACKPRESSURE, "1").strip().lower() \
        not in ("0", "false", "off", "no")


@dataclass
class AutoscalePolicy:
    """The control loop's knobs (env-overridable, see module docstring)."""

    max_workers: int = 4
    min_workers: int = 0
    target_drain_s: float = 60.0
    hysteresis_s: float = 10.0
    window_s: float = 300.0
    default_eta_s: float = 30.0
    qos: bool = True

    @classmethod
    def from_env(cls):
        return cls(
            max_workers=int(_env_float(ENV_MAX_WORKERS, 4)),
            min_workers=int(_env_float(ENV_MIN_WORKERS, 0)),
            target_drain_s=_env_float(ENV_TARGET_DRAIN_S, 60.0),
            hysteresis_s=_env_float(ENV_HYSTERESIS_S, 10.0),
            window_s=_env_float(ENV_WINDOW_S, 300.0),
            default_eta_s=_env_float(ENV_DEFAULT_ETA_S, 30.0),
            qos=os.environ.get(ENV_QOS, "1").strip().lower()
            not in ("0", "false", "off", "no"),
        )


# ---------------------------------------------------------------------------
# durable control state: <root>/autoscale.json + <root>/qos/<tenant>.json
# ---------------------------------------------------------------------------
def _write_json_atomic(path, obj):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f, allow_nan=False)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _read_json(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def load_state(root):
    """The autoscaler's last published control state
    (``<root>/autoscale.json``), or None when no autoscaler ever ran."""
    return _read_json(os.path.join(str(root), STATE_NAME))


def _qos_path(root, tenant):
    return os.path.join(str(root), QOS_DIR, f"{tenant}.json")


def set_qos(root, tenant, rung, reason=None, now=None):
    """Set (or clear, ``rung<=0``) a tenant's durable QoS demotion rung.
    Returns the written record (None on clear)."""
    now = time.time() if now is None else now
    path = _qos_path(root, str(tenant))
    if int(rung) <= 0:
        try:
            os.unlink(path)
        except OSError:
            pass
        return None
    os.makedirs(os.path.dirname(path), exist_ok=True)
    rec = dict(qos_knobs(int(rung)), tenant=str(tenant), set_at=now,
               reason=reason)
    _write_json_atomic(path, rec)
    return rec


def active_qos(root):
    """``{tenant: rung_record}`` for every tenant currently demoted
    (``<root>/qos/*.json``); empty dict when the ladder is idle."""
    d = os.path.join(str(root), QOS_DIR)
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return {}
    out = {}
    for name in names:
        if not name.endswith(".json") or ".tmp." in name:
            continue
        rec = _read_json(os.path.join(d, name))
        if isinstance(rec, dict) and rec.get("rung"):
            out[rec.get("tenant") or name[:-len(".json")]] = rec
    return out


# ---------------------------------------------------------------------------
# the QoS ladder
# ---------------------------------------------------------------------------
def qos_knobs(rung):
    """The knob set one ladder rung applies. Only train_config keys the
    batch driver's ``RedcliffTrainConfig`` actually accepts may appear
    here — an invented key would crash every demoted fit."""
    rung = max(0, min(int(rung), QOS_MAX_RUNG))
    knobs = {"rung": rung}
    if rung >= 1:
        knobs["precision_mode"] = "mixed"
    if rung >= 2:
        knobs["check_every_factor"] = QOS_CHECK_EVERY_FACTOR
    return knobs


def apply_qos(request, rungs):
    """Apply a tenant's active demotion rung to one queued request record.

    ``rungs`` is :func:`active_qos` output. Returns the request UNCHANGED
    (same object — the bit-identity guarantee for un-breached co-tenants)
    when its tenant holds no rung; otherwise a deep copy whose
    ``spec.train_config`` carries the rung's knobs and whose top-level
    ``"qos"`` field records the demotion for the results manifest. The
    mutated spec changes ``planner.batch_key``, so demoted work never
    merges with an undemoted sibling's batch."""
    rec = (rungs or {}).get(str(request.get("tenant")))
    rung = int((rec or {}).get("rung") or 0)
    if rung <= 0:
        return request
    out = json.loads(json.dumps(request))  # deep copy, JSON-clean
    tc = out.setdefault("spec", {}).setdefault("train_config", {})
    applied = {"rung": rung, "reason": rec.get("reason"),
               "set_at": rec.get("set_at")}
    if rec.get("precision_mode"):
        tc["precision_mode"] = rec["precision_mode"]
        applied["precision_mode"] = rec["precision_mode"]
    factor = rec.get("check_every_factor")
    if factor:
        base = int(tc.get("check_every") or 1)
        tc["check_every"] = max(base, 1) * int(factor)
        applied["check_every"] = tc["check_every"]
    out["qos"] = applied
    return out


# ---------------------------------------------------------------------------
# drain / queue-wait prediction (the backpressure gate's math)
# ---------------------------------------------------------------------------
def predicted_drain(q, cost_model=None, n_devices=1, default_eta_s=30.0,
                    now=None, root=None):
    """Predicted per-worker drain time of the PENDING queue: one admission
    plan's batch ETAs (cost-model priced where a matching shape rung
    exists, ``default_eta_s`` per unpriced batch). In-flight work is
    deliberately excluded — its lease already ended the wait obs/slo.py
    measures, and undercounting keeps the backpressure gate honest
    (rejecting on work we cannot price would reject on guesses).

    Slot-awareness (ISSUE 18 satellite): a PACKED worker drains several
    batches concurrently on disjoint sub-mesh slots, so pricing the queue
    serially over-predicts drain — and over-spawns workers. When the
    worker publishes live slot occupancy (``<root>/packing.json``,
    parallel/packing.py ``publish_state``; ``root`` defaults to
    ``q.root``), the serial total divides by the published packing width
    (live concurrent batches, floored at 1). A stale/missing publication
    keeps the serial estimate — the conservative pre-packing behavior.

    Returns ``{"pending", "batches", "priced", "unpriced",
    "total_eta_s", "packing_width"}``."""
    from redcliff_tpu.fleet import planner as _planner
    from redcliff_tpu.parallel import packing as _packing

    pending = q.pending(now=now)
    if not pending:
        return {"pending": 0, "batches": 0, "priced": 0, "unpriced": 0,
                "total_eta_s": 0.0, "packing_width": 1}
    pl = _planner.plan(pending, n_devices=n_devices, cost_model=cost_model)
    total, priced, unpriced = 0.0, 0, 0
    for b in pl["batches"]:
        eta = b.get("eta_s")
        if isinstance(eta, (int, float)):
            total += float(eta)
            priced += 1
        else:
            total += float(default_eta_s)
            unpriced += 1
    # requests the planner cannot admit still occupy the queue: price them
    # like unpriced batches so a wedged-unschedulable backlog reads as load
    total += float(default_eta_s) * len(pl["unschedulable"])
    unpriced += len(pl["unschedulable"])
    width = 1
    pack_state = _packing.load_state(root if root is not None else q.root,
                                     now=now)
    if pack_state is not None:
        width = max(int(pack_state.get("concurrent_batches") or 0), 1)
    return {"pending": len(pending), "batches": len(pl["batches"]),
            "priced": priced, "unpriced": unpriced,
            "total_eta_s": round(total / width, 3),
            "packing_width": width}


def _worker_count(root, q, now):
    """Best available live-worker estimate for the submit-side gate: the
    autoscaler's published state when fresh, else distinct live-lease
    workers, else 1 (a lone default worker — the conservative floor)."""
    state = load_state(root)
    wt = (state or {}).get("wall_time")
    if state is not None and isinstance(wt, (int, float)) \
            and (now - wt) <= STATE_FRESH_S:
        return max(int(state.get("workers") or 0), 1), "autoscaler"
    workers = {l.get("worker") for l in q.live_leases(now=now)
               if l.get("worker")}
    if workers:
        return len(workers), "leases"
    return 1, "default"


def predict_queue_wait_s(root, q=None, cost_model=None, now=None,
                         default_eta_s=None):
    """Predicted queue wait for a request submitted NOW: the pending
    queue's serial drain estimate divided by the live worker count.
    Returns ``{"eta_s", "workers", "workers_source", "queue_depth",
    "priced", "unpriced"}`` (``eta_s`` 0.0 on an empty queue)."""
    from redcliff_tpu.fleet.queue import FleetQueue
    from redcliff_tpu.obs import costmodel as _costmodel

    now = time.time() if now is None else now
    q = FleetQueue(root, create=False) if q is None else q
    if cost_model is None:
        cost_model = _costmodel.load()
    state = load_state(root)
    if default_eta_s is None:
        default_eta_s = _env_float(ENV_DEFAULT_ETA_S, 30.0)
    drain = predicted_drain(
        q, cost_model=cost_model,
        n_devices=int((state or {}).get("n_devices") or 1),
        default_eta_s=default_eta_s, now=now)
    workers, source = _worker_count(root, q, now)
    return {
        "eta_s": round(drain["total_eta_s"] / max(workers, 1), 3),
        "workers": workers,
        "workers_source": source,
        "queue_depth": drain["pending"],
        "priced": drain["priced"],
        "unpriced": drain["unpriced"],
    }


# ---------------------------------------------------------------------------
# the control loop
# ---------------------------------------------------------------------------
class Autoscaler:
    """The SLO-driven fleet control loop (see the module docstring).

    ``spawn`` is injectable for tests (called with the worker argv, must
    return a Popen-like object with ``poll()``); ``thresholds`` overrides
    the ``REDCLIFF_SLO_*`` env thresholds the windowed breach check uses.
    ``worker_args`` are appended to every spawned worker's argv."""

    def __init__(self, root, policy=None, n_devices=1, lease_s=60.0,
                 poll_s=0.5, max_attempts=3, max_restarts=2,
                 worker_args=(), env=None, python=None, spawn=None,
                 thresholds=None, supervisor_policy=None, logger=None,
                 scaler_id=None):
        from redcliff_tpu.fleet.queue import FleetQueue

        self.root = str(root)
        self.q = FleetQueue(self.root)
        self.policy = policy or AutoscalePolicy.from_env()
        self.n_devices = int(n_devices)
        self.lease_s = float(lease_s)
        self.poll_s = float(poll_s)
        self.max_attempts = int(max_attempts)
        self.max_restarts = int(max_restarts)
        self.worker_args = list(worker_args)
        self.env = dict(env) if env is not None else None
        self.python = python or sys.executable
        self._spawn = spawn
        self.thresholds = thresholds
        self.supervisor_policy = supervisor_policy
        self.scaler_id = scaler_id or f"autoscaler-{uuid.uuid4().hex[:6]}"
        self._logger = logger
        self._owns_logger = False
        # live pool: worker_id -> {"proc", "spawned_at", "restarts"}
        self.workers = {}
        self._spawn_seq = 0
        self.last_scale_wall = None
        self.last_decision = None
        self.first_breach_wall = None
        self.ticks = 0
        self._qos_wall = {}  # tenant -> last rung-change wall (hysteresis)

    # -- worker lifecycle --------------------------------------------------
    def _worker_cmd(self, worker_id):
        return [self.python, "-m", "redcliff_tpu.fleet", "work",
                "--root", self.root, "--drain",
                "--worker-id", worker_id,
                "--lease-s", str(self.lease_s),
                "--poll-s", str(self.poll_s),
                "--max-attempts", str(self.max_attempts),
                "--n-devices", str(self.n_devices),
                ] + self.worker_args

    def _spawn_worker(self, restarts=0):
        self._spawn_seq += 1
        worker_id = f"{self.scaler_id}-w{self._spawn_seq}"
        cmd = self._worker_cmd(worker_id)
        if self._spawn is not None:
            proc = self._spawn(cmd)
        else:
            # own process group, exactly like the chaos harness's fleet:
            # a supervised batch child dies with its worker, never orphans
            proc = subprocess.Popen(cmd, env=self.env,
                                    start_new_session=True,
                                    stdout=subprocess.DEVNULL,
                                    stderr=subprocess.DEVNULL)
        self.workers[worker_id] = {"proc": proc, "spawned_at": time.time(),
                                   "restarts": int(restarts)}
        return worker_id

    def _reap(self, logger, now, pending):
        """Collect exited workers: clean drains RETIRE (the passive
        scale-down), restartable crashes respawn within the budget."""
        from redcliff_tpu.runtime.supervisor import worker_exit_action

        for worker_id, w in list(self.workers.items()):
            rc = w["proc"].poll()
            if rc is None:
                continue
            del self.workers[worker_id]
            classification, action = worker_exit_action(
                rc, w["restarts"], max_restarts=self.max_restarts)
            if action == "respawn" and pending:
                replacement = self._spawn_worker(restarts=w["restarts"] + 1)
                logger.log("autoscale", kind="respawn", worker=replacement,
                           classification=classification,
                           restarts=w["restarts"] + 1,
                           workers=len(self.workers),
                           reason=f"worker {worker_id} exited "
                                  f"{classification}")
                self._ledger("autoscale", worker=replacement,
                             reason=f"respawn after {classification}",
                             workers=len(self.workers), now=now)
            else:
                reason = ("drained" if classification == "drained"
                          else f"exited {classification}")
                logger.log("autoscale", kind="scale_down", worker=worker_id,
                           classification=classification,
                           workers=len(self.workers), reason=reason)
                self._ledger("autoscale", worker=worker_id,
                             reason=f"scale_down: {reason}",
                             workers=len(self.workers), now=now)

    def _ledger(self, kind, now=None, **fields):
        from redcliff_tpu.fleet import history as _history

        _history.append_event(self.root, kind, now=now, **fields)

    # -- the decision ------------------------------------------------------
    def _windowed_slo(self, now):
        from redcliff_tpu.obs import slo as _slo

        return _slo.slo_for_root(self.root, thresholds=self.thresholds,
                                 window_s=self.policy.window_s)

    def _target_workers(self, drain, breached, live):
        """Pool size that drains the predicted backlog inside the target:
        ``ceil(total_eta / target_drain_s)``, nudged one ABOVE the live
        pool while a recent waiting-time SLO breach stands (observed
        lateness outranks a prediction that says we are fine)."""
        p = self.policy
        target = 0
        if drain["pending"]:
            target = max(int(math.ceil(
                drain["total_eta_s"] / max(p.target_drain_s, 1e-9))), 1)
        if breached and drain["pending"]:
            target = max(target, live + 1)
        return max(min(target, p.max_workers), p.min_workers)

    def tick(self, now=None):
        """One control decision; returns the decision record (also logged
        as an ``autoscale`` event and published to ``autoscale.json``)."""
        now = time.time() if now is None else now
        self.ticks += 1
        logger = self._ensure_logger()
        from redcliff_tpu.obs import costmodel as _costmodel

        drain = predicted_drain(self.q, cost_model=_costmodel.load(),
                                n_devices=self.n_devices,
                                default_eta_s=self.policy.default_eta_s,
                                now=now)
        self._reap(logger, now, pending=bool(drain["pending"]))
        slo = self._windowed_slo(now)
        breaches = [b for b in ((slo or {}).get("breaches") or [])
                    if b.get("slo") in _QOS_SLOS]
        if breaches and self.first_breach_wall is None:
            self.first_breach_wall = now
        live = len(self.workers)
        target = self._target_workers(drain, bool(breaches), live)
        cooled = (self.last_scale_wall is None
                  or (now - self.last_scale_wall)
                  >= self.policy.hysteresis_s)
        decision = {"kind": "hold", "workers": live, "target": target,
                    "reason": "steady"}
        if target > live and cooled:
            spawned = [self._spawn_worker() for _ in range(target - live)]
            self.last_scale_wall = now
            decision = {
                "kind": "scale_up", "workers": len(self.workers),
                "target": target,
                "reason": (f"predicted drain {drain['total_eta_s']:.1f}s > "
                           f"target {self.policy.target_drain_s:.0f}s"
                           + (f"; {len(breaches)} windowed SLO breach(es)"
                              if breaches else "")),
                "spawned": spawned,
            }
            self._ledger("autoscale", reason=decision["reason"],
                         workers=len(self.workers), target=target, now=now)
        elif target > live:
            decision = {"kind": "hold", "workers": live, "target": target,
                        "reason": "hysteresis cooldown"}
        elif target < live:
            # passive scale-down: --drain workers retire themselves; the
            # hold here just names why the pool is (temporarily) oversized
            decision = {"kind": "hold", "workers": live, "target": target,
                        "reason": "awaiting worker self-drain"}
        qos_changes = self._qos_tick(logger, slo, breaches, live, now)
        rec = dict(decision, queue_depth=drain["pending"],
                   drain_eta_s=drain["total_eta_s"],
                   target_drain_s=self.policy.target_drain_s,
                   window_s=self.policy.window_s,
                   breaches=len(breaches), max_workers=self.policy.max_workers)
        # log every pool change; holds only when something else moved
        # (a multi-hour steady loop must not write a record per tick)
        if rec["kind"] != "hold" or qos_changes \
                or self.last_decision is None \
                or rec["reason"] != self.last_decision.get("reason"):
            logger.log("autoscale", **rec)
        self.last_decision = dict(rec, wall_time=now)
        self._publish(now, drain)
        return self.last_decision

    def _qos_tick(self, logger, slo, breaches, live, now):
        """The degraded-QoS ladder: demote a breaching tenant one rung when
        scaling is exhausted (pool at cap), restore when its window is
        clean. Rate-limited per tenant by the same hysteresis."""
        if not self.policy.qos:
            return 0
        rungs = active_qos(self.root)
        breached_tenants = {b["scope"] for b in breaches
                            if b.get("scope") not in (None, "overall")}
        changes = 0

        def cooled(tenant):
            last = self._qos_wall.get(tenant)
            return last is None or (now - last) >= self.policy.hysteresis_s

        if live >= self.policy.max_workers:
            for tenant in sorted(breached_tenants):
                cur = int((rungs.get(tenant) or {}).get("rung") or 0)
                if cur >= QOS_MAX_RUNG or not cooled(tenant):
                    continue
                rung = cur + 1
                reason = (f"windowed SLO breach at max workers "
                          f"({live}/{self.policy.max_workers})")
                rec = set_qos(self.root, tenant, rung, reason=reason,
                              now=now)
                self._qos_wall[tenant] = now
                changes += 1
                logger.log("qos", kind="demote", tenant=tenant, rung=rung,
                           from_rung=cur, reason=reason,
                           precision_mode=rec.get("precision_mode"),
                           check_every_factor=rec.get("check_every_factor"),
                           window_s=self.policy.window_s,
                           worker=self.scaler_id)
                self._ledger("qos", tenant=tenant, rung=rung,
                             reason=reason, now=now)
        for tenant in sorted(set(rungs) - breached_tenants):
            if not cooled(tenant):
                continue
            cur = int((rungs.get(tenant) or {}).get("rung") or 0)
            set_qos(self.root, tenant, 0, now=now)
            self._qos_wall[tenant] = now
            changes += 1
            logger.log("qos", kind="restore", tenant=tenant, rung=0,
                       from_rung=cur, reason="window clean",
                       window_s=self.policy.window_s, worker=self.scaler_id)
            self._ledger("qos", tenant=tenant, rung=0,
                         reason="restore: window clean", now=now)
        return changes

    def _publish(self, now, drain):
        state = {
            "wall_time": now,
            "scaler": self.scaler_id,
            "workers": len(self.workers),
            "worker_ids": sorted(self.workers),
            "target": (self.last_decision or {}).get("target"),
            "max_workers": self.policy.max_workers,
            "min_workers": self.policy.min_workers,
            "n_devices": self.n_devices,
            "pending": drain["pending"],
            "drain_eta_s": drain["total_eta_s"],
            "last_decision": self.last_decision,
            "qos": {t: r.get("rung")
                    for t, r in sorted(active_qos(self.root).items())},
            "ticks": self.ticks,
        }
        _write_json_atomic(os.path.join(self.root, STATE_NAME), state)

    # -- loop --------------------------------------------------------------
    def _ensure_logger(self):
        if self._logger is None:
            from redcliff_tpu.obs.logging import MetricLogger

            self._logger = MetricLogger(self.root).__enter__()
            self._owns_logger = True
        return self._logger

    def close(self):
        # live --drain workers are left to finish and retire themselves:
        # stopping the control loop must never SIGKILL a supervised batch
        logger = self._ensure_logger()
        logger.log("autoscale", kind="stop", workers=len(self.workers),
                   ticks=self.ticks)
        if self._owns_logger:
            self._logger.__exit__(None, None, None)
            self._logger, self._owns_logger = None, False

    def settled(self, now=None):
        """True when the queue holds no pending work and no live lease —
        the drain-mode exit condition."""
        now = time.time() if now is None else now
        return not self.q.pending(now=now) and not self.q.live_leases(now=now)

    def run(self, interval_s=2.0, max_ticks=None, drain=False,
            sleep=time.sleep):
        """Run the control loop. ``drain``: exit once the queue is fully
        settled AND every spawned worker has retired. ``max_ticks`` bounds
        the loop (tests / smoke). Returns a summary dict."""
        logger = self._ensure_logger()
        logger.log("autoscale", kind="start", worker=self.scaler_id,
                   max_workers=self.policy.max_workers,
                   min_workers=self.policy.min_workers,
                   target_drain_s=self.policy.target_drain_s,
                   window_s=self.policy.window_s)
        t0 = time.time()
        try:
            while True:
                now = time.time()
                self.tick(now=now)
                if max_ticks is not None and self.ticks >= int(max_ticks):
                    break
                if drain and self.settled(now=now) and not any(
                        w["proc"].poll() is None
                        for w in self.workers.values()):
                    # one final reap so the retire events land, and a final
                    # publish so observers see the emptied pool
                    self._reap(logger, now, pending=False)
                    self._publish(now, {"pending": 0, "total_eta_s": 0.0})
                    break
                sleep(interval_s)
        finally:
            self.close()
        return {
            "ticks": self.ticks,
            "wall_s": round(time.time() - t0, 3),
            "workers": len(self.workers),
            "first_breach_wall": self.first_breach_wall,
            "last_decision": self.last_decision,
        }
