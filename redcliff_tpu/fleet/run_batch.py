"""Fleet batch driver: one merged multi-tenant grid fit, supervised.

``python -m redcliff_tpu.fleet.run_batch <batch.json>`` is the jax-side
child the fleet worker runs under the crash-loop supervisor. The batch file
(written by fleet/worker.py from the claimed composition) holds the merged
member requests in claim order; this driver:

1. validates that every member shares the identical non-point spec (same
   model config, train config, data, horizon — the planner's
   ``batch_key`` contract re-checked at the trust boundary);
2. concatenates the members' grid points into ONE :class:`~redcliff_tpu
   .parallel.grid.GridSpec` and fits it with the grid engine — checkpointed
   into the batch run dir every ``checkpoint_every`` epochs, so a SIGKILLed
   worker's reclaimed batch RESUMES bit-identically instead of restarting;
3. logs the tenant manifest (request id + trace id -> merged point range)
   as a ``fleet`` metrics event in the run dir, so ``obs report`` can
   attribute fits/lane-epochs/quarantines per tenant. The worker exports
   ``REDCLIFF_TRACE_CTX`` into this child, so every span and metrics
   record the fit writes additionally carries the batch/request trace
   join keys (obs/spans.py trace context — zero-cost when
   ``REDCLIFF_TRACE=0``);
4. splits the :class:`~redcliff_tpu.parallel.grid.GridResult` back into
   per-request ``results/<request_id>.json`` records (criteria, epochs,
   val history slice, quarantine causes — strict JSON, no params: the
   checkpoint owns the heavy artifacts), and writes the merged-grid
   ``failures.json`` (every quarantined point with its owning request and
   tenant) — the worker's poison-attribution artifact.

Containment plumbing (docs/ARCHITECTURE.md "Fleet failure containment"):
every lane's init key derives from a CONTENT hash of its own point
(``GridSpec.lane_seeds``), never from its position or the grid width — so a
request fits identically whatever batch the planner (or a bisection) lands
it in, which is what makes bisected survivors bit-identical to an
uninterrupted merged run. ``__chaos__`` sentinel keys in points (the fleet
chaos harness's poison request specs, fleet/chaos.py) are always STRIPPED
before the fit and only ACTED on when the fault grammar arms
``fleet_poison`` — an unarmed replay of a chaos spool completes instead of
crash-looping.

Exit codes follow the watchdog taxonomy (runtime/watchdog.py) exactly like
the faultinject child: preempted 17, deadline 20, host-lost 21 — so the
supervisor's restart/stop classification applies unchanged.

This is the ONE fleet module that initializes a jax backend; the queue,
planner, and worker stay backend-free by design.
"""
from __future__ import annotations

import hashlib
import json
import os
import sys

__all__ = ["run_batch_file", "main", "lane_seed"]

# spec keys every member of a batch must agree on, byte-for-byte after
# canonical JSON: one merged GridSpec must mean the same math for everyone
_MERGE_KEYS = ("model", "model_config", "train_config", "data", "epochs",
               "mesh")


def _canon(spec):
    return json.dumps({k: spec.get(k) for k in _MERGE_KEYS}, sort_keys=True)


def _tupled(d):
    """JSON round-trips tuples as lists; model/train config dataclasses
    expect tuples for the size fields."""
    return {k: tuple(v) if isinstance(v, list) else v for k, v in d.items()}


def lane_seed(point):
    """Composition-independent lane seed: a stable hash of the point's own
    (chaos-stripped) content. Two submissions of the same point — in any
    batch, any position, any leg of a chaos test — init identically."""
    blob = json.dumps(point, sort_keys=True)
    return int(hashlib.sha1(blob.encode("utf-8")).hexdigest()[:8], 16) \
        & 0x7FFFFFFF


def _build_dataset(data_spec, cfg):
    import numpy as np

    from redcliff_tpu.data.datasets import ArrayDataset

    kind = (data_spec or {}).get("kind", "synthetic")
    if kind == "synthetic":
        # the faultinject tiny-fit contract: deterministic arrays from the
        # seed + the model's window shape (bit-identical across workers)
        rng = np.random.default_rng(int(data_spec.get("seed", 0)))
        n = int(data_spec.get("n", 48))
        T = cfg.max_lag + cfg.num_sims
        X = rng.normal(size=(n, T, cfg.num_chans)).astype(np.float32)
        Y = rng.uniform(size=(n, 3, 1)).astype(np.float32)
        return ArrayDataset(X, Y), ArrayDataset(X, Y)
    if kind == "npz":
        blob = np.load(data_spec["path"])
        train = ArrayDataset(blob["X"], blob.get("Y"))
        if "X_val" in blob:
            return train, ArrayDataset(blob["X_val"], blob.get("Y_val"))
        return train, train
    raise ValueError(f"unknown fleet data kind {kind!r}")


def run_batch_file(batch_file):
    """Run one batch file end-to-end; returns the GridResult."""
    import jax

    from redcliff_tpu.models.redcliff import (RedcliffSCMLP,
                                              RedcliffSCMLPConfig)
    from redcliff_tpu.obs.logging import MetricLogger, jsonable
    from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner
    from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig

    with open(batch_file) as f:
        batch = json.load(f)
    run_dir = batch["run_dir"]
    requests = batch["requests"]
    if not requests:
        raise ValueError(f"{batch_file}: empty batch")
    canon = _canon(requests[0].get("spec") or {})
    for r in requests[1:]:
        if _canon(r.get("spec") or {}) != canon:
            raise ValueError(
                f"{batch_file}: members disagree on the non-point spec — "
                f"the planner must never merge them "
                f"({requests[0]['request_id']} vs {r['request_id']})")
    spec0 = requests[0].get("spec") or {}
    model_name = spec0.get("model", "RedcliffSCMLP")
    if model_name != "RedcliffSCMLP":
        raise ValueError(f"unsupported fleet model {model_name!r}")
    model = RedcliffSCMLP(RedcliffSCMLPConfig(
        **_tupled(spec0.get("model_config") or {})))
    tc_kwargs = dict(spec0.get("train_config") or {})
    epochs = spec0.get("epochs") or requests[0].get("epochs")
    if epochs is not None:
        tc_kwargs["max_iter"] = int(epochs)
    if isinstance(tc_kwargs.get("numerics"), dict):
        # JSON round-trips the sentinel policy as a plain dict
        from redcliff_tpu.runtime.numerics import NumericsPolicy

        tc_kwargs["numerics"] = NumericsPolicy(**tc_kwargs["numerics"])
    tc = RedcliffTrainConfig(**_tupled(tc_kwargs))
    train_ds, val_ds = _build_dataset(spec0.get("data"), model.config)

    from redcliff_tpu.fleet import chaos as _chaos
    from redcliff_tpu.runtime import faultinject as _fi

    merged, manifest, start = [], [], 0
    chaos_specs = []
    for r in requests:
        pts = [_chaos.strip_chaos(p, chaos_specs) for p in
               (r.get("points") or ())]
        merged.extend(pts)
        row = {"request_id": r["request_id"],
               "tenant": str(r.get("tenant")),
               "trace_id": r.get("trace_id"),
               "start": start, "stop": start + len(pts)}
        if r.get("qos"):
            # degraded-QoS stamp (fleet/autoscale.py apply_qos): the rung
            # this request was admitted under rides into the manifest and
            # its results record — the durable "completed at degraded
            # settings" evidence the ISSUE-16 acceptance requires
            row["qos"] = r["qos"]
        manifest.append(row)
        start += len(pts)
    if chaos_specs and _fi.fleet_poison_armed():
        # a poison request spec (fleet chaos harness): die the way the
        # sentinel says, BEFORE any fit — the blind-failure mode the
        # worker's bisection must corner without attribution
        _chaos.detonate(chaos_specs[0])

    # sub-mesh slot (ISSUE 18): a PACKED worker assigned this batch a
    # disjoint device interval of the pool — mesh over exactly those
    # devices so co-resident batches never share a device. Device ids are
    # stable (remesh.visible_devices), so a reclaimed batch meshes over the
    # SAME devices its checkpoint was fitted on. A slot that no longer
    # fits the visible pool (devices lost since the claim) degrades to the
    # auto-mesh recipe rather than crash-looping the batch
    mesh = None
    slot = batch.get("slot")
    if spec0.get("mesh") == "auto":
        from redcliff_tpu.parallel import remesh as _remesh

        mesh = None
        if isinstance(slot, dict):
            try:
                lo, width = int(slot["lo"]), int(slot["width"])
            except (KeyError, TypeError, ValueError):
                lo = width = None
            if width:
                devs = _remesh.visible_devices()[lo:lo + width]
                if len(devs) == width:
                    from redcliff_tpu.parallel.mesh import grid_mesh

                    mesh = grid_mesh(devices=devs, axis_name="grid")
        if mesh is None:
            mesh = _remesh.visible_mesh(n_lanes=len(merged))

    # predictive-policy widening ceiling (ISSUE 15, parallel/policy.py
    # ENV_POLICY_MAX_WIDTH): the admission planner's HBM gate and
    # max_bucket cap were priced at the ADMITTED width recorded in the
    # batch file — a warm-rung initial-width widening inside this child
    # must never exceed it (per-lane footprint scales with width)
    if batch.get("g_bucket"):
        os.environ["REDCLIFF_POLICY_MAX_WIDTH"] = str(int(batch["g_bucket"]))

    import numpy as np

    results_dir = os.path.join(run_dir, "results")
    os.makedirs(results_dir, exist_ok=True)

    def _owner(point):
        return next((m for m in manifest
                     if m["start"] <= point < m["stop"]), None)

    # per-point result streaming (ISSUE 18): lanes the compaction ladder
    # retires at a check window (early-stopped or quarantined — their state
    # never changes again) are appended to the owning tenant's
    # results/<id>.partial.jsonl IMMEDIATELY, not at batch settle, each
    # also landing as a schema-registered `partial_result` event. Delivery
    # is at-least-once: a resumed attempt may re-append rows an earlier
    # attempt already streamed (and batch settle re-appends every point
    # with final=true) — consumers keep the LAST record per point.
    streamed = set()

    # tenant manifest into the run dir's metrics chain BEFORE the fit, so
    # even a crashed attempt's telemetry is tenant-attributable; the grid
    # engine appends its own events to the same chain next. The logger
    # stays open across the fit: it is also the partial-result event sink
    with MetricLogger(run_dir) as plog:
        plog.log("fleet", kind="manifest", batch_id=batch.get("batch_id"),
                 requests=manifest,
                 tenants=sorted({m["tenant"] for m in manifest}),
                 n_points=len(merged))

        def _stream_partial(pid, rec, epoch, final=False):
            own = _owner(int(pid))
            if own is None:
                return
            failed_epoch = rec.get("failed_epoch")
            failed = isinstance(failed_epoch, (int, float)) \
                and failed_epoch >= 0
            row = jsonable({
                "request_id": own["request_id"],
                "tenant": own["tenant"],
                "batch_id": batch.get("batch_id"),
                "point": int(pid) - own["start"],
                "merged_point": int(pid),
                "epoch": int(epoch),
                "best_criterion": rec.get("best_crit"),
                "best_epoch": rec.get("best_epoch"),
                "failed": bool(failed),
                "final": bool(final),
            })
            path = os.path.join(results_dir,
                                f"{own['request_id']}.partial.jsonl")
            try:
                with open(path, "a") as fh:
                    fh.write(json.dumps(row, allow_nan=False) + "\n")
                plog.log("partial_result", **row)
                streamed.add(int(pid))
            except (OSError, ValueError):
                pass  # streaming is a tenant convenience, never fatal

        runner = RedcliffGridRunner(
            model, tc,
            GridSpec(points=merged,
                     lane_seeds=[lane_seed(p) for p in merged]),
            mesh=mesh)
        result = runner.fit(jax.random.PRNGKey(tc.seed), train_ds, val_ds,
                            checkpoint_dir=run_dir,
                            checkpoint_every=int(
                                batch.get("checkpoint_every") or 1),
                            log_dir=run_dir,
                            on_lane_retire=_stream_partial)

        # complete the stream at batch settle: every lane that ran to the
        # end (never early-retired) gets its terminal row, final=true
        best_crit_arr = np.asarray(result.best_criteria)
        best_epoch_arr = np.asarray(result.best_epoch)
        failed_pts = {int(f["point"]) for f in result.failures}
        for pid in range(len(merged)):
            if pid in streamed:
                continue
            _stream_partial(pid, {
                "best_crit": float(best_crit_arr[pid]),
                "best_epoch": int(best_epoch_arr[pid]),
                "failed_epoch": 0 if pid in failed_pts else -1,
            }, epoch=int(best_epoch_arr[pid]), final=True)

    # ---- split the merged result into per-request records ----------------
    val_hist = np.asarray(result.val_history)

    # model-quality observatory (obs/quality.py): the engine's rolling
    # convergence snapshot, keyed by ORIGINAL merged point id — sliced per
    # request below so results/<id>.json carries each tenant's own quality
    # block (None when REDCLIFF_QUALITY=0 or no check window ran)
    qstats = (getattr(runner, "dispatch_stats", None) or {}).get("quality")

    def _request_quality(lo, hi):
        if not isinstance(qstats, dict) or not qstats.get("windows"):
            return None
        pick = lambda key: ([(qstats.get(key) or {}).get(str(p))
                             for p in range(lo, hi)]
                            if qstats.get(key) is not None else None)
        plats = pick("plateaued_at_epoch") or []
        return {
            "windows": qstats.get("windows"),
            "mode": qstats.get("mode"),
            "plateaued_at_epoch": plats,
            "converged_at_epoch": (max(plats) if plats
                                   and all(p is not None for p in plats)
                                   else None),
            "edge_stability": pick("edge_stability"),
            "topk_hash": pick("topk_hash"),
            "auroc": pick("auroc"),
            "aupr": pick("aupr"),
        }

    # merged-grid failures.json (train/driver.py's artifact, with per-point
    # request/tenant attribution): the worker's poison-attribution input
    # and the dead-letter dossier's quarantine evidence
    attributed = []
    for f in result.failures:
        own = _owner(int(f["point"])) or {}
        attributed.append(dict(f, request_id=own.get("request_id"),
                               tenant=own.get("tenant")))
    tmp = os.path.join(run_dir, f".failures.json.tmp.{os.getpid()}")
    with open(tmp, "w") as fh:
        json.dump({"batch_id": batch.get("batch_id"),
                   "grid_size": len(merged),
                   "failures": jsonable(attributed)}, fh, allow_nan=False)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, os.path.join(run_dir, "failures.json"))
    for row in manifest:
        lo, hi = row["start"], row["stop"]
        failures = [dict(f, point=int(f["point"]) - lo,
                         merged_point=int(f["point"]))
                    for f in result.failures if lo <= f["point"] < hi]
        rec = {
            "request_id": row["request_id"],
            "tenant": row["tenant"],
            "batch_id": batch.get("batch_id"),
            "n_points": hi - lo,
            "best_criteria": jsonable(result.best_criteria[lo:hi]),
            "best_epoch": jsonable(result.best_epoch[lo:hi]),
            "active": jsonable(result.active[lo:hi]),
            "val_history": jsonable(val_hist[:, lo:hi]),
            "failures": jsonable(failures),
            "quality": jsonable(_request_quality(lo, hi)),
        }
        if row.get("qos"):
            rec["qos"] = row["qos"]
        tmp = os.path.join(results_dir,
                           f".{row['request_id']}.tmp.{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump(rec, f, allow_nan=False)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(results_dir,
                                     f"{row['request_id']}.json"))
    return result


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) != 1:
        print("usage: python -m redcliff_tpu.fleet.run_batch <batch.json>",
              file=sys.stderr)
        return 2
    from redcliff_tpu.parallel.remesh import HostLostError
    from redcliff_tpu.runtime.preempt import DeadlineExceeded, Preempted
    from redcliff_tpu.runtime.watchdog import (EXIT_DEADLINE,
                                               EXIT_HOST_LOST,
                                               EXIT_PREEMPTED)

    try:
        run_batch_file(argv[0])
    except Preempted as e:
        print(f"fleet run_batch: {e}", file=sys.stderr)
        return EXIT_PREEMPTED
    except DeadlineExceeded as e:
        print(f"fleet run_batch: {e}", file=sys.stderr)
        return EXIT_DEADLINE
    except HostLostError as e:
        print(f"fleet run_batch: {e}", file=sys.stderr)
        return EXIT_HOST_LOST
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
