"""Fleet worker loop: claim planned batches, supervise fits, mark results.

One worker = one long-lived control process on a host with accelerators::

    python -m redcliff_tpu.fleet work --root /fleet

Each cycle it (1) prefers RECLAIM work — expired leases whose recorded
batch composition it re-claims so the dead worker's grid fit resumes from
its durable checkpoint in the same ``work/<batch_id>`` run dir; then (2)
plans fresh admission over the pending queue (fleet/planner.py) and claims
the first admitted batch; then (3) runs the batch as a supervised child —
:func:`redcliff_tpu.runtime.supervisor.supervise` around ``python -m
redcliff_tpu.fleet.run_batch <batch.json>`` — so crashes, hangs, and
preemptions restart from checkpoint under the existing exit-code taxonomy,
while a background thread renews the members' leases on a cadence well
inside ``lease_s``.

Tenant stamping: before supervising, the worker appends a ``fleet``
manifest record (batch id + per-request tenant and merged point range) to
the batch's ``run_ledger.jsonl``; ``run_batch`` logs the same manifest as a
metrics event. ``obs report`` joins both into its per-tenant section, and
every planner/claim/batch transition lands as a schema-registered ``fleet``
event in the FLEET ROOT's ``metrics.jsonl`` (what ``obs watch <root>``
tails in fleet mode).

Completion discipline: only a ``clean`` supervised outcome marks requests
done (first ``done/<id>.json`` writer wins — never run twice);
deterministic-failure classes (``numerics_abort``/``deadline``/
``giving_up``/``mesh_exhausted``) mark them failed; anything else releases
the leases so another worker retries.

stdlib-only imports at module scope, and NEVER jax (obs/schema.py
``--check`` enforces it): the worker is a control process — the jax backend
initializes only inside the supervised ``run_batch`` child.
"""
from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time
import uuid

from redcliff_tpu.obs import record_span
from redcliff_tpu.obs import costmodel as _costmodel
from redcliff_tpu.runtime.supervisor import SupervisorPolicy, supervise
from redcliff_tpu.fleet import planner as _planner
from redcliff_tpu.fleet.queue import FleetQueue, LeaseLost

__all__ = ["work", "run_one_batch", "default_worker_id",
           "TERMINAL_FAIL_CLASSES"]

# supervised outcomes a restart cannot fix: the request is terminally failed
# instead of released for another worker to burn the same budget on
TERMINAL_FAIL_CLASSES = ("numerics_abort", "deadline", "giving_up",
                         "mesh_exhausted")


def default_worker_id():
    return f"{socket.gethostname()}-{os.getpid()}-{uuid.uuid4().hex[:6]}"


def _logger(root):
    """The fleet root's MetricLogger (lazy import: obs.logging pulls numpy,
    which is fine for a control process — only jax is banned here)."""
    from redcliff_tpu.obs.logging import MetricLogger

    return MetricLogger(root)


def _manifest_rows(requests):
    """Per-request merged-point ranges: [{request_id, tenant, start, stop}]
    — the tenant-attribution map every report join keys on."""
    rows, start = [], 0
    for r in requests:
        n = len(r.get("points") or ())
        rows.append({"request_id": r["request_id"],
                     "tenant": str(r.get("tenant")),
                     "start": start, "stop": start + n})
        start += n
    return rows


def _claim_batch(q, worker_id, lease_s, batch_id, request_ids, by_id,
                 logger, reclaim=False, all_ids=None):
    """Claim every member of one batch (all-or-nothing); returns
    {request_id: Lease} or None. ``all_ids`` records the FULL batch
    composition on each lease (it may exceed ``request_ids`` on a reclaim
    whose other members already completed)."""
    leases = {}
    for rid in request_ids:
        rec = by_id.get(rid)
        lease = q.claim(rid, worker_id, lease_s, batch_id=batch_id,
                        batch_request_ids=list(all_ids or request_ids),
                        tenant=(rec or {}).get("tenant"))
        if lease is None:
            if q.is_terminal(rid):
                continue  # already finished by someone: not a conflict
            for l in leases.values():
                l.release()
            return None
        leases[rid] = lease
    if leases:
        logger.log("fleet", kind="reclaim" if reclaim else "claim",
                   batch_id=batch_id, requests=list(leases),
                   tenants=sorted({str(by_id[r].get("tenant"))
                                   for r in leases if r in by_id}),
                   worker=worker_id)
    return leases or None


def _next_batch(q, worker_id, lease_s, n_devices, budget_bytes, max_bucket,
                logger):
    """Reclaim-first, then plan-and-claim. Returns (batch_view, leases,
    member_requests) or None when nothing is claimable right now."""
    by_id = {r["request_id"]: r for r in q.requests()}

    # 1) reclaim: an expired lease records the batch it was claimed under —
    # resume THAT composition so the grid checkpoint fingerprint matches.
    # The FULL recorded member list stays the batch (manifest offsets must
    # match the merged grid the checkpoint was written under); only the
    # not-yet-terminal members need fresh claims
    for batch_id, stale in sorted(q.expired_claims().items(),
                                  key=lambda kv: str(kv[0])):
        if batch_id is None:
            continue  # no recorded composition: replanned below
        rids_all = (stale[0].get("batch_request_ids")
                    or [l["request_id"] for l in stale])
        rids_all = [r for r in rids_all if r in by_id]
        claimable = [r for r in rids_all if not q.is_terminal(r)]
        if not claimable:
            continue
        leases = _claim_batch(q, worker_id, lease_s, batch_id, claimable,
                              by_id, logger, reclaim=True,
                              all_ids=rids_all)
        if leases:
            members = [by_id[r] for r in rids_all]
            batch = _planner._batch_view(members, n_devices)
            batch["batch_id"] = batch_id  # preserve the recorded run dir
            return batch, leases, members

    # 2) fresh admission plan over the pending queue (derived from the one
    # spool scan above: non-terminal, no live lease, submission order)
    now = time.time()
    pending = []
    for rid, rec in by_id.items():
        if q.is_terminal(rid):
            continue
        lease = q.lease_of(rid)
        if lease is not None and float(lease.get("expires_at") or 0.0) > now:
            continue
        pending.append(rec)
    if not pending:
        return None
    t0 = time.perf_counter()
    pl = _planner.plan(pending, n_devices=n_devices,
                       budget_bytes=budget_bytes,
                       cost_model=_costmodel.load(), max_bucket=max_bucket)
    record_span("fleet.plan", (time.perf_counter() - t0) * 1e3,
                component="fleet", logger=logger, emit=True,
                queue_depth=pl["queue_depth"], batches=len(pl["batches"]))
    logger.log("fleet", kind="plan", queue_depth=pl["queue_depth"],
               batches=len(pl["batches"]),
               unschedulable=len(pl["unschedulable"]),
               plan_ms=pl["plan_ms"],
               utilization_pct=pl["utilization"]["utilization_pct"],
               decisions=[{k: b.get(k) for k in
                           ("batch_id", "requests", "tenants", "n_points",
                            "g_bucket", "predicted_bytes", "eta_s",
                            "priority")}
                          for b in pl["batches"][:8]],
               worker=worker_id)
    for b in pl["batches"]:
        leases = _claim_batch(q, worker_id, lease_s, b["batch_id"],
                              b["requests"], by_id, logger)
        if leases:
            members = [by_id[r] for r in b["requests"] if r in by_id]
            return b, leases, members
    return None


class _LeaseHeartbeat:
    """Renews a batch's leases every ``lease_s / 3`` seconds while the
    supervised fit runs; a lost lease (reclaimed by another worker after an
    expiry we slept through) stops renewals and is surfaced to the caller
    so it will not publish results it no longer owns."""

    def __init__(self, leases, lease_s, logger):
        self._leases = leases
        self._lease_s = float(lease_s)
        self._logger = logger
        self._stop = threading.Event()
        self.lost = []
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-lease-heartbeat")

    def _run(self):
        period = max(self._lease_s / 3.0, 0.05)
        while not self._stop.wait(period):
            for rid, lease in list(self._leases.items()):
                try:
                    lease.renew(self._lease_s)
                except LeaseLost:
                    self.lost.append(rid)
                    self._leases.pop(rid, None)
                    self._logger.log("fleet", kind="lease_lost",
                                     requests=[rid])
                except OSError:
                    pass  # transient fs hiccup: retry next period

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=self._lease_s)


def run_one_batch(q, batch, leases, members, logger, worker_id,
                  lease_s=60.0, checkpoint_every=1, supervisor_policy=None,
                  env=None, python=None):
    """Run one claimed batch under the crash-loop supervisor and settle its
    requests; returns the :class:`~redcliff_tpu.runtime.supervisor
    .SuperviseOutcome`."""
    batch_id = batch["batch_id"]
    run_dir = q.batch_dir(batch_id)
    os.makedirs(run_dir, exist_ok=True)
    batch_file = os.path.join(run_dir, "batch.json")
    if not os.path.exists(batch_file):
        # deterministic from the claimed composition: a reclaiming worker
        # that finds the file missing (claimant died pre-write) rebuilds
        # the identical content from the lease-recorded member order
        tmp = f"{batch_file}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"batch_id": batch_id, "run_dir": run_dir,
                       "checkpoint_every": int(checkpoint_every),
                       "requests": members}, f, allow_nan=False)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, batch_file)
    # tenant stamping into the supervisor ledger: the manifest row set the
    # per-tenant report section joins on (run_batch logs the same manifest
    # as a metrics event inside the run dir)
    ledger_path = os.path.join(run_dir, "run_ledger.jsonl")
    with open(ledger_path, "a") as f:
        f.write(json.dumps({"event": "fleet", "kind": "manifest",
                            "batch_id": batch_id, "worker": worker_id,
                            "requests": _manifest_rows(members)}) + "\n")
    logger.log("fleet", kind="batch_start", batch_id=batch_id,
               run_dir=run_dir, requests=batch["requests"],
               tenants=batch["tenants"], n_points=batch["n_points"],
               g_bucket=batch["g_bucket"], eta_s=batch.get("eta_s"),
               predicted_bytes=batch.get("predicted_bytes"),
               worker=worker_id)
    cmd = [python or sys.executable, "-m", "redcliff_tpu.fleet.run_batch",
           batch_file]
    t0 = time.perf_counter()
    with _LeaseHeartbeat(leases, lease_s, logger) as hb:
        outcome = supervise(
            cmd, ledger_path=ledger_path,
            policy=supervisor_policy or SupervisorPolicy(max_restarts=2),
            env=env)
    dur_ms = (time.perf_counter() - t0) * 1e3
    record_span("fleet.batch", dur_ms, component="fleet", logger=logger,
                emit=True, batch_id=batch_id,
                classification=outcome.classification)

    lost = set(hb.lost)
    settled = {"done": [], "failed": [], "released": [], "lost": sorted(lost)}
    for rid, lease in list(leases.items()):
        if rid in lost:
            continue
        rec = next((m for m in members if m["request_id"] == rid), {})
        if outcome.classification == "clean":
            result = _read_result(run_dir, rid)
            q.complete(rid, result=result)
            settled["done"].append(rid)
            logger.log("fleet", kind="complete", batch_id=batch_id,
                       requests=[rid], tenants=[str(rec.get("tenant"))],
                       worker=worker_id)
        elif outcome.classification in TERMINAL_FAIL_CLASSES:
            q.fail(rid, outcome.classification)
            settled["failed"].append(rid)
        else:
            lease.release()
            settled["released"].append(rid)
    logger.log("fleet", kind="batch_end", batch_id=batch_id,
               classification=outcome.classification, rc=outcome.returncode,
               attempts=len(outcome.attempts),
               wall_s=round(dur_ms / 1e3, 3),
               done=len(settled["done"]), failed=len(settled["failed"]),
               released=len(settled["released"]), worker=worker_id)
    return outcome


def _read_result(run_dir, request_id):
    path = os.path.join(run_dir, "results", f"{request_id}.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        # clean exit but no per-request artifact (should not happen):
        # record the run dir so the operator can dig
        return {"run_dir": run_dir, "missing_result": True}


def work(root, worker_id=None, lease_s=60.0, poll_s=2.0, max_batches=None,
         drain=False, once=False, n_devices=1, budget_bytes=None,
         max_bucket=_planner.DEFAULT_MAX_BUCKET, checkpoint_every=1,
         supervisor_policy=None, env=None, python=None):
    """The worker loop; returns the number of batches run.

    ``drain``: exit once the queue holds no claimable or running work.
    ``once``: run at most one claim cycle. ``max_batches`` bounds the run.
    ``budget_bytes``: the admission HBM budget (``check_headroom``'s
    ``budget_bytes`` on the serving mesh; None = ungated, e.g. this CPU
    container)."""
    q = FleetQueue(root)
    worker_id = worker_id or default_worker_id()
    batches_run = 0
    with _logger(root) as logger:
        logger.log("fleet", kind="worker_start", worker=worker_id,
                   n_devices=n_devices, budget_bytes=budget_bytes,
                   lease_s=lease_s)
        while True:
            got = _next_batch(q, worker_id, lease_s, n_devices,
                              budget_bytes, max_bucket, logger)
            if got is not None:
                batch, leases, members = got
                run_one_batch(q, batch, leases, members, logger, worker_id,
                              lease_s=lease_s,
                              checkpoint_every=checkpoint_every,
                              supervisor_policy=supervisor_policy, env=env,
                              python=python)
                batches_run += 1
                if max_batches is not None and batches_run >= max_batches:
                    break
                if once:
                    break
                continue
            if once:
                break
            # drain: nothing is claimable right now (_next_batch came back
            # empty — the queue is empty OR holds only unschedulable
            # requests the planner can never admit) and nothing is in
            # flight anywhere whose completion/expiry could change that
            if drain and not q.live_leases():
                break
            time.sleep(poll_s)
        logger.log("fleet", kind="worker_stop", worker=worker_id,
                   batches=batches_run)
    return batches_run
