"""Fleet worker loop: claim planned batches, supervise fits, mark results.

One worker = one long-lived control process on a host with accelerators::

    python -m redcliff_tpu.fleet work --root /fleet

Each cycle it (1) prefers RECLAIM work — expired leases whose recorded
batch composition it re-claims so the dead worker's grid fit resumes from
its durable checkpoint in the same ``work/<batch_id>`` run dir; then (2)
plans fresh admission over the pending queue (fleet/planner.py) and claims
the first admitted batch; then (3) runs the batch as a supervised child —
:func:`redcliff_tpu.runtime.supervisor.supervise` around ``python -m
redcliff_tpu.fleet.run_batch <batch.json>`` — so crashes, hangs, and
preemptions restart from checkpoint under the existing exit-code taxonomy,
while a background thread renews the members' leases on a cadence well
inside ``lease_s``.

Tenant stamping: before supervising, the worker appends a ``fleet``
manifest record (batch id + per-request tenant and merged point range) to
the batch's ``run_ledger.jsonl``; ``run_batch`` logs the same manifest as a
metrics event. ``obs report`` joins both into its per-tenant section, and
every planner/claim/batch transition lands as a schema-registered ``fleet``
event in the FLEET ROOT's ``metrics.jsonl`` (what ``obs watch <root>``
tails in fleet mode).

Settle discipline (blast-radius containment, docs/ARCHITECTURE.md "Fleet
failure containment"): a ``clean`` supervised outcome marks requests done
(first ``done/<id>.json`` writer wins — never run twice) — except a member
whose per-request artifact is missing (routed through the retry budget) or
whose EVERY point the grid engine quarantined for a deterministic-numerics
cause (the attribution path: the poison tenant is dead-lettered with its
quarantine causes while healthy co-tenants still complete; wall-clock
``deadline`` evictions never attribute). A terminal failure of a MERGED
batch is never blamed on its members: with 2+ live leases the batch is
split in half and the halves requeued as pinned compositions, so repeated
halving deterministically corners a poison request while its siblings
finish; with <=1 live lease (the rest lost or terminal) the survivor —
possibly a healthy co-tenant — is budget-routed, never verdicted. Only a
terminal failure of a genuinely SOLO composition is charged as that
request's own: deterministic classes fail it outright, a crash/hang loop
(``giving_up``) releases it against its durable retry budget (queue
``attempts/``) until the budget is spent, then routes it to ``deadletter/``
with a failure dossier. Anything non-terminal releases the leases so
another worker retries.

Predictive scheduling (ISSUE 15, ``REDCLIFF_PREDICTIVE``,
docs/ARCHITECTURE.md "Predictive scheduling & preemption"): the worker
closes the learning loop on two decisions — fresh admission plans are
claimed COLD-COMPILE-FIRST within an urgency class (parallel/policy.py
``compile_order``: the longest predicted missing executable starts
compiling earliest, so the shared persistent cache warms on the critical
path), and a running batch is CHECKPOINT-AND-PREEMPTED when
``predict_fit_eta`` shows a queued higher-priority tenant's deadline would
otherwise be missed (:class:`_PreemptMonitor`). A preemption is a reclaim,
never a charged failure attempt: leases release cleanly, the composition is
pinned with its beneficiary (``after_request``) and resumes bit-identically
from its checkpoint after the deadline tenant is served.

No jax anywhere in this module's import chain (obs/schema.py ``--check``
enforces it): the worker is a control process — the jax backend
initializes only inside the supervised ``run_batch`` child.
"""
from __future__ import annotations

import contextlib
import glob
import json
import os
import socket
import sys
import threading
import time
import uuid

from redcliff_tpu.obs import record_span
from redcliff_tpu.obs import costmodel as _costmodel
from redcliff_tpu.obs import flight as _flight
from redcliff_tpu.obs import spans as _spans
from redcliff_tpu.runtime.supervisor import (SupervisorPolicy,
                                             latest_cost_model_eta,
                                             supervise)
from redcliff_tpu.fleet import autoscale as _autoscale
from redcliff_tpu.fleet import history as _history
from redcliff_tpu.fleet import planner as _planner
from redcliff_tpu.fleet.queue import FleetQueue, LeaseLost
from redcliff_tpu.parallel import packing as _packing
# parallel/policy.py is jax-free by contract (schema --check pins it via
# this import chain): the predictive-scheduling gate + the cold-compile
# claim-ordering decision live there, beside the width/compaction pricing
from redcliff_tpu.parallel.policy import (PredictiveSchedulingPolicy,
                                          predictive_enabled)

__all__ = ["work", "run_one_batch", "default_worker_id",
           "TERMINAL_FAIL_CLASSES", "DETERMINISTIC_FAIL_CLASSES",
           "DEFAULT_MAX_ATTEMPTS", "DEFAULT_PREEMPT_GRACE_S"]

# supervised outcomes a restart cannot fix: the batch will not be re-run
# as-is (solo requests are failed or budget-routed; merged batches bisect)
TERMINAL_FAIL_CLASSES = ("numerics_abort", "deadline", "giving_up",
                         "mesh_exhausted")

# the subset that is a deterministic VERDICT on a solo request (a replay
# provably repeats it): recorded in failed/, not dead-lettered. giving_up
# is deliberately absent — a crash loop is *suspicious*, not proven
# deterministic (the host may be at fault), so it burns retry budget and
# dead-letters only when the budget is spent
DETERMINISTIC_FAIL_CLASSES = ("numerics_abort", "deadline", "mesh_exhausted")

# default per-request retry budget: failure attempts (giving_up /
# missing_result) a request may accumulate before it is dead-lettered.
# Lease-expiry reclaims deliberately do NOT count — a worker SIGKILL storm
# is an infrastructure fault, and letting it spend tenants' budgets would
# dead-letter healthy requests (the exact blast radius this layer exists
# to contain)
DEFAULT_MAX_ATTEMPTS = 3

# deadline-aware preemption knobs (ISSUE 15; armed by REDCLIFF_PREDICTIVE,
# parallel/policy.py): the grace term is the charged checkpoint-and-yield
# overhead — the in-flight epoch the child drains plus its final checkpoint
# and the beneficiary's supervised-child spawn — and the poll is how often
# the monitor re-prices the queue against the running batch
ENV_PREEMPT_GRACE = "REDCLIFF_PREEMPT_GRACE_S"
ENV_PREEMPT_POLL = "REDCLIFF_PREEMPT_POLL_S"
DEFAULT_PREEMPT_GRACE_S = 5.0
DEFAULT_PREEMPT_POLL_S = 0.5


def default_worker_id():
    return f"{socket.gethostname()}-{os.getpid()}-{uuid.uuid4().hex[:6]}"


def _logger(root):
    """The fleet root's MetricLogger (lazy import: obs.logging pulls numpy,
    which is fine for a control process — only jax is banned here)."""
    from redcliff_tpu.obs.logging import MetricLogger

    return MetricLogger(root)


def _manifest_rows(requests):
    """Per-request merged-point ranges: [{request_id, tenant, trace_id,
    start, stop}] — the tenant-attribution map every report join keys on
    (``trace_id`` links each range back to the request's lifecycle
    trace)."""
    rows, start = [], 0
    for r in requests:
        n = len(r.get("points") or ())
        rows.append({"request_id": r["request_id"],
                     "tenant": str(r.get("tenant")),
                     "trace_id": r.get("trace_id"),
                     "start": start, "stop": start + n})
        start += n
    return rows


def _trace_context(batch_id, members):
    """The cross-process trace context for one batch: batch id + every
    member's durable trace identity (minted at submit). Set in-process for
    the worker's own spans/events and exported to the supervised run_batch
    child via ``REDCLIFF_TRACE_CTX`` (obs/spans.py)."""
    tids = {m["request_id"]: m["trace_id"]
            for m in members if m.get("trace_id")}
    ctx = {"batch_id": batch_id}
    if tids:
        ctx["trace_ids"] = tids
    return ctx


def _claim_batch(q, worker_id, lease_s, batch_id, request_ids, by_id,
                 logger, reclaim=False, all_ids=None):
    """Claim every member of one batch (all-or-nothing); returns
    {request_id: Lease} or None. ``all_ids`` records the FULL batch
    composition on each lease (it may exceed ``request_ids`` on a reclaim
    whose other members already completed)."""
    leases = {}
    for rid in request_ids:
        rec = by_id.get(rid)
        lease = q.claim(rid, worker_id, lease_s, batch_id=batch_id,
                        batch_request_ids=list(all_ids or request_ids),
                        tenant=(rec or {}).get("tenant"),
                        trace_id=(rec or {}).get("trace_id"))
        if lease is None:
            if q.is_terminal(rid):
                continue  # already finished by someone: not a conflict
            for l in leases.values():
                l.release()
            return None
        leases[rid] = lease
    if leases:
        logger.log("fleet", kind="reclaim" if reclaim else "claim",
                   batch_id=batch_id, requests=list(leases),
                   tenants=sorted({str(by_id[r].get("tenant"))
                                   for r in leases if r in by_id}),
                   worker=worker_id)
    return leases or None


def _next_batch(q, worker_id, lease_s, n_devices, budget_bytes, max_bucket,
                logger, predictive=False, tenant_slots=None,
                inflight_slots=None, plan_out=None):
    """Reclaim-first, then pinned compositions, then plan-and-claim.
    Returns (batch_view, leases, member_requests) or None when nothing is
    claimable right now. ``predictive`` arms the cold-compile claim
    ordering over fresh admission plans (ISSUE 15).

    Packing hooks (ISSUE 18): ``tenant_slots``/``inflight_slots`` ride into
    the planner's fair-share quota, and ``plan_out`` (a mutable dict) is
    filled with the fresh plan's ``packing`` decision + ``quota_deferred``
    list so the packed worker loop can gang-schedule without re-planning."""
    now = time.time()
    by_id = {r["request_id"]: r for r in q.requests()}

    # 1) reclaim: an expired lease records the batch it was claimed under —
    # resume THAT composition so the grid checkpoint fingerprint matches.
    # The FULL recorded member list stays the batch (manifest offsets must
    # match the merged grid the checkpoint was written under); only the
    # not-yet-terminal members need fresh claims
    for batch_id, stale in sorted(q.expired_claims().items(),
                                  key=lambda kv: str(kv[0])):
        if batch_id is None:
            continue  # no recorded composition: replanned below
        rids_all = (stale[0].get("batch_request_ids")
                    or [l["request_id"] for l in stale])
        rids_all = [r for r in rids_all if r in by_id]
        claimable = [r for r in rids_all if not q.is_terminal(r)]
        if not claimable:
            continue
        leases = _claim_batch(q, worker_id, lease_s, batch_id, claimable,
                              by_id, logger, reclaim=True,
                              all_ids=rids_all)
        if leases:
            # the reclaim is recorded on each member's durable attempt
            # ledger (kind="reclaim": dossier evidence, NOT budget — worker
            # deaths are infra faults, see DEFAULT_MAX_ATTEMPTS)
            for rid in leases:
                q.record_attempt(rid, "lease_expired", batch_id=batch_id,
                                 run_dir=q.batch_dir(batch_id),
                                 kind="reclaim")
            members = [by_id[r] for r in rids_all]
            batch = _planner._batch_view(members, n_devices)
            batch["batch_id"] = batch_id  # preserve the recorded run dir
            return batch, leases, members

    # 1b) pinned compositions (bisection halves): claimed EXACTLY as
    # pinned, bypassing the planner — a just-bisected suspect must never be
    # re-merged with healthy tenants. The pin is consumed at claim time;
    # from then on the lease records carry the composition (so a worker
    # dying mid-half lands back in the reclaim path above)
    pinned = q.pinned_batches()
    pinned_ids = {rid for p in pinned for rid in (p.get("requests") or ())}
    for pin in pinned:
        batch_id = pin["batch_id"]
        # deadline-aware preemption (ISSUE 15): a preempted composition is
        # pinned WITH the beneficiary it yielded the mesh to — defer
        # claiming it while that request is still waiting (no terminal
        # record, no live lease), so this cycle falls through to fresh
        # planning and serves the beneficiary first. Once it is being
        # served (live lease elsewhere) or settled, the pin resumes the
        # preempted fit from its checkpoint in the same run dir
        after = pin.get("after_request")
        if after and after in by_id and not q.is_terminal(after):
            lease = q.lease_of(after)
            if lease is None \
                    or float(lease.get("expires_at") or 0.0) <= now:
                continue
        rids_all = [r for r in pin["requests"] if r in by_id]
        claimable = [r for r in rids_all if not q.is_terminal(r)]
        if not claimable:
            q.unpin_batch(batch_id)  # everyone settled elsewhere
            continue
        if claimable != rids_all:
            # a member settled elsewhere (canceled/dead-lettered) between
            # pin and claim: its points must NOT ride back into the fit —
            # unlike a RECLAIM there is no checkpoint fingerprint to
            # preserve here, so re-key the half to the surviving
            # composition (same content-derived lane seeds, so any prior
            # run of this exact composition still resumes cleanly)
            new_id = _planner.batch_id_for(claimable)
            # a re-keyed pin keeps its preemption-beneficiary deferral:
            # dropping after_request here would let the preempted batch
            # jump ahead of the tenant it yielded the mesh to
            q.pin_batch(new_id, claimable,
                        parent_batch_id=pin.get("parent_batch_id"),
                        after_request=pin.get("after_request"))
            q.unpin_batch(batch_id)
            batch_id, rids_all = new_id, claimable
        leases = _claim_batch(q, worker_id, lease_s, batch_id, claimable,
                              by_id, logger, all_ids=rids_all)
        if leases:
            q.unpin_batch(batch_id)
            members = [by_id[r] for r in rids_all]
            batch = _planner._batch_view(members, n_devices)
            batch["batch_id"] = batch_id
            return batch, leases, members

    # 2) fresh admission plan over the pending queue (derived from the one
    # spool scan above: non-terminal, no live lease, not pinned, submission
    # order), with prior-failure suspects quarantined into solo batches
    now = time.time()
    pending, suspects = [], set()
    for rid, rec in by_id.items():
        if rid in pinned_ids or q.is_terminal(rid):
            continue
        lease = q.lease_of(rid)
        if lease is not None and float(lease.get("expires_at") or 0.0) > now:
            continue
        pending.append(rec)
        att = q.attempt_record(rid)
        if att and (int(att.get("attempts") or 0) > 0
                    or att.get("suspect")):
            # prior failed attempts, or a requeued dead-letter (fresh
            # budget but still a suspect until it proves clean)
            suspects.add(rid)
    if not pending:
        return None
    # degraded-QoS ladder (ISSUE 16): apply any durable per-tenant demotion
    # rung (fleet/autoscale.py, <root>/qos/<tenant>.json) to the FRESH
    # admission population only. A demoted spec no longer shares a
    # planner.batch_key with undemoted work, so un-breached co-tenants'
    # batches are bit-identical with the ladder active or not. The reclaim
    # and pinned paths above deliberately bypass this: their compositions
    # must resume the exact spec their grid checkpoint was fitted under
    qos_rungs = _autoscale.active_qos(q.root)
    if qos_rungs:
        pending = [_autoscale.apply_qos(rec, qos_rungs) for rec in pending]
    pend_map = {r["request_id"]: r for r in pending}
    t0 = time.perf_counter()
    cost_model = _costmodel.load()
    pl = _planner.plan(pending, n_devices=n_devices,
                       budget_bytes=budget_bytes,
                       cost_model=cost_model, max_bucket=max_bucket,
                       suspects=suspects, tenant_slots=tenant_slots,
                       inflight_slots=inflight_slots)
    if plan_out is not None:
        plan_out["packing"] = pl.get("packing")
        plan_out["quota_deferred"] = pl.get("quota_deferred") or []
    record_span("fleet.plan", (time.perf_counter() - t0) * 1e3,
                component="fleet", logger=logger, emit=True,
                queue_depth=pl["queue_depth"], batches=len(pl["batches"]))
    logger.log("fleet", kind="plan", queue_depth=pl["queue_depth"],
               batches=len(pl["batches"]),
               unschedulable=len(pl["unschedulable"]),
               quota_deferred=(pl.get("quota_deferred") or None),
               plan_ms=pl["plan_ms"],
               suspects=sorted(suspects),
               utilization_pct=pl["utilization"]["utilization_pct"],
               decisions=[{k: b.get(k) for k in
                           ("batch_id", "requests", "tenants", "n_points",
                            "g_bucket", "predicted_bytes", "eta_s",
                            "priority", "suspect")}
                          for b in pl["batches"][:8]],
               worker=worker_id)
    batches = pl["batches"]
    if predictive and cost_model is not None and len(batches) > 1:
        batches = _cold_compile_order(batches, logger, worker_id)
    for b in batches:
        rids = [r for r in b["requests"]
                if r in by_id and not q.is_terminal(r)]
        if not rids:
            continue
        if rids != b["requests"]:
            # a member settled (e.g. canceled) between planning and this
            # claim: its points must not ride into the fit — rebuild the
            # batch from the survivors (fresh id, fresh run dir; same
            # content-derived lane seeds, so results are unchanged)
            b = _planner._batch_view([pend_map[r] for r in rids
                                      if r in pend_map], n_devices)
        leases = _claim_batch(q, worker_id, lease_s, b["batch_id"],
                              b["requests"], by_id, logger)
        if leases:
            # the merge decision that actually claimed work becomes a
            # durable `planned` lifecycle event (the decisions that were
            # merely proposed this cycle re-plan next cycle — recording
            # them all every poll would spam the ledger)
            _history.append_event(
                q.root, "planned", batch_id=b["batch_id"],
                requests=b["requests"], trace_ids=b.get("trace_ids"),
                n_points=b["n_points"], g_bucket=b["g_bucket"],
                worker=worker_id)
            # members come from the QoS-transformed map: the demoted spec
            # (and its "qos" stamp) is what rides into batch.json and the
            # supervised fit
            members = [pend_map.get(r) or by_id[r]
                       for r in b["requests"] if r in by_id]
            return b, leases, members
    return None


def _cold_compile_order(batches, logger, worker_id):
    """Cold-compile claim ordering (ISSUE 15 tentpole, the worker's half of
    warming the compile cache on the critical path): within the plan's
    LEADING urgency class — the prefix of batches sharing the head's
    (priority, deadline) — claim the batch whose first-touch program is the
    LONGEST predicted cold compile first. Whoever claims it starts XLA on
    the fleet's most expensive missing executable immediately (overlapped
    with that fit's own prefetch/warmup, under the engine's op-scoped
    ``compile`` heartbeat excuse), so sibling workers and every later batch
    of the same family hit the shared persistent cache warm. Warm and
    unpriceable batches keep their urgency order after the cold group —
    ordering is pure decision math in parallel/policy.py ``compile_order``
    over the batch views' ``cold_compile_ms`` (priced ONCE at plan time,
    the single source of truth); urgency classes are never crossed."""
    head = batches[0]
    hkey = (head.get("priority"), head.get("deadline_s"))
    n = 0
    for b in batches:
        if (b.get("priority"), b.get("deadline_s")) != hkey:
            break
        n += 1
    if n <= 1:
        return batches
    order = PredictiveSchedulingPolicy.compile_order(batches[:n])
    if order == list(range(n)):
        return batches
    logger.log("policy", kind="compile_order",
               order=[batches[i]["batch_id"] for i in order],
               worker=worker_id)
    return [batches[i] for i in order] + batches[n:]


class _PreemptMonitor:
    """Deadline-aware preemption (ISSUE 15 tentpole): while a supervised
    batch runs, periodically price every queued HIGHER-priority tenant
    with a deadline against the running batch — would its deadline be
    missed if we wait, and met if we checkpoint-and-yield now? Preempt only
    when BOTH predictions exist and both answers are yes: a preemption is
    never triggered on a guess (no usable cost-model prior on either side
    means hold, mirroring the policy's bit-identical fallback contract).

    Mechanics ride machinery that already exists end to end: the SIGTERM
    lands on the supervised ``run_batch`` child, whose PreemptionGuard
    (PR 1) drains the in-flight epoch, writes a final checkpoint, and exits
    ``EXIT_PREEMPTED``; ``supervise``'s ``should_stop`` hook turns that
    into a stop instead of a restart; the settle path releases the leases
    as ZERO-CHARGE reclaims (PR 11 attempt budgets untouched — a preemption
    is a reclaim, never a failure) and pins the exact composition with
    ``after_request`` so the beneficiary claims the mesh first and the
    preempted fit then resumes bit-identically from its checkpoint in the
    same run dir (PR 10 lease/pin paths). The signal is gated on the
    batch's first durable grid checkpoint: before it exists the child's
    guard may not be installed and there is nothing to resume from.

    Remaining-work estimate for the running batch: the fit's own newest
    ``cost_model`` ETA (metrics tail beside the batch ledger — the PR 8
    scoring events), else the store-level ``predict_fit_eta`` minus elapsed
    wall; the queued tenant's cost is the planner's own batch-view pricing,
    cold compile included. Every pricing lands as a ``policy`` event
    (kind=preempt_price, action=hold|preempt) and the signal as a
    ``preempt`` event — the ``obs watch`` fleet headline's source."""

    def __init__(self, q, batch, members, run_dir, logger, worker_id,
                 n_devices=1, grace_s=None, poll_s=None, now=None):
        self._q = q
        self._batch = batch
        self._members = members
        self._member_ids = {m["request_id"] for m in members}
        self._run_dir = run_dir
        self._logger = logger
        self._worker = worker_id
        self._n_devices = int(n_devices or 1)
        self._grace = float(grace_s if grace_s is not None else
                            os.environ.get(ENV_PREEMPT_GRACE,
                                           DEFAULT_PREEMPT_GRACE_S))
        self._poll = float(poll_s if poll_s is not None else
                           os.environ.get(ENV_PREEMPT_POLL,
                                          DEFAULT_PREEMPT_POLL_S))
        self._started = time.time() if now is None else now
        self._proc = None
        self._held = set()    # candidates already priced+logged as hold
        # poll-tick caches (the monitor runs for the whole batch lifetime):
        # the cost model re-parses only when the store file changes (the
        # watch.py (mtime, size)-signature pattern), and the queue rescan
        # is skipped while the spool is unchanged AND the last scan found
        # no candidate — the steady no-urgent-work state costs two stats.
        # The skip is bounded by _RESCAN_S: a candidate can also become
        # pending WITHOUT a spool write (another worker's lease on it
        # expires/releases), so a periodic full rescan backstops the
        # signature gate
        self._cm_sig = None
        self._cm = None
        self._spool_sig = ()
        self._had_candidates = False
        self._last_scan = 0.0
        self._errored = False
        self.requested = False
        self.decision = None
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-preempt-monitor")

    # full-queue rescan backstop cadence (see __init__): pending-set changes
    # that bypass the spool signature are picked up within this bound
    _RESCAN_S = 2.0

    # supervise() hooks -------------------------------------------------
    def on_spawn(self, proc):
        self._proc = proc

    def should_stop(self):
        return self.requested

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=5.0)

    # -------------------------------------------------------------------
    def _log(self, event, **kw):
        try:
            self._logger.log(event, **kw)
        except Exception:  # noqa: BLE001 — telemetry trouble must never
            pass           # take down the batch loop

    def _run(self):
        while not self._stop.wait(self._poll):
            if self.requested:
                return
            try:
                self._check(time.time())
            except Exception as e:  # noqa: BLE001 — pricing is advisory;
                if not self._errored:  # a bug here must not kill the batch
                    self._errored = True
                    self._log("policy", kind="preempt_price",
                              action="error", worker=self._worker,
                              reason=f"{type(e).__name__}: {e}")

    def _running_remaining_s(self, now, cost_model):
        """Predicted seconds until the RUNNING batch finishes: the fit's
        own newest cost_model ETA when THIS batch's telemetry has one
        (since_wall pins it to this batch — a stale dir never leaks an
        old attempt's eta), discounted by the event's age so a sparse
        check-window cadence cannot overstate remaining work by a whole
        window; else the store-level whole-fit prediction minus elapsed
        wall; None = no usable prior (never preempt on a guess)."""
        eta = latest_cost_model_eta(
            os.path.join(self._run_dir, "run_ledger.jsonl"),
            since_wall=self._started)
        if eta is not None and isinstance(eta.get("eta_s"), (int, float)):
            age = (max(now - eta["wall_time"], 0.0)
                   if isinstance(eta.get("wall_time"), (int, float))
                   else 0.0)
            return max(float(eta["eta_s"]) - age, 0.0)
        view = _planner._batch_view(self._members, self._n_devices,
                                    cost_model=cost_model)
        if view.get("eta_s") is None:
            return None
        return max(float(view["eta_s"]) - (now - self._started), 0.0)

    def _load_cost_model(self):
        path = _costmodel.store_path()
        if path is None:
            return None
        try:
            st = os.stat(path)
            sig = (st.st_mtime_ns, st.st_size)
        except OSError:
            sig = None
        if sig != self._cm_sig:
            self._cm_sig = sig
            self._cm = _costmodel.load() if sig is not None else None
        return self._cm

    def _check(self, now):
        spool_sig = None
        try:
            st = os.stat(self._q.spool_path)
            spool_sig = (st.st_mtime_ns, st.st_size)
        except OSError:
            pass
        if spool_sig == self._spool_sig and not self._had_candidates \
                and now - self._last_scan < self._RESCAN_S:
            return  # nothing new submitted, nobody was waiting last scan
        cost_model = self._load_cost_model()
        if cost_model is None:
            return
        self._spool_sig = spool_sig
        self._last_scan = now
        batch_pri = int(self._batch.get("priority") or 0)
        cands = [r for r in self._q.pending(now=now)
                 if r["request_id"] not in self._member_ids
                 and r.get("deadline_s") is not None
                 and int(r.get("priority") or 0) > batch_pri]
        self._had_candidates = bool(cands)
        if not cands:
            return
        run_rem = self._running_remaining_s(now, cost_model)
        if run_rem is None:
            return
        for r in sorted(cands, key=_planner._order_key):
            rid = r["request_id"]
            view = _planner._batch_view([r], self._n_devices,
                                        cost_model=cost_model)
            eta_r = view.get("eta_s")
            if eta_r is None:
                continue  # no prior for the tenant's shape: hold
            deadline_at = (float(r.get("submitted_at") or 0.0)
                           + float(r["deadline_s"]))
            miss_if_wait = now + run_rem + eta_r > deadline_at
            meets_if_preempt = now + self._grace + eta_r <= deadline_at
            fields = {
                "batch_id": self._batch["batch_id"],
                "queued_eta_s": round(float(eta_r), 3),
                "running_rem_s": round(run_rem, 3),
                "deadline_at": round(deadline_at, 3),
                "slack_s": round(deadline_at - now - eta_r, 3),
                "grace_s": self._grace,
                "priority": int(r.get("priority") or 0),
                "worker": self._worker,
            }
            if miss_if_wait and meets_if_preempt:
                # durable-state gate: without a checkpoint there is nothing
                # to resume and the child's guard may not be up yet — hold
                # this poll, the decision re-prices next tick
                if not os.path.exists(os.path.join(self._run_dir,
                                                   "grid_checkpoint.pkl")):
                    return
                proc = self._proc
                if proc is None or proc.poll() is not None:
                    return  # no live child to yield (racing an exit)
                self.decision = dict(fields, beneficiary=rid,
                                     tenant=str(r.get("tenant")))
                self.requested = True
                self._log("policy", kind="preempt_price", action="preempt",
                          request_id=rid, **fields)
                self._log("preempt", kind="signal", beneficiary=rid,
                          tenant=str(r.get("tenant")),
                          requests=sorted(self._member_ids),
                          run_dir=self._run_dir, **fields)
                try:
                    proc.terminate()
                except OSError:
                    pass
                return
            if rid not in self._held:
                # first hold pricing per candidate (not every poll): the
                # audit trail that the monitor SAW the tenant and why it
                # stayed its hand
                self._held.add(rid)
                self._log("policy", kind="preempt_price", action="hold",
                          request_id=rid,
                          reason=("meets_deadline" if not miss_if_wait
                                  else "missed_even_preempting"), **fields)


class _CancelWatch:
    """Sub-mesh slot cancellation (ISSUE 18 satellite, extending the PR-11
    cancel/requeue tombstones to packed batches): while a gang-scheduled
    batch runs, poll the queue for member cancellation; once EVERY member
    is terminal (canceled/requeued elsewhere — first-writer-wins terminal
    records), SIGTERM the supervised child so its PreemptionGuard drains
    the in-flight epoch, checkpoints, and exits at the next check-window
    boundary. The settle path then just releases the (already-terminal)
    leases and the gang loop re-offers the freed slot to the queue — the
    surviving co-tenant's fit is a separate process on a disjoint sub-mesh
    and is never touched (bit-identity pinned by tests/test_packing.py)."""

    def __init__(self, q, members, logger, worker_id, poll_s=None):
        self._q = q
        self._member_ids = sorted(m["request_id"] for m in members)
        self._logger = logger
        self._worker = worker_id
        self._poll = float(poll_s if poll_s is not None else
                           os.environ.get(ENV_PREEMPT_POLL,
                                          DEFAULT_PREEMPT_POLL_S))
        self._proc = None
        self.requested = False
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-cancel-watch")

    # supervise() hooks -------------------------------------------------
    def on_spawn(self, proc):
        self._proc = proc

    def should_stop(self):
        return self.requested

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=5.0)

    def _run(self):
        while not self._stop.wait(self._poll):
            if self.requested:
                return
            try:
                if not all(self._q.is_terminal(rid)
                           for rid in self._member_ids):
                    continue
            except Exception:  # noqa: BLE001 — the watch is advisory;
                continue       # queue I/O trouble must not kill the batch
            self.requested = True
            try:
                self._logger.log("packing", kind="cancel_stop",
                                 requests=self._member_ids,
                                 worker=self._worker)
            except Exception:  # noqa: BLE001 — telemetry best-effort
                pass
            proc = self._proc
            if proc is not None and proc.poll() is None:
                try:
                    proc.terminate()
                except OSError:
                    pass
            return


class _LeaseHeartbeat:
    """Renews a batch's leases every ``lease_s / 3`` seconds while the
    supervised fit runs; a lost lease (reclaimed by another worker after an
    expiry we slept through) stops renewals and is surfaced to the caller
    so it will not publish results it no longer owns.

    Renewal ERRORS are not silent: each miss logs a structured ``fleet``
    event with the error kind, and ``max_renew_misses`` CONSECUTIVE misses
    on one lease escalate to lease-lost handling — after that many failed
    renewals we can no longer prove the on-disk lease is ours (it may have
    expired and been reclaimed behind the unreadable filesystem), so
    publishing results would race the new owner."""

    def __init__(self, leases, lease_s, logger, max_renew_misses=3):
        self._leases = leases
        self._lease_s = float(lease_s)
        self._logger = logger
        self._max_misses = max(int(max_renew_misses), 1)
        self._misses = {}
        self._stop = threading.Event()
        self.lost = []
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-lease-heartbeat")

    def _log(self, **kw):
        try:
            self._logger.log("fleet", **kw)
        except Exception:  # noqa: BLE001 — the same fs trouble that broke
            pass           # the renewal must not kill the heartbeat thread

    def _run(self):
        period = max(self._lease_s / 3.0, 0.05)
        while not self._stop.wait(period):
            for rid, lease in list(self._leases.items()):
                try:
                    lease.renew(self._lease_s)
                except LeaseLost:
                    self.lost.append(rid)
                    self._leases.pop(rid, None)
                    self._misses.pop(rid, None)
                    self._log(kind="lease_lost", requests=[rid])
                except OSError as e:
                    n = self._misses.get(rid, 0) + 1
                    self._misses[rid] = n
                    self._log(kind="renew_error", requests=[rid],
                              consecutive=n,
                              error=f"{type(e).__name__}: {e}")
                    if n >= self._max_misses:
                        self.lost.append(rid)
                        self._leases.pop(rid, None)
                        self._misses.pop(rid, None)
                        self._log(kind="lease_lost", requests=[rid],
                                  consecutive=n,
                                  error="renewal misses exhausted")
                else:
                    self._misses.pop(rid, None)

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=self._lease_s)


def run_one_batch(q, batch, leases, members, logger, worker_id,
                  lease_s=60.0, checkpoint_every=1, supervisor_policy=None,
                  env=None, python=None,
                  max_attempts=DEFAULT_MAX_ATTEMPTS, n_devices=1,
                  predictive=None, preempt_monitor=None, slot=None,
                  cancel_watch=None):
    """Run one claimed batch under the crash-loop supervisor and settle its
    requests (containment discipline — see the module docstring); returns
    the :class:`~redcliff_tpu.runtime.supervisor.SuperviseOutcome`.

    ``predictive`` (None = the ``REDCLIFF_PREDICTIVE`` env gate) arms the
    deadline-aware preemption monitor; ``preempt_monitor`` injects a
    pre-built monitor (tests).

    ``slot`` (``{"lo", "width"}``, ISSUE 18): the sub-mesh device interval
    a PACKED worker assigned this batch — recorded in batch.json so the
    supervised child meshes over exactly those devices and a reclaim
    resumes in the SAME slot; ``cancel_watch`` arms the gang-scheduling
    cancel hook (:class:`_CancelWatch`).

    The batch runs under its TRACE CONTEXT (batch id + each member's
    submit-minted trace id): set process-wide (thread-scoped inside a
    packed worker's gang threads) for the worker's own spans and fleet
    events, exported into the supervised run_batch child via
    ``REDCLIFF_TRACE_CTX`` (so every record the jax child writes carries
    the same join keys), and scoped — restored on every exit path."""
    ctx = _trace_context(batch["batch_id"], members)
    prev_ctx = _spans.set_trace_ctx(ctx)
    try:
        return _run_one_batch(q, batch, leases, members, logger, worker_id,
                              ctx, lease_s=lease_s,
                              checkpoint_every=checkpoint_every,
                              supervisor_policy=supervisor_policy, env=env,
                              python=python, max_attempts=max_attempts,
                              n_devices=n_devices, predictive=predictive,
                              preempt_monitor=preempt_monitor, slot=slot,
                              cancel_watch=cancel_watch)
    finally:
        _spans.set_trace_ctx(prev_ctx)


def _run_one_batch(q, batch, leases, members, logger, worker_id, trace_ctx,
                   lease_s=60.0, checkpoint_every=1, supervisor_policy=None,
                   env=None, python=None,
                   max_attempts=DEFAULT_MAX_ATTEMPTS, n_devices=1,
                   predictive=None, preempt_monitor=None, slot=None,
                   cancel_watch=None):
    batch_id = batch["batch_id"]
    run_dir = q.batch_dir(batch_id)
    os.makedirs(run_dir, exist_ok=True)
    batch_file = os.path.join(run_dir, "batch.json")
    if not os.path.exists(batch_file):
        # deterministic from the claimed composition: a reclaiming worker
        # that finds the file missing (claimant died pre-write) rebuilds
        # the identical content from the lease-recorded member order
        tmp = f"{batch_file}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            # g_bucket: the planner-ADMITTED width (deterministic from the
            # composition, so a reclaiming worker rebuilds it identically);
            # run_batch exports it as the predictive policy's widening
            # ceiling — the HBM admission gate priced THIS width. slot:
            # the packed worker's sub-mesh assignment — durable here (not
            # in the lease) so a SIGKILLed packing resumes every batch in
            # its ORIGINAL slot
            json.dump({"batch_id": batch_id, "run_dir": run_dir,
                       "checkpoint_every": int(checkpoint_every),
                       "g_bucket": batch.get("g_bucket"),
                       "slot": slot,
                       "requests": members}, f, allow_nan=False)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, batch_file)
    # tenant stamping into the supervisor ledger: the manifest row set the
    # per-tenant report section joins on (run_batch logs the same manifest
    # as a metrics event inside the run dir)
    ledger_path = os.path.join(run_dir, "run_ledger.jsonl")
    with open(ledger_path, "a") as f:
        f.write(json.dumps({"event": "fleet", "kind": "manifest",
                            "batch_id": batch_id, "worker": worker_id,
                            "requests": _manifest_rows(members)}) + "\n")
    logger.log("fleet", kind="batch_start", batch_id=batch_id,
               run_dir=run_dir, requests=batch["requests"],
               tenants=batch["tenants"], n_points=batch["n_points"],
               g_bucket=batch["g_bucket"], eta_s=batch.get("eta_s"),
               predicted_bytes=batch.get("predicted_bytes"),
               slot=slot, worker=worker_id)
    cmd = [python or sys.executable, "-m", "redcliff_tpu.fleet.run_batch",
           batch_file]
    # the trace context crosses the process boundary as env: the jax child
    # (and any grand-children the supervisor restarts) stamps every span
    # and metrics record with the same batch/request join keys
    child_env = dict(env if env is not None else os.environ)
    child_env[_spans.ENV_TRACE_CTX] = json.dumps(trace_ctx)
    started_at = time.time()
    t0 = time.perf_counter()
    # deadline-aware preemption monitor (ISSUE 15): armed by the
    # REDCLIFF_PREDICTIVE gate (or injected by tests); inert when off —
    # supervise runs exactly as before
    monitor = preempt_monitor
    if monitor is None and (predictive if predictive is not None
                            else predictive_enabled()):
        monitor = _PreemptMonitor(q, batch, members, run_dir, logger,
                                  worker_id, n_devices=n_devices,
                                  now=started_at)
    # supervise() hook composition: the preempt monitor and the packed
    # cancel watch each SIGTERM the child themselves; either one asking is
    # a stop, not a restart
    hooks = [h for h in (monitor, cancel_watch) if h is not None]
    on_spawn = ((lambda proc: [h.on_spawn(proc) for h in hooks])
                if hooks else None)
    should_stop = ((lambda: any(h.should_stop() for h in hooks))
                   if hooks else None)
    with _LeaseHeartbeat(leases, lease_s, logger) as hb, \
            (monitor if monitor is not None else contextlib.nullcontext()), \
            (cancel_watch if cancel_watch is not None
             else contextlib.nullcontext()):
        outcome = supervise(
            cmd, ledger_path=ledger_path,
            policy=supervisor_policy or SupervisorPolicy(max_restarts=2),
            env=child_env,
            on_spawn=on_spawn,
            should_stop=should_stop)
    dur_ms = (time.perf_counter() - t0) * 1e3
    record_span("fleet.batch", dur_ms, component="fleet", logger=logger,
                emit=True, batch_id=batch_id,
                classification=outcome.classification)

    lost = set(hb.lost)
    settled = {"done": [], "failed": [], "released": [], "deadletter": [],
               "bisected": [], "preempted": [], "lost": sorted(lost)}
    cls = outcome.classification
    live = [(rid, leases[rid]) for rid in leases if rid not in lost]

    def member_of(rid):
        return next((m for m in members if m["request_id"] == rid), {})

    def trace_of(rid):
        return member_of(rid).get("trace_id")

    # one durable `attempt` lifecycle transition per still-owned member:
    # when the supervised run STARTED (the SLO layer's time-to-first-
    # attempt endpoint), how it classified, and how many supervisor
    # attempts it burned. Lost leases are the new owner's story to record.
    for rid, _lease in live:
        _history.append_event(
            q.root, "attempt", request_id=rid, trace_id=trace_of(rid),
            batch_id=batch_id, tenant=member_of(rid).get("tenant"),
            worker=worker_id, classification=cls,
            attempts=len(outcome.attempts), started_at=started_at,
            run_dir=run_dir)

    def send_to_deadletter(rid, att, reason, causes=None):
        rec = member_of(rid)
        q.deadletter(rid, dossier=_dossier(rec, att, reason, run_dir,
                                           causes=causes),
                     trace_id=trace_of(rid))
        settled["deadletter"].append(rid)
        logger.log("fleet", kind="deadletter", batch_id=batch_id,
                   requests=[rid], tenants=[str(rec.get("tenant"))],
                   reason=reason, attempts=(att or {}).get("attempts"),
                   run_dir=run_dir, worker=worker_id)

    if cls == "clean":
        for rid, lease in live:
            rec = member_of(rid)
            result = _read_result(run_dir, rid)
            if result is None:
                # clean exit, no per-request artifact (should not happen):
                # a durability bug, not a verdict — retry on the budget,
                # dead-letter when it is spent (never a stub "done")
                att = q.record_attempt(rid, "missing_result",
                                       batch_id=batch_id, run_dir=run_dir)
                if att["attempts"] >= max_attempts:
                    send_to_deadletter(rid, att, "missing_result")
                else:
                    lease.release()
                    settled["released"].append(rid)
                continue
            causes = _poison_causes(result)
            if causes is not None:
                # attribution: the grid engine quarantined EVERY point of
                # this request (deterministic per-lane causes) — the poison
                # tenant is contained without touching its siblings
                att = q.record_attempt(rid, "poison_quarantine",
                                       batch_id=batch_id, run_dir=run_dir)
                send_to_deadletter(rid, att, "poison_quarantine",
                                   causes=causes)
                continue
            q.complete(rid, result=result, trace_id=trace_of(rid))
            settled["done"].append(rid)
            logger.log("fleet", kind="complete", batch_id=batch_id,
                       requests=[rid], tenants=[str(rec.get("tenant"))],
                       worker=worker_id)
    elif cancel_watch is not None and cancel_watch.requested:
        # packed-slot cancellation settle (ISSUE 18 satellite): the child
        # was stopped because EVERY member is already terminal (canceled /
        # settled elsewhere — their tombstones are the verdict). Nothing to
        # charge, nothing to pin: release whatever leases are still ours
        # and let the gang loop re-offer the freed slot to the queue
        for rid, lease in live:
            lease.release()
            settled["released"].append(rid)
        logger.log("packing", kind="slot_canceled", batch_id=batch_id,
                   requests=[rid for rid, _ in live], slot=slot,
                   worker=worker_id)
    elif monitor is not None and monitor.requested:
        # deadline-aware preemption settle (ISSUE 15): the batch stopped
        # because THIS worker asked it to yield — whatever the exact exit
        # class (normally `preempted`; `signal` if the SIGTERM landed in a
        # pre-guard window), it is a RECLAIM, never a charged failure:
        # attempts record kind="reclaim" (dossier evidence, budget
        # untouched — PR 11), the leases release cleanly, and the exact
        # composition is pinned with the beneficiary so the mesh serves the
        # deadline tenant first and this fit then resumes bit-identically
        # from its checkpoint in the same run dir
        rids_all = [m["request_id"] for m in members]
        beneficiary = (monitor.decision or {}).get("beneficiary")
        for rid, lease in live:
            q.record_attempt(rid, "preempted", batch_id=batch_id,
                             run_dir=run_dir, kind="reclaim")
            lease.release()
            settled["preempted"].append(rid)
        q.pin_batch(batch_id, rids_all, after_request=beneficiary)
        logger.log("preempt", kind="preempted", batch_id=batch_id,
                   requests=rids_all, tenants=batch.get("tenants"),
                   beneficiary=beneficiary, run_dir=run_dir,
                   worker=worker_id)
        _history.append_event(
            q.root, "preempted", batch_id=batch_id, requests=rids_all,
            trace_ids={rid: trace_of(rid) for rid in rids_all
                       if trace_of(rid)},
            beneficiary=beneficiary, worker=worker_id)
    elif cls in TERMINAL_FAIL_CLASSES and len(live) > 1:
        # terminal failure of a MERGED batch with no per-lane attribution:
        # never blame every member — bisect, so halving corners the poison
        # while healthy siblings still finish (as pinned compositions the
        # planner cannot re-merge)
        _bisect(q, batch_id, run_dir, cls, live, member_of, settled,
                logger, worker_id)
    elif cls in TERMINAL_FAIL_CLASSES and len(members) == 1:
        # genuinely SOLO composition: the verdict is attributable to this
        # request alone
        for rid, lease in live:
            att = q.record_attempt(rid, cls, batch_id=batch_id,
                                   run_dir=run_dir)
            if cls in DETERMINISTIC_FAIL_CLASSES:
                q.fail(rid, cls, trace_id=trace_of(rid))
                settled["failed"].append(rid)
            elif att["attempts"] >= max_attempts:
                # a solo crash/hang loop (giving_up) past its budget
                send_to_deadletter(rid, att, "crash_loop")
            else:
                lease.release()
                settled["released"].append(rid)
    elif cls in TERMINAL_FAIL_CLASSES:
        # MERGED composition but at most one lease is still ours (the rest
        # were lost or already terminal): the batch the child ran still
        # carried co-tenants' lanes, so the terminal class cannot be
        # pinned on the lone survivor — it may be a healthy co-tenant of
        # the real poison. Budget-route instead of issuing a verdict; the
        # dossier reason keeps the recorded class (`merged_<class>`) so an
        # operator never misreads a deterministic deadline/numerics death
        # as an infra crash loop
        for rid, lease in live:
            att = q.record_attempt(rid, cls, batch_id=batch_id,
                                   run_dir=run_dir)
            if att["attempts"] >= max_attempts:
                send_to_deadletter(rid, att,
                                   "crash_loop" if cls == "giving_up"
                                   else f"merged_{cls}")
            else:
                lease.release()
                settled["released"].append(rid)
    else:
        for rid, lease in live:
            lease.release()
            settled["released"].append(rid)
    logger.log("fleet", kind="batch_end", batch_id=batch_id,
               classification=outcome.classification, rc=outcome.returncode,
               attempts=len(outcome.attempts),
               wall_s=round(dur_ms / 1e3, 3),
               done=len(settled["done"]), failed=len(settled["failed"]),
               released=len(settled["released"]),
               deadlettered=len(settled["deadletter"]),
               bisected=len(settled["bisected"]),
               preempted=len(settled["preempted"]), worker=worker_id)
    return outcome


def _bisect(q, batch_id, run_dir, classification, live, member_of, settled,
            logger, worker_id):
    """Split a blind-failed merged batch into two pinned halves (claim
    order) and release the leases: the next claim cycles — this worker's or
    any other's — run the halves as exact compositions. Each member's
    durable attempt ledger is charged one failure (the classification the
    batch died with), so the eventual solo culprit carries its history."""
    rids = [rid for rid, _ in live]
    mid = (len(rids) + 1) // 2
    halves = []
    for ids in (rids[:mid], rids[mid:]):
        half_id = _planner.batch_id_for(ids)
        q.pin_batch(half_id, ids, parent_batch_id=batch_id)
        halves.append({"batch_id": half_id, "requests": ids})
    for rid, lease in live:
        q.record_attempt(rid, classification, batch_id=batch_id,
                         run_dir=run_dir)
        lease.release()
        settled["bisected"].append(rid)
    logger.log("fleet", kind="bisect", batch_id=batch_id, requests=rids,
               classification=classification, halves=halves,
               worker=worker_id)
    # the bisection round stays on each member's lifecycle timeline: the
    # halves' batch ids link the pinned re-runs back to the same traces
    _history.append_event(
        q.root, "bisected", batch_id=batch_id, requests=rids,
        trace_ids={rid: member_of(rid).get("trace_id") for rid in rids
                   if member_of(rid).get("trace_id")},
        halves=[h["batch_id"] for h in halves],
        classification=classification, worker=worker_id)


# quarantine causes that are a DETERMINISTIC verdict on the point itself
# (a replay provably diverges again). deadline is deliberately absent:
# eviction at a wall-clock budget depends on how loaded the host was, so a
# fully-deadline-evicted request completes done-with-failures, not poison
_POISON_CAUSES = ("nonfinite_grad", "nonfinite_val")


def _poison_causes(result):
    """The per-cause quarantine counts when EVERY point of this request was
    quarantined by the grid engine for a deterministic-numerics cause (the
    poison-attribution signal), else None. A partial quarantine — or any
    wall-clock-dependent cause like ``deadline`` — is normal sweep behavior
    and completes as done with the failures recorded."""
    n = result.get("n_points") or 0
    fails = result.get("failures") or []
    points = {f.get("point") for f in fails
              if isinstance(f.get("point"), int)}
    if not n or len(points) < n:
        return None
    causes = {}
    for f in fails:
        cause = str(f.get("cause") or "?")
        causes[cause] = causes.get(cause, 0) + 1
    if any(c not in _POISON_CAUSES for c in causes):
        return None
    return causes


def _dossier(rec, att, reason, run_dir, causes=None):
    """The dead-letter failure dossier: everything an operator needs to
    judge the request without spelunking run dirs — attempt/classification
    history, the run dirs it burned, and any crash flight records they
    hold."""
    att = att or {}
    history = att.get("history") or []
    run_dirs = sorted({h.get("run_dir") for h in history
                       if h.get("run_dir")} | {run_dir})
    flights = []
    for d in run_dirs:
        flights.extend(sorted(
            glob.glob(os.path.join(d, "flight_record*.json"))))
    return {
        "request_id": rec.get("request_id"),
        "tenant": str(rec.get("tenant")),
        "reason": reason,
        "attempts": int(att.get("attempts") or 0),
        "reclaims": int(att.get("reclaims") or 0),
        "classifications": [h.get("classification") for h in history],
        "last_classification": (att.get("last") or {}).get("classification"),
        "run_dirs": run_dirs,
        "flight_records": flights,
        "quarantine_causes": causes,
    }


def _read_result(run_dir, request_id):
    """The per-request result record, or None when the clean-exited child
    left no artifact — the caller routes that through the retry budget
    instead of recording a stub done."""
    path = os.path.join(run_dir, "results", f"{request_id}.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def work(root, worker_id=None, lease_s=60.0, poll_s=2.0, max_batches=None,
         drain=False, once=False, n_devices=1, budget_bytes=None,
         max_bucket=_planner.DEFAULT_MAX_BUCKET, checkpoint_every=1,
         supervisor_policy=None, env=None, python=None,
         max_attempts=DEFAULT_MAX_ATTEMPTS, predictive=None, packing=None):
    """The worker loop; returns the number of batches run.

    ``drain``: exit once the queue holds no claimable or running work.
    ``once``: run at most one claim cycle. ``max_batches`` bounds the run.
    ``budget_bytes``: the admission HBM budget (``check_headroom``'s
    ``budget_bytes`` on the serving mesh; None = ungated, e.g. this CPU
    container). ``max_attempts``: the per-request retry budget (failure
    attempts before a request is dead-lettered). ``predictive`` (None =
    the ``REDCLIFF_PREDICTIVE`` env gate) arms the cold-compile claim
    ordering and the deadline-aware preemption monitor (ISSUE 15).

    ``packing`` (ISSUE 18, None = the ``REDCLIFF_FLEET_PACKING`` env gate;
    True = ``"force"``, or a mode string): spatial multi-tenant mesh
    packing — with 2+ devices the worker gang-schedules CONCURRENT batches
    on disjoint sub-mesh slots (:func:`_work_packed`). ``"auto"`` packs
    only when the planner's priced makespan beats serial (empty cost store
    = the serial loop, bit-identical); ``"force"`` always packs."""
    q = FleetQueue(root)
    worker_id = worker_id or default_worker_id()
    predictive = (predictive_enabled() if predictive is None
                  else bool(predictive))
    if packing is None:
        pack_mode = _packing.packing_mode()
    elif isinstance(packing, str):
        pack_mode = _packing.packing_mode(env=packing)
    else:
        pack_mode = "force" if packing else "off"
    if pack_mode != "off" and int(n_devices or 1) >= 2:
        return _work_packed(q, worker_id=worker_id, lease_s=lease_s,
                            poll_s=poll_s, max_batches=max_batches,
                            drain=drain, once=once, n_devices=n_devices,
                            budget_bytes=budget_bytes,
                            max_bucket=max_bucket,
                            checkpoint_every=checkpoint_every,
                            supervisor_policy=supervisor_policy, env=env,
                            python=python, max_attempts=max_attempts,
                            predictive=predictive, mode=pack_mode)
    batches_run = 0
    with _logger(root) as logger:
        logger.log("fleet", kind="worker_start", worker=worker_id,
                   n_devices=n_devices, budget_bytes=budget_bytes,
                   lease_s=lease_s)
        try:
            while True:
                got = _next_batch(q, worker_id, lease_s, n_devices,
                                  budget_bytes, max_bucket, logger,
                                  predictive=predictive)
                if got is not None:
                    batch, leases, members = got
                    run_one_batch(q, batch, leases, members, logger,
                                  worker_id, lease_s=lease_s,
                                  checkpoint_every=checkpoint_every,
                                  supervisor_policy=supervisor_policy,
                                  env=env, python=python,
                                  max_attempts=max_attempts,
                                  n_devices=n_devices,
                                  predictive=predictive)
                    batches_run += 1
                    if max_batches is not None \
                            and batches_run >= max_batches:
                        break
                    if once:
                        break
                    continue
                if once:
                    break
                # drain: nothing is claimable right now (_next_batch came
                # back empty — the queue is empty OR holds only
                # unschedulable requests the planner can never admit) and
                # nothing is in flight anywhere whose completion/expiry
                # could change that
                if drain and not q.live_leases():
                    break
                time.sleep(poll_s)
        except Exception as e:
            # an uncaught worker-loop exception used to die without a
            # record: mirror the watchdog's escalation path — dump the
            # flight recorder (the worker's last spans/events) next to the
            # fleet root's metrics and emit a structured worker_crash
            # event, THEN re-raise so the exit code still says crash
            path = None
            try:
                path = _flight.dump(str(root), "worker_crash", extra={
                    "worker": worker_id,
                    "error": f"{type(e).__name__}: {e}"})
            except Exception:  # noqa: BLE001 — the dump must not mask
                pass           # the original crash
            try:
                logger.log("fleet", kind="worker_crash", worker=worker_id,
                           error=f"{type(e).__name__}: {e}",
                           flight_record=path, batches=batches_run)
            except Exception:  # noqa: BLE001 — same: the crash record is
                pass           # best-effort, the original exception wins
            raise
        logger.log("fleet", kind="worker_stop", worker=worker_id,
                   batches=batches_run)
    return batches_run


def _recorded_slot(q, batch_id):
    """The sub-mesh slot a batch's durable batch.json recorded, or None —
    the reclaim path's slot pin: a resumed packing lands every batch back
    in its original slot."""
    try:
        with open(os.path.join(q.batch_dir(batch_id), "batch.json")) as f:
            slot = (json.load(f) or {}).get("slot")
    except (OSError, ValueError):
        return None
    if isinstance(slot, dict) and isinstance(slot.get("lo"), int) \
            and isinstance(slot.get("width"), int):
        return {"lo": slot["lo"], "width": slot["width"]}
    return None


def _work_packed(q, worker_id, lease_s, poll_s, max_batches, drain, once,
                 n_devices, budget_bytes, max_bucket, checkpoint_every,
                 supervisor_policy, env, python, max_attempts, predictive,
                 mode):
    """The spatial-packing worker loop (ISSUE 18 tentpole): gang-schedule
    concurrent batches on disjoint sub-mesh slots of one device pool.

    Claims happen only in THIS thread (the planner/queue protocol is
    untouched); each claimed batch then runs :func:`run_one_batch` in its
    own gang thread — a separate supervised jax child on its own slot's
    devices, with its own lease heartbeat, preempt monitor, and cancel
    watch. Slot claims/frees happen only between supervised runs — i.e. at
    batch boundaries, which are check-window boundaries for the fits
    (checkpoint cadence) — so PR-15 preemption and PR-5 compaction compose
    without new synchronization. A freed slot is re-offered to the queue on
    the next claim poll.

    Co-residency discipline: the planner is consulted with the FREE slot
    width as its pool and the REMAINING HBM budget (pool budget minus live
    co-tenants' ``predicted_bytes``) as its gate, so an admitted batch
    satisfies the headroom model by construction — zero headroom
    violations. A running batch with no memory evidence blocks
    co-scheduling entirely while a budget is set (conservative, mirroring
    ``check_headroom``'s explicit-None degradation). In ``auto`` mode
    co-scheduling additionally requires the plan's priced packing verdict
    (``decision == "packed"``); an empty cost store prices nothing, so the
    loop degrades to one-batch-at-a-time — bit-identical to the serial
    worker's claims."""
    batches_run = 0
    slots = _packing.SlotTable(n_devices)
    tenant_slots = _planner.tenant_slot_quota()
    running = {}  # batch_id -> {"thread", "slot", "batch", "leases"}
    co_ok = (mode == "force")
    wave_started = False
    last_pub = None
    last_decision = None

    with _logger(q.root) as logger:
        logger.log("fleet", kind="worker_start", worker=worker_id,
                   n_devices=n_devices, budget_bytes=budget_bytes,
                   lease_s=lease_s, packing=mode)

        def publish():
            nonlocal last_pub
            occ = slots.occupancy()
            sig = (tuple((s["lo"], s["width"]) for s in occ["slots"]),
                   len(running))
            if sig == last_pub:
                return
            last_pub = sig
            try:
                _packing.publish_state(q.root, occ,
                                       concurrent_batches=len(running))
            except OSError:
                pass

        def reap():
            nonlocal batches_run
            for bid in list(running):
                st = running[bid]
                if st["thread"].is_alive():
                    continue
                st["thread"].join()
                slots.free(st["slot"])
                logger.log("packing", kind="slot_free", batch_id=bid,
                           slot=st["slot"], worker=worker_id)
                del running[bid]
                batches_run += 1

        def inflight_tenants():
            out = {}
            for st in running.values():
                for t in st["batch"].get("tenants") or ():
                    out[t] = out.get(t, 0) + 1
            return out

        def launch(batch, leases, members, slot):
            cw = _CancelWatch(q, members, logger, worker_id)

            def _target():
                try:
                    run_one_batch(
                        q, batch, leases, members, logger, worker_id,
                        lease_s=lease_s, checkpoint_every=checkpoint_every,
                        supervisor_policy=supervisor_policy, env=env,
                        python=python, max_attempts=max_attempts,
                        n_devices=slot["width"], predictive=predictive,
                        slot=slot, cancel_watch=cw)
                except Exception as e:  # noqa: BLE001 — a gang thread must
                    # never die silently: record the crash and release the
                    # leases so the composition is reclaimable (same story
                    # as a worker process death, minus the wait for expiry)
                    try:
                        logger.log("fleet", kind="worker_crash",
                                   worker=worker_id,
                                   error=f"{type(e).__name__}: {e}",
                                   batches=batches_run)
                    except Exception:  # noqa: BLE001
                        pass
                    for lease in leases.values():
                        try:
                            lease.release()
                        except Exception:  # noqa: BLE001 — lost/settled
                            pass

            t = threading.Thread(target=_target, daemon=True,
                                 name=f"fleet-gang-{batch['batch_id']}")
            running[batch["batch_id"]] = {"thread": t, "slot": slot,
                                          "batch": batch, "leases": leases}
            logger.log("packing", kind="slot_claim",
                       batch_id=batch["batch_id"], slot=slot,
                       requests=batch["requests"],
                       tenants=batch.get("tenants"),
                       predicted_bytes=batch.get("predicted_bytes"),
                       worker=worker_id)
            t.start()

        try:
            while True:
                reap()
                publish()
                free = slots.free_widths()
                cap_left = (max_batches is None
                            or batches_run + len(running) < max_batches)
                may_claim = (cap_left and bool(free)
                             and (not running or co_ok)
                             and not (once and wave_started
                                      and not running))
                claimed = False
                if may_claim:
                    eff_dev = free[0]
                    used = [st["batch"].get("predicted_bytes")
                            for st in running.values()]
                    if budget_bytes is None:
                        eff_budget = None
                    elif any(u is None for u in used):
                        eff_budget = 0  # no evidence: never co-resident
                    else:
                        eff_budget = budget_bytes - sum(used)
                    if eff_budget is None or eff_budget > 0:
                        plan_out = {}
                        got = _next_batch(
                            q, worker_id, lease_s, eff_dev, eff_budget,
                            max_bucket, logger, predictive=predictive,
                            tenant_slots=tenant_slots,
                            inflight_slots=inflight_tenants(),
                            plan_out=plan_out)
                        pk = plan_out.get("packing")
                        if pk is not None:
                            if mode == "auto":
                                co_ok = (pk.get("decision") == "packed")
                            dec = {k: pk.get(k) for k in
                                   ("decision", "reason", "makespan_s",
                                    "serial_s", "makespan_ratio",
                                    "n_devices", "pool",
                                    "headroom_violations")}
                            if dec != last_decision:
                                last_decision = dec
                                logger.log("packing", kind="plan",
                                           worker=worker_id, **dec)
                        if got is not None:
                            batch, leases, members = got
                            slot = None
                            recorded = _recorded_slot(q, batch["batch_id"])
                            if recorded is not None:
                                if slots.reserve(recorded):
                                    slot = recorded
                                else:
                                    # reclaim whose ORIGINAL slot is still
                                    # occupied: wait for it (release the
                                    # claims — zero-charge, the reclaim
                                    # attempt is already on the ledger)
                                    logger.log(
                                        "packing", kind="slot_wait",
                                        batch_id=batch["batch_id"],
                                        slot=recorded, worker=worker_id)
                            else:
                                slot = slots.alloc(_packing.devices_for(
                                    batch.get("g_bucket"), eff_dev))
                            if slot is None:
                                for lease in leases.values():
                                    try:
                                        lease.release()
                                    except Exception:  # noqa: BLE001
                                        pass
                            else:
                                wave_started = True
                                claimed = True
                                launch(batch, leases, members, slot)
                if claimed:
                    continue  # greedily fill remaining slots this poll
                if running:
                    time.sleep(min(poll_s, 0.2))
                    continue
                if once and wave_started:
                    break
                if max_batches is not None and batches_run >= max_batches:
                    break
                if once:
                    break
                if drain and not q.live_leases():
                    break
                time.sleep(poll_s)
        except Exception as e:
            path = None
            try:
                path = _flight.dump(str(q.root), "worker_crash", extra={
                    "worker": worker_id,
                    "error": f"{type(e).__name__}: {e}"})
            except Exception:  # noqa: BLE001 — the dump must not mask
                pass           # the original crash
            try:
                logger.log("fleet", kind="worker_crash", worker=worker_id,
                           error=f"{type(e).__name__}: {e}",
                           flight_record=path, batches=batches_run)
            except Exception:  # noqa: BLE001
                pass
            raise
        finally:
            publish()
        logger.log("fleet", kind="worker_stop", worker=worker_id,
                   batches=batches_run)
    return batches_run
