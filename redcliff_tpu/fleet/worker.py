"""Fleet worker loop: claim planned batches, supervise fits, mark results.

One worker = one long-lived control process on a host with accelerators::

    python -m redcliff_tpu.fleet work --root /fleet

Each cycle it (1) prefers RECLAIM work — expired leases whose recorded
batch composition it re-claims so the dead worker's grid fit resumes from
its durable checkpoint in the same ``work/<batch_id>`` run dir; then (2)
plans fresh admission over the pending queue (fleet/planner.py) and claims
the first admitted batch; then (3) runs the batch as a supervised child —
:func:`redcliff_tpu.runtime.supervisor.supervise` around ``python -m
redcliff_tpu.fleet.run_batch <batch.json>`` — so crashes, hangs, and
preemptions restart from checkpoint under the existing exit-code taxonomy,
while a background thread renews the members' leases on a cadence well
inside ``lease_s``.

Tenant stamping: before supervising, the worker appends a ``fleet``
manifest record (batch id + per-request tenant and merged point range) to
the batch's ``run_ledger.jsonl``; ``run_batch`` logs the same manifest as a
metrics event. ``obs report`` joins both into its per-tenant section, and
every planner/claim/batch transition lands as a schema-registered ``fleet``
event in the FLEET ROOT's ``metrics.jsonl`` (what ``obs watch <root>``
tails in fleet mode).

Settle discipline (blast-radius containment, docs/ARCHITECTURE.md "Fleet
failure containment"): a ``clean`` supervised outcome marks requests done
(first ``done/<id>.json`` writer wins — never run twice) — except a member
whose per-request artifact is missing (routed through the retry budget) or
whose EVERY point the grid engine quarantined for a deterministic-numerics
cause (the attribution path: the poison tenant is dead-lettered with its
quarantine causes while healthy co-tenants still complete; wall-clock
``deadline`` evictions never attribute). A terminal failure of a MERGED
batch is never blamed on its members: with 2+ live leases the batch is
split in half and the halves requeued as pinned compositions, so repeated
halving deterministically corners a poison request while its siblings
finish; with <=1 live lease (the rest lost or terminal) the survivor —
possibly a healthy co-tenant — is budget-routed, never verdicted. Only a
terminal failure of a genuinely SOLO composition is charged as that
request's own: deterministic classes fail it outright, a crash/hang loop
(``giving_up``) releases it against its durable retry budget (queue
``attempts/``) until the budget is spent, then routes it to ``deadletter/``
with a failure dossier. Anything non-terminal releases the leases so
another worker retries.

stdlib-only imports at module scope, and NEVER jax (obs/schema.py
``--check`` enforces it): the worker is a control process — the jax backend
initializes only inside the supervised ``run_batch`` child.
"""
from __future__ import annotations

import glob
import json
import os
import socket
import sys
import threading
import time
import uuid

from redcliff_tpu.obs import record_span
from redcliff_tpu.obs import costmodel as _costmodel
from redcliff_tpu.obs import flight as _flight
from redcliff_tpu.obs import spans as _spans
from redcliff_tpu.runtime.supervisor import SupervisorPolicy, supervise
from redcliff_tpu.fleet import history as _history
from redcliff_tpu.fleet import planner as _planner
from redcliff_tpu.fleet.queue import FleetQueue, LeaseLost

__all__ = ["work", "run_one_batch", "default_worker_id",
           "TERMINAL_FAIL_CLASSES", "DETERMINISTIC_FAIL_CLASSES",
           "DEFAULT_MAX_ATTEMPTS"]

# supervised outcomes a restart cannot fix: the batch will not be re-run
# as-is (solo requests are failed or budget-routed; merged batches bisect)
TERMINAL_FAIL_CLASSES = ("numerics_abort", "deadline", "giving_up",
                         "mesh_exhausted")

# the subset that is a deterministic VERDICT on a solo request (a replay
# provably repeats it): recorded in failed/, not dead-lettered. giving_up
# is deliberately absent — a crash loop is *suspicious*, not proven
# deterministic (the host may be at fault), so it burns retry budget and
# dead-letters only when the budget is spent
DETERMINISTIC_FAIL_CLASSES = ("numerics_abort", "deadline", "mesh_exhausted")

# default per-request retry budget: failure attempts (giving_up /
# missing_result) a request may accumulate before it is dead-lettered.
# Lease-expiry reclaims deliberately do NOT count — a worker SIGKILL storm
# is an infrastructure fault, and letting it spend tenants' budgets would
# dead-letter healthy requests (the exact blast radius this layer exists
# to contain)
DEFAULT_MAX_ATTEMPTS = 3


def default_worker_id():
    return f"{socket.gethostname()}-{os.getpid()}-{uuid.uuid4().hex[:6]}"


def _logger(root):
    """The fleet root's MetricLogger (lazy import: obs.logging pulls numpy,
    which is fine for a control process — only jax is banned here)."""
    from redcliff_tpu.obs.logging import MetricLogger

    return MetricLogger(root)


def _manifest_rows(requests):
    """Per-request merged-point ranges: [{request_id, tenant, trace_id,
    start, stop}] — the tenant-attribution map every report join keys on
    (``trace_id`` links each range back to the request's lifecycle
    trace)."""
    rows, start = [], 0
    for r in requests:
        n = len(r.get("points") or ())
        rows.append({"request_id": r["request_id"],
                     "tenant": str(r.get("tenant")),
                     "trace_id": r.get("trace_id"),
                     "start": start, "stop": start + n})
        start += n
    return rows


def _trace_context(batch_id, members):
    """The cross-process trace context for one batch: batch id + every
    member's durable trace identity (minted at submit). Set in-process for
    the worker's own spans/events and exported to the supervised run_batch
    child via ``REDCLIFF_TRACE_CTX`` (obs/spans.py)."""
    tids = {m["request_id"]: m["trace_id"]
            for m in members if m.get("trace_id")}
    ctx = {"batch_id": batch_id}
    if tids:
        ctx["trace_ids"] = tids
    return ctx


def _claim_batch(q, worker_id, lease_s, batch_id, request_ids, by_id,
                 logger, reclaim=False, all_ids=None):
    """Claim every member of one batch (all-or-nothing); returns
    {request_id: Lease} or None. ``all_ids`` records the FULL batch
    composition on each lease (it may exceed ``request_ids`` on a reclaim
    whose other members already completed)."""
    leases = {}
    for rid in request_ids:
        rec = by_id.get(rid)
        lease = q.claim(rid, worker_id, lease_s, batch_id=batch_id,
                        batch_request_ids=list(all_ids or request_ids),
                        tenant=(rec or {}).get("tenant"),
                        trace_id=(rec or {}).get("trace_id"))
        if lease is None:
            if q.is_terminal(rid):
                continue  # already finished by someone: not a conflict
            for l in leases.values():
                l.release()
            return None
        leases[rid] = lease
    if leases:
        logger.log("fleet", kind="reclaim" if reclaim else "claim",
                   batch_id=batch_id, requests=list(leases),
                   tenants=sorted({str(by_id[r].get("tenant"))
                                   for r in leases if r in by_id}),
                   worker=worker_id)
    return leases or None


def _next_batch(q, worker_id, lease_s, n_devices, budget_bytes, max_bucket,
                logger):
    """Reclaim-first, then plan-and-claim. Returns (batch_view, leases,
    member_requests) or None when nothing is claimable right now."""
    by_id = {r["request_id"]: r for r in q.requests()}

    # 1) reclaim: an expired lease records the batch it was claimed under —
    # resume THAT composition so the grid checkpoint fingerprint matches.
    # The FULL recorded member list stays the batch (manifest offsets must
    # match the merged grid the checkpoint was written under); only the
    # not-yet-terminal members need fresh claims
    for batch_id, stale in sorted(q.expired_claims().items(),
                                  key=lambda kv: str(kv[0])):
        if batch_id is None:
            continue  # no recorded composition: replanned below
        rids_all = (stale[0].get("batch_request_ids")
                    or [l["request_id"] for l in stale])
        rids_all = [r for r in rids_all if r in by_id]
        claimable = [r for r in rids_all if not q.is_terminal(r)]
        if not claimable:
            continue
        leases = _claim_batch(q, worker_id, lease_s, batch_id, claimable,
                              by_id, logger, reclaim=True,
                              all_ids=rids_all)
        if leases:
            # the reclaim is recorded on each member's durable attempt
            # ledger (kind="reclaim": dossier evidence, NOT budget — worker
            # deaths are infra faults, see DEFAULT_MAX_ATTEMPTS)
            for rid in leases:
                q.record_attempt(rid, "lease_expired", batch_id=batch_id,
                                 run_dir=q.batch_dir(batch_id),
                                 kind="reclaim")
            members = [by_id[r] for r in rids_all]
            batch = _planner._batch_view(members, n_devices)
            batch["batch_id"] = batch_id  # preserve the recorded run dir
            return batch, leases, members

    # 1b) pinned compositions (bisection halves): claimed EXACTLY as
    # pinned, bypassing the planner — a just-bisected suspect must never be
    # re-merged with healthy tenants. The pin is consumed at claim time;
    # from then on the lease records carry the composition (so a worker
    # dying mid-half lands back in the reclaim path above)
    pinned = q.pinned_batches()
    pinned_ids = {rid for p in pinned for rid in (p.get("requests") or ())}
    for pin in pinned:
        batch_id = pin["batch_id"]
        rids_all = [r for r in pin["requests"] if r in by_id]
        claimable = [r for r in rids_all if not q.is_terminal(r)]
        if not claimable:
            q.unpin_batch(batch_id)  # everyone settled elsewhere
            continue
        if claimable != rids_all:
            # a member settled elsewhere (canceled/dead-lettered) between
            # pin and claim: its points must NOT ride back into the fit —
            # unlike a RECLAIM there is no checkpoint fingerprint to
            # preserve here, so re-key the half to the surviving
            # composition (same content-derived lane seeds, so any prior
            # run of this exact composition still resumes cleanly)
            new_id = _planner.batch_id_for(claimable)
            q.pin_batch(new_id, claimable,
                        parent_batch_id=pin.get("parent_batch_id"))
            q.unpin_batch(batch_id)
            batch_id, rids_all = new_id, claimable
        leases = _claim_batch(q, worker_id, lease_s, batch_id, claimable,
                              by_id, logger, all_ids=rids_all)
        if leases:
            q.unpin_batch(batch_id)
            members = [by_id[r] for r in rids_all]
            batch = _planner._batch_view(members, n_devices)
            batch["batch_id"] = batch_id
            return batch, leases, members

    # 2) fresh admission plan over the pending queue (derived from the one
    # spool scan above: non-terminal, no live lease, not pinned, submission
    # order), with prior-failure suspects quarantined into solo batches
    now = time.time()
    pending, suspects = [], set()
    for rid, rec in by_id.items():
        if rid in pinned_ids or q.is_terminal(rid):
            continue
        lease = q.lease_of(rid)
        if lease is not None and float(lease.get("expires_at") or 0.0) > now:
            continue
        pending.append(rec)
        att = q.attempt_record(rid)
        if att and (int(att.get("attempts") or 0) > 0
                    or att.get("suspect")):
            # prior failed attempts, or a requeued dead-letter (fresh
            # budget but still a suspect until it proves clean)
            suspects.add(rid)
    if not pending:
        return None
    t0 = time.perf_counter()
    pl = _planner.plan(pending, n_devices=n_devices,
                       budget_bytes=budget_bytes,
                       cost_model=_costmodel.load(), max_bucket=max_bucket,
                       suspects=suspects)
    record_span("fleet.plan", (time.perf_counter() - t0) * 1e3,
                component="fleet", logger=logger, emit=True,
                queue_depth=pl["queue_depth"], batches=len(pl["batches"]))
    logger.log("fleet", kind="plan", queue_depth=pl["queue_depth"],
               batches=len(pl["batches"]),
               unschedulable=len(pl["unschedulable"]),
               plan_ms=pl["plan_ms"],
               suspects=sorted(suspects),
               utilization_pct=pl["utilization"]["utilization_pct"],
               decisions=[{k: b.get(k) for k in
                           ("batch_id", "requests", "tenants", "n_points",
                            "g_bucket", "predicted_bytes", "eta_s",
                            "priority", "suspect")}
                          for b in pl["batches"][:8]],
               worker=worker_id)
    for b in pl["batches"]:
        rids = [r for r in b["requests"]
                if r in by_id and not q.is_terminal(r)]
        if not rids:
            continue
        if rids != b["requests"]:
            # a member settled (e.g. canceled) between planning and this
            # claim: its points must not ride into the fit — rebuild the
            # batch from the survivors (fresh id, fresh run dir; same
            # content-derived lane seeds, so results are unchanged)
            b = _planner._batch_view([by_id[r] for r in rids], n_devices)
        leases = _claim_batch(q, worker_id, lease_s, b["batch_id"],
                              b["requests"], by_id, logger)
        if leases:
            # the merge decision that actually claimed work becomes a
            # durable `planned` lifecycle event (the decisions that were
            # merely proposed this cycle re-plan next cycle — recording
            # them all every poll would spam the ledger)
            _history.append_event(
                q.root, "planned", batch_id=b["batch_id"],
                requests=b["requests"], trace_ids=b.get("trace_ids"),
                n_points=b["n_points"], g_bucket=b["g_bucket"],
                worker=worker_id)
            members = [by_id[r] for r in b["requests"] if r in by_id]
            return b, leases, members
    return None


class _LeaseHeartbeat:
    """Renews a batch's leases every ``lease_s / 3`` seconds while the
    supervised fit runs; a lost lease (reclaimed by another worker after an
    expiry we slept through) stops renewals and is surfaced to the caller
    so it will not publish results it no longer owns.

    Renewal ERRORS are not silent: each miss logs a structured ``fleet``
    event with the error kind, and ``max_renew_misses`` CONSECUTIVE misses
    on one lease escalate to lease-lost handling — after that many failed
    renewals we can no longer prove the on-disk lease is ours (it may have
    expired and been reclaimed behind the unreadable filesystem), so
    publishing results would race the new owner."""

    def __init__(self, leases, lease_s, logger, max_renew_misses=3):
        self._leases = leases
        self._lease_s = float(lease_s)
        self._logger = logger
        self._max_misses = max(int(max_renew_misses), 1)
        self._misses = {}
        self._stop = threading.Event()
        self.lost = []
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-lease-heartbeat")

    def _log(self, **kw):
        try:
            self._logger.log("fleet", **kw)
        except Exception:  # noqa: BLE001 — the same fs trouble that broke
            pass           # the renewal must not kill the heartbeat thread

    def _run(self):
        period = max(self._lease_s / 3.0, 0.05)
        while not self._stop.wait(period):
            for rid, lease in list(self._leases.items()):
                try:
                    lease.renew(self._lease_s)
                except LeaseLost:
                    self.lost.append(rid)
                    self._leases.pop(rid, None)
                    self._misses.pop(rid, None)
                    self._log(kind="lease_lost", requests=[rid])
                except OSError as e:
                    n = self._misses.get(rid, 0) + 1
                    self._misses[rid] = n
                    self._log(kind="renew_error", requests=[rid],
                              consecutive=n,
                              error=f"{type(e).__name__}: {e}")
                    if n >= self._max_misses:
                        self.lost.append(rid)
                        self._leases.pop(rid, None)
                        self._misses.pop(rid, None)
                        self._log(kind="lease_lost", requests=[rid],
                                  consecutive=n,
                                  error="renewal misses exhausted")
                else:
                    self._misses.pop(rid, None)

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=self._lease_s)


def run_one_batch(q, batch, leases, members, logger, worker_id,
                  lease_s=60.0, checkpoint_every=1, supervisor_policy=None,
                  env=None, python=None,
                  max_attempts=DEFAULT_MAX_ATTEMPTS):
    """Run one claimed batch under the crash-loop supervisor and settle its
    requests (containment discipline — see the module docstring); returns
    the :class:`~redcliff_tpu.runtime.supervisor.SuperviseOutcome`.

    The batch runs under its TRACE CONTEXT (batch id + each member's
    submit-minted trace id): set process-wide for the worker's own spans
    and fleet events, exported into the supervised run_batch child via
    ``REDCLIFF_TRACE_CTX`` (so every record the jax child writes carries
    the same join keys), and scoped — restored on every exit path."""
    ctx = _trace_context(batch["batch_id"], members)
    prev_ctx = _spans.set_trace_ctx(ctx)
    try:
        return _run_one_batch(q, batch, leases, members, logger, worker_id,
                              ctx, lease_s=lease_s,
                              checkpoint_every=checkpoint_every,
                              supervisor_policy=supervisor_policy, env=env,
                              python=python, max_attempts=max_attempts)
    finally:
        _spans.set_trace_ctx(prev_ctx)


def _run_one_batch(q, batch, leases, members, logger, worker_id, trace_ctx,
                   lease_s=60.0, checkpoint_every=1, supervisor_policy=None,
                   env=None, python=None,
                   max_attempts=DEFAULT_MAX_ATTEMPTS):
    batch_id = batch["batch_id"]
    run_dir = q.batch_dir(batch_id)
    os.makedirs(run_dir, exist_ok=True)
    batch_file = os.path.join(run_dir, "batch.json")
    if not os.path.exists(batch_file):
        # deterministic from the claimed composition: a reclaiming worker
        # that finds the file missing (claimant died pre-write) rebuilds
        # the identical content from the lease-recorded member order
        tmp = f"{batch_file}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"batch_id": batch_id, "run_dir": run_dir,
                       "checkpoint_every": int(checkpoint_every),
                       "requests": members}, f, allow_nan=False)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, batch_file)
    # tenant stamping into the supervisor ledger: the manifest row set the
    # per-tenant report section joins on (run_batch logs the same manifest
    # as a metrics event inside the run dir)
    ledger_path = os.path.join(run_dir, "run_ledger.jsonl")
    with open(ledger_path, "a") as f:
        f.write(json.dumps({"event": "fleet", "kind": "manifest",
                            "batch_id": batch_id, "worker": worker_id,
                            "requests": _manifest_rows(members)}) + "\n")
    logger.log("fleet", kind="batch_start", batch_id=batch_id,
               run_dir=run_dir, requests=batch["requests"],
               tenants=batch["tenants"], n_points=batch["n_points"],
               g_bucket=batch["g_bucket"], eta_s=batch.get("eta_s"),
               predicted_bytes=batch.get("predicted_bytes"),
               worker=worker_id)
    cmd = [python or sys.executable, "-m", "redcliff_tpu.fleet.run_batch",
           batch_file]
    # the trace context crosses the process boundary as env: the jax child
    # (and any grand-children the supervisor restarts) stamps every span
    # and metrics record with the same batch/request join keys
    child_env = dict(env if env is not None else os.environ)
    child_env[_spans.ENV_TRACE_CTX] = json.dumps(trace_ctx)
    started_at = time.time()
    t0 = time.perf_counter()
    with _LeaseHeartbeat(leases, lease_s, logger) as hb:
        outcome = supervise(
            cmd, ledger_path=ledger_path,
            policy=supervisor_policy or SupervisorPolicy(max_restarts=2),
            env=child_env)
    dur_ms = (time.perf_counter() - t0) * 1e3
    record_span("fleet.batch", dur_ms, component="fleet", logger=logger,
                emit=True, batch_id=batch_id,
                classification=outcome.classification)

    lost = set(hb.lost)
    settled = {"done": [], "failed": [], "released": [], "deadletter": [],
               "bisected": [], "lost": sorted(lost)}
    cls = outcome.classification
    live = [(rid, leases[rid]) for rid in leases if rid not in lost]

    def member_of(rid):
        return next((m for m in members if m["request_id"] == rid), {})

    def trace_of(rid):
        return member_of(rid).get("trace_id")

    # one durable `attempt` lifecycle transition per still-owned member:
    # when the supervised run STARTED (the SLO layer's time-to-first-
    # attempt endpoint), how it classified, and how many supervisor
    # attempts it burned. Lost leases are the new owner's story to record.
    for rid, _lease in live:
        _history.append_event(
            q.root, "attempt", request_id=rid, trace_id=trace_of(rid),
            batch_id=batch_id, tenant=member_of(rid).get("tenant"),
            worker=worker_id, classification=cls,
            attempts=len(outcome.attempts), started_at=started_at,
            run_dir=run_dir)

    def send_to_deadletter(rid, att, reason, causes=None):
        rec = member_of(rid)
        q.deadletter(rid, dossier=_dossier(rec, att, reason, run_dir,
                                           causes=causes),
                     trace_id=trace_of(rid))
        settled["deadletter"].append(rid)
        logger.log("fleet", kind="deadletter", batch_id=batch_id,
                   requests=[rid], tenants=[str(rec.get("tenant"))],
                   reason=reason, attempts=(att or {}).get("attempts"),
                   run_dir=run_dir, worker=worker_id)

    if cls == "clean":
        for rid, lease in live:
            rec = member_of(rid)
            result = _read_result(run_dir, rid)
            if result is None:
                # clean exit, no per-request artifact (should not happen):
                # a durability bug, not a verdict — retry on the budget,
                # dead-letter when it is spent (never a stub "done")
                att = q.record_attempt(rid, "missing_result",
                                       batch_id=batch_id, run_dir=run_dir)
                if att["attempts"] >= max_attempts:
                    send_to_deadletter(rid, att, "missing_result")
                else:
                    lease.release()
                    settled["released"].append(rid)
                continue
            causes = _poison_causes(result)
            if causes is not None:
                # attribution: the grid engine quarantined EVERY point of
                # this request (deterministic per-lane causes) — the poison
                # tenant is contained without touching its siblings
                att = q.record_attempt(rid, "poison_quarantine",
                                       batch_id=batch_id, run_dir=run_dir)
                send_to_deadletter(rid, att, "poison_quarantine",
                                   causes=causes)
                continue
            q.complete(rid, result=result, trace_id=trace_of(rid))
            settled["done"].append(rid)
            logger.log("fleet", kind="complete", batch_id=batch_id,
                       requests=[rid], tenants=[str(rec.get("tenant"))],
                       worker=worker_id)
    elif cls in TERMINAL_FAIL_CLASSES and len(live) > 1:
        # terminal failure of a MERGED batch with no per-lane attribution:
        # never blame every member — bisect, so halving corners the poison
        # while healthy siblings still finish (as pinned compositions the
        # planner cannot re-merge)
        _bisect(q, batch_id, run_dir, cls, live, member_of, settled,
                logger, worker_id)
    elif cls in TERMINAL_FAIL_CLASSES and len(members) == 1:
        # genuinely SOLO composition: the verdict is attributable to this
        # request alone
        for rid, lease in live:
            att = q.record_attempt(rid, cls, batch_id=batch_id,
                                   run_dir=run_dir)
            if cls in DETERMINISTIC_FAIL_CLASSES:
                q.fail(rid, cls, trace_id=trace_of(rid))
                settled["failed"].append(rid)
            elif att["attempts"] >= max_attempts:
                # a solo crash/hang loop (giving_up) past its budget
                send_to_deadletter(rid, att, "crash_loop")
            else:
                lease.release()
                settled["released"].append(rid)
    elif cls in TERMINAL_FAIL_CLASSES:
        # MERGED composition but at most one lease is still ours (the rest
        # were lost or already terminal): the batch the child ran still
        # carried co-tenants' lanes, so the terminal class cannot be
        # pinned on the lone survivor — it may be a healthy co-tenant of
        # the real poison. Budget-route instead of issuing a verdict; the
        # dossier reason keeps the recorded class (`merged_<class>`) so an
        # operator never misreads a deterministic deadline/numerics death
        # as an infra crash loop
        for rid, lease in live:
            att = q.record_attempt(rid, cls, batch_id=batch_id,
                                   run_dir=run_dir)
            if att["attempts"] >= max_attempts:
                send_to_deadletter(rid, att,
                                   "crash_loop" if cls == "giving_up"
                                   else f"merged_{cls}")
            else:
                lease.release()
                settled["released"].append(rid)
    else:
        for rid, lease in live:
            lease.release()
            settled["released"].append(rid)
    logger.log("fleet", kind="batch_end", batch_id=batch_id,
               classification=outcome.classification, rc=outcome.returncode,
               attempts=len(outcome.attempts),
               wall_s=round(dur_ms / 1e3, 3),
               done=len(settled["done"]), failed=len(settled["failed"]),
               released=len(settled["released"]),
               deadlettered=len(settled["deadletter"]),
               bisected=len(settled["bisected"]), worker=worker_id)
    return outcome


def _bisect(q, batch_id, run_dir, classification, live, member_of, settled,
            logger, worker_id):
    """Split a blind-failed merged batch into two pinned halves (claim
    order) and release the leases: the next claim cycles — this worker's or
    any other's — run the halves as exact compositions. Each member's
    durable attempt ledger is charged one failure (the classification the
    batch died with), so the eventual solo culprit carries its history."""
    rids = [rid for rid, _ in live]
    mid = (len(rids) + 1) // 2
    halves = []
    for ids in (rids[:mid], rids[mid:]):
        half_id = _planner.batch_id_for(ids)
        q.pin_batch(half_id, ids, parent_batch_id=batch_id)
        halves.append({"batch_id": half_id, "requests": ids})
    for rid, lease in live:
        q.record_attempt(rid, classification, batch_id=batch_id,
                         run_dir=run_dir)
        lease.release()
        settled["bisected"].append(rid)
    logger.log("fleet", kind="bisect", batch_id=batch_id, requests=rids,
               classification=classification, halves=halves,
               worker=worker_id)
    # the bisection round stays on each member's lifecycle timeline: the
    # halves' batch ids link the pinned re-runs back to the same traces
    _history.append_event(
        q.root, "bisected", batch_id=batch_id, requests=rids,
        trace_ids={rid: member_of(rid).get("trace_id") for rid in rids
                   if member_of(rid).get("trace_id")},
        halves=[h["batch_id"] for h in halves],
        classification=classification, worker=worker_id)


# quarantine causes that are a DETERMINISTIC verdict on the point itself
# (a replay provably diverges again). deadline is deliberately absent:
# eviction at a wall-clock budget depends on how loaded the host was, so a
# fully-deadline-evicted request completes done-with-failures, not poison
_POISON_CAUSES = ("nonfinite_grad", "nonfinite_val")


def _poison_causes(result):
    """The per-cause quarantine counts when EVERY point of this request was
    quarantined by the grid engine for a deterministic-numerics cause (the
    poison-attribution signal), else None. A partial quarantine — or any
    wall-clock-dependent cause like ``deadline`` — is normal sweep behavior
    and completes as done with the failures recorded."""
    n = result.get("n_points") or 0
    fails = result.get("failures") or []
    points = {f.get("point") for f in fails
              if isinstance(f.get("point"), int)}
    if not n or len(points) < n:
        return None
    causes = {}
    for f in fails:
        cause = str(f.get("cause") or "?")
        causes[cause] = causes.get(cause, 0) + 1
    if any(c not in _POISON_CAUSES for c in causes):
        return None
    return causes


def _dossier(rec, att, reason, run_dir, causes=None):
    """The dead-letter failure dossier: everything an operator needs to
    judge the request without spelunking run dirs — attempt/classification
    history, the run dirs it burned, and any crash flight records they
    hold."""
    att = att or {}
    history = att.get("history") or []
    run_dirs = sorted({h.get("run_dir") for h in history
                       if h.get("run_dir")} | {run_dir})
    flights = []
    for d in run_dirs:
        flights.extend(sorted(
            glob.glob(os.path.join(d, "flight_record*.json"))))
    return {
        "request_id": rec.get("request_id"),
        "tenant": str(rec.get("tenant")),
        "reason": reason,
        "attempts": int(att.get("attempts") or 0),
        "reclaims": int(att.get("reclaims") or 0),
        "classifications": [h.get("classification") for h in history],
        "last_classification": (att.get("last") or {}).get("classification"),
        "run_dirs": run_dirs,
        "flight_records": flights,
        "quarantine_causes": causes,
    }


def _read_result(run_dir, request_id):
    """The per-request result record, or None when the clean-exited child
    left no artifact — the caller routes that through the retry budget
    instead of recording a stub done."""
    path = os.path.join(run_dir, "results", f"{request_id}.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def work(root, worker_id=None, lease_s=60.0, poll_s=2.0, max_batches=None,
         drain=False, once=False, n_devices=1, budget_bytes=None,
         max_bucket=_planner.DEFAULT_MAX_BUCKET, checkpoint_every=1,
         supervisor_policy=None, env=None, python=None,
         max_attempts=DEFAULT_MAX_ATTEMPTS):
    """The worker loop; returns the number of batches run.

    ``drain``: exit once the queue holds no claimable or running work.
    ``once``: run at most one claim cycle. ``max_batches`` bounds the run.
    ``budget_bytes``: the admission HBM budget (``check_headroom``'s
    ``budget_bytes`` on the serving mesh; None = ungated, e.g. this CPU
    container). ``max_attempts``: the per-request retry budget (failure
    attempts before a request is dead-lettered)."""
    q = FleetQueue(root)
    worker_id = worker_id or default_worker_id()
    batches_run = 0
    with _logger(root) as logger:
        logger.log("fleet", kind="worker_start", worker=worker_id,
                   n_devices=n_devices, budget_bytes=budget_bytes,
                   lease_s=lease_s)
        try:
            while True:
                got = _next_batch(q, worker_id, lease_s, n_devices,
                                  budget_bytes, max_bucket, logger)
                if got is not None:
                    batch, leases, members = got
                    run_one_batch(q, batch, leases, members, logger,
                                  worker_id, lease_s=lease_s,
                                  checkpoint_every=checkpoint_every,
                                  supervisor_policy=supervisor_policy,
                                  env=env, python=python,
                                  max_attempts=max_attempts)
                    batches_run += 1
                    if max_batches is not None \
                            and batches_run >= max_batches:
                        break
                    if once:
                        break
                    continue
                if once:
                    break
                # drain: nothing is claimable right now (_next_batch came
                # back empty — the queue is empty OR holds only
                # unschedulable requests the planner can never admit) and
                # nothing is in flight anywhere whose completion/expiry
                # could change that
                if drain and not q.live_leases():
                    break
                time.sleep(poll_s)
        except Exception as e:
            # an uncaught worker-loop exception used to die without a
            # record: mirror the watchdog's escalation path — dump the
            # flight recorder (the worker's last spans/events) next to the
            # fleet root's metrics and emit a structured worker_crash
            # event, THEN re-raise so the exit code still says crash
            path = None
            try:
                path = _flight.dump(str(root), "worker_crash", extra={
                    "worker": worker_id,
                    "error": f"{type(e).__name__}: {e}"})
            except Exception:  # noqa: BLE001 — the dump must not mask
                pass           # the original crash
            try:
                logger.log("fleet", kind="worker_crash", worker=worker_id,
                           error=f"{type(e).__name__}: {e}",
                           flight_record=path, batches=batches_run)
            except Exception:  # noqa: BLE001 — same: the crash record is
                pass           # best-effort, the original exception wins
            raise
        logger.log("fleet", kind="worker_stop", worker=worker_id,
                   batches=batches_run)
    return batches_run
