"""Elastic re-meshing: host-fault detection and degraded-mesh resume planning.

The multi-host story so far (parallel/distributed.py) treats the mesh as
static: lose a host and the whole fit dies, and while the durable checkpoints
*can* resume on a different mesh (they hold gathered host state), doing so is
a manual operation — an operator restarts the driver by hand with a smaller
device set. Production ML runtimes treat worker loss as an expected event the
system absorbs (TensorFlow couples checkpoint durability with supervised
restart exactly so long runs survive worker failure, arXiv:1605.08695); this
module is the planning half of that story for REDCLIFF grid sweeps:

- :class:`HostLostError` — the TYPED "part of the mesh is gone" failure. The
  grid engine raises it when a dispatch dies with a device-loss /
  collective-timeout / coordinator-loss signature
  (:func:`classify_device_error`), the watchdog's host-scoped staleness
  detector exits with its taxonomy code (``EXIT_HOST_LOST``), and fault
  injection raises it directly (``host_drop:h``).
- :func:`plan_resharding` — given the lanes a checkpoint holds and the device
  count actually visible *now*, the lane re-sharding plan that lands the
  survivors on the largest viable execution mesh: live lanes ride the PR-5
  bucket ladder at the new device count, frozen-but-unretired lanes retire to
  the host store, filler lanes pad the remainder. Reuses
  :class:`~redcliff_tpu.parallel.compaction.CompactionPlan` — a re-mesh IS a
  compaction whose trigger is the mesh shrinking rather than lanes retiring
  (and, unlike a compaction, it may *grow* the width when the new device
  count divides nothing smaller).
- :func:`apply_reshard` — applies that plan to a loaded checkpoint payload on
  the host (pure numpy gathers), before any device array exists. Results keep
  reporting under ORIGINAL point ids; nothing about the resume fingerprint
  changes (the fingerprint is deliberately mesh-agnostic).
- :func:`visible_devices` / :func:`visible_mesh` — the device set this
  attempt may use, capped by ``REDCLIFF_MESH_DEVICES`` (the knob the
  supervisor decrements on a ``host_lost`` exit: re-mesh-then-restart).
- :func:`mesh_shape` — {n_hosts, n_devices, device_kind} metadata recorded
  per attempt in ``run_ledger.jsonl`` and in every grid checkpoint payload,
  so degraded-mesh resumes are auditable end to end.

Single-process simulation caveat (pinned in project memory + ROADMAP item 5):
this container's CPU backend cannot run 2-process collectives, so tier-1
coverage simulates hosts as partitions of the virtual 8-device CPU mesh
(``REDCLIFF_SIM_HOSTS`` declares the partition count) and a "host drop" is a
typed-error exit + a smaller ``REDCLIFF_MESH_DEVICES`` on the next attempt.
The real 2-process DCN leg stays in the dry-run/slow tier.

numpy-only at module scope; jax is imported lazily so backend-free processes
(the supervisor parent, bench.py's parent) can import this safely.
"""
from __future__ import annotations

import os

import numpy as np

from redcliff_tpu.parallel import compaction

__all__ = [
    "HostLostError",
    "classify_device_error",
    "mesh_shape",
    "visible_devices",
    "visible_mesh",
    "choose_mesh_devices",
    "plan_resharding",
    "apply_reshard",
    "width_fits",
    "ENV_MESH_DEVICES",
    "ENV_SIM_HOSTS",
]

# the degraded-mesh knob: the supervisor sets/decrements this on a host_lost
# exit; visible_devices() caps the device list to it on the next attempt
ENV_MESH_DEVICES = "REDCLIFF_MESH_DEVICES"
# single-process simulation: how many "hosts" partition the local device
# list (tier-1 runs cannot spawn real 2-process collectives on this CPU
# backend); real multi-process runs ignore it (process_index is the truth)
ENV_SIM_HOSTS = "REDCLIFF_SIM_HOSTS"


class HostLostError(RuntimeError):
    """Part of the execution mesh is gone: a host stopped heartbeating, a
    collective timed out, or the backend reported a device/coordinator loss.

    This is a RESTARTABLE-after-re-mesh failure, not a crash: the durable
    checkpoint holds gathered host state, so the supervisor's answer is
    "shrink the mesh and resume" (taxonomy exit code
    :data:`~redcliff_tpu.runtime.watchdog.EXIT_HOST_LOST`), never a page.

    ``reason`` is the detection route (``host_drop`` / ``device_lost`` /
    ``collective_timeout`` / ``coordinator_loss`` / ``host_stale``);
    ``host`` is the lost host's index when the detector knows it."""

    def __init__(self, reason, host=None, detail=None):
        self.reason = reason
        self.host = host
        at = f" (host {host})" if host is not None else ""
        msg = f"mesh degraded: {reason}{at}"
        if detail:
            msg += f" — {detail}"
        msg += ("; resume from the durable checkpoint on the surviving "
                "devices (supervisor: re-mesh-then-restart)")
        super().__init__(msg)


# detection signatures for backend errors that mean "the mesh lost capacity",
# not "the math is wrong". Matched against lowercased str(exc); deliberately
# substring-based — XLA/PJRT error text varies by backend and version, and a
# false negative merely degrades to the old behavior (crash -> same-shape
# restart). A false POSITIVE is costlier (the supervisor irreversibly drops
# a host's worth of healthy devices), so the conjunctive branches require an
# explicit timeout word next to the collective/coordinator evidence — the
# looser "unavailable" (any gRPC UNAVAILABLE status) counts only for the
# coordinator, whose loss genuinely presents that way.
_DEVICE_LOST_SIGS = (
    "device lost", "device is lost", "lost device", "device disconnected",
    "device failure", "device removed", "device_lost",
)
_COORDINATOR_SIGS = (
    "coordinator", "distributed runtime service", "preemption notice",
)
_TIMEOUT_SIGS = ("timed out", "timeout", "deadline exceeded")
_COORD_TIMEOUT_SIGS = _TIMEOUT_SIGS + ("unavailable",)
_COLLECTIVE_SIGS = ("collective", "all-reduce", "allreduce", "all-gather",
                    "allgather", "psum", "nccl", "cross-host")


def classify_device_error(exc):
    """Map a backend exception onto a host-loss detection route, or None.

    Returns ``"device_lost"`` (explicit device-loss signal),
    ``"collective_timeout"`` (a cross-device/host collective timed out — the
    signature of a peer that stopped participating), or
    ``"coordinator_loss"`` (the distributed coordinator went away). None
    means "not mesh-shaped": the caller re-raises and the failure stays in
    its original class."""
    if exc is None:
        return None
    text = f"{type(exc).__name__}: {exc}".lower()
    if any(s in text for s in _DEVICE_LOST_SIGS):
        return "device_lost"
    if any(s in text for s in _COORDINATOR_SIGS) \
            and any(s in text for s in _COORD_TIMEOUT_SIGS):
        return "coordinator_loss"
    if any(s in text for s in _COLLECTIVE_SIGS) \
            and any(s in text for s in _TIMEOUT_SIGS):
        return "collective_timeout"
    return None


def visible_devices(devices=None, env=ENV_MESH_DEVICES):
    """The device list this attempt may mesh over: ``jax.devices()`` capped
    by the ``REDCLIFF_MESH_DEVICES`` env knob (unset/invalid = no cap).

    The cap takes the FIRST n devices — device ids are stable across
    restarts, so every attempt at the same cap meshes over the same devices
    (in the single-process simulation, "losing host h" = capping below h's
    partition; on a real multi-host mesh the dead host's devices are simply
    absent from ``jax.devices()`` and the cap is belt-and-braces)."""
    if devices is None:
        import jax

        devices = jax.devices()
    devices = list(devices)
    spec = os.environ.get(env, "").strip()
    if spec:
        try:
            n = int(spec)
        except ValueError:
            return devices
        if n >= 1:
            devices = devices[:n]
    return devices


def visible_mesh(axis_name="grid", devices=None, n_lanes=None):
    """1-D grid mesh over :func:`visible_devices` — what drivers build when
    they want the supervisor's re-mesh decisions honored. With ``n_lanes``
    the mesh is additionally trimmed to :func:`choose_mesh_devices`'s
    largest VIABLE device count for that many lanes (the one auto-mesh
    recipe, shared by `run_coefficient_grid(mesh="auto")` and the
    fault-injection child)."""
    from redcliff_tpu.parallel.mesh import grid_mesh

    devs = visible_devices(devices)
    if n_lanes is not None:
        devs = devs[: choose_mesh_devices(len(devs), n_lanes)]
    return grid_mesh(devices=devs, axis_name=axis_name)


def mesh_shape(mesh=None, devices=None, sim_hosts=None):
    """{n_hosts, n_devices, device_kind} for a mesh / device list — the
    audit metadata stamped into ``run_ledger.jsonl`` attempts and grid
    checkpoint payloads (NOT the resume fingerprint: checkpoints stay
    mesh-agnostic by design).

    ``n_hosts`` counts distinct ``process_index`` values; in the
    single-process simulation ``REDCLIFF_SIM_HOSTS`` (or ``sim_hosts``)
    overrides it with the declared partition count."""
    if devices is None:
        if mesh is not None:
            devices = list(np.asarray(mesh.devices).ravel())
        else:
            import jax

            devices = jax.local_devices()[:1]
    devices = list(devices)
    if sim_hosts is None:
        spec = os.environ.get(ENV_SIM_HOSTS, "").strip()
        if spec:
            try:
                sim_hosts = int(spec)
            except ValueError:
                sim_hosts = None
    n_hosts = len({getattr(d, "process_index", 0) for d in devices}) or 1
    if n_hosts == 1 and sim_hosts is not None and sim_hosts >= 1:
        # the simulated partition count applies ONLY when the devices are
        # genuinely single-process; on a real multi-controller mesh the
        # process_index spread is the truth and a stale/declared sim value
        # (the supervisor exports it alongside n_hosts) must not distort
        # the audit trail
        n_hosts = min(int(sim_hosts), len(devices))
    kind = getattr(devices[0], "device_kind", None) if devices else None
    return {"n_hosts": int(n_hosts), "n_devices": len(devices),
            "device_kind": kind}


def width_fits(width, n_devices):
    """True when a ``width``-lane grid can shard over ``n_devices`` (the
    grid engine's sub-mesh rule: multiple OR divisor of the device count).
    The ONE place this invariant lives — the grid resume path and the
    planner both consult it, so they can never drift apart."""
    n_devices = int(n_devices or 1)
    if n_devices <= 1 or width <= 0:
        return True
    return width % n_devices == 0 or n_devices % width == 0


def choose_mesh_devices(n_visible, n_lanes):
    """The largest viable execution mesh for ``n_lanes`` lanes on
    ``n_visible`` surviving devices.

    Any device count is *runnable* (the bucket ladder pads the width to a
    multiple), so "viable" is decided by wall-clock: a dispatch takes as
    long as the lanes each device computes (width / devices). The planner
    compares the full survivor set against the largest power-of-two subset
    — e.g. 9 live lanes on 6 survivors bucket to width 18 (3 lanes/device),
    beating the 4-device pow2 sub-mesh's width 16 (4 lanes/device) — and
    picks the smaller per-device load, preferring MORE devices on a tie
    (the filler lanes a wider bucket adds burn joules, not seconds, and the
    compaction ladder reclaims them at the next check window)."""
    n_visible = max(int(n_visible), 1)
    n_lanes = max(int(n_lanes), 1)
    pow2 = 1 << (n_visible.bit_length() - 1)  # largest pow2 <= n_visible
    candidates = sorted({n_visible, pow2}, reverse=True)

    def load(n_dev):
        w = compaction.bucket_width(n_lanes, n_dev)
        # width < device count runs on a sub-mesh of `w` devices
        return w / (n_dev if w % n_dev == 0 else w)

    best = candidates[0]
    best_load = load(best)
    for cand in candidates[1:]:
        if load(cand) < best_load:
            best, best_load = cand, load(cand)
    return best


def plan_resharding(active, orig_ids, retired_ids, n_devices, compact=True):
    """Lane re-sharding plan for resuming a checkpoint onto an
    ``n_devices`` mesh, or None when the checkpointed width already fits.

    ``active``/``orig_ids`` are the checkpoint's host arrays (execution
    width; ``orig_ids`` -1 marks bucket filler), ``retired_ids`` the point
    ids whose results already live in the host-side retired store.

    With ``compact=True`` (the elastic-scheduler default) only LIVE lanes
    ride to the new mesh — frozen-but-unretired lanes (early-stopped,
    quarantined, deadline-evicted) retire their frozen results to the host
    store exactly like a check-window compaction would. With
    ``compact=False`` every real lane keeps its row (fixed-width
    semantics), re-bucketed only as far as mesh viability requires.

    Unlike :func:`~redcliff_tpu.parallel.compaction.plan_compaction`, the
    plan may GROW the width: a surviving device count that divides nothing
    smaller (say width 8 onto 6 devices) pads up the ladder with filler
    lanes rather than failing the resume."""
    active = np.asarray(active, bool)
    orig_ids = np.asarray(orig_ids, np.int32)
    real = orig_ids >= 0
    live_rows = np.flatnonzero(active & real).astype(np.int32)
    retire_rows = np.zeros((0,), np.int32)
    if compact and live_rows.size:
        keep_rows = live_rows
        retire_rows = compaction.unretired_frozen_rows(active, orig_ids,
                                                       retired_ids)
    else:
        # no live lanes (resume-to-finish) or compaction off: every real
        # lane keeps its row so the fixed-width semantics are preserved
        keep_rows = np.flatnonzero(real).astype(np.int32)
    if keep_rows.size == 0:
        return None  # nothing real on board; the fit's exit paths own this
    new_w = compaction.bucket_width(keep_rows.size, n_devices)
    if new_w == int(orig_ids.size) and width_fits(orig_ids.size, n_devices):
        return None
    # filler invariant (compaction.assemble_plan): prefer a live fill lane —
    # in the keep-all branches keep_rows[0] may be a quarantined lane
    # holding non-finite params
    fill_row = live_rows[0] if live_rows.size else keep_rows[0]
    return compaction.assemble_plan(orig_ids, keep_rows, active[keep_rows],
                                    fill_row, new_w, retire_rows)


# checkpoint payload keys holding per-lane state (leading axis = execution
# width) that a re-shard must gather through the plan's row selection.
# "active" is NOT here: the plan computes the new mask directly (a
# sel-gather would mark filler rows with the fill lane's liveness)
_LANE_STATE_KEYS = ("params", "optA_state", "optB_state", "best_params",
                    "accepted", "nstate", "best_crit", "best_epoch",
                    "failed_epoch", "failed_cause")


def apply_reshard(ckpt, retired, plan):
    """Apply a re-shard plan to a loaded checkpoint payload IN PLACE (host
    numpy gathers — no device array exists yet) and absorb the plan's
    retirements into ``retired``. Returns the number of live lanes migrated.

    ``ckpt`` is the grid checkpoint dict (host trees at the old execution
    width); ``retired`` the engine's {point_id: frozen results} store. The
    checkpoint's ``val_history`` rows are already expanded to the original
    point width, so they pass through untouched."""
    import jax  # tree mapping only; no device arrays are created here

    # retire frozen lanes' results BEFORE remapping: retire_rows index the
    # OLD width. Pre-sentinel checkpoints carry no failed_cause — backfill
    # exactly like the grid resume path does (every already-quarantined
    # lane was a validation quarantine by construction)
    failed_epoch = np.asarray(ckpt["failed_epoch"])
    fc = ckpt.get("failed_cause")
    if fc is None:
        from redcliff_tpu.runtime import numerics

        fc = np.where(failed_epoch >= 0, numerics.CAUSE_NONFINITE_VAL,
                      0).astype(np.int32)
    failed_cause = np.asarray(fc)
    for i, row in enumerate(np.asarray(plan.retire_rows)):
        pid = int(plan.retire_ids[i])
        retired[pid] = {
            "best_params": jax.tree.map(
                lambda l, _r=int(row): np.asarray(l[_r]),
                ckpt["best_params"]),
            "best_crit": float(np.asarray(ckpt["best_crit"])[row]),
            "best_epoch": int(np.asarray(ckpt["best_epoch"])[row]),
            "failed_epoch": int(failed_epoch[row]),
            "failed_cause": int(failed_cause[row]),
        }
    sel = np.asarray(plan.sel)
    for key in _LANE_STATE_KEYS:
        val = ckpt.get(key)
        if val is None:
            continue  # accepted/nstate may be absent (non-freeze fits,
        #             pre-sentinel checkpoints)
        ckpt[key] = jax.tree.map(lambda l: np.asarray(l)[sel], val)
    ckpt["active"] = np.asarray(plan.active)
    ckpt["orig_ids"] = np.asarray(plan.orig_ids, np.int32)
    ckpt["retired"] = retired
    return int(np.asarray(plan.active).sum())
