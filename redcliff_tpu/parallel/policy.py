"""Grid scheduling policy: the decision half of the engine/policy split.

``parallel/grid.py`` is the grid EXECUTION ENGINE — vmapped dispatch,
sharding, checkpoint/resume mechanics, result assembly. This module owns the
SCHEDULING DECISIONS the engine consults but never makes itself:

* **which execution width a grid runs at** (:meth:`GridSchedulingPolicy.
  initial_width`) — the power-of-two bucket ladder (parallel/compaction.py)
  or the exact width when bucketing is off, including the mesh-divisibility
  contract;
* **when live lanes compact down the ladder**
  (:meth:`GridSchedulingPolicy.compaction_plan`) — the check-window decision
  that retires dead lanes' FLOPs, gated to single-process runs;
* **which lanes a wall-clock budget evicts and when the whole grid stops**
  (:meth:`GridSchedulingPolicy.lane_evictions` /
  :meth:`GridSchedulingPolicy.grid_deadline_hit`).

Every method is pure host arithmetic on numbers the engine already holds —
no device work, no sync, no jax import. That is the point of the split: the
fleet sweep service (redcliff_tpu/fleet) and its admission planner consult
the SAME ladder/width logic when packing multi-tenant requests into
G-buckets, without instantiating an engine, and a future cost-model-driven
policy (ROADMAP item 4) swaps in here without touching dispatch mechanics.

Decision parity: the engine delegating here is a pure code movement — every
decision is computed from the same inputs by the same expressions as before
the split, so grid decision streams (and therefore per-lane update streams)
are bit-identical to the pre-split engine. Pinned by the existing
compaction/remesh bit-identity tests, which run unmodified.

numpy-only at module scope (like parallel/compaction.py).
"""
from __future__ import annotations

import numpy as np

from redcliff_tpu.parallel import compaction

__all__ = ["GridSchedulingPolicy"]


class GridSchedulingPolicy:
    """Bucket-ladder scheduling policy with check-window compaction.

    ``g_bucket``: draw execution widths from the power-of-two bucket ladder,
    padding with masked filler lanes (off: exact width, mesh-divisibility
    required). ``compaction``: gather surviving lanes down the ladder at
    check-window boundaries (single-process only — a multi-host grid would
    have to re-span hosts mid-fit).
    """

    def __init__(self, g_bucket=True, compaction=True):
        self.g_bucket = bool(g_bucket)
        self.compaction = bool(compaction)

    @classmethod
    def from_train_config(cls, train_config):
        """The policy a train config's elastic-scheduling knobs select."""
        return cls(g_bucket=getattr(train_config, "g_bucket", True),
                   compaction=getattr(train_config, "compaction", True))

    # ------------------------------------------------------------------
    # width decisions
    # ------------------------------------------------------------------
    def initial_width(self, g_real, n_devices):
        """Execution width for a fresh ``g_real``-point grid on an
        ``n_devices`` mesh: the bucket-ladder width (``g_bucket``), or the
        exact width — which must then divide the mesh evenly."""
        n_devices = int(n_devices or 1)
        if self.g_bucket:
            return compaction.bucket_width(g_real, n_devices)
        if n_devices > 1 and g_real % n_devices != 0:
            raise ValueError(
                f"grid size {g_real} must be a multiple of the mesh "
                f"device count {n_devices} (pad the grid with duplicate "
                f"points or shrink the mesh, or enable g_bucket to pad "
                f"with masked filler lanes)")
        return g_real

    def ladder(self, n_lanes, n_devices=1, max_width=None):
        """The candidate bucket-ladder rungs for ``n_lanes`` lanes — what
        the fleet admission planner enumerates footprints/ETAs over."""
        return compaction.ladder_widths(n_lanes, n_devices,
                                        max_width=max_width)

    # ------------------------------------------------------------------
    # check-window compaction decision
    # ------------------------------------------------------------------
    def compaction_plan(self, active_host, orig_ids, retired_ids, n_devices,
                        n_processes=1):
        """Plan a live-lane compaction for this check window, or None (the
        current width is already the right bucket, compaction is disabled,
        or the run spans multiple processes)."""
        if not self.compaction or n_processes != 1:
            return None
        return compaction.plan_compaction(active_host, orig_ids, retired_ids,
                                          int(n_devices or 1))

    # ------------------------------------------------------------------
    # wall-clock deadline decisions
    # ------------------------------------------------------------------
    @staticmethod
    def lane_evictions(lane_deadline, dl_done, elapsed):
        """Boolean mask of execution lanes whose per-lane budget expired
        this epoch (excluding already-evicted ones), or None when there is
        nothing to decide (no per-lane deadlines / no uniform clock this
        epoch)."""
        if lane_deadline is None or elapsed is None:
            return None
        return np.logical_and(lane_deadline < elapsed,
                              np.logical_not(dl_done))

    @staticmethod
    def grid_deadline_hit(grid_deadline_s, elapsed):
        """Whether the whole-grid budget is spent as of ``elapsed``."""
        return bool(grid_deadline_s and elapsed is not None
                    and elapsed > grid_deadline_s)
