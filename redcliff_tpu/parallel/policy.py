"""Grid scheduling policy: the decision half of the engine/policy split.

``parallel/grid.py`` is the grid EXECUTION ENGINE — vmapped dispatch,
sharding, checkpoint/resume mechanics, result assembly. This module owns the
SCHEDULING DECISIONS the engine consults but never makes itself:

* **which execution width a grid runs at** (:meth:`GridSchedulingPolicy.
  initial_width`) — the power-of-two bucket ladder (parallel/compaction.py)
  or the exact width when bucketing is off, including the mesh-divisibility
  contract;
* **when live lanes compact down the ladder**
  (:meth:`GridSchedulingPolicy.compaction_plan`) — the check-window decision
  that retires dead lanes' FLOPs, gated to single-process runs;
* **which lanes a wall-clock budget evicts and when the whole grid stops**
  (:meth:`GridSchedulingPolicy.lane_evictions` /
  :meth:`GridSchedulingPolicy.grid_deadline_hit`).

Every method is pure host arithmetic on numbers the engine already holds —
no device work, no sync, no jax import. That is the point of the split: the
fleet sweep service (redcliff_tpu/fleet) and its admission planner consult
the SAME ladder/width logic when packing multi-tenant requests into
G-buckets, without instantiating an engine.

Decision parity: the engine delegating here is a pure code movement — every
decision is computed from the same inputs by the same expressions as before
the split, so grid decision streams (and therefore per-lane update streams)
are bit-identical to the pre-split engine. Pinned by the existing
compaction/remesh bit-identity tests, which run unmodified.

**Predictive scheduling** (ISSUE 15, ROADMAP item 3 — the first place the
learned cost model's predictions steer a decision instead of only being
scored): :class:`PredictiveSchedulingPolicy` consults an
:class:`~redcliff_tpu.obs.costmodel.CostModel` view to choose by predicted
wall-clock —

* **initial width**: price every candidate ladder rung as ``predicted
  epoch cost x planned epochs + predicted cold-compile cost when the rung's
  program family is unseen`` and start at the cheapest rung. A rung with a
  WARM program family (compile evidence in the store, so the persistent XLA
  cache holds the executable) can beat the heuristic base rung when the
  recompile it avoids outweighs the padded lanes it adds — this is the grid
  engine's half of cold-compile ordering: the first-touch compile is
  steered onto the cache's critical path;
* **compaction point**: the PR-5 heuristic compacts at the first check
  window where the live-lane count drops below the next rung; the
  predictive policy compacts only when ``(epoch cost at the current width -
  at the target width) x surviving epochs`` exceeds the predicted
  compile + gather cost of moving — a near-finished fit stops paying a
  fresh XLA compile to save a handful of cheap epochs;
* **fallback contract** (pinned by tests and the bench
  ``predictive_policy`` probe): whenever the store lacks a usable prior for
  ANY input of a pricing — either width's epoch cost, the target's compile
  cost — the decision falls back BIT-IDENTICALLY to the heuristic, so an
  empty or cold store produces exactly the PR-5 decision stream. Every
  decision (including fallbacks) is recorded via :meth:`take_decision` and
  logged by the engine as a schema-registered ``policy`` event.

The gate is ``REDCLIFF_PREDICTIVE`` (:func:`predictive_enabled`, default
off): flipping it on is safe anywhere — with no store the policy IS the
heuristic — but stays opt-in so accumulated stores cannot silently move
decision streams under tests or reproductions that pin them.

numpy-only at module scope (like parallel/compaction.py) and no jax
anywhere: the fleet worker (a no-jax control process) imports this module
for :func:`predictive_enabled` and the preemption pricing helpers.
"""
from __future__ import annotations

import os

import numpy as np

from redcliff_tpu.parallel import compaction

__all__ = ["GridSchedulingPolicy", "PredictiveSchedulingPolicy",
           "ENV_PREDICTIVE", "predictive_enabled"]

# the predictive-scheduling master switch (README "Elastic scheduling"
# knobs): "1" lets PredictiveSchedulingPolicy price widths/compactions from
# the learned cost model and arms the fleet worker's deadline-aware
# preemption; default off — empty-store runs are bit-identical either way,
# but accumulated stores must never move pinned decision streams uninvited
ENV_PREDICTIVE = "REDCLIFF_PREDICTIVE"

# hard ceiling on the predictive initial-width choice, exported by callers
# whose ADMISSION decision was priced at a specific width: the fleet batch
# driver (fleet/run_batch.py) sets it to the planner-admitted G-bucket so a
# warm-rung widening can never exceed the footprint the HBM admission gate
# budgeted (predicted_batch_bytes scales per-lane with width) or the
# planner's max_bucket cap. Unset = standalone fits, bounded by the
# policy's own 4x-base candidate ladder
ENV_POLICY_MAX_WIDTH = "REDCLIFF_POLICY_MAX_WIDTH"


def predictive_enabled(env=None):
    """Whether predictive scheduling is armed (``REDCLIFF_PREDICTIVE``)."""
    val = (env if env is not None
           else os.environ.get(ENV_PREDICTIVE, "0"))
    return str(val).strip().lower() not in ("", "0", "false", "off")


class GridSchedulingPolicy:
    """Bucket-ladder scheduling policy with check-window compaction.

    ``g_bucket``: draw execution widths from the power-of-two bucket ladder,
    padding with masked filler lanes (off: exact width, mesh-divisibility
    required). ``compaction``: gather surviving lanes down the ladder at
    check-window boundaries (single-process only — a multi-host grid would
    have to re-span hosts mid-fit).
    """

    def __init__(self, g_bucket=True, compaction=True):
        self.g_bucket = bool(g_bucket)
        self.compaction = bool(compaction)

    @classmethod
    def from_train_config(cls, train_config):
        """The policy a train config's elastic-scheduling knobs select."""
        return cls(g_bucket=getattr(train_config, "g_bucket", True),
                   compaction=getattr(train_config, "compaction", True))

    # ------------------------------------------------------------------
    # width decisions
    # ------------------------------------------------------------------
    def initial_width(self, g_real, n_devices):
        """Execution width for a fresh ``g_real``-point grid on an
        ``n_devices`` mesh: the bucket-ladder width (``g_bucket``), or the
        exact width — which must then divide the mesh evenly."""
        n_devices = int(n_devices or 1)
        if self.g_bucket:
            return compaction.bucket_width(g_real, n_devices)
        if n_devices > 1 and g_real % n_devices != 0:
            raise ValueError(
                f"grid size {g_real} must be a multiple of the mesh "
                f"device count {n_devices} (pad the grid with duplicate "
                f"points or shrink the mesh, or enable g_bucket to pad "
                f"with masked filler lanes)")
        return g_real

    def ladder(self, n_lanes, n_devices=1, max_width=None):
        """The candidate bucket-ladder rungs for ``n_lanes`` lanes — what
        the fleet admission planner enumerates footprints/ETAs over."""
        return compaction.ladder_widths(n_lanes, n_devices,
                                        max_width=max_width)

    # ------------------------------------------------------------------
    # check-window compaction decision
    # ------------------------------------------------------------------
    def compaction_plan(self, active_host, orig_ids, retired_ids, n_devices,
                        n_processes=1, epochs_remaining=None):
        """Plan a live-lane compaction for this check window, or None (the
        current width is already the right bucket, compaction is disabled,
        or the run spans multiple processes). ``epochs_remaining`` is the
        predictive subclass's pricing input; the heuristic ignores it."""
        if not self.compaction or n_processes != 1:
            return None
        return compaction.plan_compaction(active_host, orig_ids, retired_ids,
                                          int(n_devices or 1))

    # ------------------------------------------------------------------
    # wall-clock deadline decisions
    # ------------------------------------------------------------------
    @staticmethod
    def lane_evictions(lane_deadline, dl_done, elapsed):
        """Boolean mask of execution lanes whose per-lane budget expired
        this epoch (excluding already-evicted ones), or None when there is
        nothing to decide (no per-lane deadlines / no uniform clock this
        epoch)."""
        if lane_deadline is None or elapsed is None:
            return None
        return np.logical_and(lane_deadline < elapsed,
                              np.logical_not(dl_done))

    @staticmethod
    def grid_deadline_hit(grid_deadline_s, elapsed):
        """Whether the whole-grid budget is spent as of ``elapsed``."""
        return bool(grid_deadline_s and elapsed is not None
                    and elapsed > grid_deadline_s)


class PredictiveSchedulingPolicy(GridSchedulingPolicy):
    """Cost-model-steered scheduling: choose widths and compaction points by
    predicted wall-clock (module docstring for the decision rules and the
    bit-identical fallback contract).

    ``cost_model`` is a read-side :class:`~redcliff_tpu.obs.costmodel
    .CostModel` view (or None — pure heuristic); ``shape_key`` /
    ``platform`` / ``precision`` identify this fit's cost buckets;
    ``epochs`` is the planned epoch budget (initial-width pricing);
    ``gather_ms`` is the charged host cost of applying one compaction (the
    state gather + re-shard — small next to a cold compile, but priced so a
    zero-compile move still needs a real saving to go).

    Every consulted decision is stashed for the engine to log as a
    ``policy`` event; :meth:`take_decision` hands it over exactly once.
    """

    def __init__(self, g_bucket=True, compaction=True, cost_model=None,
                 shape_key=None, platform=None, precision="f32",
                 epochs=None, gather_ms=250.0, max_width=None):
        super().__init__(g_bucket=g_bucket, compaction=compaction)
        self.cost_model = cost_model
        self.shape_key = shape_key
        self.platform = platform
        self.precision = precision
        self.epochs = int(epochs) if epochs else None
        self.gather_ms = float(gather_ms)
        # admission ceiling (ENV_POLICY_MAX_WIDTH): widening must never
        # outgrow the width an HBM admission gate / max_bucket cap priced
        self.max_width = int(max_width) if max_width else None
        self._last_decision = None

    # ------------------------------------------------------------------
    # decision record hand-off (engine logs it as a `policy` event)
    # ------------------------------------------------------------------
    def take_decision(self):
        """The decision record of the LAST consulted width/compaction call,
        exactly once (None when nothing was decided since the last take)."""
        dec, self._last_decision = self._last_decision, None
        return dec

    # ------------------------------------------------------------------
    # pricing primitives (None = no usable prior -> heuristic fallback)
    # ------------------------------------------------------------------
    def _epoch_ms(self, width):
        if self.cost_model is None or not self.shape_key:
            return None
        return self.cost_model.predict_epoch_ms(
            self.shape_key, width, platform=self.platform,
            precision=self.precision)

    def _compile_ms(self, width):
        if self.cost_model is None or not self.shape_key:
            return None
        return self.cost_model.predict_compile_ms(
            self.shape_key, width, platform=self.platform,
            precision=self.precision)

    def _warm(self, width):
        """Whether the program family at ``width`` has compile evidence —
        its executable rides the persistent XLA cache, so moving there pays
        a warm retrieval, not a cold compile."""
        return bool(self.cost_model is not None and self.shape_key
                    and self.cost_model.compile_warm(
                        self.shape_key, width, platform=self.platform,
                        precision=self.precision))

    def _move_cost_ms(self, width):
        """Predicted cost of first-touching ``width``'s program family plus
        the compaction gather, or None (cold with no compile prior)."""
        if self._warm(width):
            return self.gather_ms
        cm = self._compile_ms(width)
        return None if cm is None else cm + self.gather_ms

    # ------------------------------------------------------------------
    # width decisions
    # ------------------------------------------------------------------
    def initial_width(self, g_real, n_devices):
        """Cheapest-priced ladder rung for a fresh grid; the heuristic base
        rung whenever the base rung itself cannot be priced (fallback
        contract) or no rung beats it strictly."""
        base = super().initial_width(g_real, n_devices)
        self._last_decision = None
        if not self.g_bucket or self.cost_model is None \
                or not self.epochs or not self.shape_key:
            return base
        n_dev = int(n_devices or 1)
        cap = base * 4 if self.max_width is None \
            else min(base * 4, self.max_width)
        priced = {}
        for w in compaction.ladder_widths(g_real, n_dev, max_width=cap):
            em = self._epoch_ms(w)
            if em is None:
                continue
            # a rung's total: every planned epoch at that width, plus the
            # cold compile when its program family is unseen (warm rungs
            # retrieve from the persistent cache — this is the engine half
            # of cold-compile ordering: first touch lands on the cache)
            compile_ms = 0.0 if self._warm(w) else self._compile_ms(w)
            if compile_ms is None:
                continue  # cold with no compile prior: unpriceable rung
            priced[w] = em * self.epochs + compile_ms
        dec = {"kind": "initial_width", "heuristic_width": base,
               "epochs": self.epochs}
        if base not in priced:
            # no usable prior at the heuristic rung: nothing to compare
            # against — fall back bit-identically
            self._last_decision = dict(dec, action="fallback",
                                       chosen_width=base, fallback=True)
            return base
        chosen = min(priced, key=lambda w: (priced[w], w))
        if not priced[chosen] < priced[base]:
            chosen = base  # strict improvement only: ties keep the ladder
        self._last_decision = dict(
            dec, action=("widen" if chosen != base else "keep"),
            chosen_width=chosen, fallback=False,
            total_ms=round(priced[chosen], 3),
            heuristic_ms=round(priced[base], 3),
            saving_ms=round(priced[base] - priced[chosen], 3))
        return chosen

    # ------------------------------------------------------------------
    # check-window compaction decision
    # ------------------------------------------------------------------
    def compaction_plan(self, active_host, orig_ids, retired_ids, n_devices,
                        n_processes=1, epochs_remaining=None):
        """The heuristic plan, priced: compact only when the predicted
        saving over the surviving epochs exceeds the predicted
        compile + gather cost of moving; hold (return None) otherwise.
        Unpriceable inputs fall back bit-identically to the heuristic
        (compact whenever the ladder says so)."""
        plan = super().compaction_plan(active_host, orig_ids, retired_ids,
                                       n_devices, n_processes=n_processes)
        self._last_decision = None
        if plan is None:
            return None
        from_w = int(np.asarray(orig_ids).size)
        to_w = plan.new_width
        dec = {"kind": "compaction", "from_width": from_w, "to_width": to_w,
               "epochs_remaining": epochs_remaining}
        if self.cost_model is None:
            return plan  # pure heuristic policy instance: nothing to record
        cur = self._epoch_ms(from_w)
        new = self._epoch_ms(to_w)
        cost = self._move_cost_ms(to_w)
        if cur is None or new is None or cost is None \
                or epochs_remaining is None:
            self._last_decision = dict(dec, action="compact", fallback=True)
            return plan
        saving = (cur - new) * max(int(epochs_remaining), 0)
        dec.update(fallback=False, saving_ms=round(saving, 3),
                   compile_ms=round(cost - self.gather_ms, 3),
                   gather_ms=self.gather_ms)
        if saving > cost:
            self._last_decision = dict(dec, action="compact")
            return plan
        self._last_decision = dict(dec, action="hold")
        return None

    # ------------------------------------------------------------------
    # cold-compile ordering (the fleet worker's half)
    # ------------------------------------------------------------------
    @staticmethod
    def compile_order(programs, cost_model=None, platform=None):
        """Order first-touch program descriptors so the longest predicted
        COLD compile starts first and warm/unpriceable families keep their
        given (urgency) order after — the fleet worker applies this within
        one urgency class of an admission plan, so the shared persistent
        compile cache warms on the critical path while the first claimer's
        prefetch overlaps the compile.

        ``programs``: sequence of dicts. A descriptor carrying a
        ``cold_compile_ms`` field (the fleet planner's batch views price it
        once at plan time: 0 = warm, >0 = predicted cold compile, None =
        unpriceable) is used as-is — one source of truth; otherwise the
        cost is derived here from ``shape_key``/``g_bucket``/``precision``
        against ``cost_model``. Returns indices into ``programs``."""
        cold = []
        rest = []
        for i, p in enumerate(programs):
            if "cold_compile_ms" in p:
                ms = p["cold_compile_ms"]
                ms = (float(ms) if isinstance(ms, (int, float)) and ms > 0
                      else None)
            elif cost_model is not None and p.get("shape_key"):
                ms = None
                prec = p.get("precision") or "f32"
                if not cost_model.compile_warm(
                        p["shape_key"], p.get("g_bucket") or 0,
                        platform=platform, precision=prec):
                    ms = cost_model.predict_compile_ms(
                        p["shape_key"], p.get("g_bucket") or 0,
                        platform=platform, precision=prec)
            else:
                ms = None
            if ms is not None:
                cold.append((-float(ms), i))
            else:
                rest.append(i)
        return [i for _, i in sorted(cold)] + rest
