"""Grid-search execution engine: many hyperparameter points as one sharded program.

Replaces the reference's SLURM-array pattern (itertools.product over hparam
lists + SLURM_ARRAY_TASK_ID, one process per grid point — ref
train/REDCLIFF_S_CMLP_d4IC_BSCgs1.py:66-108) with a vmapped train step over a
stacked parameter/coefficient axis, sharded across the device mesh. One TPU
slice trains dozens of grid points concurrently; multi-host meshes extend the
same axis over DCN.

Shape-changing hyperparameters (hidden sizes, lags, factor counts) cannot share
a compiled program; callers group points by shape and run one GridRun per group
— the grouping helper below does this from a list of config dicts.

Engine vs. policy: this module is the EXECUTION ENGINE only — vmapped
dispatch, mesh sharding, durable checkpoint/resume, result assembly. The
SCHEDULING DECISIONS it consults (which bucket-ladder width a grid runs at,
when live lanes compact down the ladder, which lanes a wall-clock budget
evicts) live in :class:`~redcliff_tpu.parallel.policy.GridSchedulingPolicy`
(parallel/policy.py, joining the pure-host planning in
parallel/compaction.py). The split lets services — the fleet sweep service's
admission planner (redcliff_tpu/fleet) foremost — drive the engine directly
and share the ladder/width logic without instantiating a runner.

Elastic grid scheduling (parallel/policy.py + parallel/compaction.py,
docs/ARCHITECTURE.md "Elastic grid scheduling & compile caching"): execution
widths ride a power-of-two bucket ladder (``g_bucket`` pads off-ladder grids
with masked filler lanes so heterogeneous sweeps reuse a small program set),
and at check-window boundaries the engine COMPACTS the grid down the ladder
once enough lanes have early-stopped/quarantined (``compaction``) — retired
lanes stop riding every dispatch, surviving lanes' update streams stay
bit-identical, and results/failures always report under original point ids.
A persistent, versioned XLA compilation cache (``compile_cache_dir``,
runtime/compileobs.py) makes restarts warm-start their programs; compile
durations and cache hits/misses land in ``dispatch_stats`` and
metrics.jsonl.

Execution engine (data/pipeline.py stream modes): with the default
``stream_mode="auto"`` an eligible fit runs the EPOCH engine — the dataset
stays HBM-resident, each epoch's shuffled batch order becomes a device index
array, and one jit'd dispatch scans the whole epoch's updates (validation is
one scanned dispatch too, and periodic checkpoints hand their device->host
gather + durable write to a background thread). Per-dispatch overhead, not
FLOPs, dominates at these model shapes (BASELINE.md, arXiv:2008.01040), so
one-epoch~=one-dispatch is the production mode; the k-batch scan and the
per-batch step remain as bit-identical fallbacks (``RedcliffGridRunner.
dispatch_stats`` records what actually ran).
"""
from __future__ import annotations

import contextlib
import copy
import os
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from redcliff_tpu.data import pipeline
from redcliff_tpu.models.redcliff import phase_schedule
from redcliff_tpu.parallel import compaction, remesh
from redcliff_tpu.parallel import policy as gridpolicy
from redcliff_tpu.parallel.policy import GridSchedulingPolicy
from redcliff_tpu.parallel.distributed import gather_to_host, put_along_mesh
from redcliff_tpu.parallel.mesh import (Mesh, grid_mesh, replicated,
                                        shard_leading_axis)
from redcliff_tpu.runtime import checkpoint as durable_ckpt
from redcliff_tpu.runtime import compileobs, faultinject, numerics
from redcliff_tpu.runtime import watchdog as rt_watchdog
from redcliff_tpu.runtime.preempt import (DeadlineExceeded, Preempted,
                                          PreemptionGuard)
from redcliff_tpu import obs
from redcliff_tpu.obs import MetricLogger
from redcliff_tpu.obs import costmodel as _costmodel
from redcliff_tpu.obs import memory as _obsmem
from redcliff_tpu.obs import profiling as _profiling
from redcliff_tpu.obs import quality as _quality
from redcliff_tpu.ops import autotune as _autotune
from redcliff_tpu.train.freeze import apply_freeze
from redcliff_tpu.utils.precision import (matmul_precision_ctx,
                                          precision_label,
                                          resolve_matmul_precision)

__all__ = ["GridSpec", "GridResult", "RedcliffGridRunner", "group_configs_by_shape"]

COEFF_AXES = (
    "forecast_coeff", "factor_score_coeff", "factor_cos_sim_coeff",
    "factor_weight_l1_coeff", "adj_l1_reg_coeff",
    "factor_weight_smoothing_penalty_coeff",
)
OPT_AXES = ("embed_lr", "gen_lr", "embed_weight_decay", "gen_weight_decay")
# per-point stopping-criteria coefficients (the reference mirrors loss coeffs
# into these in the drivers, ref train/...BSCgs1.py:102-105); fall back to the
# train config scalars
STOP_AXES = ("stopping_criteria_forecast_coeff",
             "stopping_criteria_factor_coeff",
             "stopping_criteria_cosSim_coeff")


@dataclass
class GridSpec:
    """G hyperparameter points sharing one model shape. Each entry of ``points``
    maps coefficient/optimizer/stopping axis names (COEFF_AXES + OPT_AXES +
    STOP_AXES) to floats; unspecified axes fall back to the base config /
    train config values.

    Wall-clock deadlines (docs/ARCHITECTURE.md "Liveness & supervision"):
    ``fit_deadline_s`` budgets each LANE — a scalar applies to every point, a
    sequence gives per-point budgets; a lane still active when its budget
    expires is checkpointed and evicted into ``GridResult.failures`` with
    cause ``"deadline"`` (the non-finite quarantine machinery; sibling-lane
    math is untouched, so their results are bit-identical to a no-deadline
    run). ``grid_deadline_s`` budgets the WHOLE fit: at the first epoch
    boundary past it, in-flight work is drained, a final checkpoint written,
    and :class:`~redcliff_tpu.runtime.preempt.DeadlineExceeded` raised —
    the run exits resumable, like a self-inflicted preemption. Budgets are
    per-process wall clock (a resumed attempt gets a fresh budget) and are
    deliberately NOT part of the resume fingerprint: changing them changes
    how long you search, never what a lane computes.

    ``lane_seeds`` (optional, one int per point) makes per-lane
    initialization COMPOSITION-INDEPENDENT: lane ``i`` derives its init key
    as ``fold_in(key, lane_seeds[i])`` instead of ``split(key, G)[i]``, so a
    point's fit no longer depends on its position or its co-tenants in the
    grid. The fleet batch driver derives these from point content, which is
    what lets a bisected sub-batch's survivors finish bit-identical to the
    uninterrupted merged run (docs/ARCHITECTURE.md "Fleet failure
    containment"). Part of the resume fingerprint: changed seeds are a
    different fit."""

    points: Sequence[dict]
    fit_deadline_s: Any = None   # scalar | per-point sequence | None
    grid_deadline_s: float | None = None
    lane_seeds: Sequence[int] | None = None
    # production precision mode for THIS grid ("f32" | "mixed"); None
    # inherits RedcliffTrainConfig.precision_mode. "mixed" runs bf16 MXU
    # contractions with f32 master params/reductions under the numerics
    # sentinel's watch (a skip storm auto-demotes the whole grid to f32 —
    # `precision` event). Part of the resume fingerprint: the mode changes
    # every step's update math
    precision_mode: str | None = None

    def __post_init__(self):
        if self.precision_mode is not None:
            from redcliff_tpu.utils.precision import check_precision_mode

            check_precision_mode(self.precision_mode)
        valid = set(COEFF_AXES) | set(OPT_AXES) | set(STOP_AXES)
        for i, p in enumerate(self.points):
            unknown = set(p) - valid
            if unknown:
                raise ValueError(
                    f"grid point {i} has unknown hyperparameter axes "
                    f"{sorted(unknown)}; valid axes: {sorted(valid)}")
        if self.lane_seeds is not None \
                and len(self.lane_seeds) != len(self.points):
            raise ValueError(
                f"lane_seeds has {len(self.lane_seeds)} entries for "
                f"{len(self.points)} grid points")
        if self.grid_deadline_s is not None and self.grid_deadline_s <= 0:
            raise ValueError("grid_deadline_s must be positive")
        if self.fit_deadline_s is not None:
            lanes = self.lane_deadlines()
            if len(lanes) != len(self.points):
                raise ValueError(
                    f"fit_deadline_s has {len(lanes)} entries for "
                    f"{len(self.points)} grid points")
            if (lanes <= 0).any():
                raise ValueError("fit_deadline_s entries must be positive")

    def lane_deadlines(self):
        """Per-lane wall-clock budgets as a float array ((G,), inf = no
        budget), or None when no per-fit deadline is configured."""
        if self.fit_deadline_s is None:
            return None
        if np.ndim(self.fit_deadline_s) == 0:
            return np.full((len(self.points),), float(self.fit_deadline_s))
        return np.asarray([float(d) for d in self.fit_deadline_s])

    def stacked(self, base_cfg, train_cfg):
        G = len(self.points)
        out = {}
        for name in COEFF_AXES:
            out[name] = jnp.asarray(
                [p.get(name, getattr(base_cfg, name)) for p in self.points],
                dtype=jnp.float32)
        for name in OPT_AXES + STOP_AXES:
            out[name] = jnp.asarray(
                [p.get(name, getattr(train_cfg, name)) for p in self.points],
                dtype=jnp.float32)
        return out

    def needs_gc(self, base_cfg):
        return any(p.get("factor_cos_sim_coeff", base_cfg.factor_cos_sim_coeff) > 0
                   for p in self.points)

    def needs_gc_lagged(self, base_cfg):
        return any(p.get("adj_l1_reg_coeff", base_cfg.adj_l1_reg_coeff) > 0
                   for p in self.points)


@dataclass
class GridResult:
    best_params: Any          # pytree with leading G axis
    best_criteria: np.ndarray  # (G,)
    best_epoch: np.ndarray     # (G,)
    val_history: np.ndarray    # (epochs, G) validation combo loss
    coeffs: dict
    active: np.ndarray = None  # (G,) bool; False = point early-stopped
    # quarantined/evicted grid points, one {"point", "epoch", "cause",
    # "hparams"} record each, cause in {"nonfinite_grad", "nonfinite_val",
    # "deadline"}: ``nonfinite_grad`` — the lane's in-graph numerics guard
    # skipped max_consecutive_skips steps in a row (stuck on poisoned
    # gradients); ``nonfinite_val`` — validation loss went non-finite with
    # finite steps; ``deadline`` — the lane outlived its
    # ``GridSpec.fit_deadline_s`` wall-clock budget and was checkpointed +
    # evicted (PR 4). All three freeze the lane via the active mask while
    # the rest of the grid keeps training; every field of this result is
    # indexed by ORIGINAL point id regardless of lane compaction
    failures: list = field(default_factory=list)


def group_configs_by_shape(config_dicts, shape_keys):
    """Partition config dicts into shape-compatible groups (one compiled program
    each). Returns {shape_tuple: [indices]}.

    Ordering is deterministic: groups appear in first-appearance order of
    their shape, and indices within a group are ascending — so the grid a
    caller builds from a group is stable across runs (resume fingerprints
    include the point list). Each group's GridRun then pads its width up to
    the power-of-two bucket ladder (``RedcliffTrainConfig.g_bucket``,
    parallel/compaction.py) with masked filler lanes, so heterogeneous
    sweeps share a small set of compiled programs instead of one program
    per exact (shape, G)."""
    groups = {}
    for i, cd in enumerate(config_dicts):
        key = tuple(cd.get(k) for k in shape_keys)
        groups.setdefault(key, []).append(i)
    return groups


class RedcliffGridRunner:
    """Trains G REDCLIFF-S configurations simultaneously.

    The per-point training step is the same phase-scheduled two-optimizer update
    as RedcliffTrainer, vmapped over (params, opt states, coefficients) with the
    batch broadcast, then jit'd with the G axis sharded over the mesh. Optimizer
    hyperparameters (lr, weight decay) vary per point by scaling raw
    scale_by_adam updates with the per-point learning rate and adding coupled
    weight decay to the gradients — torch.optim.Adam semantics
    (ref model_utils.py:749-762).
    """

    # per-fit execution accounting, (re)set by _fit: stream mode actually
    # run, epochs completed, train/val dispatch counts, and the main-thread
    # checkpoint stall in ms (bench.py and the dispatch-budget tripwire
    # test read this)
    dispatch_stats = None
    # fused one-dispatch state snapshot for async saves: a per-leaf
    # jnp.copy loop would cost one dispatch per leaf and dominate the
    # hand-off it is supposed to make cheap. Jitted once, pre-warmed by
    # _fit so the first save's stall excludes the compile
    _snapshot_fn = None

    def _ensure_snapshot_fn(self):
        if self._snapshot_fn is None:
            self._snapshot_fn = jax.jit(lambda t: jax.tree.map(jnp.copy, t))
        return self._snapshot_fn

    def __init__(self, model, train_config, spec: GridSpec, mesh=None,
                 policy=None):
        self.model = model
        self.tc = train_config
        self.spec = spec
        # elastic scheduling: ``mesh`` is the FULL device capacity;
        # ``self.mesh`` is the active mesh, which may be a sub-mesh after
        # bucketing/compaction shrinks the execution width below the device
        # count. The width/compaction/deadline DECISIONS live in the
        # scheduling policy (parallel/policy.py — engine/policy split);
        # this engine only executes them
        self._mesh_full = mesh
        self.mesh = mesh
        self._g_real = G_real = len(spec.points)
        self.policy = (policy if policy is not None
                       else GridSchedulingPolicy.from_train_config(
                           train_config))
        # predictive scheduling (ISSUE 15, parallel/policy.py): when armed
        # (REDCLIFF_PREDICTIVE) and a persistent cost-model store is
        # readable, swap the default heuristic for the predictive policy
        # BEFORE the initial-width decision below. Safe to arm anywhere:
        # every decision falls back bit-identically to the heuristic when
        # the store holds no usable prior, and the resume fingerprint is
        # width-agnostic (the checkpoint carries its own era). A
        # caller-supplied policy always wins — services inject their own
        if policy is None and gridpolicy.predictive_enabled():
            cm_base = (os.environ.get(_costmodel.ENV_STORE_DIR)
                       or getattr(train_config, "compile_cache_dir", None)
                       or os.environ.get(compileobs.ENV_CACHE_DIR) or None)
            cm = _costmodel.load(cm_base) if cm_base else None
            if cm is not None:
                # REDCLIFF_POLICY_MAX_WIDTH: the admission ceiling a
                # service priced its HBM/max_bucket gate at (the fleet
                # batch driver exports the planner-admitted G-bucket) —
                # warm-rung widening must never outgrow it
                max_w = os.environ.get(gridpolicy.ENV_POLICY_MAX_WIDTH)
                self.policy = gridpolicy.PredictiveSchedulingPolicy(
                    g_bucket=self.policy.g_bucket,
                    compaction=self.policy.compaction,
                    cost_model=cm,
                    shape_key=obs.schema.shape_key(self._shape_desc()),
                    platform=jax.default_backend(),
                    precision=precision_label(
                        spec.precision_mode
                        or getattr(train_config, "precision_mode", "f32"),
                        getattr(train_config, "matmul_precision", None)),
                    epochs=getattr(train_config, "max_iter", None),
                    max_width=(int(max_w) if max_w
                               and max_w.isdigit() else None))
        self._g_bucket = self.policy.g_bucket
        self._compaction_on = self.policy.compaction
        compileobs.enable_cache(
            getattr(train_config, "compile_cache_dir", None))
        compileobs.install()
        n_dev = mesh.devices.size if mesh is not None else 1
        g_exec = self.policy.initial_width(G_real, n_dev)
        # the initial-width decision record (predictive policy only): logged
        # as a `policy` event once _fit has a logger in hand
        self._policy_init_decision = (
            self.policy.take_decision()
            if hasattr(self.policy, "take_decision") else None)
        if mesh is not None and self._g_bucket:
            self.mesh = self._mesh_for(g_exec)
        self._g_exec0 = g_exec
        # original point id per execution lane; -1 marks bucket-padding
        # filler lanes (masked from birth, never surfaced in GridResult)
        self._orig_ids0 = np.concatenate(
            [np.arange(G_real, dtype=np.int32),
             np.full((g_exec - G_real,), -1, np.int32)])
        # result-facing coefficients stay at the REAL width; the execution
        # grid's coeffs are derived per era via _coeffs_for (filler lanes
        # replicate point 0 — finite, valid math whose results are masked)
        self.result_coeffs = {
            k: np.asarray(v)
            for k, v in spec.stacked(model.config, train_config).items()}
        self.coeffs = self._coeffs_for(self._orig_ids0)
        self._need_gc = spec.needs_gc(model.config)
        self._need_gc_lagged = spec.needs_gc_lagged(model.config)
        # numerics sentinel: per-lane in-graph non-finite guard + skip
        # counters; a lane stuck past max_consecutive_skips is quarantined
        # with cause "nonfinite_grad" (vs "nonfinite_val" for a validation
        # blow-up with finite steps)
        self._guard = (train_config.numerics is not None
                       and train_config.numerics.enabled)
        self._numerics_k = (train_config.numerics.max_consecutive_skips
                            if self._guard else 0)
        # lr/eps handled per-point; scale_by_adam is shared
        self.optA = optax.scale_by_adam(b1=0.9, b2=0.999, eps=train_config.embed_eps)
        self.optB = optax.scale_by_adam(b1=0.9, b2=0.999, eps=train_config.gen_eps)
        # production precision mode (utils/precision.py): the spec override
        # wins, else the train config. "mixed" grids are DEMOTABLE — a
        # sentinel skip storm rebuilds every program at f32 mid-fit
        # (`precision` event) and persists the demotion in the checkpoint
        self._precision_mode = (spec.precision_mode
                                or getattr(train_config, "precision_mode",
                                           "f32"))
        self._precision = resolve_matmul_precision(
            self._precision_mode,
            getattr(train_config, "matmul_precision", None))
        self._demotable = (self._precision_mode == "mixed" and self._guard
                           and self._precision is not None)
        self._demoted = False
        self._build()
        self._maybe_tune_kernels()

    def _maybe_tune_kernels(self):
        """Autotune the hot-path Pallas tilings for this grid's shapes on
        real TPU hardware (the shared shape-math lives in
        ops/autotune.py:tune_for_model). No-op off-TPU / when
        REDCLIFF_AUTOTUNE=0."""
        _autotune.tune_for_model(self.model.config, self.tc.batch_size,
                                 prox_penalty=getattr(self.tc,
                                                      "prox_penalty", None))

    def _demote_to_f32(self):
        """Rebuild every grid program at f32 — the sentinel-triggered
        precision demotion. The caller logs the `precision` event and
        resets the consecutive-skip counters."""
        self._precision = None
        self._demoted = True
        # the predictive policy's cost buckets follow the demotion: pricing
        # the rebuilt f32 programs from mixed-epoch evidence would mispredict
        # every post-demotion decision
        if hasattr(self.policy, "precision"):
            self.policy.precision = "f32"
        self._build()
        # the rebuilt jit wrappers are new programs: let their first
        # dispatch run under the op-scoped compile heartbeat again
        self._seen_programs = None

    # ------------------------------------------------------------------
    def _opt_states(self, params):
        """Per-point optimizer state over a (G, ...)-stacked params tree."""
        optA_state = jax.vmap(lambda p: self.optA.init(p["embedder"]))(params)
        optB_state = jax.vmap(lambda p: self.optB.init(p["factors"]))(params)
        return optA_state, optB_state

    def init_grid(self, key):
        """G independently-seeded parameter sets, stacked on axis 0.

        With ``spec.lane_seeds`` each lane's key is ``fold_in(key, seed)``
        — a function of the point's own seed only, so the same point inits
        identically whatever grid it is merged into; without them, the
        historical ``split(key, G)`` derivation (position- and
        width-dependent) is kept bit-for-bit."""
        G = len(self.spec.points)
        if self.spec.lane_seeds is not None:
            keys = jnp.stack([jax.random.fold_in(key, int(s))
                              for s in self.spec.lane_seeds])
        else:
            keys = jax.random.split(key, G)
        params = jax.vmap(self.model.init)(keys)
        return (params,) + self._opt_states(params)

    def init_grid_from(self, point_params):
        """Replicate ONE parameter set across the grid axis — the SLURM-array
        pattern's initialization, where every per-point process seeds
        identically (ref train drivers fix all seeds to 0, ref :122-127), so
        grid-vs-per-point comparisons share the exact same starting weights."""
        G = len(self.spec.points)
        params = jax.tree.map(
            lambda x: jnp.stack([jnp.asarray(x)] * G), point_params)
        return (params,) + self._opt_states(params)

    def _build(self):
        model = self.model
        need_gc, need_gc_lagged = self._need_gc, self._need_gc_lagged
        guard = self._guard

        precision = self._precision
        prox_pen = getattr(self.tc, "prox_penalty", None)
        prox_lam = getattr(self.tc, "prox_lam", 0.0)

        def point_step(params, optA_state, optB_state, nstate, coeffs, active,
                       X, Y, phase):
            def loss_fn(p):
                return model.loss_for_phase(
                    p, X, Y, phase, coeffs=coeffs,
                    need_gc=need_gc, need_gc_lagged=need_gc_lagged)

            with matmul_precision_ctx(precision):
                (combo, _), grads = jax.value_and_grad(loss_fn,
                                                       has_aux=True)(params)

            # per-lane numerics guard: a non-finite loss/gradient makes this
            # lane's update a no-op (SPMD stays uniform — compute runs, the
            # result is discarded) and bumps its device-side skip counters
            if guard:
                gnorm = numerics.global_norm(grads)
                ok = jnp.logical_and(jnp.isfinite(combo), jnp.isfinite(gnorm))
                nstate = numerics.update_numerics_state(nstate, ok, gnorm,
                                                        count=active)
                gate = jnp.logical_and(active, ok)
            else:
                gate = active

            def apply_group(group, grads_g, opt, opt_state, lr, wd):
                g = jax.tree.map(lambda gr, pa: gr + wd * pa, grads_g, params[group])
                upd, new_state = opt.update(g, opt_state)
                upd = jax.tree.map(lambda u: -lr * u, upd)
                new_p = optax.apply_updates(params[group], upd)
                # per-point early-stop/numerics lane mask: a converged or
                # guarded point keeps its params/opt state unchanged
                keep = lambda n, o: jax.tree.map(
                    lambda a, b: jnp.where(gate, a, b), n, o)
                return keep(new_p, params[group]), keep(new_state, opt_state)

            new = dict(params)
            if phase in ("embedder_pretrain", "combined"):
                new["embedder"], optA_state = apply_group(
                    "embedder", grads["embedder"], self.optA, optA_state,
                    coeffs["embed_lr"], coeffs["embed_weight_decay"])
            if phase in ("factor_pretrain", "post_train", "combined"):
                new["factors"], optB_state = apply_group(
                    "factors", grads["factors"], self.optB, optB_state,
                    coeffs["gen_lr"], coeffs["gen_weight_decay"])
                if prox_pen is not None:
                    # GISTA prox on the factor first-layer block after the
                    # gradient step (GL rides the fused Pallas kernel on
                    # real TPUs, ops/pallas_prox.py); the lane gate keeps
                    # frozen/guarded lanes' params untouched — a prox of an
                    # unchanged iterate would still shrink it
                    proxed = model.apply_prox(new, prox_lam,
                                              coeffs["gen_lr"],
                                              prox_pen)["factors"]
                    new["factors"] = jax.tree.map(
                        lambda a, b: jnp.where(gate, a, b), proxed,
                        new["factors"])
            return new, optA_state, optB_state, nstate, combo

        def point_val(params, coeffs, X, Y):
            with matmul_precision_ctx(precision):
                combo, parts = model.loss_for_phase(
                    params, X, Y, "combined", coeffs=coeffs,
                    need_gc=need_gc, need_gc_lagged=need_gc_lagged)
            # coefficient-normalized stopping-criteria terms (the reference
            # divides each val part by its loss coefficient "for comparisson
            # in grid-searches", ref validate_training :1684-1699, mirrored
            # by RedcliffTrainer.validate); the per-point criteria
            # combination (stopping coeffs x these means, ref :1466-1538)
            # happens in _fit so the means aggregate over ALL val batches
            fo = parts["forecasting_loss"] / jnp.where(
                coeffs["forecast_coeff"] > 0, coeffs["forecast_coeff"], 1.0)
            fa = parts["factor_loss"] / jnp.where(
                coeffs["factor_score_coeff"] > 0,
                coeffs["factor_score_coeff"], 1.0)
            return combo, fo, fa

        # supervised pairwise-cosine stopping term (ref :1467): mean cosine
        # between max-normalized lag-summed supervised GC estimates on the
        # first val batch, mirroring RedcliffTrainer._epoch_gc_tracking +
        # GCTracker._track_cosines
        S = model.config.num_supervised_factors
        cfg_gc = model.config

        def point_cos(params, X):
            est = model.gc(params, cfg_gc.primary_gc_est_mode, X=X,
                           threshold=False, ignore_lag=True)[..., 0]
            S_eff = min(S, est.shape[1])
            if S_eff < 2:
                return jnp.zeros(())
            sup = est[:, :S_eff]
            m = jnp.max(sup, axis=(-2, -1), keepdims=True)
            # positive-max guard, matching GCTracker._track_cosines'
            # documented deviation from the reference's 1e-300 floor:
            # all-non-positive estimates pass through unscaled and the norm
            # floor below keeps the cosine finite (equivalence on this regime
            # is pinned by test_grid_trainer_cosine_parity_nonpositive)
            sup = sup / jnp.where(m > 0, m, 1.0)
            flat = sup.reshape(sup.shape[0], S_eff, -1)
            norms = jnp.maximum(jnp.linalg.norm(flat, axis=-1), 1e-8)
            sims = (jnp.einsum("nik,njk->nij", flat, flat)
                    / (norms[:, :, None] * norms[:, None, :]))
            iu = jnp.triu_indices(S_eff, k=1)
            return jnp.mean(sims[:, iu[0], iu[1]])

        self._cos = (jax.jit(jax.vmap(point_cos, in_axes=(0, None)))
                     if S > 1 else None)

        self._steps = {}
        self._scan_steps = {}
        self._epoch_steps = {}
        for phase in ("embedder_pretrain", "factor_pretrain", "combined", "post_train"):
            vstep = jax.vmap(
                lambda p, a, b, ns, c, act, X, Y, ph=phase: point_step(
                    p, a, b, ns, c, act, X, Y, ph),
                in_axes=(0, 0, 0, 0, 0, 0, None, None))
            # donate params + opt states + numerics counters: they are
            # consumed and rebound every step, so XLA can update them in
            # place instead of round-tripping a second copy of the whole
            # grid state through HBM
            self._steps[phase] = jax.jit(vstep, donate_argnums=(0, 1, 2, 3))

            # k-batch scanned variant: one dispatch drives lax.scan over k
            # pre-staged device-resident batches (Xs (k, B, T, C), Ys
            # (k, ...)), amortizing the per-step dispatch overhead that
            # dominates wall-clock at large G (BASELINE.md: ~0.24 ms/step
            # floor past G~64)
            def scan_step(params, optA_state, optB_state, nstate, coeffs,
                          active, Xs, Ys, _vstep=vstep):
                def body(carry, xy):
                    p, a, b, ns = carry
                    p, a, b, ns, combo = _vstep(p, a, b, ns, coeffs, active,
                                                *xy)
                    return (p, a, b, ns), combo

                (p, a, b, ns), combos = jax.lax.scan(
                    body, (params, optA_state, optB_state, nstate), (Xs, Ys))
                return p, a, b, ns, combos

            self._scan_steps[phase] = jax.jit(scan_step,
                                              donate_argnums=(0, 1, 2, 3))

            # epoch-granular variant (data/pipeline.py "epoch" stream mode):
            # ONE dispatch gathers the epoch's shuffled batch order from the
            # HBM-resident dataset (idx (num_batches, B)) and scans the
            # whole epoch of updates. The gather runs OUTSIDE the scan —
            # the scan then consumes stacked batches exactly like the
            # k-batch scan step, which is what keeps this path bit-identical
            # to the per-batch path (a per-iteration in-body gather lets
            # XLA fuse it into the step and round a few weights 1 ulp
            # differently). Costs one transient epoch-sized device buffer,
            # bounded by the pipeline's HBM-residency cap.
            def epoch_step(params, optA_state, optB_state, nstate, coeffs,
                           active, Xfull, Yfull, idx, _vstep=vstep):
                Xs = jnp.take(Xfull, idx, axis=0)
                Ys = jnp.take(Yfull, idx, axis=0)

                def body(carry, xy):
                    p, a, b, ns = carry
                    p, a, b, ns, combo = _vstep(p, a, b, ns, coeffs, active,
                                                *xy)
                    return (p, a, b, ns), combo

                (p, a, b, ns), combos = jax.lax.scan(
                    body, (params, optA_state, optB_state, nstate), (Xs, Ys))
                return p, a, b, ns, combos

            self._epoch_steps[phase] = jax.jit(epoch_step,
                                               donate_argnums=(0, 1, 2, 3))

        # Freeze-mode accept/revert choreography: the shared trainer logic
        # (train/freeze.py), vmapped over the grid axis
        mode = model.config.training_mode
        self._freeze_by_batch = "FreezeByBatch" in mode
        self._freeze = "Freeze" in mode
        if self._freeze:
            def freeze_point(c, a):
                with matmul_precision_ctx(precision):
                    return apply_freeze(model, mode, c, a)

            self._freeze_step = jax.jit(
                jax.vmap(freeze_point, in_axes=(0, 0)),
                donate_argnums=(0, 1))
        vval = jax.vmap(point_val, in_axes=(0, 0, None, None))
        self._val = jax.jit(vval)

        # whole-validation-set dispatch for the epoch stream: scan the vmapped
        # point_val over batch indices, accumulating the per-batch sums in
        # the carry IN ORDER (sequential adds from zero — bit-identical to
        # the per-batch val loop's `0.0 + combo_1 + combo_2 + ...`)
        def val_scan(params, coeffs, Xfull, Yfull, idx):
            # gather-outside-the-scan for the same reason as the epoch
            # train step: the scan consumes stacked batches, keeping the
            # per-batch loss math (and therefore the ordered sums)
            # bit-identical to the per-batch val loop
            Xs = jnp.take(Xfull, idx, axis=0)
            Ys = jnp.take(Yfull, idx, axis=0)

            def body(carry, xy):
                cs, fs, fas = carry
                c, fo, fa = vval(params, coeffs, *xy)
                return (cs + c, fs + fo, fas + fa), None

            zero = jnp.zeros(coeffs["embed_lr"].shape, jnp.float32)
            (cs, fs, fas), _ = jax.lax.scan(body, (zero, zero, zero),
                                            (Xs, Ys))
            return cs, fs, fas

        self._val_scan = jax.jit(val_scan)

        def select_best(best_params, best_crit, best_epoch, params, crit, epoch):
            better = crit < best_crit
            new_best = jax.tree.map(
                lambda b, c: jnp.where(
                    better.reshape((-1,) + (1,) * (c.ndim - 1)), c, b),
                best_params, params)
            return (new_best, jnp.where(better, crit, best_crit),
                    jnp.where(better, epoch, best_epoch))

        self._select_best = jax.jit(select_best)

    # ------------------------------------------------------------------
    def _shard(self, tree):
        if self.mesh is None:
            return tree
        # put_along_mesh handles both single-process (plain sharded
        # device_put) and multi-host (each process materializes only its
        # addressable shards) meshes
        return jax.tree.map(lambda x: put_along_mesh(x, self.mesh), tree)

    def _mesh_for(self, width):
        """The mesh an execution grid of ``width`` lanes shards over: the
        full mesh when the width is a multiple of its device count, a
        SUB-mesh over the first ``width`` devices when the width divides it
        (the G' < n_devices case after compaction). Bucket-ladder widths
        (parallel/compaction.py) always satisfy one of the two."""
        mesh = self._mesh_full
        if mesh is None:
            return None
        n_dev = mesh.devices.size
        if width % n_dev == 0:
            return mesh
        if n_dev % width == 0:
            return Mesh(mesh.devices.ravel()[:width], mesh.axis_names)
        raise ValueError(
            f"grid width {width} cannot shard over the {n_dev}-device mesh "
            f"(neither a multiple nor a divisor of the device count)")

    def _coeffs_for(self, orig_ids):
        """Execution-width stacked coefficients for one compaction era:
        real lanes take their point's values, filler lanes replicate the
        first real lane (their math must stay finite; their results are
        discarded via the active mask)."""
        ids = np.asarray(orig_ids)
        real = ids >= 0
        fill = int(ids[real][0]) if real.any() else 0
        idx = np.where(real, ids, fill)
        return {k: jnp.asarray(v[idx]) for k, v in self.result_coeffs.items()}

    def _exec_deadlines(self, orig_ids):
        """Per-execution-lane wall-clock budgets for the current era
        (filler lanes: +inf), or None when no per-fit deadline is set."""
        lane_deadline = self.spec.lane_deadlines()
        if lane_deadline is None:
            return None
        ids = np.asarray(orig_ids)
        out = np.full(ids.shape, np.inf)
        m = ids >= 0
        out[m] = lane_deadline[ids[m]]
        return out

    # programs already dispatched at least once, keyed by (kind, phase,
    # width, batch shape...): the first dispatch of a new program may pay a
    # cold XLA compile, so it runs under the op-scoped ``compile`` heartbeat
    # — the watchdog excuses stalled siblings while it is live instead of
    # misclassifying a long first-compile window as a hang
    _seen_programs = None
    # per-runner jit'd quality-summary program (obs/quality.py) + the
    # top-k it was built with — rebuilt only when the knob changes
    _qual_fn = None
    _qual_fn_k = None

    def _call_cold(self, key, fn, *args):
        if self._seen_programs is None:
            self._seen_programs = set()
        cold = key not in self._seen_programs
        if cold:
            self._seen_programs.add(key)
        # per-dispatch trace span: ring-only (obs.flight — the crash flight
        # recorder's evidence of what the engine was dispatching in its last
        # seconds), one dict + deque append when tracing is on, one flag
        # check when off. Measures host enqueue wall time by design — no
        # block_until_ready, no transfer (device time stays attributable via
        # dispatch_stats' counters)
        with obs.span("grid.dispatch", component="dispatch",
                      kind=str(key[0]), cold=cold):
            if cold:
                with rt_watchdog.op_scope(rt_watchdog.COMPILE_COMPONENT):
                    return fn(*args)
            return fn(*args)

    def phase_for_epoch(self, epoch):
        return phase_schedule(self.model.config, epoch)

    def _shape_desc(self):
        """fit_start's ``shape`` field: the model-config fields that key a
        compiled program family — with the grid width, the (shape, G-bucket)
        axis of the obs report's cost table."""
        return obs.schema.shape_desc(self.model.config)

    def _align_all_points(self, params, train_ds):
        """Per-point Hungarian alignment of factors to supervised labels at the
        pretrain->train transition (ref initialize_factors_with_prior :147-202),
        vectorized: one vmapped forward gathers every point's first factor
        weightings, then each point's permutation is solved on host and applied
        as a per-point gather along the factor axis."""
        cfg = self.model.config
        tc = self.tc
        preds, labels = [], []
        fw_fn = jax.jit(jax.vmap(
            lambda p, X: self.model.forward(p, X)[2][0], in_axes=(0, None)))
        for b, (X, Y) in enumerate(train_ds.batches(tc.batch_size)):
            if b >= tc.max_factor_prior_batches:
                break
            preds.append(gather_to_host(
                fw_fn(params, jnp.asarray(X[:, : cfg.max_lag, :]))))
            if Y.ndim == 3:
                col = cfg.max_lag if Y.shape[2] > cfg.max_lag else 0
                labels.append(np.asarray(Y[:, :, col]))
            else:
                labels.append(np.asarray(Y))
        preds = np.concatenate(preds, axis=1)  # (G, N, K), G = EXECUTION width
        lab = np.vstack(labels)  # (N, S)
        from redcliff_tpu.utils.misc import factor_alignment_order

        K = cfg.num_factors
        G = preds.shape[0]  # execution width (bucket filler lanes included)
        orders = np.zeros((G, K), dtype=np.int32)
        for g in range(G):
            orders[g] = np.asarray(
                factor_alignment_order(
                    preds[g], lab, K,
                    unsupervised_start_index=tc.unsupervised_start_index),
                dtype=np.int32)
        idx = jnp.asarray(orders)
        factors = jax.tree.map(
            lambda leaf: jnp.take_along_axis(
                leaf, idx.reshape(idx.shape + (1,) * (leaf.ndim - 2)), axis=1),
            params["factors"])
        return dict(params, factors=factors)

    # ------------------------------------------------------------------
    # checkpoint/resume: the grid analog of the per-point trainer's
    # resume-from-checkpoint (ref redcliff_s_cmlp.py fit/save_checkpoint) —
    # a long grid fit survives preemption and resumes BIT-IDENTICALLY
    # (optimizer moments, best-trees, lane masks, and the batch-shuffle rng
    # state are all captured). Durability (atomic writes, CRC header, .prev
    # generation, quarantine of corrupt files) lives in runtime/checkpoint.py;
    # this class owns the resume-compatibility fingerprint.
    CHECKPOINT_NAME = "grid_checkpoint.pkl"

    @staticmethod
    def _to_host(v):
        """Gather a device value to a full host array; restored-checkpoint
        entries are already host numpy and must NOT be re-gathered (the
        multi-host allgather would tile a full array per process)."""
        if isinstance(v, np.ndarray):
            return v
        return np.asarray(gather_to_host(v))

    def _checkpoint_meta(self, train_ds, val_ds):
        """The COMPLETE resume-compatibility fingerprint: every knob whose
        change would make "resume" silently mean "train something else" —
        grid points, seed, training mode, the RedcliffTrainConfig fields that
        shape the batch/epoch stream (a restored rng state replays a
        DIFFERENT batch sequence under a new batch_size), and the train/val
        dataset shapes. Deliberately absent: the mesh — checkpoints hold
        gathered host state, so a fit may resume on a smaller/larger device
        mesh (graceful degradation after losing part of a slice) — and the
        per-call ``fit(max_iter=...)`` override: the epoch stream is
        horizon-invariant (no phase schedule or early-stop term reads
        max_iter), so training the first N epochs and resuming toward a
        different horizon is bit-safe; only a changed tc.max_iter is treated
        as a different configured fit. Also deliberately absent, like the
        deadlines: the elastic-scheduling knobs (``compaction``,
        ``g_bucket``, ``compile_cache_dir``) — they change which PROGRAM
        executes (grid width, warm starts), never what a lane computes, and
        the checkpoint state itself carries the compaction era
        (``orig_ids``/``retired``) so resume always lands in the bucket the
        checkpoint was written at."""
        tc = self.tc
        return {
            "points": list(self.spec.points),
            # lane-seed derivation changes every lane's init stream, so a
            # checkpoint written under one derivation must never resume
            # under another (absent key == the historical split(key, G))
            "lane_seeds": (list(int(s) for s in self.spec.lane_seeds)
                           if self.spec.lane_seeds is not None else None),
            "seed": tc.seed,
            "training_mode": self.model.config.training_mode,
            "batch_size": tc.batch_size,
            "check_every": tc.check_every,
            "lookback": tc.lookback,
            "scan_batches": tc.scan_batches,
            # stream-mode/prefetch knobs: every mode replays the SAME batch
            # sequence today (epoch_batch_plan consumes the shuffle rng
            # exactly like batches()), but the fingerprint pins them so a
            # future mode that diverges can never silently replay a
            # different stream on resume
            "stream_mode": tc.stream_mode,
            "prefetch_batches": tc.prefetch_batches,
            "max_iter": tc.max_iter,
            # matmul precision changes every step's update math (MXU bf16 vs
            # f32 passes), so resuming under a different precision would
            # break the bit-identity promise mid-stream (ADVICE r5 audit:
            # the one update-math knob the PR-3 fingerprint missed)
            "matmul_precision": tc.matmul_precision,
            # the production precision mode is the same class of knob: a
            # resumed fit can never silently change numerics (a mid-fit
            # sentinel DEMOTION is state, not config — the checkpoint's
            # precision_demoted flag carries it, the fingerprint does not)
            "precision_mode": self._precision_mode,
            # prox knobs change the factor update math every step
            "prox": {"penalty": getattr(tc, "prox_penalty", None),
                     "lam": getattr(tc, "prox_lam", 0.0)},
            # the numerics guard gates every update and decides lane
            # quarantine, so a changed/disabled policy is a different fit
            "numerics": (None if tc.numerics is None
                         else asdict(tc.numerics)),
            "train_data": durable_ckpt.dataset_fingerprint(train_ds),
            "val_data": durable_ckpt.dataset_fingerprint(val_ds),
        }

    # device trees the jit'd train steps DONATE: the next dispatch
    # invalidates their buffers, so an asynchronous save must snapshot them
    # (cheap in-device jnp.copy) before the train loop moves on
    _DONATED_STATE_KEYS = ("params", "optA_state", "optB_state", "nstate",
                           "accepted")

    # snapshot keys that are already host-side bookkeeping (no device
    # gather): compaction-era state plus the scalar loop bookkeeping, the
    # mesh-shape audit metadata, and the dispatch_stats telemetry snapshot
    # (audit/analytics payload — like "mesh", NOT part of the resume
    # fingerprint; the obs report CLI joins it with metrics.jsonl)
    _HOST_STATE_KEYS = ("epoch", "aligned", "rng_state", "val_history",
                        "val_eras", "eras", "orig_ids", "retired", "mesh",
                        "dispatch_stats", "precision_demoted")

    @staticmethod
    def _hostify(snap, meta, to_host):
        """Snapshot dict -> the checkpoint payload (device->host gathers
        included). Runs on the background writer thread in async mode.

        The per-epoch loss rows are stored EXPANDED to the original point
        width (compaction.expand_history) so a resumed fit — which may land
        in a different compaction era than the one that wrote any given row
        — always restores a uniform, original-id-indexed history."""
        host = {
            k: (jax.tree.map(to_host, v) if v is not None else None)
            for k, v in snap.items()
            if k not in RedcliffGridRunner._HOST_STATE_KEYS
        }
        host["epoch"] = snap["epoch"]
        host["aligned"] = snap["aligned"]
        host["rng_state"] = snap["rng_state"]
        host["orig_ids"] = np.asarray(snap["orig_ids"], np.int32)
        host["retired"] = snap["retired"]
        # mesh shape the writing attempt ran at: audit metadata only — it is
        # NOT in the fingerprint (meta), so a checkpoint from an 8-device
        # mesh resumes on 4 devices (and vice versa) without rejection
        host["mesh"] = snap.get("mesh")
        host["dispatch_stats"] = snap.get("dispatch_stats")
        # sentinel-triggered precision demotion (mixed -> f32): state, not
        # fingerprint — a resume rebuilds its programs at f32
        host["precision_demoted"] = bool(snap.get("precision_demoted"))
        rows = [to_host(v) for v in snap["val_history"]]
        host["val_history"] = list(compaction.expand_history(
            rows, snap["val_eras"], snap["eras"], len(meta["points"])))
        host["meta"] = meta
        return host

    def _save_checkpoint(self, checkpoint_dir, state, meta, writer=None):
        """Write the fit state durably — atomic tmp+replace with CRC header
        and a trailing .prev generation.

        ``writer`` (an :class:`~redcliff_tpu.runtime.checkpoint
        .AsyncCheckpointWriter`, single-process only) makes the save
        asynchronous: the main thread only snapshots the donated device
        trees (in-device ``jnp.copy`` — the next train dispatch would
        invalidate the originals under the background reader) and kicks off
        the device->host copies; the blocking gather + pickle + CRC + fsync
        all run on the writer thread, overlapping the next training epoch.
        Multi-host saves stay synchronous: the gathers are collectives and
        must run on every process's main thread (process 0 writes)."""
        if writer is None or jax.process_count() > 1:
            host = self._hostify(state, meta, self._to_host)
            if jax.process_index() != 0:
                return
            os.makedirs(checkpoint_dir, exist_ok=True)
            durable_ckpt.write_checkpoint(
                os.path.join(checkpoint_dir, self.CHECKPOINT_NAME), host)
            return
        donated = {k: state[k] for k in self._DONATED_STATE_KEYS
                   if state.get(k) is not None}
        donated = self._ensure_snapshot_fn()(donated)
        snap = {}
        for k, v in state.items():
            if k in ("val_history", "val_eras", "eras"):
                snap[k] = list(v)  # the live lists keep growing
            elif k == "retired":
                snap[k] = dict(v)  # compaction may retire more lanes later
            else:
                snap[k] = donated.get(k, v) if k in self._DONATED_STATE_KEYS \
                    else v
        # start the D2H copies now (non-blocking) so the writer thread's
        # np.asarray calls mostly find the host values already materialized
        for leaf in jax.tree.leaves(snap):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()
        os.makedirs(checkpoint_dir, exist_ok=True)
        path = os.path.join(checkpoint_dir, self.CHECKPOINT_NAME)
        meta = dict(meta)
        writer.submit(lambda: durable_ckpt.write_checkpoint(
            path, self._hostify(snap, meta, self._to_host)))

    def _load_checkpoint(self, checkpoint_dir, want_meta):
        """Load the newest usable checkpoint generation, or None for a fresh
        start. Corrupt generations are quarantined to *.bad (head falls back
        to .prev); a readable checkpoint from a DIFFERENT fit is rejected
        loudly. Returns (ckpt, source_path)."""
        path = os.path.join(checkpoint_dir, self.CHECKPOINT_NAME)
        if jax.process_count() == 1:
            ckpt, src = durable_ckpt.load_checkpoint(path)
        else:
            # all processes must take the same branch or the in-loop
            # collectives deadlock; process 0's view (including which
            # generation survived quarantine) decides, and a process that
            # cannot read the generation it decided on fails loudly
            from jax.experimental import multihost_utils

            src_code = 0
            ckpt = None
            if jax.process_index() == 0:
                ckpt, src = durable_ckpt.load_checkpoint(path)
                src_code = 0 if src is None else (1 if src == path else 2)
            src_code = int(multihost_utils.broadcast_one_to_all(
                np.asarray(src_code)))
            src = (None, path, path + ".prev")[src_code]
            if src is not None and jax.process_index() != 0:
                try:
                    ckpt = durable_ckpt.read_checkpoint(src)
                except (OSError, durable_ckpt.CheckpointCorruptError) as e:
                    raise FileNotFoundError(
                        f"process {jax.process_index()} cannot read the grid "
                        f"checkpoint process 0 loaded ({src}: {e}) — "
                        f"checkpoint_dir must be on storage shared by every "
                        f"process")
        if ckpt is None:
            return None, None
        meta = ckpt.get("meta", {})
        if not any(k in meta for k in ("batch_size", "train_data")):
            # pre-durability meta ({points, seed, training_mode} only): the
            # state dict also predates the quarantine bookkeeping, so it
            # cannot resume under this code — say so, not "different fit"
            raise ValueError(
                f"checkpoint in {checkpoint_dir!r} predates the durable "
                f"checkpoint format (no compatibility fingerprint or "
                f"quarantine state); it cannot be resumed by this version — "
                f"delete it (or finish the fit with the code that wrote it) "
                f"and rerun.")
        want_meta = dict(want_meta)
        if "numerics" not in meta and want_meta.get("numerics") == asdict(
                numerics.NumericsPolicy()):
            # pre-sentinel checkpoint (no numerics key): the default guard
            # does not change healthy-lane update math, so resuming it under
            # the DEFAULT policy is sound (the loop backfills the sentinel
            # state); resuming under a non-default policy still rejects
            want_meta.pop("numerics")
        if ("stream_mode" not in meta
                and want_meta.get("stream_mode") == "auto"
                and want_meta.get("prefetch_batches") == 2):
            # pre-pipeline checkpoint: all stream modes replay the identical
            # batch sequence (the epoch plan consumes the rng exactly like
            # batches()), so resuming under the default knobs is sound;
            # non-default knobs still reject loudly
            want_meta.pop("stream_mode")
            want_meta.pop("prefetch_batches")
        if ("matmul_precision" not in meta
                and want_meta.get("matmul_precision") is None):
            # pre-watchdog checkpoint: written before the precision knob
            # joined the fingerprint; the backend-default precision (None)
            # is what every such checkpoint trained under, so resuming under
            # the default is sound — a non-default precision still rejects
            want_meta.pop("matmul_precision")
        if ("precision_mode" not in meta
                and want_meta.get("precision_mode") == "f32"):
            # pre-mixed-precision checkpoint: every such fit trained at the
            # backend default, which is exactly what precision_mode="f32"
            # means — resuming under the default is sound; "mixed" rejects
            want_meta.pop("precision_mode")
        if "prox" not in meta and want_meta.get("prox") == {
                "penalty": None, "lam": 0.0}:
            # pre-prox checkpoint: no fit ever applied a prox before the
            # knob existed, so resuming with prox OFF is sound
            want_meta.pop("prox")
        if "lane_seeds" not in meta:
            # pre-containment checkpoint: written before per-lane content
            # seeds joined the fingerprint. Lane seeds are consulted ONLY
            # by init_grid and a resumed fit never re-initializes — the
            # checkpointed params already embody whatever derivation wrote
            # them — so finishing under any current lane_seeds is sound
            # (a changed point set still rejects via "points"). Without
            # this an upgraded fleet worker reclaiming an old in-flight
            # batch would crash-loop a healthy request into the
            # dead-letter queue.
            want_meta.pop("lane_seeds", None)
        diff = ([k for k in want_meta if meta.get(k) != want_meta[k]]
                + [k for k in meta if k not in want_meta])
        if diff:
            detail = ", ".join(
                f"{k}: saved={meta.get(k)!r} current={want_meta.get(k)!r}"
                for k in diff)
            raise ValueError(
                f"checkpoint in {checkpoint_dir!r} was written by a "
                f"different fit — resuming it would silently train something "
                f"else. Mismatched fields: {detail}. Point checkpoint_dir "
                f"elsewhere, delete the stale checkpoint, or rerun with the "
                f"original configuration.")
        return ckpt, src

    def fit(self, key, train_ds, val_ds, max_iter=None,
            log_dir=None, init_params=None, copy_init=True,
            checkpoint_dir=None, checkpoint_every=None,
            true_gc=None, on_lane_retire=None) -> GridResult:
        """checkpoint_dir + checkpoint_every enable periodic fit-state
        checkpoints; a fit pointed at a directory holding one resumes from
        it (bit-identically) instead of starting over.

        ``on_lane_retire(point_id, record, epoch)`` — per-point result
        streaming hook (ISSUE 18): called at a check-window boundary for
        each lane the compaction ladder retires to the host store (its
        state never changes again — early-stopped or quarantined), with
        the retired record (``best_crit``/``best_epoch``/``failed_epoch``/
        ``failed_cause``/``best_params``) and the retiring epoch. Called
        only for lanes retired by THIS process (a resume does not replay
        earlier attempts' retirements); exceptions are swallowed — the
        hook is telemetry, decision streams and params are bit-identical
        with or without it.

        Model-quality observatory (obs/quality.py, ``REDCLIFF_QUALITY``):
        at every check-window boundary a jit'd per-lane graph summary
        (per-factor GC column norms, edge energy, sparsity, top-k edge
        set, factor-score entropy) rides the window's existing
        device->host transfer into schema-registered ``quality`` events
        and ``dispatch_stats["quality"]`` (edge-set Jaccard stability,
        edge-energy plateau detection with ``plateaued_at_epoch``).
        ``true_gc`` — the dataset's ground-truth graphs (list of
        ``(C, C[, L])`` arrays, e.g. synthetic sVAR / DREAM4) — adds live
        per-lane AUROC/AUPR on the eval/gc_estimates readout convention.
        Telemetry only: decision streams and params are bit-identical
        with the observatory on, off, or supplied with truth.

        Fault tolerance (docs/ARCHITECTURE.md "Fault tolerance & resume
        semantics"): checkpoints are written atomically with a CRC header and
        a trailing .prev generation; corrupt files are quarantined to *.bad
        and the fit restarts cleanly; a checkpoint from an incompatible fit
        (different points/seed/batch stream/dataset shapes) is REJECTED with
        the mismatching fields. While checkpointing is enabled, SIGTERM/
        SIGINT triggers one final checkpoint at the end of the in-flight
        epoch and raises :class:`~redcliff_tpu.runtime.preempt.Preempted`.
        Grid points whose validation loss goes non-finite — or whose
        in-graph numerics guard reports max_consecutive_skips straight
        non-finite-gradient steps — are quarantined (lane frozen, recorded
        with a cause in ``GridResult.failures``) while the rest of the grid
        keeps training. Because checkpoints store gathered host
        state, a fit may resume on a different (e.g. smaller) device mesh
        than the one that wrote the checkpoint; the elastic scheduler's
        compaction era (execution width, lane->point map, retired results)
        is checkpointed too, so resume lands in the same bucket.

        Elastic re-meshing (ARCHITECTURE.md "Elastic re-meshing & host-fault
        tolerance"): when the device count differs from the checkpoint's —
        the supervisor degraded ``REDCLIFF_MESH_DEVICES`` after a
        ``host_lost`` exit, or part of a slice came back — the resume
        RE-SHARDS automatically: surviving lanes ride the bucket ladder at
        the new device count, frozen lanes retire to the host store, and a
        structured ``remesh`` event (old/new width, lanes migrated, plan
        latency) lands in metrics.jsonl and ``dispatch_stats``. Dispatch
        errors with device-loss / collective-timeout / coordinator-loss
        signatures are mapped to the typed
        :class:`~redcliff_tpu.parallel.remesh.HostLostError` so drivers can
        exit with the ``host_lost`` taxonomy code (21).

        Liveness (ARCHITECTURE.md "Liveness & supervision"): when
        ``REDCLIFF_WATCHDOG`` is set, a daemon watchdog monitors the
        heartbeats stamped by this loop, the prefetcher, the shard loader,
        and the async checkpoint writer, and escalates a stale one:
        log -> final checkpoint via the preemption latch -> hard exit with
        the ``hang`` taxonomy code for the supervisor to restart.
        ``GridSpec.fit_deadline_s`` evicts over-budget lanes into
        ``failures`` (cause ``"deadline"``, state checkpointed);
        ``GridSpec.grid_deadline_s`` ends the whole fit resumably with
        :class:`~redcliff_tpu.runtime.preempt.DeadlineExceeded`."""
        # the guard wraps the whole fit so a signal during compile/data
        # staging is latched too; _fit polls it at epoch boundaries
        guard = PreemptionGuard(enabled=checkpoint_dir is not None)
        # the background checkpoint writer is scoped HERE so every exit
        # path — normal completion, Preempted, or any mid-fit exception —
        # joins the in-flight write (its __exit__ re-raises background
        # write failures on clean exits and warns instead of masking an
        # already-propagating exception). Multi-host saves stay
        # synchronous: the gathers are collectives
        writer = None
        if (checkpoint_dir is not None and self.tc.async_checkpointing
                and jax.process_count() == 1):
            writer = durable_ckpt.AsyncCheckpointWriter()
        wctx = writer if writer is not None else contextlib.nullcontext()
        # liveness watchdog (env-armed, REDCLIFF_WATCHDOG): monitors the
        # heartbeats this fit and its data/checkpoint threads stamp, and
        # escalates a stale one log -> preempt-latch (one final checkpoint
        # via `guard`) -> hard exit EXIT_HANG for the supervisor to restart.
        # Daemonized + stopped on every exit path, so no teardown can hang
        wd = rt_watchdog.maybe_start(guard=guard if guard.enabled else None)
        # bounded profiler capture window (obs/profiling.py): profile_window
        # / REDCLIFF_PROFILE bracket the requested steady-state epochs; the
        # legacy profile_dir knob now means ONE bounded window, not an
        # unbounded whole-fit jax.profiler.trace wrap (multi-GB artifacts
        # on long sweeps). Scoped here so a fit dying inside the window
        # still closes the capture
        pw = _profiling.window_for(
            self.tc, run_dir=log_dir,
            max_iter=max_iter if max_iter is not None else self.tc.max_iter)
        with guard, pw, wctx, wd as live_wd:
            try:
                return self._fit(key, train_ds, val_ds, max_iter=max_iter,
                                 log_dir=log_dir, init_params=init_params,
                                 copy_init=copy_init,
                                 checkpoint_dir=checkpoint_dir,
                                 checkpoint_every=checkpoint_every,
                                 guard=guard, writer=writer, wd=live_wd,
                                 pw=pw, true_gc=true_gc,
                                 on_lane_retire=on_lane_retire)
            except (Preempted, DeadlineExceeded, remesh.HostLostError):
                raise
            except Exception as e:
                # elastic re-meshing (parallel/remesh.py): a dispatch dying
                # with a device-loss / collective-timeout / coordinator-loss
                # signature means the MESH lost capacity, not that the fit
                # is wrong — surface it as the typed host-loss failure so
                # drivers exit EXIT_HOST_LOST and the supervisor re-meshes
                # instead of restarting at the same shape
                tag = remesh.classify_device_error(e)
                if tag is not None:
                    raise remesh.HostLostError(tag, detail=str(e)) from e
                raise

    def _fit(self, key, train_ds, val_ds, max_iter=None,
             log_dir=None, init_params=None, copy_init=True,
             checkpoint_dir=None, checkpoint_every=None,
             guard=None, writer=None, wd=None,
             pw=_profiling.NOOP, true_gc=None,
             on_lane_retire=None) -> GridResult:
        tc = self.tc
        max_iter = max_iter if max_iter is not None else tc.max_iter
        rng = np.random.default_rng(tc.seed)
        G_real = self._g_real
        # wall-clock deadline bookkeeping: budgets are per-process (a
        # resumed attempt gets a fresh budget — the deadline bounds THIS
        # allocation's spend, not the fit's total history)
        fit_t0 = time.monotonic()
        stop_after = tc.lookback * tc.check_every
        ckpt = ck_src = ck_meta = None
        if checkpoint_dir is not None:
            ck_meta = self._checkpoint_meta(train_ds, val_ds)
            ckpt, ck_src = self._load_checkpoint(checkpoint_dir, ck_meta)
        remesh_info = None
        if ckpt is not None:
            # resume: the full fit state comes from the checkpoint; the
            # (expensive) fresh grid init is skipped entirely. The
            # compaction era (execution width, lane->point map, retired
            # results) is part of that state, so a resumed fit lands in
            # exactly the bucket the checkpoint was written at
            ids = ckpt.get("orig_ids")
            orig_ids = (np.asarray(ids, np.int32) if ids is not None
                        else np.arange(len(np.asarray(ckpt["active"])),
                                       dtype=np.int32))
            retired = dict(ckpt.get("retired") or {})
            Gx = int(orig_ids.size)
            # ---- elastic re-meshing (parallel/remesh.py) -----------------
            # the checkpoint may come from a DIFFERENT mesh (the supervisor
            # degraded the device budget after a host loss, or capacity came
            # back). When the device count changed — or the checkpointed
            # width cannot shard over what is visible now — re-shard the
            # lanes onto the current mesh: survivors ride the bucket ladder
            # at the new device count, frozen lanes retire to the host
            # store, and every result still reports under original point
            # ids. The resume fingerprint is untouched (mesh-agnostic by
            # design); same-mesh resumes take the fast path unchanged.
            n_dev = (self._mesh_full.devices.size
                     if self._mesh_full is not None else 1)
            ck_mesh = ckpt.get("mesh") or {}
            mesh_changed = (ck_mesh.get("n_devices") is not None
                            and int(ck_mesh["n_devices"]) != n_dev)
            incompatible = (self._mesh_full is not None
                            and not remesh.width_fits(Gx, n_dev))
            if mesh_changed or incompatible:
                t_plan = time.perf_counter()
                # traced span (ring-only: the MetricLogger does not exist
                # yet this early in resume; the structured `remesh` event
                # below carries the same numbers into metrics.jsonl)
                with obs.span("grid.remesh", component="remesh",
                              from_width=Gx, to_devices=n_dev):
                    plan = remesh.plan_resharding(
                        np.asarray(ckpt["active"], bool), orig_ids,
                        retired.keys(), n_dev, compact=self._compaction_on)
                    if plan is not None:
                        migrated = remesh.apply_reshard(ckpt, retired, plan)
                        remesh_info = {
                            "from_width": Gx, "to_width": plan.new_width,
                            "from_devices": ck_mesh.get("n_devices"),
                            "to_devices": n_dev, "lanes_migrated": migrated,
                            "lanes_retired": [int(p) for p in plan.retire_ids],
                            "plan_ms": round(
                                (time.perf_counter() - t_plan) * 1e3, 3),
                        }
                        orig_ids = np.asarray(plan.orig_ids, np.int32)
                        Gx = plan.new_width
            if self._mesh_full is not None:
                self.mesh = self._mesh_for(Gx)
            params = self._shard(jax.tree.map(jnp.asarray, ckpt["params"]))
            optA_state = self._shard(jax.tree.map(jnp.asarray,
                                                  ckpt["optA_state"]))
            optB_state = self._shard(jax.tree.map(jnp.asarray,
                                                  ckpt["optB_state"]))
            best_params = self._shard(jax.tree.map(jnp.asarray,
                                                   ckpt["best_params"]))
            best_crit = jnp.asarray(ckpt["best_crit"])
            best_epoch = jnp.asarray(ckpt["best_epoch"])
            active = self._shard(jnp.asarray(ckpt["active"]))
            accepted = (self._shard(jax.tree.map(jnp.asarray,
                                                 ckpt["accepted"]))
                        if ckpt["accepted"] is not None else None)
            # checkpointed rows are already expanded to the original width
            # (original-id indexed); rows appended by THIS attempt carry
            # their era index instead
            val_history = list(ckpt["val_history"])
            val_eras = [-1] * len(val_history)
            eras = [orig_ids]
            era_cur = 0
            aligned = ckpt["aligned"]
            failed_epoch = self._shard(jnp.asarray(ckpt["failed_epoch"]))
            ns = ckpt.get("nstate")
            nstate = (self._shard(jax.tree.map(jnp.asarray, ns))
                      if ns is not None
                      else self._shard(numerics.init_numerics_state(lanes=Gx)))
            fc = ckpt.get("failed_cause")
            if fc is None:
                # pre-sentinel checkpoint: every already-quarantined lane was
                # a validation-loss quarantine by construction
                fc = np.where(np.asarray(ckpt["failed_epoch"]) >= 0,
                              numerics.CAUSE_NONFINITE_VAL, 0).astype(np.int32)
            failed_cause = self._shard(jnp.asarray(fc, jnp.int32))
            rng.bit_generator.state = ckpt["rng_state"]
            start_it = ckpt["epoch"] + 1
            if ckpt.get("precision_demoted") and self._demotable \
                    and not self._demoted:
                # the checkpointed fit demoted mixed -> f32 mid-run; resume
                # must rebuild its programs at f32 before the first dispatch
                # (never silently re-promote)
                self._demote_to_f32()
        else:
            # init_params: pre-stacked (G, ...) state from
            # init_grid/init_grid_from. Copy caller-supplied arrays by
            # default — the train steps donate their buffers
            # (donate_argnums), which would otherwise silently invalidate
            # the caller's tuple on the first step (e.g. reusing one init
            # for an A/B pair of fits). copy_init=False hands ownership
            # over (callers that built the init solely for this fit skip
            # the 2x transient allocation)
            if init_params is not None:
                if copy_init:
                    init_params = jax.tree.map(jnp.copy, init_params)
                params, optA_state, optB_state = init_params
            else:
                params, optA_state, optB_state = self.init_grid(key)
            orig_ids = self._orig_ids0.copy()
            retired = {}
            eras = [orig_ids]
            era_cur = 0
            Gx = self._g_exec0
            pad = Gx - G_real
            if pad:
                # bucket padding: filler lanes replicate lane 0's state —
                # finite, valid math that compiles into the same program as
                # the real lanes; the active mask below keeps them frozen
                # and orig_ids keeps them out of every result
                padf = lambda t: jax.tree.map(
                    lambda x: jnp.concatenate(
                        [x, jnp.repeat(x[:1], pad, axis=0)], axis=0), t)
                params, optA_state, optB_state = (
                    padf(params), padf(optA_state), padf(optB_state))
            params = self._shard(params)
            optA_state = self._shard(optA_state)
            optB_state = self._shard(optB_state)
            best_crit = jnp.full((Gx,), jnp.inf)
            best_epoch = jnp.zeros((Gx,), dtype=jnp.int32)
            # materialize a copy: the train steps donate (consume) the live
            # params buffers, so best_params must never alias them
            best_params = jax.tree.map(jnp.copy, params)
            # Freeze-mode accepted tree (the per-point trainer's "accepted")
            accepted = jax.tree.map(jnp.copy, params) if self._freeze else None
            # per-point early-stop lane mask: converged points stop
            # updating; bucket-padding filler lanes are born inactive
            active = self._shard(jnp.asarray(orig_ids >= 0))
            # non-finite quarantine bookkeeping: epoch a lane went bad
            # (-1 = healthy) plus its cause code; quarantined lanes freeze
            # like early-stopped ones but are reported as failures, not
            # results. The numerics sentinel counters ride per-lane
            failed_epoch = self._shard(jnp.full((Gx,), -1, jnp.int32))
            failed_cause = self._shard(jnp.zeros((Gx,), jnp.int32))
            nstate = self._shard(numerics.init_numerics_state(lanes=Gx))
            val_history = []
            val_eras = []
            aligned = False
            start_it = 0
        # per-execution-lane deadline bookkeeping (era-remapped on
        # compaction); dl_done memoizes already-evicted lanes so the
        # per-epoch check degenerates to a numpy compare (no device sync)
        lane_deadline = self._exec_deadlines(orig_ids)
        dl_done = np.zeros((Gx,), dtype=bool)
        coeffs = self._shard(self._coeffs_for(orig_ids))

        # ---- batch-stream plan (epoch engine, data/pipeline.py) ----------
        # resolved ONCE per fit: "epoch" scans the whole epoch's batch
        # indices in one dispatch against the HBM-resident dataset, "kscan"
        # scans k stacked batches per dispatch, "per_batch" dispatches every
        # batch (host streams ride the double-buffered prefetcher).
        # Multi-phase epochs degrade to per_batch per-epoch below (phases
        # interleave within each batch).
        sharding = replicated(self.mesh) if self.mesh is not None else None
        base_stream = pipeline.choose_stream_mode(
            tc.stream_mode, train_ds, scan_batches=tc.scan_batches,
            batch_size=tc.batch_size, single_phase=True,
            freeze_by_batch=self._freeze_by_batch)
        Xd = Yd = None
        if base_stream == "epoch":
            Xd, Yd = train_ds.device_arrays(sharding)
        # validation rides the epoch engine too: one scanned dispatch over a
        # fixed index plan (val order is rng-free), computed once per fit.
        # The HBM-residency cap applies to the val set independently — the
        # scan pins it device-resident (plus a transient permuted copy)
        val_bytes = pipeline.dataset_device_bytes(val_ds)
        val_scan_ok = (base_stream == "epoch"
                       and getattr(val_ds, "supports_device_batches", False)
                       and getattr(val_ds, "Y", None) is not None
                       and len(val_ds) >= tc.batch_size
                       and val_bytes is not None
                       and val_bytes
                       <= pipeline.DEFAULT_MAX_DEVICE_DATASET_BYTES)
        vXd = vYd = vidx = None
        v_rem = np.zeros((0,), np.int32)
        if val_scan_ok:
            vXd, vYd = val_ds.device_arrays(sharding)
            v_full, v_rem = pipeline.epoch_batch_plan(len(val_ds),
                                                     tc.batch_size)
            vidx = jnp.asarray(v_full)
            if sharding is not None:
                vidx = jax.device_put(vidx, sharding)
        # device-resident batches for the non-epoch paths (HBM copy +
        # per-batch device gather), replicated over the mesh; ArrayDataset
        # itself falls back to host numpy in multi-process runs
        if getattr(train_ds, "supports_device_batches", False):
            dev_kw = {"device": True, "sharding": sharding}
        else:
            dev_kw = {}

        def train_batch_iter():
            """One epoch's batch source for the per_batch/kscan paths; host
            streams ride the prefetcher so batch assembly + device_put of
            batch t+1 overlap compute of batch t."""
            src = train_ds.batches(tc.batch_size, rng=rng, **dev_kw)
            if not dev_kw and tc.prefetch_batches > 0:
                if jax.process_count() == 1:
                    put = ((lambda a: jax.device_put(a, sharding))
                           if sharding is not None else jax.device_put)
                else:
                    put = None  # multi-host inputs stay uncommitted numpy
                src = pipeline.prefetch_batches(
                    src, depth=tc.prefetch_batches, put=put)
            return src

        # hoisted cos-tracking window: the first val batch's slice becomes a
        # once-per-fit device constant instead of a per-epoch
        # np.asarray(first_val_X) device->host sync
        cos_Xw = None
        if self._cos is not None:
            first = next(iter(val_ds.batches(tc.batch_size)), None)
            if first is not None:
                cos_Xw = jnp.asarray(np.asarray(first[0])[
                    : tc.max_samples_for_gc_tracking,
                    : self.model.config.max_lag, :])
                if sharding is not None:
                    cos_Xw = jax.device_put(cos_Xw, sharding)
        # ---- model-quality observatory (obs/quality.py) ------------------
        # per-lane Granger-graph summaries on the check-window cadence: one
        # jit'd vmapped readout of params (pure read — no donation, no
        # effect on any update stream) whose gather piggybacks on the
        # window's existing device->host transfer. Zero work — no jit, no
        # monitor, no per-window branch beyond one None check — when
        # REDCLIFF_QUALITY=0. The entropy/conditional window is hoisted
        # from the first val batch like cos_Xw (a once-per-fit constant)
        qmon = qual_fn = qual_Xw = None
        if _quality.enabled():
            # identical slice to the cos window — share the device constant
            # when cosine tracking already built it
            qual_Xw = cos_Xw
            if qual_Xw is None:
                qfirst = next(iter(val_ds.batches(tc.batch_size)), None)
                if qfirst is not None:
                    qual_Xw = jnp.asarray(np.asarray(qfirst[0])[
                        : tc.max_samples_for_gc_tracking,
                        : self.model.config.max_lag, :])
                    if sharding is not None:
                        qual_Xw = jax.device_put(qual_Xw, sharding)
            if qual_Xw is not None:
                qmode = _quality.readout_mode(self.model.config)
                # jit once per runner (keyed by the top-k knob): every
                # other engine program lives on self, and a second fit on
                # the same runner must not recompile the summary (the
                # steady-state zero-recompile tripwire counts it)
                qk = _quality.topk_k()
                if self._qual_fn is None or self._qual_fn_k != qk:
                    self._qual_fn = jax.jit(jax.vmap(
                        _quality.make_summary_fn(self.model, k=qk),
                        in_axes=(0, None)))
                    self._qual_fn_k = qk
                qual_fn = self._qual_fn
                qmon = _quality.QualityMonitor(true_gc=true_gc, mode=qmode)
        # per-fit dispatch/stall/compile/lane accounting (bench.py's schema
        # and the tier-1 dispatch-budget + recompile tripwires read this).
        # lane_epochs counts lanes actually computed (width x epochs);
        # lane_epochs_nominal is what an uncompacted run of this attempt
        # would have computed — their gap is the dead-lane FLOPs saved
        self.dispatch_stats = stats = {
            "mode": base_stream, "epochs": 0, "train_dispatches": 0,
            "val_dispatches": 0, "ckpt_stall_ms": 0.0,
            "grid_width": Gx, "lanes_real": G_real,
            "lanes_padded": int((orig_ids < 0).sum()), "lanes_live": None,
            "compactions": 0, "lane_epochs": 0, "lane_epochs_nominal": 0,
            "compile_ms": 0.0, "compiles": 0, "cache_hits": 0,
            "cache_misses": 0,
            # telemetry-spine timing (redcliff_tpu/obs): host wall time in
            # the train/val dispatch sections per epoch (enqueue time — no
            # host sync is added to measure it), bucketed by execution
            # width for the obs report's (shape, G-bucket) cost table, plus
            # the cross-thread stall counters (prefetch consumer waits,
            # async-checkpoint submit barriers) folded from obs.counters
            "train_time_ms": 0.0, "val_time_ms": 0.0,
            "epoch_ms_by_width": {}, "epochs_by_width": {},
            # first observed epoch per width: carries the cold/warm compile
            # and cache-priming skew, so the cost-model store and the
            # observed-mean predictor both exclude it (steady-state cost is
            # what scheduling needs; raw per-epoch wall stays in
            # epoch_ms_by_width and the epoch events)
            "first_epoch_ms_by_width": {},
            "prefetch_stall_ms": 0.0, "prefetch_items": 0,
            "ckpt_barrier_stall_ms": 0.0,
            # degraded-mesh resume accounting (parallel/remesh.py): count +
            # the full plan record (old/new width, lanes migrated, plan
            # latency) when THIS attempt re-sharded a checkpoint onto a
            # different mesh
            "remeshes": 1 if remesh_info else 0, "remesh": remesh_info,
            # learned-cost-model scoring (obs/costmodel.py): the remaining-
            # fit ETA and the prediction-residual summary, refreshed every
            # check window — the obs watch CLI and the supervisor's
            # per-attempt ledger ETA both read these through the run's
            # cost_model events
            "eta": None, "cost_model": None,
            # device-memory observatory (obs/memory.py): the analytical HBM
            # prediction for this fit's (shape, G-bucket) + the measured
            # watermark where the backend reports memory_stats
            "memory": None,
            # model-quality observatory (obs/quality.py): the rolling
            # convergence snapshot — plateaued_at_epoch per original point
            # id (ROADMAP item 3's plateau readout), edge-set stability,
            # and AUROC/AUPR when ground truth was supplied. None when
            # REDCLIFF_QUALITY=0 or before the first check window
            "quality": None}
        compile_t0 = compileobs.snapshot()
        counters_t0 = obs.counters.snapshot()
        width_nominal = Gx
        # background checkpoint writer (created and scoped by fit(), which
        # joins it on EVERY exit path): pre-compile the fused donated-state
        # snapshot here so the FIRST save's main-thread stall is the
        # hand-off, not a jit compile (the save-time structure is exactly
        # these keys)
        if writer is not None:
            warm = {k: v for k, v in (
                ("params", params), ("optA_state", optA_state),
                ("optB_state", optB_state), ("nstate", nstate),
                ("accepted", accepted)) if v is not None}
            jax.block_until_ready(self._ensure_snapshot_fn()(warm))

        # the full-capacity mesh shape, recorded in every checkpoint payload
        # (audit metadata, NOT part of the resume fingerprint) and in the
        # run's metrics — the other half of the degraded-resume audit trail
        mesh_desc = remesh.mesh_shape(self._mesh_full)
        # learned cost model (obs/costmodel.py): the persistent store rides
        # the compile-cache base dir. Loaded once per fit, host-side only;
        # predictions are scored against observed epoch times each check
        # window (cost_model events + stats["eta"]) — they do not steer any
        # scheduling decision yet (ROADMAP item 4's follow-up)
        # resolution order mirrors costmodel.store_path() so the store this
        # fit writes is the store obs report reads: the explicit
        # REDCLIFF_COST_MODEL_DIR override first, then the compile-cache
        # base (config knob, then env)
        cm_base = (os.environ.get(_costmodel.ENV_STORE_DIR)
                   or getattr(tc, "compile_cache_dir", None)
                   or os.environ.get(compileobs.ENV_CACHE_DIR) or None)
        cm_platform = jax.default_backend()
        cost_model = _costmodel.load(cm_base) if cm_base else None
        cm_shape_key = obs.schema.shape_key(self._shape_desc())
        # precision half of the cost bucket (obs/costmodel.py): bf16 and
        # f32 epochs of the same program family are different costs — a
        # demoted fit folds/predicts under "f32" from the demotion on
        cm_precision0 = precision_label(self._precision_mode,
                                        getattr(tc, "matmul_precision",
                                                None))
        cm_n = 0          # residual samples scored this fit
        cm_abs_pct = 0.0  # running sum of |residual_pct| (MAPE numerator)
        # per-width accumulators frozen at a mid-fit demotion: epochs before
        # it fold into the "mixed" cost bucket, epochs after into "f32".
        # demote_compile_snap splits the compile accumulators at the same
        # boundary (the f32 rebuild's recompiles belong to the f32 era), and
        # demote_first_f32 records the first post-demotion epoch per width —
        # it carries the rebuild's compile skew and must be excluded from
        # the f32 bucket mean exactly like a width's first epoch
        demote_snap = demote_compile_snap = None
        demote_pending = False
        demote_first_f32 = {}
        logger = MetricLogger(log_dir)
        if wd is not None:
            # hang incidents land in THIS fit's metrics.jsonl
            wd.bind(logger=logger)
        logger.log("fit_start", model="RedcliffGridRunner", grid_size=G_real,
                   grid_width=Gx, lanes_padded=stats["lanes_padded"],
                   training_mode=self.model.config.training_mode,
                   shape=self._shape_desc(), max_iter=max_iter,
                   stream_mode=base_stream, mesh=mesh_desc,
                   compile_cache_dir=jax.config.jax_compilation_cache_dir,
                   resumed_from_epoch=start_it - 1 if ckpt else None,
                   resumed_from=ck_src,
                   precision_mode=self._precision_mode,
                   points=list(self.spec.points))
        # kernel-tiling searches/lookups performed at construction
        # (ops/autotune.py) land as schema-registered events in THIS fit's
        # metrics chain
        for atrec in _autotune.drain_records():
            logger.log("autotune", **atrec)
        # the predictive policy's initial-width decision (ISSUE 15): priced
        # at construction, logged here where the metrics chain exists —
        # chosen rung, heuristic rung, predicted saving, fallback flag
        if getattr(self, "_policy_init_decision", None):
            logger.log("policy", epoch=start_it - 1, grid_width=Gx,
                       **self._policy_init_decision)
            self._policy_init_decision = None
        if self._demoted and start_it > 0:
            logger.log("precision", kind="resume_demoted",
                       epoch=start_it - 1, mode_from="mixed",
                       mode_to="f32", grid_width=Gx)
        if remesh_info is not None:
            # structured re-mesh event: which mesh the checkpoint came from,
            # which it landed on, how many lanes migrated, plan latency
            logger.log("remesh", epoch=start_it - 1, **remesh_info)
        # ---- device-memory observatory (obs/memory.py) -------------------
        # the analytical HBM footprint of THIS fit's (shape, G-bucket):
        # abstract-shape arithmetic only (jax.eval_shape over init + dataset
        # nbytes metadata — no device work), plus the headroom verdict the
        # bucket ladder consults at the width it just chose (advisory: on
        # backends without memory_stats the verdict is an explicit None).
        # The prediction + live watermark ride dispatch_stats (-> every
        # checkpoint) and schema-registered `memory` events
        mem_poll = _obsmem.polling_enabled()
        mem_devices = (list(self._mesh_full.devices.ravel())
                       if self._mesh_full is not None else None)
        n_mesh_dev = len(mem_devices) if mem_devices else 1
        try:
            mem_pred = _obsmem.grid_footprint(
                self.model, tc, Gx, train_ds=train_ds, val_ds=val_ds,
                stream_mode=base_stream, freeze=self._freeze)
            headroom = _obsmem.check_headroom(
                mem_pred["total_bytes"], devices=mem_devices,
                n_devices=n_mesh_dev)
        except Exception:  # noqa: BLE001 — the memory axis must never
            mem_pred = headroom = None  # fail a fit; telemetry is garnish
        if mem_pred is not None:
            stats["memory"] = {
                "predicted_bytes": mem_pred["total_bytes"],
                "per_lane_bytes": mem_pred["per_lane_bytes"],
                "g_bucket": Gx, "peak_bytes": None,
                "bytes_limit": headroom["bytes_limit"],
                "fits": headroom["fits"], "polls": 0}
            logger.log(
                "memory", kind="predicted", epoch=start_it - 1,
                g_bucket=Gx, grid_width=Gx,
                predicted_bytes=mem_pred["total_bytes"],
                params_bytes=mem_pred["params_bytes"],
                opt_bytes=mem_pred["opt_bytes"],
                best_bytes=mem_pred["best_bytes"],
                per_lane_bytes=mem_pred["per_lane_bytes"],
                dataset_bytes=mem_pred["dataset_bytes"],
                epoch_gather_bytes=mem_pred["epoch_gather_bytes"],
                fits=headroom["fits"], bytes_limit=headroom["bytes_limit"],
                budget_bytes=headroom["budget_bytes"],
                headroom_bytes=headroom["headroom_bytes"],
                backend=headroom["backend"], n_devices=n_mesh_dev)
        # fault-injection step index for the host-stream paths (nan_batch /
        # grad_blowup / skip specs); per-process, like the trainers'
        fi_step = 0
        for it in range(start_it, max_iter):
            # the epoch engine's own heartbeat: one stamp per epoch boundary
            # (a cold compile inside the epoch additionally stamps the
            # op-scoped ``compile`` beat via _call_cold, which excuses this
            # one while XLA runs)
            rt_watchdog.stamp("epoch_engine")
            # bounded profiler window: arms jax.profiler only when this
            # epoch enters the requested window (a no-op method call on the
            # shared NOOP window otherwise — never a sync, never a decision)
            pw.on_epoch_start(it)
            epoch_width = Gx
            epoch_compile_t0 = compileobs.snapshot()
            # per-epoch host wall clock (enqueue time; no host sync added):
            # the step-cost sample the obs report's (shape, G-bucket) cost
            # table aggregates
            t_epoch0 = time.perf_counter()
            cfg0 = self.model.config
            if (not aligned and "pretrain_factor" in cfg0.training_mode
                    and it == cfg0.num_pretrain_epochs
                    and cfg0.num_supervised_factors > 0):
                params = self._align_all_points(params, train_ds)
                params = self._shard(params)
                aligned = True
            phases = self.phase_for_epoch(it)
            # per-epoch skip baseline for quarantine-cause attribution
            # (jnp.copy: the train steps donate nstate's buffers, so the
            # original reference would be invalidated by the first dispatch)
            epoch_skip_base = jnp.copy(nstate["skipped"])
            # scanned modes preserve update order only when the epoch runs a
            # single phase (multi-phase epochs interleave phases within each
            # batch); such epochs degrade to per_batch
            mode_e = base_stream if len(phases) == 1 else "per_batch"
            if mode_e == "epoch":
                # ONE dispatch for the whole epoch: the shuffled batch order
                # becomes a device index array and lax.scan gathers each
                # batch in-graph from the HBM-resident dataset; only the
                # short epoch remainder takes the per-batch step
                phase = phases[0]
                full_idx, rem_idx = pipeline.epoch_batch_plan(
                    len(train_ds), tc.batch_size, rng=rng)
                idx = jnp.asarray(full_idx)
                if sharding is not None:
                    idx = jax.device_put(idx, sharding)
                params, optA_state, optB_state, nstate = self._call_cold(
                    ("epoch", phase, Gx, idx.shape),
                    self._epoch_steps[phase], params, optA_state, optB_state,
                    nstate, coeffs, active, Xd, Yd, idx)[:4]
                stats["train_dispatches"] += 1
                if len(rem_idx):
                    params, optA_state, optB_state, nstate = self._call_cold(
                        ("step", phase, Gx, len(rem_idx)),
                        self._steps[phase], params, optA_state, optB_state,
                        nstate, coeffs, active,
                        Xd[rem_idx], Yd[rem_idx])[:4]
                    stats["train_dispatches"] += 1
            elif mode_e == "kscan":
                # group FULL-SIZE labeled batches and drive each group with
                # one scanned dispatch; short batches (the epoch remainder,
                # which would break jnp.stack's uniform shapes) and
                # label-less batches take the per-batch step in order
                k = tc.scan_batches
                phase = phases[0]
                state = (params, optA_state, optB_state, nstate)
                group = []

                def run_group(state, group):
                    # only full k-groups take the scanned dispatch: a
                    # remainder group of 2..k-1 would jit-specialize (and
                    # fully compile) a second scanned step per distinct size
                    if len(group) == k:
                        Xs = jnp.stack([jnp.asarray(x) for x, _ in group])
                        Ys = jnp.stack([jnp.asarray(y) for _, y in group])
                        stats["train_dispatches"] += 1
                        return self._call_cold(
                            ("kscan", phase, Gx, Xs.shape),
                            self._scan_steps[phase], *state, coeffs, active,
                            Xs, Ys)[:4]
                    for X, Y in group:
                        stats["train_dispatches"] += 1
                        state = self._call_cold(
                            ("step", phase, Gx, X.shape, Y is None),
                            self._steps[phase], *state, coeffs, active,
                            X, Y)[:4]
                    return state

                for X, Y in train_batch_iter():
                    rt_watchdog.stamp("batch_loop")
                    if Y is None or X.shape[0] != tc.batch_size:
                        state = run_group(state, group)
                        group = []
                        stats["train_dispatches"] += 1
                        state = self._call_cold(
                            ("step", phase, Gx, X.shape, Y is None),
                            self._steps[phase], *state, coeffs, active,
                            X, Y)[:4]
                        continue
                    group.append((X, Y))
                    if len(group) == k:
                        state = run_group(state, group)
                        group = []
                state = run_group(state, group)
                rt_watchdog.retire("batch_loop")
                params, optA_state, optB_state, nstate = state
            else:
                for X, Y in train_batch_iter():
                    rt_watchdog.stamp("batch_loop")
                    # numerical fault injection rides the host per-batch
                    # path only (the scanned modes consume device-resident
                    # data); one env lookup when unarmed
                    X = faultinject.poison_batch(X, fi_step)
                    fi_step += 1
                    for phase in phases:
                        stats["train_dispatches"] += 1
                        params, optA_state, optB_state, nstate, _ = \
                            self._call_cold(
                                ("step", phase, Gx, X.shape, Y is None),
                                self._steps[phase], params, optA_state,
                                optB_state, nstate, coeffs, active, X, Y)
                    if self._freeze_by_batch:
                        params, accepted = self._freeze_step(params, accepted)
                rt_watchdog.retire("batch_loop")
            t_val0 = time.perf_counter()
            stats["train_time_ms"] += (t_val0 - t_epoch0) * 1e3
            if val_scan_ok:
                # whole validation set in one scanned dispatch (sequential
                # carry adds — bit-identical to the per-batch loop's sums);
                # the short remainder batch adds one per-batch dispatch
                combo_sum, forecast_sum, factor_sum = self._call_cold(
                    ("val_scan", Gx), self._val_scan,
                    params, coeffs, vXd, vYd, vidx)
                stats["val_dispatches"] += 1
                n = int(vidx.shape[0])
                if len(v_rem):
                    combo, fo, fa = self._call_cold(
                        ("val", Gx, len(v_rem)), self._val,
                        params, coeffs, vXd[v_rem], vYd[v_rem])
                    stats["val_dispatches"] += 1
                    combo_sum = combo_sum + combo
                    forecast_sum = forecast_sum + fo
                    factor_sum = factor_sum + fa
                    n += 1
            else:
                combo_sum = 0.0
                forecast_sum = 0.0
                factor_sum = 0.0
                n = 0
                for X, Y in val_ds.batches(tc.batch_size):
                    combo, fo, fa = self._call_cold(
                        ("val", Gx, X.shape), self._val, params, coeffs, X, Y)
                    stats["val_dispatches"] += 1
                    combo_sum = combo_sum + combo
                    forecast_sum = forecast_sum + fo
                    factor_sum = factor_sum + fa
                    n += 1
            if n == 0:
                raise ValueError(
                    "validation dataset yielded no batches — increase "
                    "val_fraction or dataset size")
            # fold this epoch's timing into the per-width cost accumulators
            # and refresh the cross-thread stall counters (absolute deltas
            # vs the fit-start snapshot, so every checkpoint sees current
            # totals). One lock acquisition per epoch
            t_val1 = time.perf_counter()
            stats["val_time_ms"] += (t_val1 - t_val0) * 1e3
            epoch_ms = (t_val1 - t_epoch0) * 1e3
            wkey = str(Gx)
            stats["epoch_ms_by_width"][wkey] = (
                stats["epoch_ms_by_width"].get(wkey, 0.0) + epoch_ms)
            stats["epochs_by_width"][wkey] = (
                stats["epochs_by_width"].get(wkey, 0) + 1)
            stats["first_epoch_ms_by_width"].setdefault(wkey, epoch_ms)
            if demote_pending:
                # the first epoch after a mid-fit demotion: its wall time
                # includes the f32 rebuild's recompiles — excluded from the
                # f32 cost bucket like any width's first epoch
                demote_first_f32[wkey] = epoch_ms
                demote_pending = False
            cdelta = obs.counters.delta(counters_t0)
            stats["prefetch_stall_ms"] = cdelta.get("prefetch_stall_ms", 0.0)
            stats["prefetch_items"] = int(cdelta.get("prefetch_items", 0))
            stats["ckpt_barrier_stall_ms"] = cdelta.get(
                "ckpt_barrier_stall_ms", 0.0)
            # keep per-epoch losses device-resident; one host transfer at
            # the end (rows are execution-width — the era index records
            # which lane->point map they were computed under, and
            # compaction.expand_history scatters them back to original ids)
            val_now = combo_sum / n
            val_history.append(val_now)
            val_eras.append(era_cur)
            # graceful degradation: a point whose val loss went non-finite,
            # OR whose in-graph guard skipped max_consecutive_skips steps in
            # a row (the lane is stuck on poisoned gradients), is quarantined
            # — its lane freezes via the active mask while the REST of the
            # grid keeps training. Pure device compute (no host sync); the
            # failed epochs + causes surface in GridResult.failures and
            # failures.json
            bad = jnp.logical_not(jnp.isfinite(val_now))
            if self._guard:
                bad = jnp.logical_or(
                    bad, nstate["consecutive"] >= self._numerics_k)
                # implicate gradients only when THIS epoch skipped steps: a
                # transient skip epochs ago must not relabel a later pure
                # validation blow-up as nonfinite_grad
                grad_implicated = (nstate["skipped"] - epoch_skip_base) > 0
            else:
                grad_implicated = jnp.zeros_like(active)
            if self._demotable and not self._demoted and self._guard:
                # precision-cliff watch (mixed mode only): a lane stuck on
                # an in-graph SKIP STORM — max_consecutive_skips straight
                # non-finite-gradient steps, the bf16-contraction signature
                # — blames bf16 before blaming the lane. The whole grid
                # demotes to f32 (rebuilt programs, `precision` event) and
                # the stuck lanes get one f32 epoch before quarantine can
                # re-judge them; a plain validation blow-up with finite
                # steps (the classic bad-lr divergence) quarantines
                # normally even in mixed mode — it carries no bf16
                # evidence. A lane that keeps storming at f32 quarantines
                # within max_consecutive_skips further epochs, so the
                # worst case of misattribution is one grid recompile.
                # Costs one small device->host transfer per epoch, paid
                # only by mixed-mode fits
                hit = np.asarray(gather_to_host(jnp.logical_and(
                    jnp.logical_and(active, grad_implicated),
                    nstate["consecutive"] >= self._numerics_k)))
                if bool(hit.any()):
                    nhost = numerics.numerics_summary(nstate)
                    self._demote_to_f32()
                    nstate = numerics.reset_consecutive(nstate)
                    # freeze the mixed era's cost accumulators (this epoch
                    # ran bf16 and is already folded in) so the store fold
                    # below can split the two precision eras; the compile
                    # counters split at the same boundary
                    demote_snap = {
                        k: dict(stats[k])
                        for k in ("epochs_by_width", "epoch_ms_by_width",
                                  "first_epoch_ms_by_width")}
                    demote_compile_snap = compileobs.delta(compile_t0)
                    demote_pending = True
                    logger.log(
                        "precision", kind="demote", epoch=it,
                        cause="precision_cliff",
                        mode_from="mixed", mode_to="f32", grid_width=Gx,
                        lanes=[int(orig_ids[g])
                               for g in np.flatnonzero(hit)],
                        skipped=nhost["skipped"],
                        consecutive=nhost["consecutive"])
                    bad = jnp.zeros_like(bad)
                    grad_implicated = jnp.zeros_like(active)
            newly_failed = jnp.logical_and(active, bad)
            failed_epoch = jnp.where(newly_failed, jnp.int32(it), failed_epoch)
            failed_cause = jnp.where(
                newly_failed,
                jnp.where(grad_implicated,
                          jnp.int32(numerics.CAUSE_NONFINITE_GRAD),
                          jnp.int32(numerics.CAUSE_NONFINITE_VAL)),
                failed_cause)
            active = jnp.logical_and(active, jnp.logical_not(bad))
            cfg = self.model.config
            if it >= cfg.num_pretrain_epochs + cfg.num_acclimation_epochs:
                # per-point stopping criteria, the trainer's branches
                # (redcliff_trainer.py:336-346, ref :1466-1538): stopping
                # coefficients x coefficient-normalized val means, plus the
                # supervised pairwise-cosine term when
                # num_supervised_factors > 1. The trainer now also tracks
                # cosines unconditionally (its tracker no longer requires
                # ground truth), so grid and per-point criteria agree on
                # labeled AND unlabeled runs
                crit = (coeffs["stopping_criteria_forecast_coeff"]
                        * (forecast_sum / n))
                if cfg.num_supervised_factors >= 1:
                    crit = crit + (coeffs["stopping_criteria_factor_coeff"]
                                   * (factor_sum / n))
                if self._cos is not None:
                    # cos_Xw is the once-per-fit hoisted device constant —
                    # no per-epoch host slice/transfer in the hot loop
                    crit = crit + (coeffs["stopping_criteria_cosSim_coeff"]
                                   * self._call_cold(("cos", Gx), self._cos,
                                                     params, cos_Xw))
                if self._freeze:
                    # end-of-epoch accept/revert; the accepted tree IS the
                    # best-params analog (trainer fit loop, freeze branch)
                    if not self._freeze_by_batch:
                        params, accepted = self._freeze_step(params, accepted)
                    _, best_crit, best_epoch = self._select_best(
                        best_params, best_crit, best_epoch, params,
                        crit, jnp.int32(it))
                    best_params = jax.tree.map(jnp.copy, accepted)
                else:
                    best_params, best_crit, best_epoch = self._select_best(
                        best_params, best_crit, best_epoch, params, crit,
                        jnp.int32(it))
                # per-point early stop: a point whose criteria has not
                # improved for lookback*check_every epochs goes inactive
                # (the per-point trainer's break, ref :1522-1538) — applied
                # in Freeze modes too, matching the trainer's all-modes rule
                active = jnp.logical_and(
                    active, (jnp.int32(it) - best_epoch) < stop_after)
            else:
                # pretrain/acclimation epochs track the live params as best —
                # but only for healthy lanes: a quarantined point keeps its
                # last finite snapshot instead of copying NaN params forward
                best_params = jax.tree.map(
                    lambda b, c: jnp.where(
                        active.reshape((-1,) + (1,) * (c.ndim - 1)), c, b),
                    best_params, params)
                best_epoch = jnp.where(active, jnp.int32(it), best_epoch)

            # ---- wall-clock deadlines (ARCHITECTURE.md "Liveness &
            # supervision"). Lane eviction runs AFTER this epoch's best/
            # early-stop bookkeeping: the evicted lane keeps everything it
            # earned through this epoch, and from the next epoch its lane
            # freezes via the same active-mask machinery as a non-finite
            # quarantine — sibling-lane math is untouched, so their results
            # stay bit-identical to a no-deadline run
            force_ckpt = False
            grid_dl_hit = False
            elapsed = None
            if lane_deadline is not None or self.spec.grid_deadline_s:
                elapsed = time.monotonic() - fit_t0
                if jax.process_count() > 1:
                    # deadline decisions feed collectives (the eviction
                    # gather, the final save), so every process must take
                    # them on the same epoch: process 0's clock decides, on
                    # the check_every cadence so the broadcast rides an
                    # existing sync point instead of adding a per-epoch one
                    if (it + 1) % tc.check_every == 0:
                        from jax.experimental import multihost_utils

                        elapsed = float(multihost_utils.broadcast_one_to_all(
                            np.asarray(elapsed)))
                    else:
                        elapsed = None
            # eviction decisions come from the scheduling policy
            # (parallel/policy.py); the engine owns the uniform clock above
            # and the mask/checkpoint mechanics below
            over = self.policy.lane_evictions(lane_deadline, dl_done,
                                              elapsed)
            if over is not None:
                if over.any():
                    dl_done |= over
                    dl_bad = self._shard(jnp.asarray(over))
                    newly_dl = jnp.logical_and(active, dl_bad)
                    # host sync only on the (rare) eviction epoch itself
                    n_evict = int(np.asarray(gather_to_host(
                        jnp.sum(newly_dl))))
                    if n_evict:
                        failed_epoch = jnp.where(newly_dl, jnp.int32(it),
                                                 failed_epoch)
                        failed_cause = jnp.where(
                            newly_dl, jnp.int32(numerics.CAUSE_DEADLINE),
                            failed_cause)
                        active = jnp.logical_and(active,
                                                 jnp.logical_not(dl_bad))
                        # the evicted lane's state must land durably: force
                        # a checkpoint at this epoch regardless of cadence
                        force_ckpt = True
                        # report ORIGINAL point ids, not execution rows —
                        # after a compaction the two disagree
                        logger.log("deadline_evicted", epoch=it,
                                   elapsed_s=round(elapsed, 3),
                                   lanes=[int(orig_ids[g])
                                          for g in np.flatnonzero(over)],
                                   num_evicted=n_evict)
            if self.policy.grid_deadline_hit(self.spec.grid_deadline_s,
                                             elapsed):
                grid_dl_hit = True

            # structured per-epoch record; syncing the grid losses to host
            # costs one transfer, so only do it on the check_every cadence.
            # gather_to_host is a collective on multi-host meshes, so the
            # guard must be uniform across processes (logger.active is not:
            # typically only process 0 writes) — gather everywhere, write
            # wherever a logger is attached
            if it % tc.check_every == 0:
                # traced check window: the per-check-window host sync (the
                # act_host gather + the epoch record's loss gathers) is the
                # one place the hot loop touches the host — its span makes
                # that cost visible per window in metrics.jsonl
                with obs.span("grid.check_window", component="check_window",
                              logger=logger, emit=True, epoch=it, width=Gx):
                    # one gather serves the epoch log, the exit test, and
                    # the compaction decision
                    act_host = gather_to_host(active)
                    stats["lanes_live"] = int(act_host.sum())
                    if logger.active or jax.process_count() > 1:
                        failed_host = gather_to_host(failed_epoch)
                        skipped_host = np.asarray(
                            gather_to_host(nstate["skipped"]))
                        logger.log(
                            "epoch", epoch=it, phases=list(phases),
                            val_combo_loss=gather_to_host(val_now),
                            best_criteria=gather_to_host(best_crit),
                            num_active=int(act_host.sum()),
                            lanes_live=stats["lanes_live"],
                            grid_width=Gx,
                            lanes_padded=int((orig_ids < 0).sum()),
                            num_quarantined=int((failed_host >= 0).sum()),
                            guarded_steps_skipped=int(skipped_host.sum()),
                            epoch_ms=round(epoch_ms, 3))
                    # ---- live graph-quality summary (obs/quality.py) -----
                    # one extra jit'd dispatch (pure read of params) whose
                    # gather rides THIS window's existing device->host
                    # transfer; the host-side monitor folds it into
                    # convergence diagnostics keyed by original point id
                    # (compaction-safe) and the event + snapshot below
                    if qmon is not None:
                        qdev = self._call_cold(("quality", Gx), qual_fn,
                                               params, qual_Xw)
                        # the (G, K, C, C) matrix stack is only consumed
                        # host-side for ground-truth scoring — without
                        # truth, skip its device->host transfer entirely
                        qhost = {qk: np.asarray(gather_to_host(qv))
                                 for qk, qv in qdev.items()
                                 if qmon.true_gc is not None or qk != "gc"}
                        qrec = qmon.update(it, qhost, orig_ids)
                        stats["quality"] = qmon.snapshot()
                        if logger.active:
                            logger.log("quality", grid_width=Gx, **qrec)
                # ---- learned-cost-model scoring (obs/costmodel.py) -------
                # score the prediction that existed BEFORE this epoch ran:
                # the persistent store's (shape, G-bucket) estimate when one
                # is available, else the fit's own prior-epoch mean at this
                # width. Pure host arithmetic on numbers already measured —
                # no device sync, nothing when no prediction exists yet.
                # The width's FIRST epoch is never scored: it carries the
                # compile/cache-priming skew the model deliberately does
                # not learn (a steady-state prediction vs a compile epoch
                # is not a residual, it is a category error that would
                # dominate MAPE)
                pred_ms = cm_src = None
                steady_epoch = stats["epochs_by_width"].get(wkey, 0) > 1
                if steady_epoch and cost_model is not None:
                    pred_ms = cost_model.predict_epoch_ms(
                        cm_shape_key, Gx, platform=cm_platform,
                        precision=("f32" if self._demoted
                                   else cm_precision0))
                    if pred_ms is not None:
                        cm_src = "store"
                if pred_ms is None:
                    # prior-epoch mean at this width, ALWAYS excluding the
                    # width's first epoch — it carries the compile/
                    # cache-priming skew (~20x steady state) and using it
                    # as the lone prior would emit one wildly-wrong scored
                    # window whose eta could land in a checkpoint or the
                    # supervisor ledger before the next window corrects it.
                    # No post-first-epoch prior yet -> no score this window
                    n_w = stats["epochs_by_width"].get(wkey, 0)
                    tot_prior = stats["epoch_ms_by_width"][wkey] - epoch_ms
                    n_prior = n_w - 1
                    first = stats["first_epoch_ms_by_width"].get(wkey)
                    if first is not None and n_prior >= 1:
                        tot_prior -= first
                        n_prior -= 1
                    if n_prior > 0 and tot_prior > 0:
                        pred_ms = tot_prior / n_prior
                        cm_src = "observed"
                if pred_ms is not None and pred_ms > 0:
                    residual_pct = 100.0 * (epoch_ms - pred_ms) / pred_ms
                    cm_n += 1
                    cm_abs_pct += abs(residual_pct)
                    epochs_remaining = max(max_iter - it - 1, 0)
                    eta_s = epochs_remaining * pred_ms / 1e3
                    stats["eta"] = {
                        "epoch": it, "predicted_epoch_ms": round(pred_ms, 3),
                        "epochs_remaining": epochs_remaining,
                        "eta_s": round(eta_s, 3), "source": cm_src}
                    stats["cost_model"] = {
                        "samples": cm_n,
                        "mape_pct": round(cm_abs_pct / cm_n, 2),
                        "source": cm_src}
                    if logger.active:
                        logger.log(
                            "cost_model", epoch=it, grid_width=Gx,
                            predicted_epoch_ms=round(pred_ms, 3),
                            actual_epoch_ms=round(epoch_ms, 3),
                            residual_pct=round(residual_pct, 2),
                            source=cm_src, eta_s=round(eta_s, 3),
                            epochs_remaining=epochs_remaining,
                            samples=cm_n,
                            mape_pct=stats["cost_model"]["mape_pct"])
                # ---- live HBM watermark poll (obs/memory.py) -------------
                # host allocator metadata read on the check-window cadence —
                # no dispatch, no sync, nothing on backends that return
                # None (this container's CPU). The peak rides
                # dispatch_stats -> every checkpoint, and each poll lands
                # as a `memory` event (the Perfetto counter track's source)
                if mem_poll and stats["memory"] is not None:
                    wm = _obsmem.poll_watermark(mem_devices)
                    if wm is not None:
                        sm = stats["memory"]
                        sm["polls"] += 1
                        if wm["peak_bytes"] is not None:
                            sm["peak_bytes"] = max(sm["peak_bytes"] or 0,
                                                   wm["peak_bytes"])
                        if logger.active:
                            logger.log("memory", kind="measured", epoch=it,
                                       grid_width=Gx,
                                       bytes_in_use=wm["bytes_in_use"],
                                       peak_bytes=wm["peak_bytes"],
                                       bytes_limit=wm["bytes_limit"],
                                       n_devices=wm["n_devices"],
                                       device_kind=wm["device_kind"])
                # global early exit: once EVERY lane has hit its per-point
                # patience, further epochs are pure masked compute (the
                # per-point trainer would have broken out of each run long
                # before, ref :1522-1538). Checked on the check_every cadence
                # so the host sync amortizes; uniform across processes
                # (gather_to_host is a collective on multi-host meshes)
                if (it >= cfg.num_pretrain_epochs + cfg.num_acclimation_epochs
                        and not bool(np.any(act_host))):
                    logger.log("early_exit_all_inactive", epoch=it)
                    break

                # ---- elastic lane compaction (policy decision, engine
                # apply) ---- when the live-lane count has dropped below the
                # next bucket on the power-of-two ladder, gather the
                # survivors into a compacted grid and stop paying FLOPs for
                # retired lanes. The DECISION comes from the scheduling
                # policy (parallel/policy.py -> compaction.plan_compaction);
                # this engine applies the plan. Runs at check-window
                # boundaries only (the act_host gather above is the decision
                # input — no extra sync) and BEFORE the checkpoint block, so
                # the epoch-it checkpoint stores the compacted state and a
                # resume lands in the same bucket. Per-lane updates are
                # bit-identical across widths: the vmapped step is
                # lane-independent, the same property the active-mask freeze
                # already relies on. Single-process only (a multi-host grid
                # would have to re-span hosts mid-fit)
                plan = self.policy.compaction_plan(
                    act_host, orig_ids, retired.keys(),
                    self._mesh_full.devices.size
                    if self._mesh_full is not None else 1,
                    n_processes=jax.process_count(),
                    epochs_remaining=max(max_iter - it - 1, 0))
                # predictive compaction pricing (ISSUE 15): the policy's
                # decision record — compact / hold / heuristic fallback with
                # the predicted saving vs compile+gather cost — lands as a
                # schema-registered `policy` event (obs watch/report render
                # these; the heuristic base policy records nothing)
                pol_dec = (self.policy.take_decision()
                           if hasattr(self.policy, "take_decision")
                           else None)
                if pol_dec is not None:
                    logger.log("policy", epoch=it, grid_width=Gx, **pol_dec)
                if plan is not None:
                    t_comp = time.perf_counter()
                    # retire frozen lanes' results to host before their
                    # rows are dropped (their state never changes again)
                    if plan.retire_rows.size:
                        rows = jnp.asarray(plan.retire_rows)
                        frozen = gather_to_host({
                            "best_params": jax.tree.map(
                                lambda l: l[rows], best_params),
                            "best_crit": best_crit[rows],
                            "best_epoch": best_epoch[rows],
                            "failed_epoch": failed_epoch[rows],
                            "failed_cause": failed_cause[rows],
                        })
                        for i, pid in enumerate(plan.retire_ids):
                            retired[int(pid)] = {
                                "best_params": jax.tree.map(
                                    lambda l, _i=i: np.asarray(l[_i]),
                                    frozen["best_params"]),
                                "best_crit": float(frozen["best_crit"][i]),
                                "best_epoch": int(frozen["best_epoch"][i]),
                                "failed_epoch": int(
                                    frozen["failed_epoch"][i]),
                                "failed_cause": int(
                                    frozen["failed_cause"][i]),
                            }
                        if on_lane_retire is not None:
                            # per-point streaming (ISSUE 18): a retired
                            # lane's result is FINAL — surface it now, at
                            # the check-window boundary, not at batch
                            # settle. Advisory: a hook failure must never
                            # perturb the fit
                            for pid in plan.retire_ids:
                                try:
                                    on_lane_retire(int(pid),
                                                   retired[int(pid)], it)
                                except Exception:  # noqa: BLE001
                                    pass
                    old_width = Gx
                    self.mesh = self._mesh_for(plan.new_width)
                    sel = jnp.asarray(plan.sel)
                    take = lambda t: self._shard(
                        jax.tree.map(lambda l: l[sel], t))
                    params = take(params)
                    optA_state = take(optA_state)
                    optB_state = take(optB_state)
                    nstate = take(nstate)
                    best_params = take(best_params)
                    if accepted is not None:
                        accepted = take(accepted)
                    best_crit = take(best_crit)
                    best_epoch = take(best_epoch)
                    failed_epoch = take(failed_epoch)
                    failed_cause = take(failed_cause)
                    active = self._shard(jnp.asarray(plan.active))
                    orig_ids = plan.orig_ids
                    Gx = plan.new_width
                    coeffs = self._shard(self._coeffs_for(orig_ids))
                    lane_deadline = self._exec_deadlines(orig_ids)
                    dl_done = dl_done[plan.sel]
                    # replicated device data must follow the (possibly
                    # shrunken) active mesh; device_arrays keeps one copy
                    # per placement, so this is a cache hit when the mesh
                    # is unchanged
                    sharding = (replicated(self.mesh)
                                if self.mesh is not None else None)
                    if Xd is not None:
                        Xd, Yd = train_ds.device_arrays(sharding)
                    if val_scan_ok:
                        vXd, vYd = val_ds.device_arrays(sharding)
                        vidx = jnp.asarray(v_full)
                        if sharding is not None:
                            vidx = jax.device_put(vidx, sharding)
                    if cos_Xw is not None and sharding is not None:
                        cos_Xw = jax.device_put(cos_Xw, sharding)
                    if qual_Xw is not None and sharding is not None:
                        qual_Xw = jax.device_put(qual_Xw, sharding)
                    eras.append(orig_ids)
                    era_cur += 1
                    stats["compactions"] += 1
                    stats["grid_width"] = Gx
                    stats["lanes_padded"] = int((orig_ids < 0).sum())
                    logger.log(
                        "compaction", epoch=it, from_width=old_width,
                        to_width=Gx, lanes_live=stats["lanes_live"],
                        retired=[int(p) for p in plan.retire_ids],
                        mesh_devices=(self.mesh.devices.size
                                      if self.mesh is not None else None))
                    # traced span for the whole apply (retire gather +
                    # survivor re-shard + device-data re-placement)
                    obs.record_span(
                        "grid.compaction",
                        (time.perf_counter() - t_comp) * 1e3,
                        component="compaction", logger=logger, emit=True,
                        epoch=it, from_width=old_width, to_width=Gx)

            if checkpoint_dir is not None:
                snap = {
                    "params": params, "optA_state": optA_state,
                    "optB_state": optB_state, "best_params": best_params,
                    "best_crit": best_crit, "best_epoch": best_epoch,
                    "active": active, "accepted": accepted,
                    "failed_epoch": failed_epoch,
                    "failed_cause": failed_cause, "nstate": nstate,
                    "val_history": val_history, "val_eras": val_eras,
                    "eras": eras, "orig_ids": orig_ids, "retired": retired,
                    "aligned": aligned, "mesh": mesh_desc,
                    "precision_demoted": self._demoted,
                    # telemetry snapshot for the obs report CLI (deep copy:
                    # the live dict keeps mutating under the async writer)
                    "dispatch_stats": copy.deepcopy(stats),
                    "rng_state": rng.bit_generator.state, "epoch": it,
                }
                saved = False
                if (checkpoint_every and (it + 1) % checkpoint_every == 0) \
                        or force_ckpt or grid_dl_hit:
                    t_save = time.perf_counter()
                    self._save_checkpoint(checkpoint_dir, snap, ck_meta,
                                          writer=writer)
                    stall_ms = (time.perf_counter() - t_save) * 1e3
                    stats["ckpt_stall_ms"] += stall_ms
                    # main-thread hand-off stall span (the async writer's
                    # own write span lands under the "ckpt" component)
                    obs.record_span("grid.ckpt_save", stall_ms,
                                    component="ckpt", epoch=it,
                                    background=writer is not None)
                    saved = True
                    if writer is not None and faultinject.armed():
                        # fault-test determinism: "checkpoint_saved" must
                        # mean durably on disk before the crash point fires
                        writer.wait()
                    faultinject.crash_point("checkpoint_saved", epoch=it)
                # preemption: the guard latched SIGTERM/SIGINT; write one
                # final checkpoint at this epoch boundary and stop. Multi-host
                # meshes must decide uniformly (the save runs collectives) —
                # a notice landing on ANY host preempts the whole fit. The
                # uniformity allgather is itself a cross-host sync, so it
                # rides the existing checkpoint/check_every cadences instead
                # of adding a per-epoch collective (at most check_every
                # epochs of latency on a save that waits for an epoch
                # boundary anyway); single-host polls the flag every epoch
                # for free
                preempted = bool(guard is not None and guard.preempted)
                if jax.process_count() > 1:
                    if saved or (it + 1) % tc.check_every == 0:
                        from jax.experimental import multihost_utils

                        preempted = bool(np.any(
                            multihost_utils.process_allgather(
                                np.asarray(preempted))))
                    else:
                        preempted = False
                if preempted:
                    if not saved:
                        self._save_checkpoint(checkpoint_dir, snap, ck_meta,
                                              writer=writer)
                    if writer is not None:
                        # the final checkpoint must be durable before the
                        # process acts on Preempted (typically: exits)
                        writer.wait()
                    logger.log("preempted_final_checkpoint", epoch=it,
                               signum=guard.signum if guard else None)
                    # close an open capture window while the logger can
                    # still record the truncated `profile` event
                    pw.finish(logger=logger)
                    logger.close()
                    raise Preempted(guard.signum if guard else None,
                                    epoch=it)
            if grid_dl_hit:
                # whole-grid deadline: in-flight work is already drained
                # (the epoch completed; the forced save above is the final
                # checkpoint when checkpointing is on) — flush and exit
                # resumable, a self-inflicted preemption with its own
                # taxonomy code
                if writer is not None:
                    writer.wait()
                logger.log("grid_deadline_final_checkpoint", epoch=it,
                           elapsed_s=round(elapsed, 3),
                           deadline_s=float(self.spec.grid_deadline_s),
                           checkpointed=checkpoint_dir is not None)
                pw.finish(logger=logger)
                logger.close()
                raise DeadlineExceeded(
                    "grid", epoch=it, elapsed_s=elapsed,
                    deadline_s=float(self.spec.grid_deadline_s))
            stats["epochs"] += 1
            # dead-lane accounting: lanes this epoch actually computed vs
            # what an uncompacted run would have (their gap, summed over
            # epochs, is the FLOPs compaction saved — bench.py reports it
            # as dead_lane_flops_saved_pct)
            stats["lane_epochs"] += epoch_width
            stats["lane_epochs_nominal"] += width_nominal
            # per-epoch compile observability: any epoch that compiled a
            # program logs what it cost and whether the persistent cache
            # served it (runtime/compileobs.py)
            if logger.active:
                dc = compileobs.delta(epoch_compile_t0)
                if dc["compiles"]:
                    logger.log("compile", epoch=it,
                               programs=dc["compiles"],
                               compile_ms=dc["compile_ms"],
                               cache_hits=dc["cache_hits"],
                               cache_misses=dc["cache_misses"],
                               grid_width=Gx)
            # close the profiler capture when this epoch ends the window
            # (the `profile` event announcing the artifact rides this call)
            pw.on_epoch_end(it, logger=logger)
            faultinject.crash_point("epoch_end", epoch=it)

        rt_watchdog.retire("epoch_engine")
        if writer is not None:
            # completion barrier: surface any background write failure and
            # guarantee the last generation is durable before results return
            writer.wait()
        # final watermark sample so short fits (under one check window) still
        # record a measured peak where the backend reports one
        if mem_poll and stats["memory"] is not None:
            wm = _obsmem.poll_watermark(mem_devices)
            if wm is not None and wm["peak_bytes"] is not None:
                stats["memory"]["polls"] += 1
                stats["memory"]["peak_bytes"] = max(
                    stats["memory"]["peak_bytes"] or 0, wm["peak_bytes"])
        stats.update(compileobs.delta(compile_t0))
        cdelta = obs.counters.delta(counters_t0)
        stats["prefetch_stall_ms"] = cdelta.get("prefetch_stall_ms", 0.0)
        stats["prefetch_items"] = int(cdelta.get("prefetch_items", 0))
        stats["ckpt_barrier_stall_ms"] = cdelta.get(
            "ckpt_barrier_stall_ms", 0.0)
        # fold this fit's observed per-width epoch costs + compile totals
        # into the persistent cost-model store (obs/costmodel.py) so the
        # model accumulates across runs and tenants like the compile cache
        # it lives beside. Advisory: a store failure must never fail a fit
        if cm_base and jax.process_index() == 0:
            try:
                if self._demoted and demote_snap is not None:
                    # per-era fold: epochs before the demotion ran mixed,
                    # epochs after ran f32 — each era lands in its own
                    # precision bucket, with the compile accumulators split
                    # at the same boundary (the f32 rebuild's recompiles
                    # belong to the f32 era, the fit's cold compiles to the
                    # mixed one)
                    csnap = demote_compile_snap or {}
                    cm_rows = _costmodel.rows_from_dispatch_stats(
                        cm_shape_key, {**stats, **demote_snap, **csnap},
                        precision=cm_precision0)
                    post = {
                        "epochs_by_width": {
                            w: n - demote_snap["epochs_by_width"].get(w, 0)
                            for w, n in stats["epochs_by_width"].items()},
                        "epoch_ms_by_width": {
                            w: ms
                            - demote_snap["epoch_ms_by_width"].get(w, 0.0)
                            for w, ms
                            in stats["epoch_ms_by_width"].items()},
                        # first-epoch (compile-skew) exclusion: widths born
                        # after the demotion keep their own firsts, and the
                        # first post-demotion epoch (the rebuild's
                        # recompile cost) is excluded the same way
                        "first_epoch_ms_by_width": {
                            **{w: v for w, v in
                               stats["first_epoch_ms_by_width"].items()
                               if w not in
                               demote_snap["first_epoch_ms_by_width"]},
                            **demote_first_f32},
                        **{k: stats.get(k, 0) - csnap.get(k, 0)
                           for k in ("compiles", "compile_ms",
                                     "cache_hits", "cache_misses")},
                    }
                    cm_rows += _costmodel.rows_from_dispatch_stats(
                        cm_shape_key, post, precision="f32")
                else:
                    # a fit that RESUMED already-demoted ran f32 throughout
                    cm_rows = _costmodel.rows_from_dispatch_stats(
                        cm_shape_key, stats,
                        precision=("f32" if self._demoted
                                   else cm_precision0))
                _costmodel.update_store(cm_base, cm_rows,
                                        platform=cm_platform)
            except Exception:  # noqa: BLE001 — best-effort telemetry fold
                pass

        # ---- result assembly under ORIGINAL point ids -------------------
        # one gather each; live execution lanes scatter through orig_ids,
        # lanes retired by earlier compactions come from the host-side
        # retired store, filler lanes are dropped
        exec_crit = gather_to_host(best_crit)
        exec_epoch = gather_to_host(best_epoch)
        exec_active = gather_to_host(active)
        exec_failed = np.asarray(gather_to_host(failed_epoch))
        exec_cause = np.asarray(gather_to_host(failed_cause))
        exec_best = gather_to_host(best_params)
        real = orig_ids >= 0
        ids = orig_ids[real]
        G_real = self._g_real

        def full_of(exec_arr, fill, dtype=None):
            out = np.full((G_real,) + np.shape(exec_arr)[1:], fill,
                          dtype or np.asarray(exec_arr).dtype)
            out[ids] = np.asarray(exec_arr)[real]
            return out

        final_crit = full_of(exec_crit, np.inf)
        final_epoch = full_of(exec_epoch, 0)
        final_active = full_of(exec_active, False)
        final_failed = full_of(exec_failed, -1)
        final_cause = full_of(exec_cause, 0)
        leaves, treedef = jax.tree.flatten(exec_best)
        retired_leaves = {pid: jax.tree.leaves(rec["best_params"])
                          for pid, rec in retired.items()}
        full_leaves = []
        for li, leaf in enumerate(leaves):
            full = np.zeros((G_real,) + leaf.shape[1:], leaf.dtype)
            full[ids] = np.asarray(leaf)[real]
            for pid, rls in retired_leaves.items():
                full[pid] = rls[li]
            full_leaves.append(full)
        best_params_full = jax.tree.unflatten(treedef, full_leaves)
        for pid, rec in retired.items():
            final_crit[pid] = rec["best_crit"]
            final_epoch[pid] = rec["best_epoch"]
            final_failed[pid] = rec["failed_epoch"]
            final_cause[pid] = rec["failed_cause"]
        failures = [{"point": int(g), "epoch": int(e),
                     "cause": numerics.QUARANTINE_CAUSES.get(
                         int(c), "nonfinite_val"),
                     "hparams": dict(self.spec.points[g])}
                    for g, (e, c) in enumerate(zip(final_failed, final_cause))
                    if e >= 0]
        logger.log("fit_end", best_epoch=final_epoch,
                   best_criteria=final_crit,
                   num_active=int(final_active.sum()),
                   compactions=stats["compactions"],
                   compile_ms=stats["compile_ms"],
                   failures=failures,
                   # the full per-fit telemetry (dispatch counts, stall and
                   # per-width timing accumulators): the obs report CLI's
                   # primary input for the time breakdown + cost table
                   dispatch_stats=stats)
        # a window the fit's epochs never closed (early exit inside it, or
        # a window past the horizon) announces its truncated capture now
        pw.finish(logger=logger)
        logger.close()
        return GridResult(
            best_params=best_params_full,
            best_criteria=final_crit,
            best_epoch=final_epoch,
            val_history=compaction.expand_history(
                [self._to_host(v) for v in val_history], val_eras, eras,
                G_real),
            coeffs=dict(self.result_coeffs),
            active=final_active,
            failures=failures,
        )
