"""Device-mesh helpers for grid / data parallelism.

The reference's only scale-out mechanism is SLURM job arrays — one process per
hyperparameter point, filesystem as the communication medium (SURVEY.md §2.8).
Here the grid is a sharded array axis on a jax Mesh: grid points ride ICI within
a slice, and the same code spans hosts over DCN via jax.distributed
initialization (the mesh just gets bigger).
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["grid_mesh", "shard_leading_axis", "replicated",
           "shard_factor_axis", "P", "Mesh"]


def grid_mesh(n_devices=None, axis_name="grid", devices=None):
    """1-D mesh over all (or the first n) devices for grid-axis sharding."""
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def shard_leading_axis(mesh, axis_name="grid"):
    """NamedSharding that splits axis 0 across the mesh."""
    return NamedSharding(mesh, P(axis_name))


def replicated(mesh):
    return NamedSharding(mesh, P())


def shard_factor_axis(params, mesh, axis_name=None):
    """Expert-style factor parallelism (SURVEY §2.8): the K factor networks
    are structurally a dense MoE, so their stacked parameters (leading K
    axis on every ``params["factors"]`` leaf) shard across the mesh like
    experts, while the embedder replicates.  XLA then partitions the
    vmapped per-factor einsums and inserts the psum at the mixture sum.

    K must be divisible by the mesh size.  ``axis_name`` defaults to the
    mesh's (single) axis, so any 1-D mesh works regardless of its name."""
    axis_name = mesh.axis_names[0] if axis_name is None else axis_name
    fac_sh = NamedSharding(mesh, P(axis_name))
    rep = NamedSharding(mesh, P())
    out = dict(params)
    out["factors"] = jax.tree.map(
        lambda x: jax.device_put(x, fac_sh), params["factors"])
    for key, sub in params.items():
        if key != "factors":
            out[key] = jax.tree.map(lambda x: jax.device_put(x, rep), sub)
    return out
