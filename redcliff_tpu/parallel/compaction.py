"""Elastic grid scheduling: live-lane compaction + G-bucketed program reuse.

REDCLIFF-S model selection is a grid sweep with per-lane early stopping
(stopping-criteria coefficients, PAPER §4), but a vmapped grid program has a
FIXED width: every dispatch computes all G lanes whether or not the ``active``
mask has already retired them. On an early-stopping sweep that means up to
half the FLOPs are spent updating frozen lanes and immediately discarding the
result (BENCH_r05: per-chip throughput halves from G=1 to G=16 — the dead
lanes ride every dispatch). The same amortize-across-a-population lever that
NAVAR-style ensembles and DYNOTEARS batched solves exploit (PAPERS.md) cuts
the other way once the population shrinks.

This module owns the pure-host planning half of the fix; the grid engine
(parallel/grid.py) executes it:

* **Bucket ladder** (:func:`bucket_width`) — execution widths are drawn from
  a power-of-two ladder (mesh-compatible: multiples of the device count
  above it, divisors of it below), so the set of compiled programs stays
  small and reusable instead of one program per exact (shape, G). Real lanes
  beyond the live count are padded with masked FILLER lanes (``active`` is
  False from birth; ``orig_id`` -1), which never surface in results.
* **Compaction plan** (:func:`plan_compaction`) — at a check-window boundary,
  when the live-lane count drops below the next ladder rung, the surviving
  lanes' state (params, opt states, numerics counters, coeffs, rng-free lane
  bookkeeping, best-trees) is gathered into a compacted grid of the new
  width and point indices are remapped. Each surviving lane's update stream
  is BIT-IDENTICAL to the uncompacted run: the vmapped step is per-lane
  independent (lane g's update reads only lane g's state + the broadcast
  batch), so removing sibling lanes changes which program runs, never what a
  lane computes — the same argument the deadline-eviction and early-stop
  masks already rely on, pinned by tests/test_compaction.py.
* **History expansion** (:func:`expand_history`) — per-epoch loss rows are
  recorded at execution width; this scatters them back to original point ids
  and carries retired lanes' last value forward. Carrying forward IS the
  uncompacted semantics bit-for-bit: an inactive lane's parameters are
  frozen, so the uncompacted run recomputes the identical loss every epoch.

Results and failures are always reported under ORIGINAL point ids; filler
lanes never leak into :class:`~redcliff_tpu.parallel.grid.GridResult`.

numpy-only at module scope (the grid engine calls in with host arrays).
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "bucket_width",
    "next_pow2",
    "ladder_widths",
    "serve_rung",
    "plan_compaction",
    "assemble_plan",
    "unretired_frozen_rows",
    "expand_history",
    "CompactionPlan",
]


def next_pow2(n):
    """Smallest power of two >= max(n, 1)."""
    n = max(int(n), 1)
    return 1 << (n - 1).bit_length()


def bucket_width(n_lanes, n_devices=1):
    """Execution width for ``n_lanes`` live lanes on an ``n_devices`` mesh.

    The ladder is powers of two, adjusted for mesh divisibility so the
    compacted grid always re-shards cleanly:

    - no mesh (``n_devices <= 1``): the next power of two;
    - width >= mesh: rounded up to a multiple of ``n_devices`` (a no-op on
      power-of-two meshes);
    - width < mesh: kept when it divides ``n_devices`` (the engine runs on a
      SUB-mesh of that many devices — the G' < n_devices case), otherwise
      rounded up to ``n_devices``.
    """
    b = next_pow2(n_lanes)
    n_devices = int(n_devices or 1)
    if n_devices <= 1:
        return b
    if b >= n_devices:
        return -(-b // n_devices) * n_devices
    return b if n_devices % b == 0 else n_devices


def ladder_widths(n_lanes, n_devices=1, max_width=None):
    """The bucket-ladder rungs from the width ``n_lanes`` requires up to
    ``max_width`` (default: 8x the base rung), ascending. The enumeration
    input for the device-memory observatory's per-rung HBM footprints
    (obs/memory.py ``footprint_by_bucket``), the fleet admission planner,
    and the predictive scheduling policy's initial-width pricing
    (parallel/policy.py ``PredictiveSchedulingPolicy``, ISSUE 15): which
    widths COULD this shape run at, before asking what each one costs in
    bytes and milliseconds."""
    base = bucket_width(n_lanes, n_devices)
    if max_width is None:
        max_width = base * 8
    out = []
    w = base
    while w <= int(max_width):
        out.append(w)
        w = bucket_width(w + 1, n_devices)
    return out


def serve_rung(n_live, capacity, min_rung=1):
    """Slot-table dispatch width for ``n_live`` leased serve lanes.

    The serving twin of :func:`bucket_width`: the smallest power-of-two
    rung >= ``max(n_live, min_rung)``, clamped to ``capacity`` (the full
    table is always a legal rung even when capacity is not itself a power
    of two). The serve engine dispatches at this width and pads/slices its
    slot table at tick boundaries; ``min_rung`` is the churn floor — below
    it, saving another lane is not worth a cold program (serve/service.py
    sets 4)."""
    cap = max(int(capacity), 1)
    return min(next_pow2(max(int(n_live), int(min_rung), 1)), cap)


class CompactionPlan:
    """Host-side recipe for one compaction event.

    Attributes:
      sel: (new_width,) int32 — exec-row gather indices into the CURRENT
        grid (surviving lanes first, then filler rows replicating the first
        survivor so every gathered row holds finite, valid state).
      orig_ids: (new_width,) int32 — original point id per new exec row,
        -1 for filler.
      active: (new_width,) bool — True for the surviving (live) rows only.
      retire_rows: (k,) int32 — CURRENT exec rows holding real, inactive,
        not-yet-retired lanes whose frozen results must be gathered to host
        before their rows are dropped.
      retire_ids: (k,) int32 — those rows' original point ids.
    """

    def __init__(self, sel, orig_ids, active, retire_rows, retire_ids):
        self.sel = sel
        self.orig_ids = orig_ids
        self.active = active
        self.retire_rows = retire_rows
        self.retire_ids = retire_ids

    @property
    def new_width(self):
        return int(self.sel.shape[0])


def unretired_frozen_rows(active, orig_ids, retired_ids):
    """Exec rows holding real, inactive, NOT-yet-retired lanes — the lanes
    whose frozen results a plan must gather to host before their rows drop.
    Shared by check-window compaction and degraded-mesh re-sharding
    (parallel/remesh.py)."""
    already = set(int(i) for i in retired_ids)
    return np.asarray(
        [r for r in np.flatnonzero(~active & (orig_ids >= 0))
         if int(orig_ids[r]) not in already], np.int32)


def assemble_plan(orig_ids, keep_rows, keep_active, fill_row, new_w,
                  retire_rows):
    """Build a :class:`CompactionPlan` from a keep-row selection: kept rows
    first (their active flags preserved), then filler rows replicating
    ``fill_row``. The filler invariant lives HERE, once: callers must point
    ``fill_row`` at a lane holding finite, valid state (filler lanes run
    real masked math — non-finite state would poison device-side anomaly
    accounting even though results are discarded)."""
    orig_ids = np.asarray(orig_ids, np.int32)
    keep_rows = np.asarray(keep_rows, np.int32)
    retire_rows = np.asarray(retire_rows, np.int32)
    pad = int(new_w) - keep_rows.size
    sel = np.concatenate([keep_rows, np.full((pad,), fill_row, np.int32)])
    new_ids = np.concatenate(
        [orig_ids[keep_rows], np.full((pad,), -1, np.int32)])
    new_active = np.zeros((int(new_w),), bool)
    new_active[: keep_rows.size] = keep_active
    return CompactionPlan(sel, new_ids, new_active, retire_rows,
                          orig_ids[retire_rows].astype(np.int32))


def plan_compaction(active, orig_ids, retired_ids, n_devices=1):
    """Plan a compaction, or return None when the current width is already
    the right bucket.

    ``active``: (G_exec,) bool host mask; ``orig_ids``: (G_exec,) int32
    original point id per exec row (-1 = filler); ``retired_ids``: ids whose
    results were already captured by an earlier compaction (their rows are
    gone). Lanes are kept in exec-row order, so surviving lanes' relative
    order is stable across compactions.
    """
    active = np.asarray(active, bool)
    orig_ids = np.asarray(orig_ids, np.int32)
    live_rows = np.flatnonzero(active & (orig_ids >= 0)).astype(np.int32)
    n_live = int(live_rows.size)
    if n_live == 0:
        return None  # nothing to run; the fit's own exit paths handle this
    new_w = bucket_width(n_live, n_devices)
    if new_w >= orig_ids.size:
        return None
    return assemble_plan(
        orig_ids, live_rows, True, live_rows[0], new_w,
        unretired_frozen_rows(active, orig_ids, retired_ids))


def expand_history(rows, row_eras, eras, n_points):
    """Scatter exec-width per-epoch rows back to (epochs, n_points) under
    original point ids, carrying retired lanes' last value forward.

    ``rows``: per-epoch host arrays — exec width (era-indexed) or already
    full width (``row_eras`` entry -1, e.g. restored from a checkpoint that
    stored expanded history). ``eras``: list of orig_ids arrays, one per
    compaction era. Filler entries (orig_id -1) are dropped.
    """
    carry = np.full((int(n_points),), np.nan, np.float32)
    out = []
    for row, era in zip(rows, row_eras):
        row = np.asarray(row, np.float32)
        if era < 0:
            carry = row.copy()
        else:
            ids = eras[era]
            real = ids >= 0
            carry[ids[real]] = row[real]
        out.append(carry.copy())
    return np.stack(out) if out else np.zeros((0, int(n_points)), np.float32)
