"""Spatial mesh packing: disjoint sub-mesh slots over one device pool.

The fleet v2 tentpole (ROADMAP item 2): one worker runs CONCURRENT batches
on disjoint power-of-two device groups of the same pool instead of parking
the whole mesh on one fit at a time. This module is the pure decision /
bookkeeping layer:

* :class:`SlotTable` — a buddy-style allocator over the pool's largest
  power-of-two prefix. Slots are aligned device intervals ``{"lo", "width"}``
  (``lo % width == 0``), so any two live slots are disjoint by construction
  and a slot freed at a check-window boundary re-coalesces for free;
* :func:`devices_for` — the sub-mesh width a planned batch occupies, riding
  the PR-5 bucket ladder (an admitted ``g_bucket`` of lanes runs on
  ``min(g_bucket, pool)`` devices — the same G' < n_devices sub-mesh case
  ``compaction.bucket_width`` already prices);
* :func:`price_packing` — the predictive packing decision: simulate the
  plan's batches draining through the slot table (first-fit in plan order,
  co-resident HBM never over ``budget_bytes``) and compare the packed
  makespan against the serial worker's ``sum(eta)``. The decision record is
  what the planner emits as a schema-registered ``packing`` event. With an
  EMPTY cost store (any batch unpriced) the verdict is ``serial`` — the
  worker's behavior stays bit-identical to the pre-packing heuristic, the
  same fallback discipline as parallel/policy.py;
* :func:`publish_state` / :func:`load_state` — the worker publishes its
  live slot occupancy to ``<root>/packing.json`` so the autoscaler's
  ``predicted_drain`` can divide the queue ETA by the real packing width
  instead of assuming one batch at a time.

Gating rides ``REDCLIFF_FLEET_PACKING``: ``0``/unset = off (the serial
worker, unchanged), ``1``/``auto`` = pack only when the priced makespan
beats serial, ``force`` = always pack (bench/CI legs that must exercise
concurrency without warming a cost store first).

stdlib only, no jax (obs/schema.py ``--check`` enforces it): packing
decisions run in the worker control process, which must never initialize a
backend. The jax-side sub-mesh construction lives in fleet/run_batch.py.
"""
from __future__ import annotations

import json
import os
import time

__all__ = ["SlotTable", "devices_for", "price_packing", "packing_mode",
           "packing_enabled", "publish_state", "load_state", "ENV_PACKING",
           "STATE_FILE", "STATE_FRESH_S"]

ENV_PACKING = "REDCLIFF_FLEET_PACKING"

# worker-published slot occupancy (autoscaler input); stale files are
# ignored the same way autoscale.json freshness works
STATE_FILE = "packing.json"
STATE_FRESH_S = 120.0


def packing_mode(env=None):
    """The packing gate: ``"off"`` (default), ``"auto"`` (pack only on a
    priced makespan win), or ``"force"`` (always pack — bench/CI legs)."""
    raw = (os.environ.get(ENV_PACKING, "") if env is None else env)
    raw = str(raw).strip().lower()
    if raw in ("", "0", "off", "false", "no"):
        return "off"
    if raw in ("force", "always", "2"):
        return "force"
    return "auto"


def packing_enabled(env=None):
    return packing_mode(env) != "off"


def largest_pow2(n):
    """Largest power of two <= n (0 for n < 1)."""
    n = int(n)
    return 1 << (n.bit_length() - 1) if n >= 1 else 0


def devices_for(g_bucket, n_devices):
    """Sub-mesh width (device count) a batch admitted at ``g_bucket`` lanes
    occupies on an ``n_devices`` pool: the bucket width itself while it fits
    (bucket widths are ladder powers of two, so the slot stays aligned),
    else the pool's whole packable region — the G' < n_devices sub-mesh
    case from parallel/compaction.py, now packable side by side."""
    pool = largest_pow2(n_devices)
    if pool <= 0:
        return 1
    return min(max(int(g_bucket or 1), 1), pool)


class SlotTable:
    """Aligned power-of-two slot allocator over a device pool.

    The packable region is the largest power-of-two prefix of the pool
    (device ids are stable — parallel/remesh.py ``visible_devices`` — so
    slot ``{"lo": 2, "width": 2}`` means the same two devices to every
    worker and every reclaim). Alignment (``lo % width == 0``) makes slots
    buddy-disjoint: no two live slots ever overlap, and :meth:`reserve`
    lets a reclaiming worker re-occupy the EXACT slot a dead worker's
    batch.json recorded."""

    def __init__(self, n_devices):
        self.n_devices = max(int(n_devices), 1)
        self.pool = largest_pow2(self.n_devices)
        self._busy = {}  # lo -> width

    def _overlaps(self, lo, width):
        hi = lo + width
        return any(not (hi <= b_lo or lo >= b_lo + b_w)
                   for b_lo, b_w in self._busy.items())

    def alloc(self, width):
        """Claim the lowest free aligned slot of ``width`` devices (width
        is clamped to a power of two within the pool). None when no slot of
        that width is free."""
        width = largest_pow2(min(max(int(width), 1), self.pool))
        for lo in range(0, self.pool, width):
            if not self._overlaps(lo, width):
                self._busy[lo] = width
                return {"lo": lo, "width": width}
        return None

    def reserve(self, slot):
        """Re-occupy an exact recorded slot (reclaim path). False when the
        slot is malformed, out of range, or overlaps a live slot."""
        try:
            lo, width = int(slot["lo"]), int(slot["width"])
        except (TypeError, KeyError, ValueError):
            return False
        if width < 1 or lo < 0 or lo % width or lo + width > self.pool:
            return False
        if self._overlaps(lo, width):
            return False
        self._busy[lo] = width
        return True

    def free(self, slot):
        """Release a slot (idempotent — double-free at settle races is a
        no-op, first-writer-wins like every fleet terminal record)."""
        try:
            self._busy.pop(int(slot["lo"]), None)
        except (TypeError, KeyError, ValueError):
            pass

    def free_widths(self):
        """Descending widths still allocatable — the planner is called
        with ``n_devices=max(free_widths())`` so its bucket ladder prices
        the sub-mesh the claim will actually land on."""
        out = set()
        width = self.pool
        while width >= 1:
            if any(not self._overlaps(lo, width)
                   for lo in range(0, self.pool, width)):
                out.add(width)
            width //= 2
        return sorted(out, reverse=True)

    def occupancy(self):
        busy = sum(self._busy.values())
        return {
            "n_devices": self.n_devices,
            "pool": self.pool,
            "busy_devices": busy,
            "free_devices": self.pool - busy,
            "slots": [{"lo": lo, "width": w}
                      for lo, w in sorted(self._busy.items())],
            "utilization_pct": (round(100.0 * busy / self.pool, 1)
                                if self.pool else None),
        }


def price_packing(batches, n_devices, budget_bytes=None):
    """Predictive packing decision over a plan's ordered batch views.

    Simulates the batches draining through a :class:`SlotTable` — first-fit
    in plan order at :func:`devices_for` widths, a batch co-residing only
    while the co-resident ``predicted_bytes`` sum stays within
    ``budget_bytes`` (the PR-9 per-lane HBM model; zero headroom violations
    by construction) — and prices the packed makespan against the serial
    worker's ``sum(eta_s)``.

    Returns a decision record: ``{"decision": "packed"|"serial", "reason",
    "makespan_s", "serial_s", "makespan_ratio", "n_devices", "pool",
    "assignments": [{batch_id, lo, width, start_s}], "headroom_violations":
    0}``. The verdict is ``serial`` whenever any batch is unpriced (empty
    cost store — the bit-identical heuristic fallback), the pool has no
    room for two slots, or the simulated packing does not beat serial."""
    batches = list(batches or ())
    pool = largest_pow2(n_devices)
    base = {"n_devices": int(n_devices or 0), "pool": pool,
            "headroom_violations": 0}
    if len(batches) < 2:
        return dict(base, decision="serial", reason="single_batch",
                    makespan_s=None, serial_s=None, makespan_ratio=None,
                    assignments=[])
    if pool < 2:
        return dict(base, decision="serial", reason="pool_too_small",
                    makespan_s=None, serial_s=None, makespan_ratio=None,
                    assignments=[])
    etas = [b.get("eta_s") for b in batches]
    if any(not isinstance(e, (int, float)) or e <= 0 for e in etas):
        # empty/partial cost store: no pricing evidence — fall back to the
        # serial heuristic bit-identically (parallel/policy.py discipline)
        return dict(base, decision="serial", reason="unpriced",
                    makespan_s=None, serial_s=None, makespan_ratio=None,
                    assignments=[])
    serial_s = float(sum(etas))

    # event-driven simulation: running = [(end_s, slot, bytes)]
    table = SlotTable(n_devices)
    queue = list(zip(batches, etas))
    running, assignments = [], []
    now = 0.0
    resident_bytes = 0
    makespan = 0.0
    while queue or running:
        progressed = True
        while progressed and queue:
            progressed = False
            for i, (b, eta) in enumerate(queue):
                width = devices_for(b.get("g_bucket"), n_devices)
                pb = b.get("predicted_bytes")
                if budget_bytes is not None:
                    if pb is None and running:
                        continue  # no memory evidence: never co-resident
                    if pb is not None and running \
                            and resident_bytes + pb > budget_bytes:
                        continue
                slot = table.alloc(width)
                if slot is None:
                    continue
                running.append((now + float(eta), slot, pb or 0))
                resident_bytes += pb or 0
                assignments.append({"batch_id": b.get("batch_id"),
                                    "lo": slot["lo"],
                                    "width": slot["width"],
                                    "start_s": round(now, 3)})
                del queue[i]
                progressed = True
                break
        if not running:
            # nothing placeable (shouldn't happen: a solo batch always
            # fits the admission gate) — price it serially and bail
            return dict(base, decision="serial", reason="unpackable",
                        makespan_s=None, serial_s=round(serial_s, 3),
                        makespan_ratio=None, assignments=[])
        running.sort(key=lambda t: t[0])
        end, slot, pb = running.pop(0)
        now = makespan = end
        table.free(slot)
        resident_bytes -= pb

    ratio = makespan / serial_s if serial_s > 0 else None
    packed = ratio is not None and ratio < 1.0 \
        and any(a["width"] < pool for a in assignments)
    return dict(base,
                decision="packed" if packed else "serial",
                reason="priced" if packed else "no_predicted_win",
                makespan_s=round(makespan, 3),
                serial_s=round(serial_s, 3),
                makespan_ratio=(round(ratio, 4) if ratio is not None
                                else None),
                assignments=assignments)


def publish_state(root, occupancy, concurrent_batches=0, now=None):
    """Atomically publish the worker's live slot occupancy to
    ``<root>/packing.json`` — the autoscaler's slot-awareness input
    (``predicted_drain`` divides the serial queue ETA by the published
    packing width) and an ``obs watch``/``fleet status`` surface."""
    state = dict(occupancy or {})
    state["concurrent_batches"] = int(concurrent_batches)
    state["updated_at"] = float(time.time() if now is None else now)
    path = os.path.join(str(root), STATE_FILE)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(state, f, allow_nan=False)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def load_state(root, now=None, fresh_s=STATE_FRESH_S):
    """The live published packing state, or None (missing, corrupt, or
    stale past ``fresh_s`` — a dead packed worker must not keep scaling
    decisions slot-optimistic forever)."""
    path = os.path.join(str(root), STATE_FILE)
    try:
        with open(path) as f:
            state = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(state, dict):
        return None
    age = (time.time() if now is None else now) \
        - float(state.get("updated_at") or 0.0)
    if age > fresh_s:
        return None
    return state
