"""Sequence/context parallelism: ring attention over a device-mesh axis.

The reference consumes only short windows (T <= ~1000 and models read <= 16
steps of context per prediction — SURVEY §5 "long-context"), so it never
needed sequence parallelism. This framework treats long context as
first-class: full-rate LFP recordings (minutes at 1 kHz) can be encoded by
the TS transformer without windowing by sharding the TIME axis across the
mesh and running **ring attention** — the blockwise-softmax algorithm of
Liu et al. (Ring Attention with Blockwise Transformers, arXiv:2310.01889):

* every device holds one contiguous block of Q/K/V along time;
* K/V blocks rotate around the ring via ``jax.lax.ppermute`` (ICI
  neighbor exchange — no all-gather, so per-device memory stays
  O(T/n_devices) instead of O(T));
* each device folds every visiting K/V block into a numerically-stable
  online softmax (running max / normalizer, the flash-attention recurrence),
  overlapping compute with the next block's transfer.

``ring_attention`` is the kernel; ``sequence_sharded`` is the convenience
sharding constraint used to keep the rest of an encoder (projections, FFN,
norms) auto-partitioned by XLA along the same axis, with GSPMD inserting the
(cheap, exact) psums for batch-statistic norms.
"""
from __future__ import annotations

import math
from functools import lru_cache

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["ring_attention", "seq_mesh", "sequence_sharded"]

_NEG = -1e30  # softmax mask value; avoids -inf NaNs for fully-masked rows


def seq_mesh(n_devices=None, axis_name="seq", devices=None):
    """1-D mesh over the sequence axis (grid_mesh with a "seq" axis)."""
    from redcliff_tpu.parallel.mesh import grid_mesh

    return grid_mesh(n_devices, axis_name=axis_name, devices=devices)


def sequence_sharded(x, mesh, axis_name="seq", time_axis=1):
    """Constrain ``x`` to be sharded along its time axis over the mesh."""
    spec = [None] * x.ndim
    spec[time_axis] = axis_name
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*spec)))


@lru_cache(maxsize=64)
def _ring_program(mesh, axis_name, causal, scale, n_dev):
    """Compiled ring-attention program, cached per (mesh, axis, causal,
    scale) so eager call sites (one per encoder layer per forward) reuse one
    jit entry instead of recompiling."""
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def local(q_blk, k_blk, v_blk):
        B, T_loc, H, D = q_blk.shape
        my_idx = jax.lax.axis_index(axis_name)
        q_pos = my_idx * T_loc + jnp.arange(T_loc)
        # accumulators marked device-varying so the fori_loop carry type is
        # stable under shard_map's varying-manual-axes tracking
        varying = lambda a: jax.lax.pcast(a, (axis_name,), to="varying")
        m0 = varying(jnp.full((B, H, T_loc), _NEG, q_blk.dtype))
        l0 = varying(jnp.zeros((B, H, T_loc), q_blk.dtype))
        o0 = varying(jnp.zeros((B, H, T_loc, D), q_blk.dtype))

        def fold(step, k_cur, v_cur, m, l, o):
            # after `step` forward rotations, this device holds the block
            # that originated on device (my_idx - step) mod n
            logits = jnp.einsum("bqhd,bkhd->bhqk", q_blk, k_cur) * scale
            if causal:
                src = jax.lax.rem(my_idx - step + n_dev, n_dev)
                k_pos = src * T_loc + jnp.arange(T_loc)
                keep = (k_pos[None, None, None, :]
                        <= q_pos[None, None, :, None])
                logits = jnp.where(keep, logits, _NEG)
            m_cur = logits.max(axis=-1)
            m_new = jnp.maximum(m, m_cur)
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(logits - m_new[..., None])
            l_new = l * alpha + p.sum(axis=-1)
            o_new = (o * alpha[..., None]
                     + jnp.einsum("bhqk,bkhd->bhqd", p, v_cur))
            return m_new, l_new, o_new

        def body(step, carry):
            k_cur, v_cur, m, l, o = carry
            m, l, o = fold(step, k_cur, v_cur, m, l, o)
            k_next = jax.lax.ppermute(k_cur, axis_name, perm)
            v_next = jax.lax.ppermute(v_cur, axis_name, perm)
            return k_next, v_next, m, l, o

        # the last visiting block is folded outside the loop so its (unused)
        # rotation is never issued — one fewer K/V exchange per call
        k_last, v_last, m, l, o = jax.lax.fori_loop(
            0, n_dev - 1, body, (k_blk, v_blk, m0, l0, o0))
        m, l, o = fold(n_dev - 1, k_last, v_last, m, l, o)
        out = o / jnp.maximum(l, 1e-30)[..., None]  # (B, H, T_loc, D)
        return out.transpose(0, 2, 1, 3)

    spec = P(None, axis_name, None, None)
    return jax.jit(jax.shard_map(local, mesh=mesh,
                                 in_specs=(spec, spec, spec),
                                 out_specs=spec))


def ring_attention(q, k, v, mesh, axis_name="seq", causal=False, scale=None):
    """Exact multi-head attention with the sequence axis sharded over
    ``mesh``'s ``axis_name``.

    Args:
      q, k, v: (B, T, H, D) arrays, T divisible by the mesh size. They may be
        unsharded (this call shards them) or already sharded along T.
      causal: mask future keys using GLOBAL positions (block offsets are
        tracked through the rotation).
      scale: logit scale; default 1/sqrt(D).

    Returns (B, T, H, D), sharded along T like the inputs.
    """
    n_dev = mesh.devices.size
    T, D = q.shape[1], q.shape[3]
    assert T % n_dev == 0, (
        f"sequence length {T} not divisible by mesh size {n_dev}")
    scale = 1.0 / math.sqrt(D) if scale is None else scale
    return _ring_program(mesh, axis_name, bool(causal), float(scale),
                         n_dev)(q, k, v)
