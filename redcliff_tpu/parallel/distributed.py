"""Multi-host (DCN) bootstrap and host-spanning array utilities.

The reference scales past one machine only through SLURM job arrays — fully
independent processes, filesystem as the communication medium
(ref train/REDCLIFF_S_CMLP_d4IC_BSCgs1.py:77, SURVEY §2.8). The TPU-native
equivalent is jax's multi-controller runtime: every host runs the same
program, ``jax.distributed.initialize`` connects them through a coordinator,
and the device mesh simply spans all hosts — grid points ride ICI within a
slice and DCN across slices, with XLA inserting the collectives.

Recipe (documented + tested; see tests/test_multihost.py):

1. every host calls :func:`initialize_distributed` first — before any other
   jax API. Coordinator/process info comes from explicit arguments or from
   the environment (``REDCLIFF_COORDINATOR``/``REDCLIFF_NUM_PROCESSES``/
   ``REDCLIFF_PROCESS_ID``, or SLURM's variables on a cluster);
2. build the mesh over the *global* device list (``grid_mesh()`` already uses
   ``jax.devices()``, which is global after initialization);
3. materialize grid-axis arrays with :func:`put_along_mesh` — each process
   only allocates the shards it addresses;
4. run the same jit'd grid program everywhere; replicated inputs (batches)
   pass as plain numpy, identical on every host;
5. read results back with :func:`gather_to_host`, which allgathers shards
   over DCN so every host sees the full grid.
"""
from __future__ import annotations

import os

import jax
import numpy as np

__all__ = [
    "initialize_distributed",
    "is_distributed",
    "put_along_mesh",
    "gather_to_host",
    "process_local_slice",
]

_initialized = False


def _from_env(explicit, *names, cast=str):
    if explicit is not None:
        return explicit
    for name in names:
        val = os.environ.get(name)
        if val is not None:
            return cast(val)
    return None


def initialize_distributed(coordinator_address=None, num_processes=None,
                           process_id=None, local_device_ids=None):
    """Connect this process to the multi-host runtime (idempotent).

    Arguments fall back to ``REDCLIFF_*`` env vars, then SLURM's
    (``SLURM_NTASKS``/``SLURM_PROCID``), mirroring how the reference's
    drivers read ``SLURM_ARRAY_TASK_ID`` — except the processes cooperate in
    one program instead of running disjoint jobs. With no configuration at
    all this is a no-op and the program stays single-process.
    """
    global _initialized
    if _initialized:
        return True
    # NB: no jax.process_count() probe here — any backend-touching call would
    # initialize XLA and make jax.distributed.initialize() illegal
    coordinator_address = _from_env(coordinator_address, "REDCLIFF_COORDINATOR")
    if coordinator_address is None:
        return False  # single-process run
    num_processes = _from_env(num_processes, "REDCLIFF_NUM_PROCESSES",
                              "SLURM_NTASKS", cast=int)
    process_id = _from_env(process_id, "REDCLIFF_PROCESS_ID", "SLURM_PROCID",
                           cast=int)
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _initialized = True
    return True


def is_distributed():
    return jax.process_count() > 1


def put_along_mesh(x, mesh, axis_name="grid"):
    """Shard ``x`` (host-replicated numpy, leading axis = grid) over the mesh.

    Single-process: a plain sharded device_put. Multi-host: each process
    materializes only its addressable shards via make_array_from_callback —
    the host-partitioned grid, every host holding 1/num_processes of the
    points in device memory.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    sh = NamedSharding(mesh, PartitionSpec(axis_name))
    if jax.process_count() == 1:
        return jax.device_put(x, sh)
    arr = np.asarray(x)
    return jax.make_array_from_callback(arr.shape, sh, lambda idx: arr[idx])


def gather_to_host(tree):
    """Full numpy values on every host. Multi-host arrays allgather their
    shards over DCN; single-process arrays just transfer."""
    if jax.process_count() == 1:
        return jax.tree.map(np.asarray, tree)
    from jax.experimental import multihost_utils

    return jax.tree.map(np.asarray,
                        multihost_utils.process_allgather(tree, tiled=True))


def process_local_slice(total, process_id=None, num_processes=None):
    """The contiguous [start, stop) range of grid points this host feeds when
    staging host-partitioned inputs (e.g. streaming per-point datasets)."""
    pid = jax.process_index() if process_id is None else process_id
    n = jax.process_count() if num_processes is None else num_processes
    if total % n != 0:
        raise ValueError(f"grid size {total} not divisible by {n} processes")
    per = total // n
    return pid * per, (pid + 1) * per
