"""Numerics sentinel: in-graph non-finite guards + host-side divergence policy.

PR 1 made the runtime survive the *machine* (preemption, torn checkpoints);
this module makes it survive the *math*. REDCLIFF-S fits are long grid
searches over proximal-regularized factor models whose losses go non-finite
at hot learning rates, and before this module a single NaN batch silently
poisoned ``params`` for every remaining step of an epoch — validation only
noticed after the damage was done. Large-scale training systems keep this
guard INSIDE the compiled step (cf. the TPU performance-model line of work:
host-side syncs serialize the device stream), so:

* :func:`guarded_update` wraps the optimizer-apply half of a train step in a
  ``lax.cond`` on loss + global-gradient finiteness. A poisoned step is
  skipped — params and optimizer state pass through untouched — and
  device-side counters (total/consecutive skips, gradient-norm running
  stats) are carried in a :func:`init_numerics_state` pytree. No per-step
  host sync; the host reads the counters once per epoch.
* :class:`NumericsPolicy` is the declarative knob set (skip thresholds,
  divergence factor, learning-rate backoff, rollback/abort budgets).
* :class:`DivergenceMonitor` is the host-side half: it snapshots the last
  known-good (params, opt_state) each healthy epoch, and on K consecutive
  in-graph skips or a validation-criteria blow-up past ``factor x best``
  rolls the fit back to that snapshot with the learning rate backed off
  (via :func:`scale_learning_rate` over ``optax.inject_hyperparams`` state).
  When no good snapshot exists (the fit never produced a finite epoch) or
  the rollback budget is spent, it aborts with a recorded cause instead of
  burning the remaining epoch budget on garbage.

Like the rest of :mod:`redcliff_tpu.runtime`, nothing here imports jax at
module scope — bench.py's backend-free parent imports this package.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

__all__ = [
    "NumericsPolicy", "NumericsAction", "DivergenceMonitor",
    "init_numerics_state", "update_numerics_state", "guarded_update",
    "global_norm", "numerics_summary", "reset_consecutive",
    "scale_learning_rate", "CAUSE_NONFINITE_GRAD", "CAUSE_NONFINITE_VAL",
    "CAUSE_DEADLINE", "QUARANTINE_CAUSES",
]

# grid-lane quarantine cause codes (device-side int32; decoded into
# GridResult.failures / failures.json records). CAUSE_DEADLINE is not a
# numerical fault — it is the wall-clock eviction (parallel/grid.py
# fit_deadline_s) riding the same per-lane quarantine machinery
CAUSE_NONFINITE_GRAD = 1
CAUSE_NONFINITE_VAL = 2
CAUSE_DEADLINE = 3
QUARANTINE_CAUSES = {CAUSE_NONFINITE_GRAD: "nonfinite_grad",
                     CAUSE_NONFINITE_VAL: "nonfinite_val",
                     CAUSE_DEADLINE: "deadline"}


@dataclass(frozen=True)
class NumericsPolicy:
    """Declarative numerical-fault policy shared by the trainers and the grid.

    ``enabled=False`` removes the in-graph guard entirely (the step compiles
    exactly as before). The proximal step keeps its configured learning rate
    across backoffs — lr backoff applies to the gradient step only (the prox
    scale is baked into the compiled step; re-jitting mid-fit would cost more
    than the slightly-too-strong shrinkage).
    """

    enabled: bool = True
    # K consecutive in-graph skipped steps => the fit is stuck on poisoned
    # state; roll back (trainers) / quarantine the lane (grid)
    max_consecutive_skips: int = 3
    # validation criteria blowing past
    # ``best + divergence_factor * max(|best|, divergence_atol)`` (best
    # finite) is a divergence even when every step stayed finite; the
    # absolute floor keeps near-zero best criteria (a well-converged fit)
    # from turning routine noise into spurious rollbacks
    divergence_factor: float = 10.0
    divergence_atol: float = 1e-2
    # learning-rate multiplier applied on each rollback
    lr_backoff: float = 0.5
    # rollbacks after which the fit aborts instead of thrashing
    max_rollbacks: int = 3
    # consecutive epochs of non-finite validation criteria (with no finite
    # epoch ever seen) after which the fit aborts — the all-NaN stall that
    # previously burned all of max_iter because ``best_it`` never set
    max_nonfinite_epochs: int = 3


@dataclass(frozen=True)
class NumericsAction:
    """Verdict of :meth:`DivergenceMonitor.check` for one epoch."""

    kind: str          # "ok" | "rollback" | "abort"
    cause: str | None = None


# ---------------------------------------------------------------------------
# in-graph half: finiteness guard + device-side counters
# ---------------------------------------------------------------------------
def init_numerics_state(lanes=None):
    """Device-side sentinel counters; ``lanes=G`` makes every field per-lane
    (the grid engine's layout), ``None`` keeps scalars (the trainers)."""
    import jax.numpy as jnp

    shape = () if lanes is None else (int(lanes),)
    # one distinct buffer per field: the grid engine donates this dict to its
    # train step, and donating one buffer aliased across fields is an error
    z = lambda: jnp.zeros(shape, jnp.int32)
    f = lambda: jnp.zeros(shape, jnp.float32)
    return {
        "skipped": z(),            # total guarded steps skipped
        "consecutive": z(),        # current run of consecutive skips
        "checked": z(),            # guarded steps seen
        "grad_norm_last": f(),     # last observed global grad norm (may be inf)
        "grad_norm_sum": f(),      # running sum of FINITE grad norms
        "grad_norm_sq_sum": f(),   # ... and of their squares (for std)
        "grad_norm_max": f(),      # max finite grad norm
    }


def global_norm(tree):
    """Global L2 norm over every leaf of a gradient pytree (f32 accumulate).
    Any non-finite leaf propagates to a non-finite norm, so one
    ``isfinite`` on the result checks the whole tree."""
    import jax
    import jax.numpy as jnp

    leaves = [jnp.asarray(l) for l in jax.tree.leaves(tree)]
    total = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    return jnp.sqrt(total)


def update_numerics_state(state, ok, grad_norm, count=None):
    """Advance the sentinel counters for one guarded step (jit-safe).

    ``ok`` is the step's finiteness verdict; ``count`` optionally masks
    which lanes actually trained this step (the grid's ``active`` mask —
    frozen lanes neither accumulate skips nor reset their streak)."""
    import jax.numpy as jnp

    ok = jnp.asarray(ok)
    if count is None:
        count = jnp.ones_like(ok)
    count = jnp.asarray(count, bool)
    counted_skip = jnp.logical_and(count, jnp.logical_not(ok))
    finite_norm = jnp.where(jnp.isfinite(grad_norm), grad_norm, 0.0)
    # stats cover exactly the APPLIED steps (count & ok), matching the
    # (checked - skipped) denominator in numerics_summary — a skipped step
    # with finite grads but NaN loss must not inflate the mean
    seen = jnp.logical_and(count, ok)
    return {
        "skipped": state["skipped"] + counted_skip.astype(jnp.int32),
        "consecutive": jnp.where(
            count,
            jnp.where(ok, 0, state["consecutive"] + 1),
            state["consecutive"]),
        "checked": state["checked"] + count.astype(jnp.int32),
        "grad_norm_last": jnp.where(count, grad_norm,
                                    state["grad_norm_last"]),
        "grad_norm_sum": state["grad_norm_sum"]
        + jnp.where(seen, finite_norm, 0.0),
        "grad_norm_sq_sum": state["grad_norm_sq_sum"]
        + jnp.where(seen, jnp.square(finite_norm), 0.0),
        "grad_norm_max": jnp.maximum(
            state["grad_norm_max"], jnp.where(seen, finite_norm, 0.0)),
    }


def reset_consecutive(state):
    """Zero the consecutive-skip streak (host-side, after a rollback consumed
    it — otherwise the restored fit would immediately re-trigger)."""
    import jax.numpy as jnp

    return dict(state, consecutive=jnp.zeros_like(state["consecutive"]))


def guarded_update(state_tree, grads, loss, apply_fn, numerics_state):
    """Apply ``apply_fn(state_tree)`` only when ``loss`` and the global
    gradient norm are both finite — inside the compiled step, via
    ``lax.cond`` so the skip branch pays for no optimizer math and there is
    no host sync. Returns ``(new_state_tree, new_numerics_state, ok)``.

    ``state_tree`` is whatever the caller's update consumes and rebinds
    (params + optimizer state(s)); ``apply_fn`` closes over grads/batch.
    """
    import jax
    import jax.numpy as jnp

    gnorm = global_norm(grads)
    ok = jnp.logical_and(jnp.isfinite(jnp.asarray(loss)),
                         jnp.isfinite(gnorm))
    new_tree = jax.lax.cond(ok, apply_fn, lambda t: t, state_tree)
    return new_tree, update_numerics_state(numerics_state, ok, gnorm), ok


def numerics_summary(numerics_state):
    """One host transfer of the sentinel counters -> plain-python dict
    (scalars for trainer state, lists for per-lane grid state)."""
    host = {k: np.asarray(v) for k, v in numerics_state.items()}
    checked = np.maximum(host["checked"] - host["skipped"], 1)
    mean = host["grad_norm_sum"] / checked
    var = np.maximum(host["grad_norm_sq_sum"] / checked - mean ** 2, 0.0)

    def py(v):
        v = np.asarray(v)
        return v.item() if v.ndim == 0 else v.tolist()

    return {
        "skipped": py(host["skipped"]),
        "consecutive": py(host["consecutive"]),
        "checked": py(host["checked"]),
        "grad_norm_last": py(host["grad_norm_last"].astype(np.float64)),
        "grad_norm_mean": py(mean.astype(np.float64)),
        "grad_norm_std": py(np.sqrt(var).astype(np.float64)),
        "grad_norm_max": py(host["grad_norm_max"].astype(np.float64)),
    }


# ---------------------------------------------------------------------------
# host half: learning-rate backoff + rollback/abort policy
# ---------------------------------------------------------------------------
def scale_learning_rate(opt_state, factor):
    """Multiply every ``optax.inject_hyperparams`` ``learning_rate`` found in
    an optimizer-state tree by ``factor`` (recursing through namedtuples,
    tuples, lists and dicts). States without injected hyperparams pass
    through unchanged — callers need not know their optimizer's nesting."""
    hp = getattr(opt_state, "hyperparams", None)
    if isinstance(hp, dict) and "learning_rate" in hp:
        new_hp = dict(hp, learning_rate=hp["learning_rate"] * factor)
        inner = scale_learning_rate(opt_state.inner_state, factor)
        return opt_state._replace(hyperparams=new_hp, inner_state=inner)
    if isinstance(opt_state, tuple) and hasattr(opt_state, "_fields"):
        return type(opt_state)(*(scale_learning_rate(getattr(opt_state, f),
                                                     factor)
                                 for f in opt_state._fields))
    if isinstance(opt_state, tuple):
        return tuple(scale_learning_rate(s, factor) for s in opt_state)
    if isinstance(opt_state, list):
        return [scale_learning_rate(s, factor) for s in opt_state]
    if isinstance(opt_state, dict):
        return {k: scale_learning_rate(v, factor)
                for k, v in opt_state.items()}
    return opt_state


def adopt_legacy_opt_state(opt, params, restored):
    """Migrate an optimizer state checkpointed before the
    ``inject_hyperparams`` change (a bare optax state with no
    ``hyperparams`` wrapper) into the new state structure: a fresh template
    from ``opt.init(params)`` carries the configured hyperparams (legacy
    checkpoints stored no learning-rate state, so the configured rate is the
    right one) and the restored moments become its ``inner_state``.
    States already in the new structure pass through untouched."""
    if hasattr(restored, "hyperparams"):
        return restored
    template = opt.init(params)
    return template._replace(inner_state=restored)


def current_learning_rates(opt_state):
    """Every injected ``learning_rate`` in an optimizer-state tree, as
    floats (for the ``numerics`` rollback event log)."""
    out = []
    hp = getattr(opt_state, "hyperparams", None)
    if isinstance(hp, dict) and "learning_rate" in hp:
        out.append(float(np.asarray(hp["learning_rate"])))
        out.extend(current_learning_rates(opt_state.inner_state))
        return out
    if isinstance(opt_state, tuple):
        for s in opt_state:
            out.extend(current_learning_rates(s))
    elif isinstance(opt_state, list):
        for s in opt_state:
            out.extend(current_learning_rates(s))
    elif isinstance(opt_state, dict):
        for s in opt_state.values():
            out.extend(current_learning_rates(s))
    return out


class DivergenceMonitor:
    """Host-side divergence policy for one fit.

    Call :meth:`check` once per epoch with the epoch's
    :func:`numerics_summary` and validation criteria; it returns a
    :class:`NumericsAction`:

    * ``ok`` — call :meth:`note_good` with the live state tree to refresh
      the rollback snapshot;
    * ``rollback`` — call :meth:`rollback` for the restored tree; any
      ``optax.inject_hyperparams`` learning rates inside it come back
      already backed off (compounding across consecutive rollbacks of the
      same snapshot: the k-th restore of one snapshot applies
      ``lr_backoff**k``, so repeated divergence keeps deepening the backoff
      instead of resetting to the snapshot's original rate);
    * ``abort`` — stop the fit and record ``action.cause``.

    Divergence triggers: ``consecutive >= policy.max_consecutive_skips``
    (the in-graph guard is skipping everything — cause ``nonfinite_grad``);
    a finite criteria blowing past
    ``best + divergence_factor * max(|best|, divergence_atol)`` (cause
    ``divergence``); or criteria going non-finite after a finite best was
    seen (cause ``nonfinite_val``). A fit whose criteria was NEVER finite
    aborts after ``max_nonfinite_epochs`` epochs (cause
    ``all_nonfinite_validation``) instead of stalling to max_iter.
    """

    def __init__(self, policy: NumericsPolicy):
        self.policy = policy
        self.rollbacks = 0
        self.lr_scale = 1.0
        self.best = math.inf
        self.snapshot_epoch = None
        self._snapshot = None
        self._snapshot_rollbacks = 0
        self._nonfinite_epochs = 0

    # -- snapshots ---------------------------------------------------------
    def note_good(self, epoch, state_tree):
        """Record ``state_tree`` (any pytree of arrays) as the rollback
        target. Copied to host numpy so donated device buffers can never
        invalidate it."""
        import jax

        self._snapshot = jax.tree.map(
            lambda x: np.array(x) if hasattr(x, "ndim") else x, state_tree)
        self.snapshot_epoch = epoch
        # the snapshot embeds its own (possibly already-backed-off) learning
        # rate; remember its rollback generation so repeated restores of the
        # SAME snapshot keep compounding instead of resetting
        self._snapshot_rollbacks = self.rollbacks

    def rollback(self):
        """Return the last known-good tree (device arrays) with injected
        learning rates backed off, consuming one unit of the rollback
        budget."""
        import jax
        import jax.numpy as jnp

        assert self._snapshot is not None, "rollback without a snapshot"
        self.rollbacks += 1
        self.lr_scale *= self.policy.lr_backoff
        # a rolled-back fit starts a fresh divergence observation window
        self._nonfinite_epochs = 0
        restored = jax.tree.map(
            lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x,
            self._snapshot)
        factor = self.policy.lr_backoff ** (self.rollbacks
                                            - self._snapshot_rollbacks)
        return scale_learning_rate(restored, factor)

    # -- the per-epoch verdict --------------------------------------------
    def _diverge_action(self, cause):
        if self._snapshot is None or self.rollbacks >= self.policy.max_rollbacks:
            return NumericsAction("abort", cause)
        return NumericsAction("rollback", cause)

    def check(self, epoch, numerics, criteria) -> NumericsAction:
        """``numerics`` is :func:`numerics_summary` output (scalar layout);
        ``criteria`` is this epoch's validation criteria, or None when the
        fit phase defines no criteria yet (pretrain epochs)."""
        del epoch
        if numerics is not None and (
                numerics["consecutive"] >= self.policy.max_consecutive_skips):
            # _diverge_action aborts when no good epoch exists to roll back to
            return self._diverge_action("nonfinite_grad")
        if criteria is None:
            return NumericsAction("ok")
        crit = float(criteria)
        if not math.isfinite(crit):
            if math.isfinite(self.best):
                return self._diverge_action("nonfinite_val")
            self._nonfinite_epochs += 1
            if self._nonfinite_epochs >= self.policy.max_nonfinite_epochs:
                return NumericsAction("abort", "all_nonfinite_validation")
            return NumericsAction("ok")
        self._nonfinite_epochs = 0
        if math.isfinite(self.best):
            # blow-up threshold, continuous in best: an excursion of
            # factor x the criteria's own scale (floored by divergence_atol
            # so near-zero and negative best — cosine-dominated criteria —
            # keep a meaningful, non-degenerate trigger)
            f = self.policy.divergence_factor
            threshold = self.best + f * max(abs(self.best),
                                            self.policy.divergence_atol)
            if crit > threshold:
                return self._diverge_action("divergence")
        self.best = min(self.best, crit)
        return NumericsAction("ok")
