"""Crash-loop supervisor: restart a driver process until it finishes or the
failure is one a restart cannot fix.

``python -m redcliff_tpu.supervise -- <driver cmd ...>`` runs the driver as a
child, classifies every exit through the watchdog taxonomy
(:func:`~redcliff_tpu.runtime.watchdog.classify_exit`), and restarts on the
transient classes — preemption, watchdog hang, plain crashes/signals — with
the shared :mod:`~redcliff_tpu.runtime.retry` backoff between attempts.
Deterministic failures (``numerics_abort``: a restart replays the same
divergence) and spent budgets (``deadline``) stop immediately; a crash loop
gives up after ``max_restarts``. Resume correctness is the checkpoint
layer's guarantee (durable CRC+``.prev`` generations plus the grid
fingerprint), so a supervised run's final artifacts are bit-identical to an
uninterrupted one — pinned by tests/test_supervisor.py.

Every attempt is a line in ``run_ledger.jsonl`` (strict JSON): command, rc,
classification, action, backoff, wall times — the audit trail an operator
reads after a 12-hour grid search died at 3am.

stdlib only (the supervisor parent must never initialize a jax backend).
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field

from redcliff_tpu.runtime.retry import RetryPolicy
from redcliff_tpu.runtime.watchdog import classify_exit

__all__ = ["SupervisorPolicy", "SuperviseOutcome", "supervise", "main",
           "LEDGER_NAME"]

LEDGER_NAME = "run_ledger.jsonl"

# restart vs stop per classification; "signal:*" prefixes match "signal"
RESTART_CLASSES = ("preempted", "hang", "crash", "signal")
TERMINAL_CLASSES = ("clean", "numerics_abort", "deadline")

DEFAULT_BACKOFF = RetryPolicy(max_attempts=1_000_000, base_delay_s=1.0,
                              multiplier=2.0, max_delay_s=60.0)


@dataclass
class SupervisorPolicy:
    """``max_restarts`` bounds the crash loop (restarts, not attempts: 3
    means up to 4 child runs); ``backoff`` spaces them."""

    max_restarts: int = 5
    backoff: RetryPolicy = field(default_factory=lambda: DEFAULT_BACKOFF)


@dataclass
class SuperviseOutcome:
    classification: str   # final classification ("giving_up" on a crash loop)
    returncode: int       # last child's rc (the supervisor's own exit code)
    attempts: list        # one record per child run (the ledger lines)


def _restartable(classification):
    return any(classification == c or classification.startswith(c + ":")
               for c in RESTART_CLASSES)


class _Ledger:
    def __init__(self, path):
        self.path = path
        if path:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)

    def append(self, rec):
        if self.path:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        return rec


def supervise(cmd, ledger_path=None, policy=None, env=None,
              sleep=time.sleep, popen=subprocess.Popen, on_spawn=None,
              should_stop=None):
    """Run ``cmd`` under crash-loop supervision; returns
    :class:`SuperviseOutcome` (its ``returncode`` is what the supervisor
    process should exit with).

    ``sleep``/``popen``/``on_spawn``/``should_stop`` are injectable for
    tests and for the CLI's SIGTERM relay: ``on_spawn(proc)`` exposes the
    live child, ``should_stop()`` (checked after each attempt) turns an
    externally-preempted supervisor into a stop instead of a restart.
    """
    policy = policy or SupervisorPolicy()
    ledger = _Ledger(ledger_path)
    attempts = []
    attempt = 0
    while True:
        started = time.time()
        t0 = time.monotonic()
        proc = popen(list(cmd), env=env)
        if on_spawn is not None:
            on_spawn(proc)
        rc = proc.wait()
        classification = classify_exit(rc)
        stopping = bool(should_stop()) if should_stop is not None else False
        if classification in TERMINAL_CLASSES or stopping:
            action = "stop"
        elif not _restartable(classification):
            action = "stop"
        elif attempt >= policy.max_restarts:
            action = "give_up"
        else:
            action = "restart"
        backoff = (policy.backoff.backoff_s(attempt + 1)
                   if action == "restart" else 0.0)
        rec = ledger.append({
            "event": "attempt", "attempt": attempt, "cmd": list(cmd),
            "rc": rc, "classification": classification, "action": action,
            "backoff_s": round(backoff, 3), "started_at": started,
            "duration_s": round(time.monotonic() - t0, 3),
        })
        attempts.append(rec)
        if action != "restart":
            final = ("giving_up" if action == "give_up" else classification)
            ledger.append({"event": "final", "classification": final,
                           "rc": rc, "attempts": len(attempts)})
            return SuperviseOutcome(classification=final, returncode=rc,
                                    attempts=attempts)
        # backoff in short slices, re-checking the stop flag before the
        # respawn: a SIGTERM landing BETWEEN attempts (no live child to
        # relay it to) must stop the loop, not spawn a fresh child that
        # never saw the preemption notice
        remaining = backoff
        while remaining > 0 and not (should_stop is not None
                                     and should_stop()):
            step = min(remaining, 0.5)
            sleep(step)
            remaining -= step
        if should_stop is not None and should_stop():
            ledger.append({"event": "final", "classification": "stopped",
                           "rc": rc, "attempts": len(attempts)})
            return SuperviseOutcome(classification="stopped", returncode=rc,
                                    attempts=attempts)
        attempt += 1


def main(argv=None):
    """CLI: ``python -m redcliff_tpu.supervise [opts] -- <driver cmd ...>``.

    SIGTERM/SIGINT to the supervisor are relayed to the child (so preempting
    the supervisor preempts the run: the child latches, checkpoints, exits
    ``EXIT_PREEMPTED``) and the loop stops instead of restarting. The
    supervisor exits with the last child's returncode (0 on clean)."""
    ap = argparse.ArgumentParser(
        prog="redcliff_tpu.supervise",
        description="Crash-loop supervisor with exit-code taxonomy and a "
                    "run_ledger.jsonl audit trail.")
    ap.add_argument("--ledger", default=LEDGER_NAME,
                    help=f"ledger path (default ./{LEDGER_NAME})")
    ap.add_argument("--max-restarts", type=int, default=5)
    ap.add_argument("--base-delay-s", type=float, default=1.0)
    ap.add_argument("--max-delay-s", type=float, default=60.0)
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="-- followed by the driver command")
    args = ap.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no driver command given (use: supervise -- <cmd ...>)")

    state = {"child": None, "stop": False}

    def relay(signum, frame):
        state["stop"] = True
        child = state["child"]
        if child is not None and child.poll() is None:
            try:
                child.send_signal(signum)
            except OSError:
                pass

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, relay)

    policy = SupervisorPolicy(
        max_restarts=args.max_restarts,
        backoff=RetryPolicy(max_attempts=1_000_000,
                            base_delay_s=args.base_delay_s, multiplier=2.0,
                            max_delay_s=args.max_delay_s))
    outcome = supervise(
        cmd, ledger_path=args.ledger, policy=policy,
        on_spawn=lambda p: state.__setitem__("child", p),
        should_stop=lambda: state["stop"])
    print(f"supervise: {outcome.classification} after "
          f"{len(outcome.attempts)} attempt(s), rc={outcome.returncode}",
          file=sys.stderr)
    return outcome.returncode


if __name__ == "__main__":
    raise SystemExit(main())
