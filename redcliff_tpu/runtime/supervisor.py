"""Crash-loop supervisor: restart a driver process until it finishes or the
failure is one a restart cannot fix.

``python -m redcliff_tpu.supervise -- <driver cmd ...>`` runs the driver as a
child, classifies every exit through the watchdog taxonomy
(:func:`~redcliff_tpu.runtime.watchdog.classify_exit`), and restarts on the
transient classes — preemption, watchdog hang, plain crashes/signals — with
the shared :mod:`~redcliff_tpu.runtime.retry` backoff between attempts.
Deterministic failures (``numerics_abort``: a restart replays the same
divergence) and spent budgets (``deadline``) stop immediately; a crash loop
gives up after ``max_restarts``. Resume correctness is the checkpoint
layer's guarantee (durable CRC+``.prev`` generations plus the grid
fingerprint), so a supervised run's final artifacts are bit-identical to an
uninterrupted one — pinned by tests/test_supervisor.py.

Host-fault tolerance (elastic re-meshing, docs/ARCHITECTURE.md "Elastic
re-meshing & host-fault tolerance"): a child exiting ``host_lost`` (taxonomy
code 21 — stale per-host heartbeats, a collective timeout mapped to
:class:`~redcliff_tpu.parallel.remesh.HostLostError`, or an explicit
device-loss signal) is NOT restarted at the same shape. When the policy
declares the mesh (``mesh_devices``/``n_hosts``), the supervisor degrades
the device budget by one host's worth, exports it to the next attempt via
``REDCLIFF_MESH_DEVICES`` (which
:func:`~redcliff_tpu.parallel.remesh.visible_mesh` honors), and restarts —
the grid engine re-shards the checkpointed lanes onto the smaller mesh and
the sweep continues with results still reported under original point ids.
A mesh degraded below ``min_devices`` stops with ``mesh_exhausted``. Without
a declared mesh, ``host_lost`` degrades to a plain same-shape restart.

Every attempt is a line in ``run_ledger.jsonl`` (strict JSON): command, rc,
classification, action, backoff, wall times, and the commanded mesh shape
({n_hosts, n_devices, device_kind}) — the audit trail an operator reads
after a 12-hour grid search died at 3am, including which attempts ran
degraded.

Per-attempt ETA: when the driver writes its telemetry next to the ledger
(the usual layout — ``metrics.jsonl`` in the same run directory), each
attempt record also carries the learned cost model's remaining-work
estimate as of the attempt's last check window (the newest ``cost_model``
event: predicted epoch cost, epochs remaining, ``eta_s``) — so the ledger
answers not just "why did attempt 3 stop" but "how much work was left when
it did", the admission-planner input ROADMAP item 1 needs per request.
Read via a bounded tail of the metrics file (crash-tolerant: torn lines
skipped), absent when no telemetry or no prediction exists.

stdlib only (the supervisor parent must never initialize a jax backend).
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field

from redcliff_tpu.runtime.retry import RetryPolicy
from redcliff_tpu.runtime.watchdog import classify_exit

__all__ = ["SupervisorPolicy", "SuperviseOutcome", "supervise", "main",
           "LEDGER_NAME", "latest_cost_model_eta", "worker_exit_action"]

LEDGER_NAME = "run_ledger.jsonl"

# restart vs stop per classification; "signal:*" prefixes match "signal".
# host_lost restarts too — via the re-mesh path when the policy declares a
# mesh, degrading to a same-shape restart when it does not
RESTART_CLASSES = ("preempted", "hang", "crash", "signal", "host_lost")
TERMINAL_CLASSES = ("clean", "numerics_abort", "deadline")

DEFAULT_BACKOFF = RetryPolicy(max_attempts=1_000_000, base_delay_s=1.0,
                              multiplier=2.0, max_delay_s=60.0)

# the env knob the next attempt's visible_mesh() honors; kept as a literal
# (not imported from parallel.remesh) so this module stays stdlib-only
MESH_DEVICES_ENV = "REDCLIFF_MESH_DEVICES"
SIM_HOSTS_ENV = "REDCLIFF_SIM_HOSTS"


@dataclass
class SupervisorPolicy:
    """``max_restarts`` bounds the crash loop (restarts, not attempts: 3
    means up to 4 child runs); ``backoff`` spaces them.

    Mesh declaration (enables re-mesh-then-restart on ``host_lost`` exits):
    ``mesh_devices`` is the full-strength device count, ``n_hosts`` how many
    hosts it spans; ``devices_per_host`` defaults to the even split. On each
    ``host_lost`` the budget drops by one host's devices and the new budget
    is exported to the child via ``REDCLIFF_MESH_DEVICES``; once it would
    fall below ``min_devices`` (or the last host is gone) the run stops with
    ``mesh_exhausted``. With ``mesh_devices`` alone (host width unknown) the
    budget degrades conservatively by ONE device per loss — under-shooting
    just costs extra restart rounds until the budget fits the survivors,
    while over-shooting would discard healthy devices for the rest of the
    sweep. ``device_kind`` is audit metadata for the ledger."""

    max_restarts: int = 5
    backoff: RetryPolicy = field(default_factory=lambda: DEFAULT_BACKOFF)
    mesh_devices: int | None = None
    n_hosts: int | None = None
    devices_per_host: int | None = None
    min_devices: int = 1
    device_kind: str | None = None

    def host_width(self):
        """Devices one lost host takes with it (1 when unknown — degrade
        conservatively rather than throw away healthy capacity)."""
        if self.devices_per_host:
            return int(self.devices_per_host)
        if self.mesh_devices and self.n_hosts:
            return max(int(self.mesh_devices) // int(self.n_hosts), 1)
        return 1


@dataclass
class SuperviseOutcome:
    classification: str   # final classification ("giving_up" on a crash loop)
    returncode: int       # last child's rc (the supervisor's own exit code)
    attempts: list        # one record per child run (the ledger lines)


def _restartable(classification):
    return any(classification == c or classification.startswith(c + ":")
               for c in RESTART_CLASSES)


def worker_exit_action(returncode, restarts_used, max_restarts=None,
                       policy=None):
    """Judge one WORKER-process exit under the supervised-exit taxonomy:
    returns ``(classification, action)`` where action is ``"retire"`` (a
    clean drain — the fleet autoscaler's passive scale-down), ``"respawn"``
    (a restartable infra class with restart budget left), or ``"stop"``
    (terminal, or budget exhausted). The fleet autoscaler
    (fleet/autoscale.py) applies the same exit-code taxonomy to its worker
    POOL that :func:`supervise` applies to one child — one classification
    vocabulary across both supervision layers."""
    if max_restarts is None:
        max_restarts = (policy or SupervisorPolicy()).max_restarts
    if returncode == 0:
        return "drained", "retire"
    classification = classify_exit(returncode)
    if _restartable(classification) and int(restarts_used) < int(
            max_restarts):
        return classification, "respawn"
    return classification, "stop"


# how much of the metrics file tail to scan for the newest cost_model
# event: check windows emit one small line each, so 128 KiB covers
# thousands of windows while keeping the read O(1) in run length
_ETA_TAIL_BYTES = 128 * 1024


def latest_cost_model_eta(ledger_path, since_wall=None,
                          tail_bytes=_ETA_TAIL_BYTES):
    """The newest ``cost_model`` event's ETA fields from the metrics.jsonl
    sitting next to ``ledger_path``, or None (no metrics file, no event in
    the tail, torn/unparseable lines — all degrade silently: the ETA is
    audit garnish, never a supervision input).

    ``since_wall`` restricts to events stamped at/after that wall time —
    the supervisor passes each attempt's start so an attempt that died
    before its first check window reports NO eta instead of inheriting the
    previous attempt's."""
    run_dir = os.path.dirname(ledger_path) or "."
    path = os.path.join(run_dir, "metrics.jsonl")
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            if size > tail_bytes:
                f.seek(size - tail_bytes)
            tail = f.read().decode("utf-8", errors="replace")
    except OSError:
        return None
    for line in reversed(tail.splitlines()):
        if '"cost_model"' not in line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # torn first line of the tail window / mid-append
        if rec.get("event") != "cost_model":
            continue
        if since_wall is not None and not (
                isinstance(rec.get("wall_time"), (int, float))
                and rec["wall_time"] >= since_wall):
            return None  # newest event predates this attempt: no eta
        # wall_time: when the ETA was computed — consumers that treat
        # eta_s as "remaining from NOW" (the fleet preemption monitor)
        # must discount by its age or a sparse check-window cadence
        # overstates remaining work by up to one window
        return {k: rec.get(k) for k in
                ("eta_s", "predicted_epoch_ms", "epochs_remaining",
                 "epoch", "source", "wall_time")}
    return None


class _Ledger:
    def __init__(self, path):
        self.path = path
        if path:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)

    def append(self, rec):
        if self.path:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        return rec


def supervise(cmd, ledger_path=None, policy=None, env=None,
              sleep=time.sleep, popen=subprocess.Popen, on_spawn=None,
              should_stop=None):
    """Run ``cmd`` under crash-loop supervision; returns
    :class:`SuperviseOutcome` (its ``returncode`` is what the supervisor
    process should exit with).

    ``sleep``/``popen``/``on_spawn``/``should_stop`` are injectable for
    tests and for the CLI's SIGTERM relay: ``on_spawn(proc)`` exposes the
    live child, ``should_stop()`` (checked after each attempt) turns an
    externally-preempted supervisor into a stop instead of a restart.
    """
    policy = policy or SupervisorPolicy()
    ledger = _Ledger(ledger_path)
    attempts = []
    attempt = 0
    # commanded mesh shape: what the NEXT child may use. Degrades by one
    # host's devices on every host_lost exit; exported via
    # REDCLIFF_MESH_DEVICES so the child's visible_mesh() honors it
    cur_devices = policy.mesh_devices
    cur_hosts = policy.n_hosts

    def child_env():
        if cur_devices is None:
            return env  # no mesh tracking: pass the caller's env untouched
        e = dict(env if env is not None else os.environ)
        e[MESH_DEVICES_ENV] = str(cur_devices)
        if cur_hosts is not None:
            e[SIM_HOSTS_ENV] = str(cur_hosts)
        return e

    while True:
        started = time.time()
        t0 = time.monotonic()
        proc = popen(list(cmd), env=child_env())
        if on_spawn is not None:
            on_spawn(proc)
        rc = proc.wait()
        classification = classify_exit(rc)
        stopping = bool(should_stop()) if should_stop is not None else False
        mesh_exhausted = False
        remesh_to = None
        if classification in TERMINAL_CLASSES or stopping:
            action = "stop"
        elif not _restartable(classification):
            action = "stop"
        elif attempt >= policy.max_restarts:
            action = "give_up"
        elif classification == "host_lost" and cur_devices is not None:
            # re-mesh-then-restart: shrink the commanded mesh by one host's
            # devices; the resumed child re-shards its checkpointed lanes
            # onto the survivors. Exhausting the mesh is terminal — there
            # is nothing left to run on
            remesh_to = cur_devices - policy.host_width()
            if remesh_to < max(policy.min_devices, 1) \
                    or (cur_hosts is not None and cur_hosts <= 1):
                action = "stop"
                mesh_exhausted = True
            else:
                action = "remesh_restart"
        else:
            action = "restart"
        restarting = action in ("restart", "remesh_restart")
        backoff = (policy.backoff.backoff_s(attempt + 1)
                   if restarting else 0.0)
        rec = {
            "event": "attempt", "attempt": attempt, "cmd": list(cmd),
            "rc": rc, "classification": classification, "action": action,
            "backoff_s": round(backoff, 3), "started_at": started,
            "duration_s": round(time.monotonic() - t0, 3),
        }
        if cur_devices is not None:
            # the mesh shape THIS attempt ran under — the degraded-resume
            # audit trail (which attempts ran at which width)
            rec["mesh"] = {"n_hosts": cur_hosts, "n_devices": cur_devices,
                           "device_kind": policy.device_kind}
        if ledger.path:
            # remaining-work estimate at THIS attempt's last check window
            # (obs/costmodel.py scoring events written by the driver next
            # to this ledger); absent when this attempt left no telemetry —
            # since_wall keeps a compile-crash attempt from inheriting the
            # previous attempt's eta
            eta = latest_cost_model_eta(ledger.path, since_wall=started)
            if eta is not None:
                rec["eta"] = eta
        ledger.append(rec)
        attempts.append(rec)
        if action == "remesh_restart":
            ledger.append({
                "event": "remesh", "from_devices": cur_devices,
                "to_devices": remesh_to, "from_hosts": cur_hosts,
                "to_hosts": (cur_hosts - 1 if cur_hosts else None)})
            cur_devices = remesh_to
            if cur_hosts:
                cur_hosts -= 1
        if not restarting:
            final = ("giving_up" if action == "give_up"
                     else "mesh_exhausted" if mesh_exhausted
                     else classification)
            ledger.append({"event": "final", "classification": final,
                           "rc": rc, "attempts": len(attempts)})
            return SuperviseOutcome(classification=final, returncode=rc,
                                    attempts=attempts)
        # backoff in short slices, re-checking the stop flag before the
        # respawn: a SIGTERM landing BETWEEN attempts (no live child to
        # relay it to) must stop the loop, not spawn a fresh child that
        # never saw the preemption notice
        remaining = backoff
        while remaining > 0 and not (should_stop is not None
                                     and should_stop()):
            step = min(remaining, 0.5)
            sleep(step)
            remaining -= step
        if should_stop is not None and should_stop():
            ledger.append({"event": "final", "classification": "stopped",
                           "rc": rc, "attempts": len(attempts)})
            return SuperviseOutcome(classification="stopped", returncode=rc,
                                    attempts=attempts)
        attempt += 1


def main(argv=None):
    """CLI: ``python -m redcliff_tpu.supervise [opts] -- <driver cmd ...>``.

    SIGTERM/SIGINT to the supervisor are relayed to the child (so preempting
    the supervisor preempts the run: the child latches, checkpoints, exits
    ``EXIT_PREEMPTED``) and the loop stops instead of restarting. The
    supervisor exits with the last child's returncode (0 on clean)."""
    ap = argparse.ArgumentParser(
        prog="redcliff_tpu.supervise",
        description="Crash-loop supervisor with exit-code taxonomy and a "
                    "run_ledger.jsonl audit trail.")
    ap.add_argument("--ledger", default=LEDGER_NAME,
                    help=f"ledger path (default ./{LEDGER_NAME})")
    ap.add_argument("--max-restarts", type=int, default=5)
    ap.add_argument("--base-delay-s", type=float, default=1.0)
    ap.add_argument("--max-delay-s", type=float, default=60.0)
    ap.add_argument("--mesh-devices", type=int, default=None,
                    help="full-strength device count: enables re-mesh-then-"
                         "restart on host_lost exits (exported to the child "
                         f"via {MESH_DEVICES_ENV})")
    ap.add_argument("--n-hosts", type=int, default=None,
                    help="hosts the mesh spans (devices-per-host defaults "
                         "to the even split)")
    ap.add_argument("--devices-per-host", type=int, default=None,
                    help="devices one lost host takes with it")
    ap.add_argument("--min-devices", type=int, default=1,
                    help="stop with mesh_exhausted below this budget")
    ap.add_argument("--device-kind", default=None,
                    help="audit metadata for the ledger's mesh records")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="-- followed by the driver command")
    args = ap.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no driver command given (use: supervise -- <cmd ...>)")

    state = {"child": None, "stop": False}

    def relay(signum, frame):
        state["stop"] = True
        child = state["child"]
        if child is not None and child.poll() is None:
            try:
                child.send_signal(signum)
            except OSError:
                pass

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, relay)

    policy = SupervisorPolicy(
        max_restarts=args.max_restarts,
        backoff=RetryPolicy(max_attempts=1_000_000,
                            base_delay_s=args.base_delay_s, multiplier=2.0,
                            max_delay_s=args.max_delay_s),
        mesh_devices=args.mesh_devices, n_hosts=args.n_hosts,
        devices_per_host=args.devices_per_host,
        min_devices=args.min_devices, device_kind=args.device_kind)
    outcome = supervise(
        cmd, ledger_path=args.ledger, policy=policy,
        on_spawn=lambda p: state.__setitem__("child", p),
        should_stop=lambda: state["stop"])
    print(f"supervise: {outcome.classification} after "
          f"{len(outcome.attempts)} attempt(s), rc={outcome.returncode}",
          file=sys.stderr)
    return outcome.returncode


if __name__ == "__main__":
    raise SystemExit(main())
