"""Liveness watchdog: heartbeat registry, hang detection, exit-code taxonomy.

PRs 1-3 made the runtime survive *crashes* (durable CRC+``.prev`` checkpoints,
SIGTERM latch) and *numerical faults* (in-graph guards, rollback). A fit that
silently **hangs** — a wedged shard read, a prefetch thread deadlocked against
the async checkpoint writer, a stuck dispatch — still burned the whole
allocation with no signal. Production ML systems treat liveness as a runtime
concern (TensorFlow couples checkpointing with supervisor-driven restart so
long runs survive worker failure, arXiv:1605.08695); this module is that
layer:

- :class:`HeartbeatRegistry` — named monotonic-clock heartbeats. The epoch
  engine, per-batch loop, prefetcher, shard loader, and async checkpoint
  writer each ``stamp()`` theirs (a dict write + one ``time.monotonic`` call;
  components that finish a scope ``retire()`` so idle phases cannot read as
  hangs). Every stamp also counts into a persistent tally the tier-1
  tripwire test checks against — a registered-but-never-stamped component is
  a dead heartbeat, caught in CI, not production.
- :class:`Watchdog` — a daemon thread that polls the registry; a stamp older
  than its declared budget raises a ``hang`` incident: one structured event
  (per-component ages + all-thread stack dumps via ``sys._current_frames``)
  to metrics.jsonl/stderr, then escalation up the ladder: **log ->
  checkpoint -> exit**. The checkpoint rung latches the existing preemption
  guard, so a merely-slow loop writes a final checkpoint and exits
  ``EXIT_PREEMPTED``; a truly wedged process is hard-exited with
  ``EXIT_HANG`` after ``grace_s`` so the supervisor restarts it from the
  durable checkpoint.
- the **exit-code taxonomy** shared with :mod:`.supervisor`: a supervised
  child says *why* it died through its exit code, and the supervisor decides
  restart-vs-give-up without parsing logs.

stdlib only — no jax, no numpy: bench.py's backend-free parent and the
supervisor must both import this safely.
"""
from __future__ import annotations

import contextlib
import faulthandler
import os
import signal
import sys
import threading
import time
import traceback
from dataclasses import dataclass, field

__all__ = [
    "EXIT_CLEAN", "EXIT_PREEMPTED", "EXIT_NUMERICS_ABORT", "EXIT_HANG",
    "EXIT_DEADLINE", "EXIT_HOST_LOST", "classify_exit", "CORE_COMPONENTS",
    "HeartbeatRegistry", "REGISTRY", "stamp", "retire", "op_scope",
    "host_component", "host_of",
    "COMPILE_COMPONENT", "COMPILE_BUDGET_S",
    "WatchdogPolicy", "Watchdog", "maybe_start", "dump_stacks",
]

# ---------------------------------------------------------------------------
# exit-code taxonomy: how a supervised child says WHY it died. 0 and the
# 17-20 band are the contract with runtime/supervisor.py (and with outer
# schedulers); negative returncodes are signals (subprocess convention).
# 17 predates this module (faultinject.PREEMPTED_EXIT_CODE re-exports it).
# ---------------------------------------------------------------------------
EXIT_CLEAN = 0            # fit finished; artifacts complete
EXIT_PREEMPTED = 17       # SIGTERM/SIGINT latched; final checkpoint written
EXIT_NUMERICS_ABORT = 18  # numerics sentinel aborted (deterministic: a
#                           restart replays the same divergence)
EXIT_HANG = 19            # watchdog hard-exited a wedged process
EXIT_DEADLINE = 20        # wall-clock deadline; checkpointed + resumable
EXIT_HOST_LOST = 21       # part of the mesh is gone (host heartbeats stale /
#                           collective timeout / device-loss signal): the
#                           supervisor re-meshes (smaller device budget) and
#                           restarts from the durable checkpoint

_EXIT_NAMES = {
    EXIT_CLEAN: "clean",
    EXIT_PREEMPTED: "preempted",
    EXIT_NUMERICS_ABORT: "numerics_abort",
    EXIT_HANG: "hang",
    EXIT_DEADLINE: "deadline",
    EXIT_HOST_LOST: "host_lost",
}


def classify_exit(returncode):
    """Map a child returncode onto the taxonomy: ``clean`` / ``preempted`` /
    ``numerics_abort`` / ``hang`` / ``deadline`` / ``host_lost`` /
    ``signal:NAME`` (killed by an un-latched signal, SIGKILL included) /
    ``crash`` (anything else)."""
    if returncode in _EXIT_NAMES:
        return _EXIT_NAMES[returncode]
    if returncode is not None and returncode < 0:
        try:
            return f"signal:{signal.Signals(-returncode).name}"
        except ValueError:
            return f"signal:{-returncode}"
    return "crash"


# the heartbeat map a fully-equipped supervised fit stamps (host-stream data,
# prefetch on, async checkpointing): the tier-1 tripwire test runs such a fit
# and asserts every one of these actually beat
CORE_COMPONENTS = ("epoch_engine", "batch_loop", "prefetch", "shard_loader",
                   "ckpt_writer")

DEFAULT_BUDGET_S = 600.0
ENV_WATCHDOG = "REDCLIFF_WATCHDOG"


class HeartbeatRegistry:
    """Named monotonic-clock heartbeats with per-component age budgets.

    ``stamp(name)`` auto-registers unknown names (budget =
    ``default_budget_s``, overridable per component via ``budgets``) so deep
    components need no plumbing; ``retire(name)`` removes a component from
    liveness monitoring when its scope ends (a prefetcher between epochs is
    idle, not hung) while keeping its cumulative stamp count for the
    dead-heartbeat tripwire. All methods are thread-safe and O(components).
    """

    def __init__(self, clock=time.monotonic, default_budget_s=DEFAULT_BUDGET_S):
        self.clock = clock
        self.default_budget_s = default_budget_s
        self.budgets = {}  # per-component overrides, consulted on register
        self._lock = threading.Lock()
        self._beats = {}   # name -> [last_stamp, budget_s]
        self._counts = {}  # name -> cumulative stamps (survives retire)

    def _budget_for(self, name):
        """Configured budget for ``name``; host-scoped beats
        (``host<h>:component``) fall back to the base component's override
        (an operator tuning ``budget.shard_loader`` expects it to govern
        every host's shard loader) before the default."""
        if name in self.budgets:
            return self.budgets[name]
        if host_of(name) is not None:
            base = name.partition(":")[2]
            if base in self.budgets:
                return self.budgets[base]
        return self.default_budget_s

    def register(self, name, budget_s=None):
        if budget_s is None:
            budget_s = self._budget_for(name)
        with self._lock:
            self._beats[name] = [self.clock(), float(budget_s)]
            self._counts.setdefault(name, 0)

    def stamp(self, name):
        with self._lock:
            beat = self._beats.get(name)
            if beat is None:
                self._beats[name] = [self.clock(),
                                     float(self._budget_for(name))]
            else:
                beat[0] = self.clock()
            self._counts[name] = self._counts.get(name, 0) + 1

    def retire(self, name):
        with self._lock:
            self._beats.pop(name, None)

    def refresh(self):
        """Re-stamp every live component (no count bump): a watchdog starting
        mid-process must grant stale entries a fresh budget, not fire on a
        previous fit's leftovers."""
        with self._lock:
            now = self.clock()
            for beat in self._beats.values():
                beat[0] = now

    def ages(self):
        with self._lock:
            now = self.clock()
            return {n: now - b[0] for n, b in self._beats.items()}

    def overdue(self):
        """[(name, age_s, budget_s)] for every live heartbeat past budget."""
        with self._lock:
            now = self.clock()
            return [(n, now - b[0], b[1]) for n, b in self._beats.items()
                    if now - b[0] > b[1]]

    def counts(self):
        with self._lock:
            return dict(self._counts)

    def clear(self):
        with self._lock:
            self._beats.clear()
            self._counts.clear()


# process-global registry: components stamp without plumbing a handle through
# the data layer. Fits that start a Watchdog refresh() it so stale entries
# from a previous fit in the same process never read as hangs.
REGISTRY = HeartbeatRegistry()

# the op-scoped cold-compile heartbeat: the engines stamp it around dispatches
# that may trigger a fresh XLA compile (first call of a program at a new
# (shape, G) — parallel/grid.py). While it is live and within budget, the
# watchdog EXCUSES other overdue components: a long first-compile window
# blocks the main thread legitimately, and before this beat existed it was
# misclassified as an epoch_engine/batch_loop hang. A compile older than its
# own (generous) budget still escalates — a truly wedged XLA compile is a
# hang. Overridable like any budget via REDCLIFF_WATCHDOG=budget.compile=S.
COMPILE_COMPONENT = "compile"
COMPILE_BUDGET_S = 1800.0
REGISTRY.budgets.setdefault(COMPILE_COMPONENT, COMPILE_BUDGET_S)


def stamp(name):
    """Stamp ``name`` on the global registry (auto-registering)."""
    REGISTRY.stamp(name)


def retire(name):
    """Retire ``name`` from global liveness monitoring (counts persist)."""
    REGISTRY.retire(name)


@contextlib.contextmanager
def op_scope(name):
    """Stamp ``name`` for the duration of one operation, retiring on exit —
    the op-scoped heartbeat shape (stamp at entry, retire when the scope
    ends) used for cold compiles: ``with op_scope(COMPILE_COMPONENT): ...``.

    A closing COMPILE scope additionally ``refresh()``es the registry:
    every live component's age includes the whole compile window it was
    legitimately blocked behind, so without a fresh budget the first poll
    after a long (but in-budget) compile would fire a false hang incident
    on the still-stale siblings the excuse just stopped covering.
    """
    stamp(name)
    try:
        yield
    finally:
        retire(name)
        if name == COMPILE_COMPONENT:
            REGISTRY.refresh()


def host_component(host_id, component):
    """The host-scoped heartbeat name for ``component`` on host ``host_id``
    (``"host2:shard_loader"``). Host-scoped beats let one process observe
    per-host liveness — a real multi-controller run's cross-host heartbeat
    relay, or the single-process simulation's host partitions — and give the
    watchdog the signal for :data:`EXIT_HOST_LOST` classification: ONE
    host's components going stale while the rest of the process stays live
    is a lost host, not a wedged process."""
    return f"host{int(host_id)}:{component}"


def host_of(name):
    """The host index a heartbeat name is scoped to, or None for ordinary
    process-wide components."""
    if name.startswith("host"):
        head, sep, _ = name.partition(":")
        if sep and head[4:].isdigit():
            return int(head[4:])
    return None


def dump_stacks():
    """Every thread's current stack as one string (named per thread) — the
    forensic core of a ``hang`` event: *where* each thread is wedged."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(tid, '?')} (ident {tid}) ---")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
    return "\n".join(out)


@dataclass
class WatchdogPolicy:
    """Escalation knobs. ``grace_s`` is the window between latching the
    preemption guard (rung 2: a slow-but-alive loop checkpoints and exits
    ``EXIT_PREEMPTED`` on its own) and the hard exit (rung 3:
    ``os._exit(EXIT_HANG)`` — a wedged process cannot run cleanup, and the
    durable ``.prev`` checkpoint generation makes that safe)."""

    poll_s: float = 5.0
    grace_s: float = 30.0
    default_budget_s: float = DEFAULT_BUDGET_S
    budgets: dict = field(default_factory=dict)  # per-component overrides
    hard_exit: bool = True
    latch_preempt: bool = True
    # classify "exactly one host's heartbeats stale, everything else live"
    # as a lost host (exit EXIT_HOST_LOST: the supervisor re-meshes) instead
    # of a process hang. Disable with REDCLIFF_WATCHDOG=...,host_loss=0
    host_loss: bool = True

    @classmethod
    def from_env(cls, env=ENV_WATCHDOG):
        """Policy from ``REDCLIFF_WATCHDOG``; None when unset/empty/"0".

        ``"1"`` enables defaults; otherwise a comma-separated ``k=v`` list:
        ``poll_s``, ``grace_s``, ``budget_s`` (default budget), and
        ``budget.<component>=S`` per-component overrides — e.g.
        ``REDCLIFF_WATCHDOG="poll_s=0.5,grace_s=2,budget.prefetch=3"``.
        """
        spec = os.environ.get(env, "").strip()
        if not spec or spec == "0":
            return None
        policy = cls()
        if spec == "1":
            return policy
        for part in spec.split(","):
            k, _, v = part.strip().partition("=")
            if not v:
                continue
            if k == "poll_s":
                policy.poll_s = float(v)
            elif k == "grace_s":
                policy.grace_s = float(v)
            elif k == "budget_s":
                policy.default_budget_s = float(v)
            elif k == "host_loss":
                policy.host_loss = v not in ("0", "false", "off")
            elif k.startswith("budget."):
                policy.budgets[k[len("budget."):]] = float(v)
        return policy


class Watchdog:
    """Daemon thread that turns stale heartbeats into the escalation ladder.

    On the first poll that finds overdue heartbeats it emits ONE structured
    ``hang`` incident (per-component ages/budgets/stamp counts + all-thread
    stacks) to the bound MetricLogger and stderr, and latches the preemption
    guard (when bound) so an alive-but-slow loop can still save and exit
    cleanly. If any heartbeat is still overdue ``grace_s`` later the process
    is hard-exited with ``EXIT_HANG`` (``on_hang``-only mode — e.g.
    tpu_watch — sets ``hard_exit=False`` and just keeps logging). A recovery
    (nothing overdue) rearms the ladder.

    The thread is a daemon and ``stop()`` joins it, so pytest teardown can
    never hang on a leftover watchdog.
    """

    def __init__(self, policy=None, registry=None, guard=None, logger=None,
                 on_hang=None, exit_fn=os._exit, clock=time.monotonic):
        self.policy = policy or WatchdogPolicy()
        self.registry = registry if registry is not None else REGISTRY
        self.guard = guard
        self.logger = logger
        self.on_hang = on_hang
        self.exit_fn = exit_fn
        self.clock = clock
        self.incidents = 0
        self._stop = threading.Event()
        self._thread = None

    def bind(self, guard=None, logger=None):
        """Late-bind the escalation targets (the guard exists before the fit
        loop, the MetricLogger only inside it)."""
        if guard is not None:
            self.guard = guard
        if logger is not None:
            self.logger = logger
        return self

    def start(self):
        if self._thread is not None:
            return self
        self.registry.default_budget_s = self.policy.default_budget_s
        self.registry.budgets.update(self.policy.budgets)
        # stale stamps from earlier fits in this process get a fresh budget
        self.registry.refresh()
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="runtime-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------------
    def _lost_host(self, overdue):
        """The host index when EVERY overdue heartbeat is scoped to one host
        AND at least one other component (another host's, or any plain
        process-wide beat) is still being monitored — the signature of a
        peer that stopped participating while this process stays healthy.
        None otherwise (a process-wide stall is a hang, not a host loss)."""
        hosts = {host_of(n) for n, _, _ in overdue}
        if len(hosts) != 1 or None in hosts:
            return None
        lost = next(iter(hosts))
        others = [n for n in self.registry.ages() if host_of(n) != lost]
        return lost if others else None

    def _other_counts(self, lost):
        """Stamp counts of every component NOT scoped to the lost host —
        the proof-of-life baseline for the host-loss grace window."""
        return {n: c for n, c in self.registry.counts().items()
                if host_of(n) != lost}

    def _run(self):
        latched_at = None
        host_latched_at = None
        host_alive0 = None
        host_demoted = False  # a host-loss incident failed proof-of-life:
        #                       stay on the hang ladder until recovery
        while not self._stop.wait(self.policy.poll_s):
            overdue = self.registry.overdue()
            if overdue and not any(n == COMPILE_COMPONENT
                                   for n, _, _ in overdue) \
                    and COMPILE_COMPONENT in self.registry.ages():
                # a live, in-budget cold-compile scope legitimately blocks
                # the main thread (epoch_engine/batch_loop cannot stamp
                # while XLA compiles) — excuse everything until the compile
                # finishes or itself exceeds its own budget
                overdue = []
            if not overdue:
                latched_at = host_latched_at = None  # recovered: rearm
                host_demoted = False
                continue
            now = self.clock()
            lost = (self._lost_host(overdue)
                    if self.policy.host_loss and not host_demoted else None)
            if lost is not None:
                # host-loss ladder: one structured incident, then exit with
                # the re-mesh taxonomy code after grace. Deliberately NO
                # preempt latch — the in-process loop is healthy (nothing
                # here needs saving beyond the last periodic checkpoint),
                # and on a real multi-host mesh a final save would wedge on
                # collectives the dead host can no longer join; exiting
                # fast hands the supervisor the re-mesh decision
                latched_at = None
                if host_latched_at is None:
                    host_latched_at = now
                    host_alive0 = self._other_counts(lost)
                    self.incidents += 1
                    self._emit(overdue, event="host_lost", host=lost)
                    continue
                if now - host_latched_at >= self.policy.grace_s:
                    # proof of life: "others are merely in-budget" is not
                    # evidence this process is healthy (a whole-process
                    # wedge freezes short-budget host beats first); only a
                    # component that actually STAMPED during the grace
                    # window proves liveness. Without one, demote to the
                    # ordinary hang ladder — exit 19 and a same-shape
                    # restart, never a misclassified mesh shrink. (A main
                    # thread blocked on a dead collective takes the typed
                    # collective-timeout route in the grid engine, not
                    # this heartbeat route.)
                    counts = self._other_counts(lost)
                    alive = any(counts.get(n, 0) > c0
                                for n, c0 in host_alive0.items()) \
                        or any(n not in host_alive0 for n in counts)
                    if alive:
                        if self.policy.hard_exit:
                            self._hard_exit(
                                overdue, exit_code=EXIT_HOST_LOST,
                                event="host_lost", host=lost)
                        host_latched_at = None
                        continue
                    host_latched_at = None
                    host_demoted = True  # until recovery rearms
                    lost = None  # fall through to the hang ladder below
                else:
                    continue
            if lost is None and host_latched_at is not None:
                host_latched_at = None
            if latched_at is None:
                latched_at = now
                self.incidents += 1
                self._emit(overdue)
                if self.guard is not None and self.policy.latch_preempt:
                    # rung 2: a slow-but-alive loop sees the latch at its
                    # next epoch boundary, writes the final checkpoint, and
                    # exits EXIT_PREEMPTED on its own
                    self.guard.signum = None
                    self.guard.preempted = True
                continue
            if now - latched_at >= self.policy.grace_s:
                if self.policy.hard_exit:
                    self._hard_exit(overdue)
                # on_hang-only mode: keep logging one incident per ladder
                # cycle instead of spamming every poll
                latched_at = None

    def _record(self, overdue):
        counts = self.registry.counts()
        return {
            "components": {
                name: {"age_s": round(age, 3), "budget_s": budget,
                       "stamps": counts.get(name, 0)}
                for name, age, budget in overdue},
            "ages_s": {n: round(a, 3)
                       for n, a in self.registry.ages().items()},
            "grace_s": self.policy.grace_s,
        }

    def _dump_flight(self, reason, rec, timeout_s=5.0):
        """Dump the crash flight recorder (redcliff_tpu/obs/flight.py) next
        to the bound logger's metrics.jsonl: the stalled component's last
        spans — per-dispatch, checkpoint writes, prefetch fills, shard
        loads — are in-memory evidence that was deliberately never flushed
        to disk; an escalation is exactly when it must be. Best-effort AND
        time-bounded: the dump writes to the same filesystem whose wedge may
        be the very hang being escalated, and blocking I/O is uninterruptible
        by try/except — so it runs in a daemon thread joined for at most
        ``timeout_s``, like the hard-exit's log flush. Forensics can never
        block the ladder (or the guaranteed exit)."""
        result = [None]

        def dump():
            with contextlib.suppress(Exception):
                from redcliff_tpu.obs import flight as _flight

                result[0] = _flight.dump_for_logger(self.logger,
                                                    reason=reason, extra=rec)

        t = threading.Thread(target=dump, name="watchdog-flight",
                             daemon=True)
        t.start()
        t.join(timeout=timeout_s)
        return result[0]

    def _emit(self, overdue, event="hang", **extra):
        rec = self._record(overdue)
        rec.update(extra)
        stacks = dump_stacks()
        flight_path = self._dump_flight(event, rec)
        print(f"[watchdog] {event.upper()} detected: {rec['components']}"
              + (f"\nflight record: {flight_path}" if flight_path else "")
              + f"\n{stacks}", file=sys.stderr, flush=True)
        if self.logger is not None and getattr(self.logger, "active", False):
            self.logger.log(event, **rec, stacks=stacks)
        if self.on_hang is not None:
            try:
                self.on_hang(rec)
            except Exception:  # noqa: BLE001 — a bad callback must not
                pass           # silence the ladder

    def _hard_exit(self, overdue, exit_code=EXIT_HANG, event="hang", **extra):
        rec = self._record(overdue)
        rec.update(extra)
        # stderr forensics FIRST — guaranteed even if the jsonl logger is
        # unusable (e.g. the main thread wedged while holding its lock)
        print(f"[watchdog] {event} persists after {self.policy.grace_s:.1f}s "
              f"grace; hard exit {exit_code}: {rec['components']}",
              file=sys.stderr, flush=True)
        # refresh the flight record with the state at exit time (the _emit
        # dump is grace_s old by now); time-bounded like the log flush below
        # — a wedged filesystem must not block the exit
        self._dump_flight(event, dict(rec, exit_code=exit_code))
        with contextlib.suppress(Exception):
            faulthandler.dump_traceback(file=sys.stderr)
        sys.stderr.flush()
        if self.logger is not None and getattr(self.logger, "active", False):
            # best-effort, time-bounded: the *_exit record is nice to
            # have, but the exit must happen even if logging would block
            def flush_log():
                with contextlib.suppress(Exception):
                    self.logger.log(f"{event}_exit", exit_code=exit_code,
                                    **rec)
                    self.logger.close()

            t = threading.Thread(target=flush_log, name="watchdog-flush",
                                 daemon=True)
            t.start()
            t.join(timeout=5.0)
        # os._exit, not sys.exit: the main thread is wedged and cannot unwind;
        # durability is the checkpoint layer's job (.prev generation)
        self.exit_fn(exit_code)


def maybe_start(guard=None, logger=None, registry=None):
    """Watchdog context from the environment: a live :class:`Watchdog` when
    ``REDCLIFF_WATCHDOG`` is set (the supervised-run switch), else an inert
    nullcontext — call sites never branch."""
    policy = WatchdogPolicy.from_env()
    if policy is None:
        return contextlib.nullcontext(None)
    return Watchdog(policy=policy, guard=guard, logger=logger,
                    registry=registry)
