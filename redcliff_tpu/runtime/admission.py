"""Shared structured-admission-reject taxonomy.

Two production planes admit work against finite capacity: the fleet queue
(``fleet/queue.py submit`` — queue-wait SLO backpressure, ISSUE 16) and the
streaming inference service (``serve/service.py connect`` — fixed slot-table
capacity, ISSUE 17). Both refuse admission the same way: a TYPED exception
carrying a predicted ETA, so the caller can distinguish "come back in ~N
seconds" from a crash and machine-handle the retry. This module owns the
taxonomy so the two planes raise the same types instead of drifting copies.

* :class:`AdmissionReject` — the base: every structured refusal carries
  ``eta_s`` (predicted seconds until admission would likely succeed; the
  contract is best-effort, never a promise) and ``reason``;
* :class:`BackpressureReject` — the fleet queue's reject-with-ETA (predicted
  queue wait would breach the tenant's armed queue-wait SLO). Signature and
  message are byte-compatible with its original home in fleet/queue.py,
  which still re-exports it;
* :class:`SlotsExhausted` — the serve plane's reject: every stream slot is
  leased; ``eta_s`` is the soonest lease expiry (the earliest moment a slot
  could recycle if its subscriber goes silent);
* :class:`TenantQuotaExceeded` — the fleet admission planner's fair-share
  refusal (ISSUE 18): a tenant already holds its ``max_inflight_slots``
  sub-mesh slots, so its next batch stays QUEUED (deferred, not dropped)
  until one of its slots frees at a check-window boundary.

stdlib only, no jax (obs/schema.py ``--check`` enforces it): admission
decisions run in control processes that must never initialize a backend.
"""
from __future__ import annotations

__all__ = ["AdmissionReject", "BackpressureReject", "SlotsExhausted",
           "TenantQuotaExceeded"]


class AdmissionReject(RuntimeError):
    """Base of every structured admission refusal: the service is refusing
    work it predicts it cannot serve acceptably, with an ETA the caller can
    retry against. ``eta_s`` may be None when no prediction exists."""

    def __init__(self, message, eta_s=None, reason=None):
        self.eta_s = float(eta_s) if eta_s is not None else None
        self.reason = reason
        super().__init__(message)


class BackpressureReject(AdmissionReject):
    """``fleet submit`` refused admission: the predicted queue wait would
    breach the tenant's queue-wait SLO (``REDCLIFF_SLO_QUEUE_P99_S``). The
    structured reject-with-ETA: ``eta_s`` is the predicted wait, so the
    caller can resubmit after roughly that long (or with
    ``REDCLIFF_BACKPRESSURE=0``). Rejection beats silent lateness."""

    def __init__(self, tenant, eta_s, threshold_s, queue_depth, workers):
        self.tenant = str(tenant)
        self.threshold_s = float(threshold_s)
        self.queue_depth = int(queue_depth)
        self.workers = int(workers)
        super().__init__(
            f"backpressure: predicted queue wait {float(eta_s):.1f}s exceeds "
            f"SLO {self.threshold_s:g}s for tenant {self.tenant!r} "
            f"(queue depth {self.queue_depth}, {self.workers} worker(s)); "
            f"retry in ~{float(eta_s):.0f}s or set "
            f"REDCLIFF_BACKPRESSURE=0",
            eta_s=eta_s, reason="predicted queue wait")


class SlotsExhausted(AdmissionReject):
    """``serve connect`` refused admission: every slot in the fixed-capacity
    stream table is leased to a live session. ``eta_s`` is the soonest
    lease expiry among live sessions — the earliest moment a slot could be
    reaped and recycled if its subscriber stops heartbeating — or None when
    every lease was just renewed."""

    def __init__(self, capacity, eta_s=None):
        self.capacity = int(capacity)
        eta = (f"soonest lease expiry in ~{float(eta_s):.1f}s"
               if eta_s is not None else "no lease near expiry")
        super().__init__(
            f"serve admission: all {self.capacity} stream slot(s) leased; "
            f"{eta} — retry then, or raise REDCLIFF_SERVE_SLOTS",
            eta_s=eta_s, reason="slots exhausted")


class TenantQuotaExceeded(AdmissionReject):
    """Fleet admission planner fair-share refusal: the tenant already holds
    ``max_inflight_slots`` sub-mesh slots (in flight plus admitted earlier
    in this plan cycle), so this batch is DEFERRED — it stays queued with
    this structured reason (surfaced by ``fleet status``) and re-plans once
    a slot frees. ``eta_s`` is the tenant's soonest predicted batch
    completion when the cost model can price one, else None."""

    def __init__(self, tenant, max_inflight_slots, inflight, eta_s=None):
        self.tenant = str(tenant)
        self.max_inflight_slots = int(max_inflight_slots)
        self.inflight = int(inflight)
        super().__init__(
            f"tenant quota: {self.tenant!r} holds {self.inflight} of "
            f"{self.max_inflight_slots} fair-share slot(s); batch deferred "
            f"until one frees (REDCLIFF_FLEET_TENANT_SLOTS raises the "
            f"quota)",
            eta_s=eta_s, reason="tenant quota")
