"""Reusable retry/backoff/deadline primitives.

One policy object replaces the three ad-hoc probe/spread loops that grew in
bench.py (hand-rolled ``PROBE_WAITS`` tuple), tpu_watch.py (``while ...
time.sleep(interval)``), and ``__graft_entry__.py`` (single-shot DCN leg that
could hang 600 s on a lost port race). Every caller gets the same semantics —
exponential backoff with bounded jitter, an optional wall-clock deadline, an
attempt budget — and the same fixed-schema outcome log, so BENCH artifacts can
distinguish "tunnel dead" from "policy too impatient".

stdlib only: bench.py's parent process imports this and must never initialize
a jax backend.
"""
from __future__ import annotations

import time
from dataclasses import asdict, dataclass, field
from typing import Any, Callable

__all__ = ["RetryPolicy", "RetryOutcome", "GiveUp", "retry",
           "PROBE_RETRY_POLICY"]


class GiveUp(Exception):
    """Raised by a retried callable to abort the retry loop immediately (the
    failure is known-terminal; further attempts would waste the budget)."""


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with bounded jitter and an optional deadline.

    Attempt 0 runs immediately; attempt ``k`` waits
    ``min(base_delay_s * multiplier**(k-1), max_delay_s)`` first, widened by a
    uniform jitter of ±``jitter_frac`` when an ``rng`` is supplied (spreads
    fleet-synchronized callers; deterministic without one). ``deadline_s``
    bounds the WHOLE loop: an attempt whose backoff would land past the
    deadline is not started, and the outcome records ``deadline_hit``.
    """

    max_attempts: int = 5
    base_delay_s: float = 1.0
    multiplier: float = 2.0
    max_delay_s: float = 60.0
    jitter_frac: float = 0.0
    deadline_s: float | None = None

    def backoff_s(self, attempt: int, rng=None) -> float:
        if attempt <= 0:
            return 0.0
        d = min(self.base_delay_s * self.multiplier ** (attempt - 1),
                self.max_delay_s)
        if rng is not None and self.jitter_frac > 0:
            d *= 1.0 + self.jitter_frac * (2.0 * rng.random() - 1.0)
        return d

    def schedule(self, rng=None):
        """The full per-attempt backoff list (len == max_attempts)."""
        return [self.backoff_s(i, rng=rng) for i in range(self.max_attempts)]

    def as_dict(self):
        return asdict(self)


@dataclass
class RetryOutcome:
    """Result of a :func:`retry` loop plus its fixed-schema attempt log."""

    ok: bool
    value: Any = None
    error: str | None = None
    deadline_hit: bool = False
    attempts: list = field(default_factory=list)
    policy: dict = field(default_factory=dict)

    def log(self):
        """The fixed schema recorded into BENCH/cache artifacts: policy knobs,
        one record per attempt (index, backoff actually waited, offset from
        loop start, outcome, info), whether the deadline cut the loop."""
        return {
            "policy": dict(self.policy),
            "attempts": [dict(a) for a in self.attempts],
            "num_attempts": len(self.attempts),
            "deadline_hit": bool(self.deadline_hit),
            "ok": bool(self.ok),
            "error": self.error,
        }


def retry(fn: Callable[[int], Any], policy: RetryPolicy, *,
          is_success: Callable[[Any], bool] | None = None,
          retryable: Callable[[BaseException], bool] | None = None,
          info_of: Callable[[Any], str] | None = None,
          sleep: Callable[[float], None] = time.sleep,
          monotonic: Callable[[], float] = time.monotonic,
          rng=None) -> RetryOutcome:
    """Run ``fn(attempt_index)`` under ``policy`` until it succeeds.

    Success = the call returns (no exception) and ``is_success(result)`` (all
    returns succeed when ``is_success`` is None). Failure handling:

    - a falsy ``is_success`` verdict consumes the attempt and backs off;
    - an exception for which ``retryable(exc)`` is false (or ``retryable`` is
      None) re-raises immediately — only declared-transient errors burn
      attempts; a retryable exception that exhausts the budget re-raises too,
      so exception-style callers never get a silent None;
    - :class:`GiveUp` aborts the loop immediately with ``ok=False`` (the
      callable learned the failure is terminal).

    ``sleep``/``monotonic``/``rng`` are injectable for the fault-injection
    tests (assert the backoff schedule without waiting it out).
    Returns a :class:`RetryOutcome`; ``outcome.log()`` is the fixed schema.
    """
    t0 = monotonic()
    out = RetryOutcome(ok=False, policy=policy.as_dict())
    last_exc = None
    for attempt in range(policy.max_attempts):
        backoff = policy.backoff_s(attempt, rng=rng)
        if (policy.deadline_s is not None
                and (monotonic() - t0) + backoff > policy.deadline_s):
            out.deadline_hit = True
            break
        if backoff:
            sleep(backoff)
        rec = {"attempt": attempt, "backoff_s": round(backoff, 3),
               "t_offset_s": round(monotonic() - t0, 3)}
        try:
            result = fn(attempt)
        except GiveUp as e:
            rec.update(ok=False, info=f"gave up: {e}")
            out.attempts.append(rec)
            out.error = f"gave up: {e}"
            return out
        except Exception as e:  # noqa: BLE001 - classified right below
            if retryable is None or not retryable(e):
                raise
            last_exc = e
            rec.update(ok=False, info=repr(e)[:300])
            out.attempts.append(rec)
            continue
        ok = bool(is_success(result)) if is_success is not None else True
        rec.update(ok=ok,
                   info=(info_of(result) if info_of is not None else None))
        out.attempts.append(rec)
        last_exc = None
        if ok:
            out.ok = True
            out.value = result
            return out
    if last_exc is not None:
        raise last_exc
    if out.error is None:
        out.error = ("deadline exceeded" if out.deadline_hit
                     else f"no success in {len(out.attempts)} attempt(s)")
    return out


# The shared accelerator-probe policy: the axon TPU tunnel drops for minutes
# at a time (BENCH_r05.json probe_log), so attempts spread 15 s -> 2 min
# apart (backoffs 0/15/30/60/120 — exactly the old hand-rolled PROBE_WAITS
# gaps) and the whole loop gives up after 15 minutes, so a wedged environment
# cannot stretch pure probing past the round budget. Callers whose attempts
# embed long work (bench.py runs full measurements inside the loop) must
# widen deadline_s to cover that work — see bench.py._orchestrate. Jitter
# only applies when the caller passes an rng to retry().
PROBE_RETRY_POLICY = RetryPolicy(
    max_attempts=5, base_delay_s=15.0, multiplier=2.0, max_delay_s=120.0,
    jitter_frac=0.1, deadline_s=900.0)
