"""Compile observability + the persistent XLA compilation cache.

After PR 3 removed dispatch overhead from the hot path, the two largest
untouched costs in a grid sweep are dead-lane FLOPs (parallel/compaction.py)
and COMPILATION: every distinct (shape, G) grid program pays a full XLA
compile, again on every restart, supervisor re-attempt, and resumed
preemption. XLA ships a content-addressed persistent compilation cache
exactly for this; this module wires it in and makes compilation *visible* —
per-program compile durations, persistent-cache hits/misses — so bench
artifacts and metrics.jsonl can report warm-start wins and tier-1 tripwires
can catch silently reintroduced steady-state recompiles.

Two halves:

* :func:`install` registers ``jax.monitoring`` listeners (idempotent,
  process-global) that tally every ``backend_compile`` duration and every
  persistent-cache hit/miss. :func:`snapshot` / :func:`delta` give callers
  cheap before/after accounting; the grid engine folds the per-fit delta
  into ``dispatch_stats`` and logs a ``compile`` event per epoch that
  compiled anything.
* :func:`enable_cache` points ``jax_compilation_cache_dir`` at a VERSIONED
  subdirectory (jax/jaxlib version + backend platform + a cache schema tag),
  so upgrading the toolchain can never replay stale executables, and drops
  the min-compile-time/min-entry-size thresholds so the small grid programs
  this repo compiles actually land in the cache.

jax is imported lazily (bench.py's backend-free parent imports the runtime
package); until a caller with a live backend installs the listeners, every
function here is inert.
"""
from __future__ import annotations

import os
import threading

__all__ = [
    "install", "enable_cache", "snapshot", "delta", "cache_version_tag",
    "ENV_CACHE_DIR", "CACHE_SCHEMA",
]

# repo-side cache schema tag: bump to orphan all prior persistent-cache
# entries (e.g. if a custom-call/lowering change makes old executables
# unsafe to replay without jax itself revving)
CACHE_SCHEMA = 1
ENV_CACHE_DIR = "REDCLIFF_COMPILE_CACHE"

_lock = threading.Lock()
_installed = False
_enabled_dir = None
_counters = {
    "compiles": 0,        # backend_compile invocations (cache hits included:
    #                       a hit still runs the fast deserialize path)
    "compile_ms": 0.0,    # total wall time inside backend_compile
    "cache_hits": 0,      # persistent-cache executable reuses
    "cache_misses": 0,    # full compiles that went to (or bypassed) the cache
}

_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"


def _on_event(name, **kw):
    if name == _CACHE_HIT_EVENT:
        with _lock:
            _counters["cache_hits"] += 1
    elif name == _CACHE_MISS_EVENT:
        with _lock:
            _counters["cache_misses"] += 1


def _on_duration(name, secs, **kw):
    if name == _BACKEND_COMPILE_EVENT:
        with _lock:
            _counters["compiles"] += 1
            _counters["compile_ms"] += secs * 1e3


def install():
    """Register the monitoring listeners once per process. Safe to call from
    every engine constructor; returns True when the listeners are live."""
    global _installed
    with _lock:
        if _installed:
            return True
        _installed = True
    import jax

    jax.monitoring.register_event_listener(_on_event)
    jax.monitoring.register_event_duration_secs_listener(_on_duration)
    return True


def snapshot():
    """Current cumulative counters (a copy)."""
    with _lock:
        return dict(_counters)


def delta(before, after=None):
    """Counter difference ``after - before`` (``after`` defaults to now)."""
    after = snapshot() if after is None else after
    return {k: (round(after[k] - before[k], 3)
                if isinstance(after[k], float) else after[k] - before[k])
            for k in _counters}


def cache_version_tag():
    """The versioned subdirectory name: cache entries are only ever replayed
    by the exact toolchain (+ backend platform + repo schema) that wrote
    them."""
    import jax
    import jaxlib

    return (f"jax{jax.__version__}-jaxlib{jaxlib.__version__}-"
            f"{jax.default_backend()}-cc{CACHE_SCHEMA}")


def enable_cache(base_dir=None):
    """Enable the persistent XLA compilation cache under
    ``<base_dir>/<version-tag>/`` and install the observability listeners.

    ``base_dir`` falls back to the ``REDCLIFF_COMPILE_CACHE`` env var; with
    neither set this is a no-op returning None. Idempotent for a given
    directory; returns the resolved versioned cache dir. Thresholds are
    dropped to cache-everything (the grid's programs are many, small, and
    recompiled on every restart — exactly the workload the default
    min-compile-time heuristic skips)."""
    global _enabled_dir
    base_dir = base_dir if base_dir is not None else os.environ.get(
        ENV_CACHE_DIR) or None
    if not base_dir:
        return None
    import jax

    cache_dir = os.path.join(base_dir, cache_version_tag())
    with _lock:
        already = _enabled_dir
    if already == cache_dir:
        install()
        return cache_dir
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    with _lock:
        _enabled_dir = cache_dir
    install()
    return cache_dir
