"""Fault-tolerance runtime: the layer between "the math is right" and "the fit
survives the machine".

Round 5 showed the hardware, not the model, is the unreliable component of
this stack (BENCH_r05.json: 5/5 TPU probes hung over 765 s), and the grid
engine's "bit-identical resume" had a fingerprint hole plus non-atomic pickle
writes (ADVICE.md). Large-system practice (TensorFlow, arXiv:1605.08695)
treats checkpoint durability and worker failure as first-class design inputs;
this package does the same:

- :mod:`~redcliff_tpu.runtime.admission` — the shared structured
  admission-reject taxonomy (``AdmissionReject`` / ``BackpressureReject`` /
  ``SlotsExhausted``) both capacity-bounded planes — the fleet queue and the
  streaming inference service — raise instead of drifting copies;
- :mod:`~redcliff_tpu.runtime.checkpoint` — durable checkpoint files: atomic
  tmp+``os.replace`` writes, a trailing ``.prev`` generation, CRC/format
  version header, quarantine of corrupt files to ``*.bad``, and dataset
  fingerprints for resume-compatibility checks;
- :mod:`~redcliff_tpu.runtime.retry` — one retry/backoff/deadline policy
  object shared by every accelerator-probe loop (bench.py, tpu_watch.py,
  the DCN dry run), with a fixed-schema outcome log;
- :mod:`~redcliff_tpu.runtime.preempt` — SIGTERM/SIGINT capture that turns a
  preemption notice into a final checkpoint instead of lost work;
- :mod:`~redcliff_tpu.runtime.numerics` — the numerics sentinel: in-graph
  non-finite loss/gradient guards (``lax.cond`` inside the compiled step, no
  per-step host sync), device-side skip counters, and the host-side
  :class:`~redcliff_tpu.runtime.numerics.DivergenceMonitor` that rolls a
  diverged fit back to its last good snapshot with the learning rate backed
  off;
- :mod:`~redcliff_tpu.runtime.faultinject` — fault-injection hooks + child
  fit used by tests/test_fault_injection.py to SIGKILL fits mid-run, corrupt
  checkpoints, inject probe failures, and simulate host drops / device loss
  / coordinator loss (the elastic re-meshing story,
  :mod:`~redcliff_tpu.parallel.remesh`);
- :mod:`~redcliff_tpu.runtime.compileobs` — compile observability (per-program
  compile durations, persistent-cache hit/miss counters via
  ``jax.monitoring``) and the versioned persistent XLA compilation cache
  (``jax_compilation_cache_dir``) that makes restarts and supervisor
  re-attempts warm-start their programs instead of recompiling the world.

None of these modules import jax at module scope: bench.py's parent process
must stay backend-free (a hung TPU tunnel would wedge it in a C call), so it
can import the retry primitives safely.
"""
from redcliff_tpu.runtime.admission import (  # noqa: F401
    AdmissionReject,
    BackpressureReject,
    SlotsExhausted,
)
from redcliff_tpu.runtime.checkpoint import (  # noqa: F401
    CheckpointCorruptError,
    CheckpointWriteError,
    dataset_fingerprint,
    load_checkpoint,
    quarantine,
    read_checkpoint,
    write_checkpoint,
)
from redcliff_tpu.runtime.numerics import (  # noqa: F401
    DivergenceMonitor,
    NumericsAction,
    NumericsPolicy,
    global_norm,
    guarded_update,
    init_numerics_state,
    numerics_summary,
    scale_learning_rate,
)
from redcliff_tpu.runtime.preempt import Preempted, PreemptionGuard  # noqa: F401
from redcliff_tpu.runtime.retry import (  # noqa: F401
    PROBE_RETRY_POLICY,
    GiveUp,
    RetryOutcome,
    RetryPolicy,
    retry,
)
