"""Fault-injection harness: kill fits mid-run, corrupt checkpoints, flake probes.

tests/test_fault_injection.py drives the preemption story end-to-end with this
module: a tiny-but-real grid fit (`child fit`, run via
``python -m redcliff_tpu.runtime.faultinject``) is SIGKILLed mid-epoch in a
subprocess, resumed, and compared bit-for-bit against an uninterrupted run;
checkpoint files are truncated/bit-flipped to prove quarantine-not-crash; and
deterministic flaky probes assert the retry policy's backoff schedule without
sleeping through it.

Fault points are env-gated (``REDCLIFF_FAULT_INJECT``) so the hooks compiled
into the training loop cost one dict lookup when unarmed. Grammar: a
comma-separated list of ``name:arg``:

- ``sigkill_after_checkpoint:N`` — SIGKILL this process immediately after the
  checkpoint for epoch N is written (the preemption-without-grace case);
- ``marker_after_epoch:N`` — write the file named by
  ``REDCLIFF_FAULT_MARKER`` at the end of epoch N (lets a parent process
  synchronize a SIGTERM with a known fit phase);
- ``hang_between_ckpt_replaces:S`` — inside the durable writer's crash
  window (head already renamed to ``.prev``, new generation not yet
  promoted) write the marker file once and sleep S seconds, so a parent can
  SIGKILL the process mid-(background)-checkpoint-write and prove the
  ``.prev`` fallback resumes.

Numerical fault points (consumed through :func:`poison_batch` /
:func:`skip_update`, called by the trainers with a global step index; step
specs are either one step ``"5"`` or an inclusive range ``"5-8"``):

- ``nan_batch:SPEC`` — replace the training batch at the matching step(s)
  with all-NaN input (the classic poisoned-batch event the in-graph
  numerics guard must catch);
- ``grad_blowup:SPEC`` — scale the batch by 1e30 so the loss/gradients
  overflow to inf at the matching step(s) (exploding-gradient event);
- ``skip_update:SPEC`` — make the trainer skip the parameter update for the
  matching step(s) entirely. This is the *reference semantics* for the
  guard: a guarded fit with ``nan_batch:K`` must end bit-identical to a
  clean fit with ``skip_update:K``.

jax is imported lazily: the module is importable by backend-free processes.
"""
from __future__ import annotations

import argparse
import os
import pickle
import signal
import sys

__all__ = ["armed", "crash_point", "ckpt_write_point", "poison_batch",
           "skip_update", "corrupt_checkpoint", "flaky", "tiny_grid_fit"]

ENV_SPEC = "REDCLIFF_FAULT_INJECT"
ENV_MARKER = "REDCLIFF_FAULT_MARKER"
PREEMPTED_EXIT_CODE = 17


def _active_faults():
    spec = os.environ.get(ENV_SPEC)
    if not spec:
        return ()
    out = []
    for part in spec.split(","):
        name, _, arg = part.strip().partition(":")
        if name:
            out.append((name, arg))
    return tuple(out)


def armed():
    """True when ANY fault is armed. The engines use this to serialize
    otherwise-asynchronous work (e.g. wait for the background checkpoint
    writer before a crash point) so fault tests stay deterministic."""
    return bool(os.environ.get(ENV_SPEC))


def ckpt_write_point(stage, path=None):
    """Hook inside ``runtime.checkpoint.write_checkpoint``'s crash window
    (head renamed to ``.prev``, new generation not yet promoted).

    ``hang_between_ckpt_replaces:SECONDS`` writes the ``REDCLIFF_FAULT_MARKER``
    file (once) and then sleeps, holding the window open so a parent process
    can SIGKILL this one mid-write — the on-disk state is then exactly
    "head missing, .prev intact", which resume must recover from.
    """
    for name, arg in _active_faults():
        if (name == "hang_between_ckpt_replaces"
                and stage == "between_replaces"):
            marker = os.environ.get(ENV_MARKER)
            if marker and not os.path.exists(marker):
                with open(marker, "w") as f:
                    f.write(path or "")
                import time

                time.sleep(float(arg) if arg else 30.0)


def crash_point(stage, epoch=None):
    """Hook called by the training loop at named stages; inert unless a fault
    matching (stage, epoch) is armed via the environment."""
    for name, arg in _active_faults():
        if (name == "sigkill_after_checkpoint" and stage == "checkpoint_saved"
                and epoch == int(arg)):
            os.kill(os.getpid(), signal.SIGKILL)
        if (name == "marker_after_epoch" and stage == "epoch_end"
                and epoch == int(arg)):
            marker = os.environ.get(ENV_MARKER)
            if marker and not os.path.exists(marker):
                with open(marker, "w") as f:
                    f.write(str(epoch))


def _step_match(spec, step):
    """``"5"`` matches step 5; ``"5-8"`` matches steps 5..8 inclusive."""
    lo, sep, hi = spec.partition("-")
    if sep:
        return int(lo) <= step <= int(hi)
    return step == int(lo)


def poison_batch(X, step):
    """Numerical fault point: trainers pass every training batch through this
    with their global step index. Inert (returns ``X`` untouched, one env
    lookup) unless a ``nan_batch``/``grad_blowup`` fault matches ``step``."""
    faults = _active_faults()
    if not faults:
        return X
    import numpy as np

    for name, arg in faults:
        if name == "nan_batch" and _step_match(arg, step):
            bad = np.array(X, dtype=np.float32, copy=True)
            bad[...] = np.nan
            return bad
        if name == "grad_blowup" and _step_match(arg, step):
            # 1e30 overflows the squared-error loss/grads to inf in f32
            return np.array(X, dtype=np.float32) * np.float32(1e30)
    return X


def skip_update(step):
    """True when a ``skip_update`` fault matches ``step`` — the trainer skips
    the parameter update entirely (batch drawn, rng advanced). Reference
    semantics for the in-graph guard's skip."""
    for name, arg in _active_faults():
        if name == "skip_update" and _step_match(arg, step):
            return True
    return False


def corrupt_checkpoint(path, mode="truncate"):
    """Damage a checkpoint file in a controlled way.

    ``truncate`` cuts the file to half its length (torn write / full disk);
    ``flip_payload`` inverts a byte past the header (silent media corruption
    the CRC must catch); ``zero_header`` wipes the magic+version header.
    """
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        if mode == "truncate":
            f.truncate(max(size // 2, 1))
        elif mode == "flip_payload":
            off = min(40, size - 1)
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 0xFF]))
        elif mode == "zero_header":
            f.write(b"\0" * min(8, size))
        else:
            raise ValueError(f"unknown corruption mode {mode!r}")


def flaky(n_failures, value=True, exc=None):
    """A probe-shaped callable that fails ``n_failures`` times then succeeds:
    returns ``(False, 'injected failure k')`` (or raises ``exc``) while
    failing, then ``(value, 'ok')``. For asserting retry/backoff schedules."""
    state = {"calls": 0}

    def probe(_attempt=None):
        state["calls"] += 1
        if state["calls"] <= n_failures:
            if exc is not None:
                raise exc
            return False, f"injected failure {state['calls']}"
        return value, "ok"

    probe.calls = lambda: state["calls"]
    return probe


# ---------------------------------------------------------------------------
# the child fit: one small deterministic grid fit, identical whether run
# in-process or as a subprocess, so killed/resumed/uninterrupted legs are
# directly comparable
# ---------------------------------------------------------------------------
def tiny_grid_fit(checkpoint_dir, max_iter=4, checkpoint_every=1,
                  bad_point=False):
    """Run the harness's canonical small grid fit and return its GridResult.

    ``bad_point`` swaps point 1's learning rate for an absurd value that
    drives its loss non-finite within an epoch (exercises the non-finite
    quarantine path). Everything is seeded; two invocations with the same
    arguments produce bit-identical results on the same backend.
    """
    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")

    from redcliff_tpu.data.datasets import ArrayDataset
    from redcliff_tpu.models.redcliff import RedcliffSCMLP, RedcliffSCMLPConfig
    from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner
    from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig

    model = RedcliffSCMLP(RedcliffSCMLPConfig(
        num_chans=4, gen_lag=2, gen_hidden=(8,), embed_lag=4,
        embed_hidden_sizes=(8,), num_factors=2, num_supervised_factors=2,
        factor_weight_l1_coeff=0.01, adj_l1_reg_coeff=0.001,
        factor_cos_sim_coeff=0.01,
        factor_score_embedder_type="Vanilla_Embedder",
        primary_gc_est_mode="fixed_factor_exclusive", num_sims=1,
        training_mode="combined"))
    # 1e20 (not merely "large"): Adam-normalized updates bound the step to
    # ~lr, so the poison lr must push params past sqrt(f32 max) for the
    # squared forecast error to overflow to inf within an epoch
    points = [{"gen_lr": 1e-3},
              ({"gen_lr": 1e20, "embed_lr": 1e20} if bad_point
               else {"gen_lr": 3e-3})]
    tc = RedcliffTrainConfig(max_iter=max_iter, batch_size=16, check_every=1,
                             seed=0)
    runner = RedcliffGridRunner(model, tc, GridSpec(points=points))
    cfg = model.config
    rng = np.random.default_rng(0)
    T = cfg.max_lag + cfg.num_sims
    X = rng.normal(size=(48, T, cfg.num_chans)).astype(np.float32)
    Y = rng.uniform(size=(48, 3, 1)).astype(np.float32)
    ds = ArrayDataset(X, Y)
    return runner.fit(jax.random.PRNGKey(2), ds, ds,
                      checkpoint_dir=checkpoint_dir,
                      checkpoint_every=checkpoint_every)


def _result_blob(result):
    import jax
    import numpy as np

    return {
        "val_history": np.asarray(result.val_history),
        "best_criteria": np.asarray(result.best_criteria),
        "best_epoch": np.asarray(result.best_epoch),
        "active": np.asarray(result.active),
        "failures": result.failures,
        "best_params_leaves": [np.asarray(l)
                               for l in jax.tree.leaves(result.best_params)],
    }


def _child_main(argv):
    ap = argparse.ArgumentParser(prog="faultinject-child")
    ap.add_argument("--checkpoint-dir", required=True)
    ap.add_argument("--max-iter", type=int, default=4)
    ap.add_argument("--checkpoint-every", type=int, default=1)
    ap.add_argument("--bad-point", action="store_true")
    ap.add_argument("--result", default=None,
                    help="write the finished fit's result blob here")
    args = ap.parse_args(argv)

    from redcliff_tpu.runtime.preempt import Preempted

    try:
        result = tiny_grid_fit(args.checkpoint_dir,
                               max_iter=args.max_iter,
                               checkpoint_every=args.checkpoint_every,
                               bad_point=args.bad_point)
    except Preempted as e:
        print(f"faultinject child: {e}", file=sys.stderr)
        with open(os.path.join(args.checkpoint_dir, "preempted.json"),
                  "w") as f:
            f.write(f'{{"signum": {e.signum}, "epoch": {e.epoch}}}')
        raise SystemExit(PREEMPTED_EXIT_CODE)
    if args.result:
        with open(args.result, "wb") as f:
            pickle.dump(_result_blob(result), f)


if __name__ == "__main__":
    _child_main(sys.argv[1:])
