"""Fault-injection harness: kill fits mid-run, corrupt checkpoints, flake probes.

tests/test_fault_injection.py drives the preemption story end-to-end with this
module: a tiny-but-real grid fit (`child fit`, run via
``python -m redcliff_tpu.runtime.faultinject``) is SIGKILLed mid-epoch in a
subprocess, resumed, and compared bit-for-bit against an uninterrupted run;
checkpoint files are truncated/bit-flipped to prove quarantine-not-crash; and
deterministic flaky probes assert the retry policy's backoff schedule without
sleeping through it.

Fault points are env-gated (``REDCLIFF_FAULT_INJECT``) so the hooks compiled
into the training loop cost one dict lookup when unarmed. Grammar: a
comma-separated list of ``name:arg``:

- ``sigkill_after_checkpoint:N`` — SIGKILL this process immediately after the
  checkpoint for epoch N is written (the preemption-without-grace case);
- ``marker_after_epoch:N`` — write the file named by
  ``REDCLIFF_FAULT_MARKER`` at the end of epoch N (lets a parent process
  synchronize a SIGTERM with a known fit phase);
- ``hang_between_ckpt_replaces:S`` — inside the durable writer's crash
  window (head already renamed to ``.prev``, new generation not yet
  promoted) write the marker file once and sleep S seconds, so a parent can
  SIGKILL the process mid-(background)-checkpoint-write and prove the
  ``.prev`` fallback resumes.

Liveness fault points (the chaos-soak half of the watchdog story,
docs/ARCHITECTURE.md "Liveness & supervision"):

- ``hang_in:COMPONENT:S`` — wedge the named heartbeat-stamped component
  (``prefetch`` / ``shard_loader`` / ``ckpt_writer``) by sleeping S seconds
  at its fault point. Fires ONCE per marker file when
  ``REDCLIFF_FAULT_MARKER`` is set (a once-guard file named
  ``<marker>.hang_<component>`` is written), so a supervisor-restarted
  attempt runs clean and the hang->detect->restart->finish loop closes;
- ``slow_io:MS`` — sleep MS milliseconds at every IO fault point
  (checkpoint writes, shard reads): degraded-NFS latency, not a hang;
- ``io_error:KIND[:ERRNO]`` — raise an injected ``OSError`` (default
  ``ENOSPC``) at the named IO site (``ckpt_write``). Once-per-marker gated
  like ``hang_in`` so a restarted attempt can succeed.

Host-fault points (the elastic re-meshing story, docs/ARCHITECTURE.md
"Elastic re-meshing & host-fault tolerance"; all once-per-marker gated so a
re-meshed restart runs clean, all firing at the end of the named epoch —
AFTER that epoch's checkpoint, like a real mid-grid loss with durable state
on disk):

- ``host_drop:H[:EPOCH]`` — host H's partition of the mesh "dies": raises
  the typed :class:`~redcliff_tpu.parallel.remesh.HostLostError` directly
  (the watchdog's stale-host detection route, pre-classified). Default
  epoch 1;
- ``device_lost[:EPOCH]`` — raises a RuntimeError with an XLA-shaped
  device-loss message, exercising the
  :func:`~redcliff_tpu.parallel.remesh.classify_device_error` mapping in
  the grid engine (explicit device-loss-signal route);
- ``coordinator_loss[:EPOCH]`` — raises a RuntimeError with a coordinator
  heartbeat-timeout message (the coordinator-loss route through the same
  classifier).

All three surface as exit code ``EXIT_HOST_LOST`` (21) from the child, so
the supervisor re-meshes and restarts instead of restarting at the same
shape. :func:`random_host_fault_schedule` composes seeded host-fault
schedules for the host-drop chaos soak (tests/test_remesh.py).

:func:`random_fault_schedule` composes seeded schedules from this full
grammar (kill / nan / hang / torn write / slow IO / disk error) for the
chaos soak harness (tests/test_supervisor.py): a supervised run under ANY
schedule must terminate with correct final artifacts.

Numerical fault points (consumed through :func:`poison_batch` /
:func:`skip_update`, called by the trainers with a global step index; step
specs are either one step ``"5"`` or an inclusive range ``"5-8"``):

- ``nan_batch:SPEC`` — replace the training batch at the matching step(s)
  with all-NaN input (the classic poisoned-batch event the in-graph
  numerics guard must catch);
- ``grad_blowup:SPEC`` — scale the batch by 1e30 so the loss/gradients
  overflow to inf at the matching step(s) (exploding-gradient event);
- ``skip_update:SPEC`` — make the trainer skip the parameter update for the
  matching step(s) entirely. This is the *reference semantics* for the
  guard: a guarded fit with ``nan_batch:K`` must end bit-identical to a
  clean fit with ``skip_update:K``.

Fleet fault point (the fleet chaos harness, fleet/chaos.py — the service
half of the containment story, docs/ARCHITECTURE.md "Fleet failure
containment"):

- ``fleet_poison`` — arms the ``__chaos__`` poison sentinels in fleet grid
  points: the batch driver :func:`detonates <redcliff_tpu.fleet.chaos
  .detonate>` (SIGKILL / exit N / hang) BEFORE the fit, simulating a tenant
  request that deterministically kills any batch it is merged into. Unarmed,
  the driver strips the sentinels and fits the underlying healthy points.

jax is imported lazily: the module is importable by backend-free processes.
"""
from __future__ import annotations

import argparse
import errno as _errno
import os
import pickle
import random
import signal
import sys

from redcliff_tpu.runtime.watchdog import (EXIT_DEADLINE, EXIT_HOST_LOST,
                                           EXIT_PREEMPTED)

__all__ = ["armed", "fleet_poison_armed", "crash_point", "ckpt_write_point",
           "poison_batch", "skip_update", "hang_point", "io_point",
           "io_error_point", "corrupt_checkpoint", "flaky",
           "random_fault_schedule", "random_host_fault_schedule",
           "tiny_grid_fit", "tiny_sharded_fit"]

ENV_SPEC = "REDCLIFF_FAULT_INJECT"
ENV_MARKER = "REDCLIFF_FAULT_MARKER"
# the preempted exit code predates the watchdog taxonomy; it IS taxonomy
# code 17 now (runtime/watchdog.py), re-exported for the older tests
PREEMPTED_EXIT_CODE = EXIT_PREEMPTED


def _active_faults():
    spec = os.environ.get(ENV_SPEC)
    if not spec:
        return ()
    out = []
    for part in spec.split(","):
        name, _, arg = part.strip().partition(":")
        if name:
            out.append((name, arg))
    return tuple(out)


def armed():
    """True when ANY fault is armed. The engines use this to serialize
    otherwise-asynchronous work (e.g. wait for the background checkpoint
    writer before a crash point) so fault tests stay deterministic."""
    return bool(os.environ.get(ENV_SPEC))


def fleet_poison_armed():
    """True when the fleet chaos grammar's ``fleet_poison`` fault is armed:
    the fleet batch driver then ACTS on ``__chaos__`` poison sentinels in
    grid points (fleet/chaos.py) instead of only stripping them."""
    return any(name == "fleet_poison" for name, _ in _active_faults())


def ckpt_write_point(stage, path=None):
    """Hook inside ``runtime.checkpoint.write_checkpoint``'s crash window
    (head renamed to ``.prev``, new generation not yet promoted).

    ``hang_between_ckpt_replaces:SECONDS`` writes the ``REDCLIFF_FAULT_MARKER``
    file (once) and then sleeps, holding the window open so a parent process
    can SIGKILL this one mid-write — the on-disk state is then exactly
    "head missing, .prev intact", which resume must recover from.
    """
    for name, arg in _active_faults():
        if (name == "hang_between_ckpt_replaces"
                and stage == "between_replaces"):
            marker = os.environ.get(ENV_MARKER)
            if marker and not os.path.exists(marker):
                with open(marker, "w") as f:
                    f.write(path or "")
                import time

                time.sleep(float(arg) if arg else 30.0)


def crash_point(stage, epoch=None):
    """Hook called by the training loop at named stages; inert unless a fault
    matching (stage, epoch) is armed via the environment."""
    for name, arg in _active_faults():
        if (name == "sigkill_after_checkpoint" and stage == "checkpoint_saved"
                and epoch == int(arg)):
            os.kill(os.getpid(), signal.SIGKILL)
        if (name == "marker_after_epoch" and stage == "epoch_end"
                and epoch == int(arg)):
            marker = os.environ.get(ENV_MARKER)
            if marker and not os.path.exists(marker):
                with open(marker, "w") as f:
                    f.write(str(epoch))
        if name in HOST_FAULT_KINDS and stage == "epoch_end":
            _host_fault(name, arg, epoch)


def _host_fault(name, arg, epoch):
    """Raise the armed host fault when its epoch matches (default: end of
    epoch 1 — after that epoch's checkpoint, so durable state exists like a
    real mid-grid host loss). Once-per-marker gated: the re-meshed restart
    runs clean and the loss->re-mesh->resume loop closes."""
    if name == "host_drop":
        host_s, _, ep_s = arg.partition(":")
        host = int(host_s) if host_s else 0
    else:
        host, ep_s = None, arg
    if epoch != (int(ep_s) if ep_s else 1) or not _once_guard(f".{name}"):
        return
    if name == "host_drop":
        from redcliff_tpu.parallel.remesh import HostLostError

        raise HostLostError("host_drop", host=host,
                            detail=f"injected at epoch {epoch}")
    if name == "device_lost":
        # XLA-shaped device-loss text: must trip
        # remesh.classify_device_error -> "device_lost" in the grid engine
        raise RuntimeError(
            f"INTERNAL: device lost: local device vanished mid-dispatch "
            f"(injected host fault, epoch {epoch})")
    raise RuntimeError(
        f"DEADLINE_EXCEEDED: coordinator heartbeat timed out; distributed "
        f"runtime service unavailable (injected host fault, epoch {epoch})")


def _step_match(spec, step):
    """``"5"`` matches step 5; ``"5-8"`` matches steps 5..8 inclusive."""
    lo, sep, hi = spec.partition("-")
    if sep:
        return int(lo) <= step <= int(hi)
    return step == int(lo)


def poison_batch(X, step):
    """Numerical fault point: trainers pass every training batch through this
    with their global step index. Inert (returns ``X`` untouched, one env
    lookup) unless a ``nan_batch``/``grad_blowup`` fault matches ``step``."""
    faults = _active_faults()
    if not faults:
        return X
    import numpy as np

    for name, arg in faults:
        if name == "nan_batch" and _step_match(arg, step):
            bad = np.array(X, dtype=np.float32, copy=True)
            bad[...] = np.nan
            return bad
        if name == "grad_blowup" and _step_match(arg, step):
            # 1e30 overflows the squared-error loss/grads to inf in f32
            return np.array(X, dtype=np.float32) * np.float32(1e30)
    return X


def skip_update(step):
    """True when a ``skip_update`` fault matches ``step`` — the trainer skips
    the parameter update entirely (batch drawn, rng advanced). Reference
    semantics for the in-graph guard's skip."""
    for name, arg in _active_faults():
        if name == "skip_update" and _step_match(arg, step):
            return True
    return False


def _once_guard(suffix):
    """True when this fault may fire: with ``REDCLIFF_FAULT_MARKER`` set the
    fault fires once per marker (a ``<marker><suffix>`` guard file is
    written), so a supervisor-restarted attempt runs clean; without a marker
    the fault fires every time (unit-test mode)."""
    marker = os.environ.get(ENV_MARKER)
    if not marker:
        return True
    guard = marker + suffix
    if os.path.exists(guard):
        return False
    with open(guard, "w") as f:
        f.write(suffix)
    return True


def hang_point(component):
    """Liveness fault point: wedge ``component`` (sleep) when a matching
    ``hang_in:component:S`` fault is armed. Placed next to the component's
    heartbeat stamp, so the stamp stops and the watchdog must notice."""
    for name, arg in _active_faults():
        if name != "hang_in":
            continue
        comp, _, secs = arg.partition(":")
        if comp != component or not _once_guard(f".hang_{component}"):
            continue
        import time

        time.sleep(float(secs) if secs else 3600.0)


def io_point(kind):
    """Latency fault point: ``slow_io:MS`` sleeps MS milliseconds at every
    IO site (``kind`` is informational — degraded storage is global)."""
    for name, arg in _active_faults():
        if name == "slow_io":
            import time

            time.sleep((float(arg) if arg else 10.0) / 1e3)


def io_error_point(kind):
    """Disk-failure fault point: ``io_error:KIND[:ERRNO]`` raises an
    injected ``OSError`` (default ENOSPC — disk full) at the named IO site.
    Once-per-marker gated like :func:`hang_point`."""
    for name, arg in _active_faults():
        if name != "io_error":
            continue
        k, _, en = arg.partition(":")
        if k != kind or not _once_guard(f".ioerr_{kind}"):
            continue
        code = getattr(_errno, en, _errno.ENOSPC) if en else _errno.ENOSPC
        raise OSError(code, f"{os.strerror(code)} (injected at {kind})")


# the full chaos grammar the schedule fuzzer draws from; every entry must
# leave a supervised run able to TERMINATE (hangs are once-per-marker and
# watchdog-evictable, kills land after a durable checkpoint generation)
FAULT_KINDS = ("kill", "nan", "hang", "torn_write", "slow_io", "io_error")

# the host-fault grammar (all once-per-marker; all raise out of epoch_end)
HOST_FAULT_KINDS = ("host_drop", "device_lost", "coordinator_loss")


def random_host_fault_schedule(seed, max_epoch=1, n_hosts=4):
    """One seeded host-fault schedule for the host-drop chaos soak: a host
    drop / device loss / coordinator loss at a random epoch, optionally
    composed with degraded-storage latency. Deterministic in ``seed``; every
    schedule must leave a supervised-with-mesh run able to terminate (the
    fault is once-per-marker and fires after a durable checkpoint)."""
    r = random.Random(seed)
    kind = r.choice(HOST_FAULT_KINDS)
    ep = r.randint(0, max_epoch)
    if kind == "host_drop":
        fault = f"host_drop:{r.randrange(max(n_hosts, 1))}:{ep}"
    else:
        fault = f"{kind}:{ep}"
    faults = [fault]
    if r.random() < 0.5:
        faults.append(f"slow_io:{r.randint(1, 20)}")
    return ",".join(faults)


def random_fault_schedule(seed, max_epoch=2, components=("prefetch",
                                                         "shard_loader",
                                                         "ckpt_writer")):
    """One seeded random fault schedule (an ``REDCLIFF_FAULT_INJECT`` spec)
    composed from the full grammar: kill / nan / hang / torn write / slow IO
    / disk error. Deterministic in ``seed``; 1-2 faults per schedule so
    compositions (e.g. slow IO + a mid-write kill) occur across the soak."""
    r = random.Random(seed)
    faults = []
    for kind in r.sample(FAULT_KINDS, r.randint(1, 2)):
        if kind == "kill":
            faults.append(
                f"sigkill_after_checkpoint:{r.randint(0, max_epoch)}")
        elif kind == "nan":
            faults.append(f"nan_batch:{r.randint(0, 5)}")
        elif kind == "hang":
            faults.append(f"hang_in:{r.choice(components)}:600")
        elif kind == "torn_write":
            faults.append("hang_between_ckpt_replaces:600")
        elif kind == "slow_io":
            faults.append(f"slow_io:{r.randint(1, 25)}")
        elif kind == "io_error":
            faults.append("io_error:ckpt_write:ENOSPC")
    return ",".join(faults)


def corrupt_checkpoint(path, mode="truncate"):
    """Damage a checkpoint file in a controlled way.

    ``truncate`` cuts the file to half its length (torn write / full disk);
    ``flip_payload`` inverts a byte past the header (silent media corruption
    the CRC must catch); ``zero_header`` wipes the magic+version header.
    """
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        if mode == "truncate":
            f.truncate(max(size // 2, 1))
        elif mode == "flip_payload":
            off = min(40, size - 1)
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 0xFF]))
        elif mode == "zero_header":
            f.write(b"\0" * min(8, size))
        else:
            raise ValueError(f"unknown corruption mode {mode!r}")


def flaky(n_failures, value=True, exc=None):
    """A probe-shaped callable that fails ``n_failures`` times then succeeds:
    returns ``(False, 'injected failure k')`` (or raises ``exc``) while
    failing, then ``(value, 'ok')``. For asserting retry/backoff schedules."""
    state = {"calls": 0}

    def probe(_attempt=None):
        state["calls"] += 1
        if state["calls"] <= n_failures:
            if exc is not None:
                raise exc
            return False, f"injected failure {state['calls']}"
        return value, "ok"

    probe.calls = lambda: state["calls"]
    return probe


# ---------------------------------------------------------------------------
# the child fit: one small deterministic grid fit, identical whether run
# in-process or as a subprocess, so killed/resumed/uninterrupted legs are
# directly comparable
# ---------------------------------------------------------------------------
def _tiny_runner(max_iter, bad_point=False, fit_deadline_s=None,
                 grid_deadline_s=None, grid_size=2, use_mesh=False):
    """The harness's canonical small grid runner plus its deterministic data
    arrays (shared by the in-memory and sharded child fits).

    ``grid_size`` widens the sweep for mesh-shaped tests (the default 2
    keeps the historical point list byte-for-byte, so older fault tests'
    bit-identity baselines are untouched); ``use_mesh`` shards the grid over
    the largest viable mesh of the VISIBLE devices — capped by
    ``REDCLIFF_MESH_DEVICES``, i.e. the supervisor's re-mesh decisions are
    honored (parallel/remesh.py)."""
    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")

    from redcliff_tpu.models.redcliff import RedcliffSCMLP, RedcliffSCMLPConfig
    from redcliff_tpu.parallel.grid import GridSpec, RedcliffGridRunner
    from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig

    model = RedcliffSCMLP(RedcliffSCMLPConfig(
        num_chans=4, gen_lag=2, gen_hidden=(8,), embed_lag=4,
        embed_hidden_sizes=(8,), num_factors=2, num_supervised_factors=2,
        factor_weight_l1_coeff=0.01, adj_l1_reg_coeff=0.001,
        factor_cos_sim_coeff=0.01,
        factor_score_embedder_type="Vanilla_Embedder",
        primary_gc_est_mode="fixed_factor_exclusive", num_sims=1,
        training_mode="combined"))
    # 1e20 (not merely "large"): Adam-normalized updates bound the step to
    # ~lr, so the poison lr must push params past sqrt(f32 max) for the
    # squared forecast error to overflow to inf within an epoch
    if grid_size == 2:
        points = [{"gen_lr": 1e-3},
                  ({"gen_lr": 1e20, "embed_lr": 1e20} if bad_point
                   else {"gen_lr": 3e-3})]
    else:
        points = [{"gen_lr": 1e-3 * (1 + 0.5 * i)} for i in range(grid_size)]
        if bad_point:
            points[-1] = {"gen_lr": 1e20, "embed_lr": 1e20}
    tc = RedcliffTrainConfig(max_iter=max_iter, batch_size=16, check_every=1,
                             seed=0)
    spec = GridSpec(points=points, fit_deadline_s=fit_deadline_s,
                    grid_deadline_s=grid_deadline_s)
    mesh = None
    if use_mesh:
        from redcliff_tpu.parallel import remesh as _remesh

        mesh = _remesh.visible_mesh(n_lanes=len(points))
    runner = RedcliffGridRunner(model, tc, spec, mesh=mesh)
    cfg = model.config
    rng = np.random.default_rng(0)
    T = cfg.max_lag + cfg.num_sims
    X = rng.normal(size=(48, T, cfg.num_chans)).astype(np.float32)
    Y = rng.uniform(size=(48, 3, 1)).astype(np.float32)
    return runner, X, Y


def tiny_grid_fit(checkpoint_dir, max_iter=4, checkpoint_every=1,
                  bad_point=False, fit_deadline_s=None, grid_deadline_s=None,
                  grid_size=2, use_mesh=False):
    """Run the harness's canonical small grid fit and return its GridResult.

    ``bad_point`` swaps the last point's learning rate for an absurd value
    that drives its loss non-finite within an epoch (exercises the
    non-finite quarantine path). Everything is seeded; two invocations with
    the same arguments produce bit-identical results on the same backend.
    ``grid_size``/``use_mesh``: see :func:`_tiny_runner` — the mesh-sharded
    child for the host-fault acceptance tests.
    """
    import jax

    from redcliff_tpu.data.datasets import ArrayDataset

    runner, X, Y = _tiny_runner(max_iter, bad_point=bad_point,
                                fit_deadline_s=fit_deadline_s,
                                grid_deadline_s=grid_deadline_s,
                                grid_size=grid_size, use_mesh=use_mesh)
    ds = ArrayDataset(X, Y)
    return runner.fit(jax.random.PRNGKey(2), ds, ds,
                      checkpoint_dir=checkpoint_dir,
                      checkpoint_every=checkpoint_every,
                      log_dir=checkpoint_dir)


def tiny_sharded_fit(checkpoint_dir, max_iter=4, checkpoint_every=1,
                     fit_deadline_s=None, grid_deadline_s=None):
    """The supervised-run child: the same tiny grid fit, but streamed from
    on-disk shards so the host path exercises EVERY watchdog-stamped
    component — per-batch loop, double-buffered prefetcher, shard loader,
    async checkpoint writer. The shards are written deterministically under
    ``<checkpoint_dir>/shards`` (idempotent, so a supervisor-restarted
    attempt reuses them) and the fit is bit-identical across restarts."""
    import jax

    from redcliff_tpu.data.shards import ShardedBatchDataset

    runner, X, Y = _tiny_runner(max_iter, fit_deadline_s=fit_deadline_s,
                                grid_deadline_s=grid_deadline_s)
    split = os.path.join(checkpoint_dir, "shards", "train")
    if not os.path.isdir(split):
        os.makedirs(split)
        half = len(X) // 2
        for i, sl in enumerate((slice(0, half), slice(half, None))):
            with open(os.path.join(split, f"subset_{i}.pkl"), "wb") as f:
                pickle.dump([[x, y] for x, y in zip(X[sl], Y[sl])], f)
    # data STAGING is supervised too: the construction-time stats pass reads
    # every shard, and a read wedged there (hang_in:shard_loader fires on the
    # first load) would otherwise hang before the fit's own watchdog exists
    from redcliff_tpu.runtime import watchdog as rt_watchdog

    with rt_watchdog.maybe_start():
        train = ShardedBatchDataset(split)
        val = ShardedBatchDataset(split)
    return runner.fit(jax.random.PRNGKey(2), train, val,
                      checkpoint_dir=checkpoint_dir,
                      checkpoint_every=checkpoint_every,
                      log_dir=checkpoint_dir)


def _result_blob(result):
    import jax
    import numpy as np

    return {
        "val_history": np.asarray(result.val_history),
        "best_criteria": np.asarray(result.best_criteria),
        "best_epoch": np.asarray(result.best_epoch),
        "active": np.asarray(result.active),
        "failures": result.failures,
        "best_params_leaves": [np.asarray(l)
                               for l in jax.tree.leaves(result.best_params)],
    }


def _parse_deadlines(spec):
    """``"inf,0.05"`` -> per-lane deadline list; ``"30"`` -> scalar."""
    if spec is None:
        return None
    parts = [float(p) for p in spec.split(",")]
    return parts[0] if len(parts) == 1 else parts


def _child_main(argv):
    ap = argparse.ArgumentParser(prog="faultinject-child")
    ap.add_argument("--checkpoint-dir", required=True)
    ap.add_argument("--max-iter", type=int, default=4)
    ap.add_argument("--checkpoint-every", type=int, default=1)
    ap.add_argument("--bad-point", action="store_true")
    ap.add_argument("--sharded", action="store_true",
                    help="stream the data from on-disk shards (exercises the "
                         "prefetch/shard-loader heartbeats — the supervised "
                         "chaos child)")
    ap.add_argument("--grid-size", type=int, default=2,
                    help="number of grid points (2 = the historical tiny "
                         "fit; larger = the mesh-shaped host-fault child)")
    ap.add_argument("--mesh", action="store_true",
                    help="shard the grid over the largest viable mesh of "
                         "the visible devices (REDCLIFF_MESH_DEVICES-capped "
                         "— the supervisor's re-mesh decisions apply)")
    ap.add_argument("--fit-deadline-s", default=None,
                    help="per-lane wall-clock budget(s), comma separated")
    ap.add_argument("--grid-deadline-s", type=float, default=None)
    ap.add_argument("--result", default=None,
                    help="write the finished fit's result blob here")
    args = ap.parse_args(argv)

    from redcliff_tpu.parallel.remesh import HostLostError
    from redcliff_tpu.runtime.preempt import DeadlineExceeded, Preempted

    kw = dict(max_iter=args.max_iter,
              checkpoint_every=args.checkpoint_every,
              fit_deadline_s=_parse_deadlines(args.fit_deadline_s),
              grid_deadline_s=args.grid_deadline_s)
    try:
        if args.sharded:
            result = tiny_sharded_fit(args.checkpoint_dir, **kw)
        else:
            result = tiny_grid_fit(args.checkpoint_dir,
                                   bad_point=args.bad_point,
                                   grid_size=args.grid_size,
                                   use_mesh=args.mesh, **kw)
    except HostLostError as e:
        # taxonomy code 21: part of the mesh is gone; the durable checkpoint
        # holds gathered host state — the supervisor's answer is a smaller
        # REDCLIFF_MESH_DEVICES and a restart, never a same-shape retry
        print(f"faultinject child: {e}", file=sys.stderr)
        raise SystemExit(EXIT_HOST_LOST)
    except Preempted as e:
        print(f"faultinject child: {e}", file=sys.stderr)
        # json.dump, not an f-string: signum is None on the watchdog-latched
        # preemption path, and Python's None is not JSON's null
        import json

        with open(os.path.join(args.checkpoint_dir, "preempted.json"),
                  "w") as f:
            json.dump({"signum": e.signum, "epoch": e.epoch}, f)
        raise SystemExit(PREEMPTED_EXIT_CODE)
    except DeadlineExceeded as e:
        # taxonomy code 20: checkpointed + resumable, but the budget is
        # spent — the supervisor must NOT burn it again on a restart
        print(f"faultinject child: {e}", file=sys.stderr)
        raise SystemExit(EXIT_DEADLINE)
    if args.result:
        with open(args.result, "wb") as f:
            pickle.dump(_result_blob(result), f)


if __name__ == "__main__":
    _child_main(sys.argv[1:])
