"""Preemption capture: turn SIGTERM/SIGINT into a final checkpoint.

SLURM preemption and TPU-VM maintenance both deliver SIGTERM with a grace
window of tens of seconds — enough to finish the in-flight epoch and write
one checkpoint, and exactly what the grid engine's bit-identical resume
needs to make preemption a pause instead of lost work.

The guard is deliberately cooperative: the signal handler only sets a flag
(async-signal-safe; no I/O or jax calls in handler context), and the training
loop polls it at epoch boundaries, saves, and raises :class:`Preempted`. A
second SIGINT falls through to the previous handler (normally
KeyboardInterrupt) so an interactive user can still force-quit a hung save.
"""
from __future__ import annotations

import signal

__all__ = ["Preempted", "DeadlineExceeded", "PreemptionGuard"]


class DeadlineExceeded(Exception):
    """A fit stopped because its wall-clock deadline expired at an epoch
    boundary, AFTER draining in-flight work and writing a final checkpoint —
    so a rerun against the same checkpoint_dir resumes losslessly (taxonomy
    exit code 20: the supervisor treats the budget as spent and does not
    burn it again; an outer scheduler re-queues with a fresh budget)."""

    def __init__(self, scope, epoch=None, elapsed_s=None, deadline_s=None):
        self.scope = scope
        self.epoch = epoch
        self.elapsed_s = elapsed_s
        self.deadline_s = deadline_s
        super().__init__(
            f"{scope} deadline of {deadline_s}s exceeded at epoch {epoch} "
            f"(elapsed {None if elapsed_s is None else round(elapsed_s, 1)}s);"
            f" final checkpoint written — rerun with the same checkpoint_dir "
            f"to resume")


class Preempted(Exception):
    """A fit stopped on SIGTERM/SIGINT after writing its final checkpoint."""

    def __init__(self, signum, epoch=None):
        self.signum = signum
        self.epoch = epoch
        name = signal.Signals(signum).name if signum is not None else "signal"
        super().__init__(
            f"fit preempted by {name} at epoch {epoch}; final checkpoint "
            f"written — rerun with the same checkpoint_dir to resume")


class PreemptionGuard:
    """Context manager that latches SIGTERM/SIGINT into ``self.preempted``.

    ``enabled=False`` (or installation from a non-main thread, where Python
    forbids signal handlers) degrades to an inert guard whose flag never
    sets, so call sites never branch. Previous handlers are restored on exit.
    """

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, enabled=True):
        self.enabled = enabled
        self.preempted = False
        self.signum = None
        self._previous = {}

    def _handle(self, signum, frame):
        if self.preempted and signum == signal.SIGINT:
            # second Ctrl-C: the user wants OUT, not another checkpoint
            prev = self._previous.get(signum)
            if callable(prev):
                prev(signum, frame)
                return
            raise KeyboardInterrupt
        self.preempted = True
        self.signum = signum

    def __enter__(self):
        if not self.enabled:
            return self
        try:
            for sig in self.SIGNALS:
                self._previous[sig] = signal.signal(sig, self._handle)
        except ValueError:  # not the main thread: signals are off the table
            self._previous = {}
        return self

    def __exit__(self, *exc):
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous = {}
        return False
