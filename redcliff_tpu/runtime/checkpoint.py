"""Durable checkpoint files: atomic writes, generations, CRC, quarantine.

The grid engine's original checkpoints were bare ``pickle.dump`` to a tmp file
plus ``os.replace`` — atomic against a crash between bytes, but with no way to
*detect* a torn/corrupt file (a truncated pickle raises deep inside
``pickle.load``), no previous generation to fall back to, and no format
version to evolve against. This module owns the file format; policy about
WHAT goes in a checkpoint (and which fits may resume it) stays with callers.

Format (version 1)::

    RTCK | u32 version | u32 crc32(payload) | u64 payload_len | payload

``payload`` is a pickle. A file failing any header/CRC/unpickle check raises
:class:`CheckpointCorruptError`; :func:`load_checkpoint` turns that into
quarantine-and-fall-back: the corrupt file is renamed to ``*.bad`` (preserved
for forensics, never re-read), the trailing ``*.prev`` generation is tried
next, and only if both generations are unusable does the caller see "no
checkpoint" (fresh start) — corrupt state never crashes a fit and never
silently resumes wrong.

Legacy headerless pickles (written before this module) are still readable:
they carry no CRC, so they are verified only by unpickling.

stdlib + numpy only — no jax at module scope (bench.py's parent imports the
runtime package and must never initialize a backend).
"""
from __future__ import annotations

import os
import pickle
import struct
import threading
import time
import warnings
import zlib

import numpy as np

from redcliff_tpu import obs as _obs
from redcliff_tpu.runtime import watchdog as _watchdog

__all__ = ["CheckpointCorruptError", "CheckpointWriteError",
           "write_checkpoint", "read_checkpoint",
           "load_checkpoint", "quarantine", "dataset_fingerprint",
           "AsyncCheckpointWriter", "FORMAT_VERSION"]

MAGIC = b"RTCK"
FORMAT_VERSION = 1
_HEADER = struct.Struct("<4sIIQ")  # magic, version, crc32, payload_len


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file exists but fails header/CRC/unpickle validation."""


class CheckpointWriteError(RuntimeError):
    """A checkpoint could not be written durably (ENOSPC/EIO/permission...).

    Carries ``path`` and ``errno`` so callers can distinguish disk-full from
    anything else; the tmp file has already been cleaned up and any existing
    on-disk generations are intact (the atomic-promotion protocol never
    damages them)."""

    def __init__(self, path, cause):
        self.path = path
        self.errno = getattr(cause, "errno", None)
        import errno as _errno

        hint = (" — disk full" if self.errno == _errno.ENOSPC else
                " — I/O error" if self.errno == _errno.EIO else "")
        super().__init__(
            f"could not write checkpoint {path}{hint}: {cause}")


def write_checkpoint(path, obj):
    """Atomically write ``obj`` to ``path`` with header+CRC, keeping the
    previous file as ``path + '.prev'``.

    The tmp file is fsynced before promotion, so after ``os.replace`` returns
    the new generation is on disk; a crash between the two replaces leaves
    only ``.prev``, which :func:`load_checkpoint` restores from. OS-level
    failures (disk full, EIO, permissions) are mapped to
    :class:`CheckpointWriteError` with the tmp file removed — the write
    failed CLEANLY: prior generations are untouched and no orphan tmp is
    left to fill the disk further.
    """
    # traced (ring-only) span: durable-write latency is flight-recorder
    # evidence — a post-mortem of a hang/ENOSPC shows the last writes and
    # how long they took. The span wraps pickle+fsync+promotion below via
    # record_span at the end (no context manager around the early-returning
    # error path)
    t_span0 = time.perf_counter()
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    header = _HEADER.pack(MAGIC, FORMAT_VERSION,
                          zlib.crc32(payload) & 0xFFFFFFFF, len(payload))
    # pid + thread id: concurrent writers (e.g. a background
    # AsyncCheckpointWriter racing a synchronous fallback save in the same
    # process) must never share a tmp file
    tmp = f"{path}.tmp{os.getpid()}.{threading.get_ident()}"
    armed = os.environ.get("REDCLIFF_FAULT_INJECT")
    try:
        with open(tmp, "wb") as f:
            f.write(header)
            f.write(payload)
            if armed:
                from redcliff_tpu.runtime import faultinject

                faultinject.io_point("ckpt_write")
                faultinject.io_error_point("ckpt_write")
            f.flush()
            os.fsync(f.fileno())
        if os.path.exists(path):
            os.replace(path, path + ".prev")
            # crash window: the head is gone and the new generation not yet
            # promoted — readers fall back to .prev. Fault injection widens
            # this window on purpose (SIGKILL-during-async-write test); one
            # env lookup when unarmed
            if armed:
                from redcliff_tpu.runtime import faultinject

                faultinject.ckpt_write_point("between_replaces", path=path)
        os.replace(tmp, path)
        _obs.record_span("ckpt.write", (time.perf_counter() - t_span0) * 1e3,
                         component="ckpt", file=os.path.basename(path),
                         bytes=len(payload))
    except OSError as e:
        try:
            os.remove(tmp)
        except OSError:
            pass
        _obs.record_span("ckpt.write", (time.perf_counter() - t_span0) * 1e3,
                         component="ckpt", file=os.path.basename(path),
                         error=type(e).__name__)
        raise CheckpointWriteError(path, e) from e


def read_checkpoint(path):
    """Read + verify one checkpoint file. Raises FileNotFoundError if absent,
    :class:`CheckpointCorruptError` on any validation failure."""
    with open(path, "rb") as f:
        head = f.read(_HEADER.size)
        if head[:4] != MAGIC:
            # legacy headerless pickle: no CRC to check; unpickle IS the test
            try:
                return pickle.loads(head + f.read())
            except Exception as e:
                raise CheckpointCorruptError(
                    f"{path}: neither a versioned checkpoint (bad magic "
                    f"{head[:4]!r}) nor a loadable legacy pickle ({e!r})")
        if len(head) < _HEADER.size:
            raise CheckpointCorruptError(
                f"{path}: truncated header ({len(head)} bytes)")
        _, version, crc, length = _HEADER.unpack(head)
        if version > FORMAT_VERSION:
            raise CheckpointCorruptError(
                f"{path}: format version {version} is newer than supported "
                f"({FORMAT_VERSION})")
        payload = f.read(length + 1)  # +1 detects trailing garbage
        if len(payload) != length:
            raise CheckpointCorruptError(
                f"{path}: payload length {len(payload)} != header {length} "
                f"(truncated or overwritten)")
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            raise CheckpointCorruptError(f"{path}: CRC mismatch")
        try:
            return pickle.loads(payload)
        except Exception as e:
            raise CheckpointCorruptError(
                f"{path}: CRC-valid payload failed to unpickle ({e!r})")


def quarantine(path, reason):
    """Move a corrupt checkpoint aside to ``path + '.bad'`` with a structured
    warning (never deleted: the bytes are evidence)."""
    bad = path + ".bad"
    try:
        os.replace(path, bad)
        action = f"quarantined to {bad}"
    except OSError as e:
        action = f"could not quarantine ({e})"
    warnings.warn(
        f"corrupt checkpoint {path}: {reason}; {action}",
        RuntimeWarning, stacklevel=3)
    return bad


def load_checkpoint(path, allow_quarantine=True):
    """Load the newest usable generation of ``path``.

    Tries ``path`` then ``path + '.prev'``; a corrupt generation is moved to
    ``*.bad`` (when ``allow_quarantine`` — multi-process callers restrict the
    rename to one process) and the next one is tried. Returns
    ``(obj, source_path)`` or ``(None, None)`` when no usable generation
    exists — corrupt state degrades to a fresh start, never a crash.
    """
    for cand in (path, path + ".prev"):
        try:
            return read_checkpoint(cand), cand
        except FileNotFoundError:
            continue
        except CheckpointCorruptError as e:
            if allow_quarantine:
                quarantine(cand, str(e))
            else:
                warnings.warn(f"corrupt checkpoint {cand}: {e} (skipped)",
                              RuntimeWarning, stacklevel=2)
    return None, None


class AsyncCheckpointWriter:
    """Background durable-checkpoint writer: at most one write in flight.

    ``submit(fn)`` first waits for any previous write (the completion
    barrier: generations stay ordered and two writes can never race on one
    path's tmp file), then runs ``fn`` — typically a closure around
    :func:`write_checkpoint` whose device->host materialization blocks in
    the *background* thread — and returns immediately. The caller's train
    loop keeps dispatching while the gather + pickle + fsync happen off the
    main thread.

    ``wait()`` joins the in-flight write and re-raises anything it threw —
    :class:`CheckpointWriteError` (disk full / EIO) comes back TYPED, so the
    failure surfaces at the next submit barrier or at fit end instead of the
    writer thread dying silently. Crash safety is unchanged from the synchronous
    path: :func:`write_checkpoint` is atomic with a ``.prev`` generation,
    so a SIGKILL mid-background-write leaves the previous generation
    loadable (pinned by tests/test_fault_injection.py).

    Callers owning DONATED device buffers must snapshot them (e.g.
    ``jnp.copy``) before submitting: the next train-step dispatch would
    otherwise invalidate the buffers under the background reader.
    """

    def __init__(self):
        self._thread = None
        self._err = None

    @property
    def in_flight(self):
        return self._thread is not None and self._thread.is_alive()

    def submit(self, fn):
        # the submit barrier: how long the MAIN thread stalls waiting for
        # the previous background write. Counted always (the grid folds it
        # into dispatch_stats.ckpt_barrier_stall_ms); ring-recorded when
        # tracing is on so a flight record shows barrier pressure
        t_bar0 = time.perf_counter()
        self.wait()
        stall_ms = (time.perf_counter() - t_bar0) * 1e3
        _obs.counters.add("ckpt_barrier_stall_ms", stall_ms)
        _obs.record_span("ckpt.submit_barrier", stall_ms, component="ckpt")

        def run():
            # liveness: the writer heartbeats while a write is in flight and
            # retires after, so idle gaps between saves can never read as a
            # hang — but a wedged gather/fsync goes stale and the watchdog
            # escalates (hang_in:ckpt_writer injects exactly that)
            _watchdog.stamp("ckpt_writer")
            try:
                if os.environ.get("REDCLIFF_FAULT_INJECT"):
                    from redcliff_tpu.runtime import faultinject

                    faultinject.hang_point("ckpt_writer")
                # the background write's span (gather + pickle + fsync)
                # nests the ckpt.write span recorded by write_checkpoint
                with _obs.span("ckpt.async_write", component="ckpt"):
                    fn()
            except BaseException as e:  # noqa: BLE001 — re-raised in wait()
                self._err = e
            finally:
                _watchdog.retire("ckpt_writer")

        self._thread = threading.Thread(target=run, name="ckpt-writer",
                                        daemon=True)
        self._thread.start()

    def wait(self):
        t, self._thread = self._thread, None
        if t is not None:
            t.join()
        err, self._err = self._err, None
        if err is not None:
            if isinstance(err, CheckpointWriteError):
                raise err  # typed: callers can tell disk-full from bugs
            raise RuntimeError(
                "background checkpoint write failed") from err

    # context-manager sugar: ``with AsyncCheckpointWriter() as w`` guarantees
    # the barrier on every exit path (including exceptions mid-fit)
    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.wait()
        else:
            # an exception is already propagating; don't let a background
            # write error mask it, but still honor the barrier
            try:
                self.wait()
            except RuntimeError:
                warnings.warn(
                    "background checkpoint write failed while another "
                    "exception was propagating", RuntimeWarning)
        return False


def dataset_fingerprint(ds):
    """A cheap shape-level identity for a dataset: enough to catch "resumed
    against different data" (the rng state would replay a different batch
    stream) without hashing the arrays. Works with ArrayDataset-style objects
    (``.X``/``.Y``) and falls back to ``len`` for anything else."""
    X = getattr(ds, "X", None)
    if X is not None:
        Y = getattr(ds, "Y", None)
        return {"X_shape": tuple(int(s) for s in np.shape(X)),
                "Y_shape": (None if Y is None
                            else tuple(int(s) for s in np.shape(Y)))}
    try:
        return {"len": len(ds)}
    except TypeError:
        return {"type": type(ds).__name__}
