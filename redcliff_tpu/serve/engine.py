"""Vmapped slot-table inference engine: O(1) cached-state advance per sample.

The serving compute core. A slot table of up to ``capacity`` lanes, each
owning the cached embedder state of one subscriber stream: a device-resident
ring buffer of that stream's last ``embed_lag`` samples. Advancing a stream
by one sample is O(1) state work — one ``(W, C)`` host->device transfer for
the whole tick's arrivals, one scatter into the ring, one ring-ordered
gather — instead of re-assembling and re-transferring each stream's full
sliding window every sample (the naive O(window) host path). All lanes step
through ONE jit'd dispatch per tick, so a chip serves every live stream at
one dispatch of overhead (the gang-scheduled batching idea;
ISSUE 17 / PAPERS.md O(1) autoregressive caching).

**Occupancy ladder (ISSUE 20).** The table's resident width ``W`` rides the
pow2 rung ladder (parallel/compaction.py :func:`serve_rung`), not the full
``capacity``: dead lanes beyond the highest leased slot are not dispatched.
:meth:`resize` moves between rungs at tick boundaries only — grow is a
zero-pad of fresh rows, shrink is a row slice — and both are EXACT, because
every lane at or beyond the live high-water mark holds all-zero state (the
recycle/connect reset invariant). Row-independence along the slot axis makes
rung moves math-free for survivors: lane i's outputs are a function of lane
i's ring alone, so changing which sibling rows ride the dispatch changes
which program runs, never what a lane computes (the same argument as
training-side compaction, tests/test_compaction.py).

**Tick fusion.** :meth:`step_fused` advances every lane up to F backlogged
samples in ONE ``lax.scan`` dispatch instead of F ticks: the scan body is
the identical single-tick advance, carried over the ring state, so the
fused trajectory is bit-equal to F sequential :meth:`step` calls at the
same width.

**Mixed precision.** ``precision_mode="mixed"`` traces the dispatch under
``jax.default_matmul_precision("bfloat16")`` — embedder contractions run
bf16 on the MXU while the ring buffer, carried state, and outputs stay f32
(the PR-14 recipe) — and routes the per-lane graph blend through the
autotuned factor-mix Pallas kernel on real TPUs (ops/factor_mix.py
:func:`graph_mix`; the exact reference einsum everywhere else).
:meth:`demote` is the NaN-storm sentinel's lever: it drops the table back
to full f32 and retraces — state is already f32, so demotion changes the
program, not the rings.

Isolation is a property of the math, not of scheduling: every per-lane
computation (ring scatter, ordered gather, embedder matmuls, graph blend)
is row-independent along the slot axis, so a NaN-spewing neighbor, a
mid-tick connect, or a reaped lane changes NOTHING in co-resident lanes'
bytes (the churn-isolation pin, tests/test_serve.py). Non-finite samples
are detected in-graph and NEVER written into ring state: the offending lane
latches ``poisoned`` and its sample is discarded; co-resident lanes cannot
even observe the event.

Graph readouts reuse the jit'd :func:`obs.quality.make_summary_fn` summary:
for the fixed (non-conditional) readout modes the per-factor GC matrices are
params-only, so they are computed ONCE at load and each sample's per-state
graph is just the ``graph_mix`` blend — per-lane independent by
construction.

jax imports are lazy (obs/schema.py LAZY_JAX_MODULES): the session/admission
control plane imports this package's siblings without a backend.
"""
from __future__ import annotations

import numpy as np

from redcliff_tpu.utils.precision import (
    check_precision_mode,
    matmul_precision_ctx,
    resolve_matmul_precision,
)

__all__ = ["StreamEngine"]


class StreamEngine:
    """Elastic slot table over a fitted REDCLIFF-family model.

    ``step``/``step_fused`` are the only hot paths: one call per tick, all
    resident lanes at once, at the current rung ``width <= capacity``.
    State lives on device between ticks; ``export_state``/``import_state``
    round-trip it through numpy for the drain checkpoint (``import_state``
    re-packs lanes across rung geometries given a ``slot_map``).
    """

    def __init__(self, model, params, capacity, precision_mode="f32"):
        import jax
        import jax.numpy as jnp

        from redcliff_tpu.obs import quality as _quality

        cfg = model.config
        self.model = model
        self.capacity = int(capacity)
        self.num_chans = int(cfg.num_chans)
        self.num_factors = int(cfg.num_factors)
        self.window_len = int(cfg.embed_lag)
        self._jax = jax
        self._jnp = jnp
        self.params = params
        self.platform = jax.default_backend()
        self.precision_mode = check_precision_mode(precision_mode)
        self.demoted = False
        self._matmul = resolve_matmul_precision(self.precision_mode)

        # static per-factor GC graphs: params-only for the fixed readout
        # modes quality.readout_mode forces, so ONE offline summary call at
        # load covers every future sample; the probe window only feeds the
        # entropy field, which we discard
        probe = jnp.zeros((1, int(cfg.max_lag), self.num_chans),
                          dtype=jnp.float32)
        summ = _quality.make_summary_fn(model)(params, probe)
        self.static_gc = jnp.asarray(summ["gc"], dtype=jnp.float32)

        self.width = self.capacity
        self.state = self._zero_state(self.width)
        # (width, depth) program keys dispatched at least once since the
        # last retrace — the ladder's cold-rung oracle (a cold key pays a
        # compile on first dispatch; the cost model prices that against the
        # dead-lane saving before any shrink)
        self._programs = set()
        self._build_steps()

    def _zero_state(self, width):
        jnp = self._jnp
        L, C = self.window_len, self.num_chans
        return {
            "window": jnp.zeros((width, L, C), dtype=jnp.float32),
            "pos": jnp.zeros((width,), dtype=jnp.int32),
            "filled": jnp.zeros((width,), dtype=jnp.int32),
            "poisoned": jnp.zeros((width,), dtype=bool),
        }

    def _build_steps(self):
        import jax
        import jax.numpy as jnp

        from redcliff_tpu.ops.factor_mix import graph_mix

        model = self.model
        static_gc = self.static_gc
        L = self.window_len

        def _advance(params, state, samples, arrive):
            window, pos = state["window"], state["pos"]
            filled, poisoned = state["filled"], state["poisoned"]
            lanes = jnp.arange(window.shape[0])

            finite = jnp.all(jnp.isfinite(samples), axis=-1)
            poison_hit = arrive & ~finite & ~poisoned
            accept = arrive & finite & ~poisoned
            poisoned_n = poisoned | poison_hit

            # ring scatter: ONLY accepted lanes write — a poison sample
            # never touches device state, so quarantine+recycle is the only
            # cleanup a poisoned lane ever needs
            cur = window[lanes, pos]
            window_n = window.at[lanes, pos].set(
                jnp.where(accept[:, None], samples, cur))
            pos_n = jnp.where(accept, (pos + 1) % L, pos)
            filled_n = jnp.where(accept, jnp.minimum(filled + 1, L), filled)
            ready = accept & (filled_n >= L)

            # ring-ordered gather (oldest -> newest): after writing at pos
            # and advancing, the oldest live sample sits at the new pos
            order = (pos_n[:, None] + jnp.arange(L)[None, :]) % L
            win = jnp.take_along_axis(window_n, order[:, :, None], axis=1)

            weightings, _ = model._embed(params, win)        # (W, K)
            scores = jnp.where(ready[:, None], weightings, 0.0)
            graph = jnp.where(ready[:, None, None],
                              graph_mix(scores, static_gc), 0.0)

            new_state = {"window": window_n, "pos": pos_n,
                         "filled": filled_n, "poisoned": poisoned_n}
            out = {"scores": scores.astype(jnp.float32),
                   "graph": graph.astype(jnp.float32),
                   "ready": ready, "poison_hit": poison_hit,
                   "poisoned": poisoned_n}
            return new_state, out

        def _fused(params, state, samples, arrive):
            # samples (W, F, C), arrive (W, F) -> time-major scan over F:
            # the carry is the ring state, the body is the EXACT single-tick
            # advance, so the fused trajectory bit-matches F sequential
            # dispatches; outputs stack with leading F
            xs = (jnp.moveaxis(samples, 1, 0), jnp.moveaxis(arrive, 1, 0))

            def body(st, x):
                return _advance(params, st, x[0], x[1])

            return jax.lax.scan(body, state, xs)

        self._step = jax.jit(_advance)
        self._fused = jax.jit(_fused)

    # ------------------------------------------------------------ dispatch
    def step(self, samples, arrive):
        """Advance every arriving lane one sample; one dispatch.

        ``samples``: ``(W, C)`` float32 (rows of non-arriving lanes are
        ignored); ``arrive``: ``(W,)`` bool, with ``W == self.width``.
        Returns a dict of HOST numpy arrays: ``scores (W, K)``, ``graph
        (W, C, C)``, ``ready (W,)`` (lane produced an output this tick:
        sample accepted AND ring full), ``poison_hit (W,)`` (lane newly
        poisoned by a non-finite sample this tick), ``poisoned (W,)``
        (latched state).
        """
        jnp = self._jnp
        samples = jnp.asarray(np.asarray(samples, dtype=np.float32))
        arrive = jnp.asarray(np.asarray(arrive, dtype=bool))
        self._programs.add((self.width, 1))
        with matmul_precision_ctx(self._matmul):
            self.state, out = self._step(self.params, self.state, samples,
                                         arrive)
        return {k: np.asarray(v) for k, v in out.items()}

    def step_fused(self, samples, arrive):
        """Advance every lane through up to F backlogged samples in ONE
        ``lax.scan`` dispatch. ``samples``: ``(W, F, C)``; ``arrive``:
        ``(W, F)`` (padding positions False). Returns the same dict as
        :meth:`step` with a leading F axis on every array — element f is
        bit-equal to what the f-th sequential :meth:`step` would return.
        """
        jnp = self._jnp
        samples = jnp.asarray(np.asarray(samples, dtype=np.float32))
        arrive = jnp.asarray(np.asarray(arrive, dtype=bool))
        self._programs.add((self.width, int(samples.shape[1])))
        with matmul_precision_ctx(self._matmul):
            self.state, out = self._fused(self.params, self.state, samples,
                                          arrive)
        return {k: np.asarray(v) for k, v in out.items()}

    def is_cold(self, width, depth=1):
        """True iff dispatching at (width, depth) would compile a fresh
        program — the ladder's pricing input."""
        return (int(width), int(depth)) not in self._programs

    # ------------------------------------------------------------ the ladder
    def resize(self, width):
        """Move the resident table to a new rung at a tick boundary.

        Grow zero-pads fresh rows (zero IS the reset state — padding a
        never-leased or recycled lane in is exactly ``reset_slot``); shrink
        slices rows off the top, which the caller guarantees are all free
        (rung >= live high-water mark). Either way every surviving lane's
        row bytes are untouched.
        """
        width = int(width)
        if width == self.width:
            return
        if not 1 <= width <= self.capacity:
            raise ValueError(f"rung {width} outside [1, {self.capacity}]")
        jnp = self._jnp
        if width < self.width:
            self.state = {k: v[:width] for k, v in self.state.items()}
        else:
            pad = self._zero_state(width - self.width)
            self.state = {k: jnp.concatenate([v, pad[k]], axis=0)
                          for k, v in self.state.items()}
        self.width = width

    def demote(self):
        """Mixed -> f32 (the poisoned-lane-storm sentinel's lever): retrace
        every program at full precision. Ring/master state is already f32,
        so only the programs change; returns True iff a demotion happened."""
        if self.precision_mode != "mixed" or self.demoted:
            return False
        self.demoted = True
        self._matmul = None
        self._programs = set()
        self._build_steps()
        return True

    # ------------------------------------------------------------ slots
    def reset_slot(self, slot):
        """Zero one lane's ring + flags (slot recycle / quarantine release).
        A single-lane ``.at[slot].set`` — co-resident lanes' state bytes are
        untouched by construction. A slot at or beyond the resident width is
        already in the all-zero off-rung state: no-op."""
        s = int(slot)
        if s >= self.width:
            return
        self.state = {
            "window": self.state["window"].at[s].set(0.0),
            "pos": self.state["pos"].at[s].set(0),
            "filled": self.state["filled"].at[s].set(0),
            "poisoned": self.state["poisoned"].at[s].set(False),
        }

    # ------------------------------------------------------------ durability
    def export_state(self):
        """Slot-table state as plain numpy at the CURRENT width (drain
        checkpoint payload; the service records the rung alongside)."""
        return {k: np.asarray(v) for k, v in self.state.items()}

    def import_state(self, snap, slot_map=None):
        """Restore slot-table state from :meth:`export_state` output.

        Without ``slot_map`` the checkpoint must match the engine's resident
        geometry exactly (the caller resizes to the recorded rung first); a
        mismatch is refused with BOTH geometries in the error. With
        ``slot_map`` (``{old_slot: new_slot}``) live lanes are re-packed
        row-by-row into the current geometry — the cross-capacity resume
        path — which only requires the per-lane ``(L, C)`` ring shape to
        match; unmapped destination rows are zeroed (free-lane invariant).
        """
        jnp = self._jnp
        want = {k: tuple(v.shape) for k, v in self.state.items()}
        got = {k: tuple(np.asarray(snap[k]).shape)
               for k in want if k in snap}
        if set(got) != set(want):
            raise ValueError(
                f"serve state geometry mismatch: checkpoint keys "
                f"{sorted(got)} vs engine keys {sorted(want)}")
        if slot_map is None:
            if want != got:
                raise ValueError(
                    f"serve state geometry mismatch: checkpoint {got} vs "
                    f"engine {want} — checkpoint table is "
                    f"{got['window'][0]}x{got['window'][1:]}, engine is "
                    f"{want['window'][0]}x{want['window'][1:]} (rung/"
                    f"capacity or model changed across restart; resume with "
                    f"a slot_map to re-pack lanes across rung geometries)")
            self.state = {
                "window": jnp.asarray(snap["window"], dtype=jnp.float32),
                "pos": jnp.asarray(snap["pos"], dtype=jnp.int32),
                "filled": jnp.asarray(snap["filled"], dtype=jnp.int32),
                "poisoned": jnp.asarray(snap["poisoned"], dtype=bool),
            }
            return
        lane_want = want["window"][1:]
        lane_got = got["window"][1:]
        if lane_got != lane_want:
            raise ValueError(
                f"serve state geometry mismatch: checkpoint {got} vs "
                f"engine {want} — per-lane ring {lane_got} vs {lane_want} "
                f"(model geometry changed; lanes cannot be re-packed)")
        old_w = got["window"][0]
        host = {
            "window": np.zeros((self.width,) + lane_want, dtype=np.float32),
            "pos": np.zeros((self.width,), dtype=np.int32),
            "filled": np.zeros((self.width,), dtype=np.int32),
            "poisoned": np.zeros((self.width,), dtype=bool),
        }
        for old, new in slot_map.items():
            old, new = int(old), int(new)
            if not (0 <= old < old_w and 0 <= new < self.width):
                raise ValueError(
                    f"slot_map {old}->{new} outside checkpoint table "
                    f"[0, {old_w}) / engine table [0, {self.width})")
            for k in host:
                host[k][new] = np.asarray(snap[k])[old]
        self.state = {
            "window": jnp.asarray(host["window"], dtype=jnp.float32),
            "pos": jnp.asarray(host["pos"], dtype=jnp.int32),
            "filled": jnp.asarray(host["filled"], dtype=jnp.int32),
            "poisoned": jnp.asarray(host["poisoned"], dtype=bool),
        }
