"""Vmapped slot-table inference engine: O(1) cached-state advance per sample.

The serving compute core. A fixed-capacity table of ``capacity`` lanes, each
owning the cached embedder state of one subscriber stream: a device-resident
ring buffer of that stream's last ``embed_lag`` samples. Advancing a stream
by one sample is O(1) state work — one ``(S, C)`` host->device transfer for
the whole tick's arrivals, one scatter into the ring, one ring-ordered
gather — instead of re-assembling and re-transferring each stream's full
sliding window every sample (the naive O(window) host path). All lanes step
through ONE jit'd dispatch per tick, so a chip serves ``capacity`` streams
at one dispatch of overhead (the gang-scheduled batching idea;
ISSUE 17 / PAPERS.md O(1) autoregressive caching).

Isolation is a property of the math, not of scheduling: every per-lane
computation (ring scatter, ordered gather, embedder matmuls, graph einsum)
is row-independent along the slot axis, so lane i's outputs are a function
of lane i's ring alone — a NaN-spewing neighbor, a mid-tick connect, or a
reaped lane changes NOTHING in co-resident lanes' bytes (the churn-isolation
pin, tests/test_serve.py). Non-finite samples are detected in-graph and
NEVER written into ring state: the offending lane latches ``poisoned`` and
its sample is discarded; co-resident lanes cannot even observe the event.

Graph readouts reuse the jit'd :func:`obs.quality.make_summary_fn` summary:
for the fixed (non-conditional) readout modes the per-factor GC matrices are
params-only, so they are computed ONCE at load and each sample's per-state
graph is just ``einsum('sk,kij->sij', weightings, static_gc)`` — per-lane
independent by construction.

jax imports are lazy (obs/schema.py LAZY_JAX_MODULES): the session/admission
control plane imports this package's siblings without a backend.
"""
from __future__ import annotations

import numpy as np

__all__ = ["StreamEngine"]


class StreamEngine:
    """Fixed-capacity slot table over a fitted REDCLIFF-family model.

    ``step`` is the only hot path: one call per tick, all slots at once.
    State lives on device between ticks; ``export_state``/``import_state``
    round-trip it through numpy for the drain checkpoint.
    """

    def __init__(self, model, params, capacity):
        import jax
        import jax.numpy as jnp

        from redcliff_tpu.obs import quality as _quality

        cfg = model.config
        self.model = model
        self.capacity = int(capacity)
        self.num_chans = int(cfg.num_chans)
        self.num_factors = int(cfg.num_factors)
        self.window_len = int(cfg.embed_lag)
        self._jnp = jnp
        self.params = params

        # static per-factor GC graphs: params-only for the fixed readout
        # modes quality.readout_mode forces, so ONE offline summary call at
        # load covers every future sample; the probe window only feeds the
        # entropy field, which we discard
        probe = jnp.zeros((1, int(cfg.max_lag), self.num_chans),
                          dtype=jnp.float32)
        summ = _quality.make_summary_fn(model)(params, probe)
        self.static_gc = jnp.asarray(summ["gc"], dtype=jnp.float32)

        S, L, C = self.capacity, self.window_len, self.num_chans
        self.state = {
            "window": jnp.zeros((S, L, C), dtype=jnp.float32),
            "pos": jnp.zeros((S,), dtype=jnp.int32),
            "filled": jnp.zeros((S,), dtype=jnp.int32),
            "poisoned": jnp.zeros((S,), dtype=bool),
        }

        static_gc = self.static_gc

        def _step(params, state, samples, arrive):
            window, pos = state["window"], state["pos"]
            filled, poisoned = state["filled"], state["poisoned"]
            lanes = jnp.arange(S)

            finite = jnp.all(jnp.isfinite(samples), axis=-1)
            poison_hit = arrive & ~finite & ~poisoned
            accept = arrive & finite & ~poisoned
            poisoned_n = poisoned | poison_hit

            # ring scatter: ONLY accepted lanes write — a poison sample
            # never touches device state, so quarantine+recycle is the only
            # cleanup a poisoned lane ever needs
            cur = window[lanes, pos]
            window_n = window.at[lanes, pos].set(
                jnp.where(accept[:, None], samples, cur))
            pos_n = jnp.where(accept, (pos + 1) % L, pos)
            filled_n = jnp.where(accept, jnp.minimum(filled + 1, L), filled)
            ready = accept & (filled_n >= L)

            # ring-ordered gather (oldest -> newest): after writing at pos
            # and advancing, the oldest live sample sits at the new pos
            order = (pos_n[:, None] + jnp.arange(L)[None, :]) % L
            win = jnp.take_along_axis(window_n, order[:, :, None], axis=1)

            weightings, _ = model._embed(params, win)        # (S, K)
            scores = jnp.where(ready[:, None], weightings, 0.0)
            graph = jnp.where(ready[:, None, None],
                              jnp.einsum("sk,kij->sij", scores, static_gc),
                              0.0)

            new_state = {"window": window_n, "pos": pos_n,
                         "filled": filled_n, "poisoned": poisoned_n}
            out = {"scores": scores.astype(jnp.float32),
                   "graph": graph.astype(jnp.float32),
                   "ready": ready, "poison_hit": poison_hit,
                   "poisoned": poisoned_n}
            return new_state, out

        self._step = jax.jit(_step)

    def step(self, samples, arrive):
        """Advance every arriving lane one sample; one dispatch.

        ``samples``: ``(S, C)`` float32 (rows of non-arriving lanes are
        ignored); ``arrive``: ``(S,)`` bool. Returns a dict of HOST numpy
        arrays: ``scores (S, K)``, ``graph (S, C, C)``, ``ready (S,)``
        (lane produced an output this tick: sample accepted AND ring full),
        ``poison_hit (S,)`` (lane newly poisoned by a non-finite sample this
        tick), ``poisoned (S,)`` (latched state).
        """
        jnp = self._jnp
        samples = jnp.asarray(np.asarray(samples, dtype=np.float32))
        arrive = jnp.asarray(np.asarray(arrive, dtype=bool))
        self.state, out = self._step(self.params, self.state, samples,
                                     arrive)
        return {k: np.asarray(v) for k, v in out.items()}

    def reset_slot(self, slot):
        """Zero one lane's ring + flags (slot recycle / quarantine release).
        A single-lane ``.at[slot].set`` — co-resident lanes' state bytes are
        untouched by construction."""
        jnp = self._jnp
        s = int(slot)
        self.state = {
            "window": self.state["window"].at[s].set(0.0),
            "pos": self.state["pos"].at[s].set(0),
            "filled": self.state["filled"].at[s].set(0),
            "poisoned": self.state["poisoned"].at[s].set(False),
        }

    def export_state(self):
        """Slot-table state as plain numpy (drain checkpoint payload)."""
        return {k: np.asarray(v) for k, v in self.state.items()}

    def import_state(self, snap):
        """Restore slot-table state from :meth:`export_state` output.
        Shape-checked: a checkpoint from a different capacity/model geometry
        is refused rather than silently misapplied."""
        jnp = self._jnp
        want = {k: tuple(v.shape) for k, v in self.state.items()}
        got = {k: tuple(np.asarray(snap[k]).shape) for k in want}
        if want != got:
            raise ValueError(
                f"serve state geometry mismatch: checkpoint {got} vs "
                f"engine {want} (capacity/model changed across restart?)")
        self.state = {
            "window": jnp.asarray(snap["window"], dtype=jnp.float32),
            "pos": jnp.asarray(snap["pos"], dtype=jnp.int32),
            "filled": jnp.asarray(snap["filled"], dtype=jnp.int32),
            "poisoned": jnp.asarray(snap["poisoned"], dtype=bool),
        }
