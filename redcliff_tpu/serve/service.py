"""Streaming inference service: supervision + dispatch over the slot table.

The serving loop that ties the layers together: the
:class:`~redcliff_tpu.serve.engine.StreamEngine` slot table (device math),
the :class:`~redcliff_tpu.serve.session.SessionRegistry` (lease/heartbeat
supervision), the shared admission taxonomy (``SlotsExhausted``
reject-with-ETA), and the telemetry spine (schema-registered ``serve`` /
``session`` / ``serve_ladder`` / ``serve_fuse`` events, ``serve.dispatch``
spans, per-stream ``trace_id``).

**Tick discipline.** ``pump()`` is one tick: reap lapsed leases (recycled
lanes reset one-by-one, co-residents untouched), assemble pending samples
per ACTIVE stream into the arrival batch, run the occupancy-ladder policy,
ONE engine dispatch at the current rung, distribute outputs. ``run_loop``
rides the same tick through :func:`data.pipeline.prefetch_batches`
(depth=2), so host assembly of tick t+1 overlaps device compute of tick t —
the same double-buffered discipline the training engines use.

**Occupancy ladder (ISSUE 20).** ``REDCLIFF_SERVE_LADDER`` selects the
policy: ``off`` always dispatches the full ``capacity`` table (the PR-17
behavior, bit for bit); ``force`` always rides the smallest pow2 rung >=
the live high-water mark (deterministic — the CI ladder smoke);  ``auto``
(default) grows on demand (every leased slot MUST ride the dispatch — a
correctness move, never priced) and prices shrinks PR-15 style through the
PR-8 cost model: predicted dead-lane saving over
``REDCLIFF_SERVE_LADDER_HORIZON`` ticks vs the compile cost of a cold rung,
with ``REDCLIFF_SERVE_LADDER_HOLD`` ticks of hysteresis so occupancy
flutter cannot thrash programs. With NO evidence — empty store, no local
tick observations — auto holds the current (maximum) rung: the empty-store
fallback is bit-identical to ladder-off. Rung moves happen at tick
boundaries only, and per-stream records are pinned byte-identical across
them (row independence along the slot axis; tests/test_serve_elastic.py).

**Micro-batched tick fusion.** When a stream has in-queue backlog and
``REDCLIFF_SERVE_FUSE`` > 1, one dispatch advances up to that many samples
per lane through the engine's ``lax.scan`` program instead of N ticks.
Fusion composes with the degraded-QoS cadence ladder: the per-stream
``answered`` counter drives graph cadence exactly as if the samples had
arrived over N ticks, so readouts still thin under load and the record
stream is bit-identical to the unfused run.

**Mixed precision.** ``precision_mode="mixed"`` (or
``REDCLIFF_SERVE_PRECISION=mixed``) traces dispatches with bf16 MXU
contractions over f32 ring/master state and routes the graph blend through
the autotuned factor-mix Pallas kernel on real TPUs. The per-lane NaN latch
doubles as the demotion sentinel: ``REDCLIFF_SERVE_DEMOTE_STORM`` poisoned
lanes inside ``REDCLIFF_SERVE_DEMOTE_WINDOW`` ticks auto-demote the whole
table to f32 (retrace only — state is already f32), emit a schema-
registered ``precision`` event, and persist the demotion in
``serve_state.bin`` so a resume can never silently re-promote.

**Input contracts (per stream, never per table).** A shape-violating sample
quarantines its stream HOST-side (it never reaches the device); a
non-finite sample is detected in-graph and quarantines the stream with its
lane's ring untouched (the poison sample is discarded, the ``poisoned``
flag latches). Either way the stream degrades to a structured error state —
its subscriber polls the verdict — while co-resident lanes' outputs stay
bit-identical to a run where the poisoner never existed (pinned,
tests/test_serve.py).

**Overload ladder.** Admission rejects with ETA when slots are exhausted
(``SlotsExhausted``); a stream whose backlog climbs sheds graph-readout
cadence through :data:`QOS_CADENCE` rungs (factor scores keep flowing at
full rate — the cheap output — while the ``C x C`` graph emission thins)
BEFORE any latency SLO breach; per-sample ingest past the backlog cap gets
a structured non-accept; a slow consumer's out-queue drops ITS oldest
results past :data:`ENV_OUT_CAP` (counted) instead of growing without
bound or stalling siblings. Demotion is per-stream: one greedy subscriber
degrades alone.

**Drain.** ``drain()`` (or SIGTERM via :meth:`ServeService.
install_signal_handlers`) answers every in-flight sample, converts nothing
to loss, checkpoints sessions + slot-table rings + the active rung + the
precision state + undelivered outputs through runtime/checkpoint.py
(atomic, CRC, ``.prev``), and a restarted server resumes every session —
same ``trace_id``, same ring state, same undelivered outputs — with a
fresh lease so subscribers can re-attach. A restart into a DIFFERENT
capacity re-packs live lanes into the new geometry (dense from slot 0,
relative order preserved) instead of failing the shape check; only a table
too small for the live streams refuses, naming both geometries.

jax stays out of module scope (LAZY_JAX_MODULES): constructing/driving a
service in tests pulls jax only when the engine spins up.
"""
from __future__ import annotations

import os
import signal
import threading
import time
from collections import deque

import numpy as np

from redcliff_tpu import obs as _obs
from redcliff_tpu.obs import slo as _slo
from redcliff_tpu.obs.logging import MetricLogger
from redcliff_tpu.parallel import compaction as _compaction
from redcliff_tpu.runtime.admission import SlotsExhausted  # noqa: F401 (re-export)
from redcliff_tpu.runtime.checkpoint import (
    load_checkpoint,
    write_checkpoint,
)
from redcliff_tpu.serve import session as _session
from redcliff_tpu.utils.precision import check_precision_mode, precision_label

__all__ = ["ServeService", "ServeLadder", "SlotsExhausted",
           "ENV_SLOTS", "DEFAULT_SLOTS", "ENV_INGEST_CAP", "ENV_OUT_CAP",
           "ENV_LADDER", "ENV_FUSE", "ENV_PRECISION", "LADDER_MODES",
           "MIN_RUNG", "QOS_CADENCE", "STATE_BASENAME"]

ENV_SLOTS = "REDCLIFF_SERVE_SLOTS"
DEFAULT_SLOTS = 8
ENV_INGEST_CAP = "REDCLIFF_SERVE_INGEST_CAP"
DEFAULT_INGEST_CAP = 64
ENV_OUT_CAP = "REDCLIFF_SERVE_OUT_CAP"
DEFAULT_OUT_CAP = 256

# ---- occupancy ladder (ISSUE 20) ----
ENV_LADDER = "REDCLIFF_SERVE_LADDER"
DEFAULT_LADDER = "auto"
LADDER_MODES = ("off", "auto", "force")
# churn floor: below this rung another saved lane cannot pay for a cold
# program, and sub-4 tables thrash on any connect
MIN_RUNG = 4
ENV_LADDER_HOLD = "REDCLIFF_SERVE_LADDER_HOLD"
DEFAULT_LADDER_HOLD = 8
ENV_LADDER_HORIZON = "REDCLIFF_SERVE_LADDER_HORIZON"
DEFAULT_LADDER_HORIZON = 500

# ---- micro-batched tick fusion ----
ENV_FUSE = "REDCLIFF_SERVE_FUSE"
DEFAULT_FUSE = 1

# ---- mixed-precision serve path ----
ENV_PRECISION = "REDCLIFF_SERVE_PRECISION"
ENV_DEMOTE_STORM = "REDCLIFF_SERVE_DEMOTE_STORM"
DEFAULT_DEMOTE_STORM = 3
ENV_DEMOTE_WINDOW = "REDCLIFF_SERVE_DEMOTE_WINDOW"
DEFAULT_DEMOTE_WINDOW = 200

# degraded-QoS ladder: graph-readout cadence per rung (emit the (C, C)
# combined graph on every Nth answered sample). Factor scores always flow
# at rung cadence 1 — they are the cheap per-sample product; the graph is
# the payload that thins under load. Mirrors the fleet ladder's
# demote-before-deadline philosophy (fleet/autoscale.py).
QOS_CADENCE = (1, 4, 16)
# backlog hysteresis (fractions of the ingest cap): demote above, restore
# below — the gap prevents rung flapping at a steady backlog
_QOS_DEMOTE_FRAC = 0.5
_QOS_RESTORE_FRAC = 0.25

STATE_BASENAME = "serve_state.bin"

# cumulative latency reservoir cap: p50/p99 over the run, bounded memory
_MAX_LAT_SAMPLES = 100_000
# tick-event cadence (every Nth pump emits the counters/latency record)
_TICK_EVERY = 25


def _int_env(name, default):
    try:
        v = int(os.environ.get(name, default))
        return v if v > 0 else default
    except ValueError:
        return default


class ServeLadder:
    """Host-side occupancy-ladder policy: which rung should this tick
    dispatch, and is a shrink worth a cold program?

    The serve twin of the PR-15 predictive scheduling policy. Growth is
    mandatory (every leased slot must ride the dispatch); shrink below the
    current rung is approved only after ``hold`` consecutive ticks of the
    live high-water mark sitting under the smaller rung AND (in ``auto``)
    a positive pricing verdict: predicted dead-lane saving over ``horizon``
    ticks vs the compile cost of the target rung if it is cold. Evidence
    comes first from this process's own per-width dispatch timings, then
    from the persistent PR-8 cost store (keyed under the serve shape so
    tick costs never merge with training epochs); with NO evidence the
    policy holds the current (maximum) rung — the bit-identical
    empty-store fallback.
    """

    def __init__(self, capacity, mode=None, min_rung=MIN_RUNG, hold=None,
                 horizon=None, shape_key="serve", precision="f32"):
        mode = (mode if mode is not None
                else os.environ.get(ENV_LADDER, DEFAULT_LADDER))
        mode = str(mode).lower()
        if mode not in LADDER_MODES:
            raise ValueError(
                f"{ENV_LADDER} must be one of {LADDER_MODES}, got {mode!r}")
        self.mode = mode
        self.capacity = int(capacity)
        self.min_rung = max(1, min(int(min_rung), self.capacity))
        self.hold = int(hold if hold is not None
                        else _int_env(ENV_LADDER_HOLD, DEFAULT_LADDER_HOLD))
        self.horizon = int(horizon if horizon is not None
                           else _int_env(ENV_LADDER_HORIZON,
                                         DEFAULT_LADDER_HORIZON))
        self.shape_key = shape_key
        self.precision = precision
        self._obs = {}          # width -> [steady ticks, total ms]
        self._compile_obs = {}  # width -> measured first-dispatch skew ms
        self._below = 0         # consecutive ticks want < current
        self._store = None
        self._store_loaded = False

    def target(self, live_hi):
        """The rung ``live_hi`` leased lanes want under this mode."""
        if self.mode == "off":
            return self.capacity
        return _compaction.serve_rung(live_hi, self.capacity, self.min_rung)

    # ------------------------------------------------------------ evidence
    def observe(self, width, ms, cold):
        """Fold one dispatch's wall ms into the per-width accumulators.
        A cold dispatch carries the compile skew (measured far above steady
        state): it is recorded as compile evidence, never averaged into the
        steady tick cost (the rows_from_dispatch_stats discipline)."""
        if cold:
            base = self._steady_ms(width)
            self._compile_obs[width] = max(
                0.0, float(ms) - (base if base is not None else 0.0))
        else:
            o = self._obs.setdefault(int(width), [0, 0.0])
            o[0] += 1
            o[1] += float(ms)

    def _steady_ms(self, width):
        o = self._obs.get(int(width))
        if o and o[0]:
            return o[1] / o[0]
        return None

    def _cost_model(self):
        if not self._store_loaded:
            self._store_loaded = True
            try:
                from redcliff_tpu.obs import costmodel as _costmodel
                self._store = _costmodel.load(None)
            except Exception:
                self._store = None
        return self._store

    def tick_ms(self, width, platform=None):
        """Best per-tick wall estimate at a width: exact local mean, else
        the nearest locally measured width scaled per-lane, else the
        persistent store, else None (no evidence)."""
        exact = self._steady_ms(width)
        if exact is not None:
            return exact
        near = [(abs(w - width), w) for w, o in self._obs.items() if o[0]]
        if near:
            _, w = min(near)
            return self._steady_ms(w) * (float(width) / w)
        cm = self._cost_model()
        if cm is not None:
            return cm.predict_epoch_ms(self.shape_key, width,
                                       platform=platform,
                                       precision=self.precision)
        return None

    def compile_ms(self, width, platform=None):
        """Predicted cost of compiling the rung cold: exact local
        measurement, else the store, else the nearest locally measured
        compile (compile cost tracks the program, not the lane count),
        else None."""
        if int(width) in self._compile_obs:
            return self._compile_obs[int(width)]
        cm = self._cost_model()
        if cm is not None:
            est = cm.predict_compile_ms(self.shape_key, width,
                                        platform=platform,
                                        precision=self.precision)
            if est is not None:
                return est
        if self._compile_obs:
            _, w = min((abs(w - width), w) for w in self._compile_obs)
            return self._compile_obs[w]
        return None

    # ------------------------------------------------------------ the verdict
    def decide(self, live_hi, current, cold_fn, platform=None):
        """One tick's rung decision at the tick boundary.

        Returns ``(new_width, event)`` where event is a dict for the
        ``serve_ladder`` record (None when nothing noteworthy happened —
        steady-state holds are silent; priced holds/fallbacks emit once per
        hysteresis episode, not per tick).
        """
        if self.mode == "off":
            return self.capacity, None
        want = self.target(live_hi)
        if want > current:
            # growth is correctness, not economics: a leased slot beyond
            # the rung would never be dispatched
            self._below = 0
            return want, {"kind": "grow", "from_width": current,
                          "to_width": want, "live": int(live_hi),
                          "cold": bool(cold_fn(want))}
        if want == current:
            self._below = 0
            return current, None
        self._below += 1
        if self._below < self.hold:
            return current, None
        first = self._below == self.hold
        if self.mode == "force":
            self._below = 0
            return want, {"kind": "shrink", "from_width": current,
                          "to_width": want, "live": int(live_hi),
                          "cold": bool(cold_fn(want)), "reason": "forced"}
        cur_ms = self.tick_ms(current, platform)
        if cur_ms is None:
            # empty store + no local evidence: hold the current (maximum)
            # rung — the bit-identical always-max fallback
            ev = {"kind": "fallback", "from_width": current,
                  "to_width": current, "live": int(live_hi),
                  "reason": "no_evidence"} if first else None
            return current, ev
        want_ms = self.tick_ms(want, platform)
        if want_ms is None:
            # per-lane-proportional prior off the measured rung
            want_ms = cur_ms * (float(want) / current)
        saving = max(0.0, cur_ms - want_ms) * self.horizon
        cold = bool(cold_fn(want))
        comp = 0.0 if not cold else self.compile_ms(want, platform)
        if comp is None:
            ev = {"kind": "fallback", "from_width": current,
                  "to_width": current, "live": int(live_hi),
                  "reason": "compile_unpriceable"} if first else None
            return current, ev
        if saving > comp:
            self._below = 0
            return want, {"kind": "shrink", "from_width": current,
                          "to_width": want, "live": int(live_hi),
                          "cold": cold, "saving_ms": round(saving, 3),
                          "compile_ms": round(comp, 3),
                          "horizon_ticks": self.horizon}
        ev = {"kind": "hold", "from_width": current, "to_width": want,
              "live": int(live_hi), "saving_ms": round(saving, 3),
              "compile_ms": round(comp, 3), "horizon_ticks": self.horizon,
              "reason": "not_worth_compile"} if first else None
        return current, ev

    def rows(self):
        """This process's per-width observations as PR-8 store rows
        (folded into the persistent store at stop — the next server's
        shrink pricing starts warm)."""
        rows = []
        for w in sorted(set(self._obs) | set(self._compile_obs)):
            n, tot = self._obs.get(w, (0, 0.0))
            comp = self._compile_obs.get(w)
            if not n and comp is None:
                continue
            rows.append({"shape": self.shape_key, "g_bucket": int(w),
                         "precision": self.precision,
                         "epochs": int(n), "epoch_ms": float(tot),
                         "compiles": 1 if comp is not None else 0,
                         "compile_ms": float(comp or 0.0)})
        return rows


class ServeService:
    """One serving process: slot table + sessions + queues + telemetry.

    All public methods accept an explicit ``now`` (tests and the chaos
    harness drive virtual clocks); wall time is only the default. Public
    methods are serialized on an internal lock; ``pump``/``run_loop`` must
    be driven from one thread (the engine owns device state).
    """

    def __init__(self, model, params, root=None, capacity=None,
                 lease_s=None, resume=True, precision_mode=None,
                 ladder=None, fuse=None):
        from redcliff_tpu.serve.engine import StreamEngine

        self.capacity = int(capacity if capacity is not None
                            else _int_env(ENV_SLOTS, DEFAULT_SLOTS))
        self.ingest_cap = _int_env(ENV_INGEST_CAP, DEFAULT_INGEST_CAP)
        self.out_cap = _int_env(ENV_OUT_CAP, DEFAULT_OUT_CAP)
        self.root = root
        self._mu = threading.RLock()
        self.precision_mode = check_precision_mode(
            precision_mode if precision_mode is not None
            else os.environ.get(ENV_PRECISION, "f32"))
        self.engine = StreamEngine(model, params, self.capacity,
                                   precision_mode=self.precision_mode)
        self.fuse = max(1, int(fuse if fuse is not None
                               else _int_env(ENV_FUSE, DEFAULT_FUSE)))
        # serve-prefixed shape key: tick costs bucket separately from any
        # training epochs of the same model geometry
        shape_key = (f"serve|c{self.engine.num_chans}"
                     f"l{self.engine.window_len}k{self.engine.num_factors}")
        self.ladder = ServeLadder(
            self.capacity, mode=ladder, shape_key=shape_key,
            precision=precision_label(self.precision_mode))
        self._demote_storm = _int_env(ENV_DEMOTE_STORM, DEFAULT_DEMOTE_STORM)
        self._demote_window = _int_env(ENV_DEMOTE_WINDOW,
                                       DEFAULT_DEMOTE_WINDOW)
        self._poison_ticks = deque()
        self.registry = _session.SessionRegistry(self.capacity,
                                                 lease_s=lease_s)
        self.pending = {}    # sid -> deque[(sample (C,), t_enq)]
        self.out = {}        # sid -> deque[record]
        self.drops = {}      # sid -> slow-consumer drops
        self._answered = {}  # sid -> answered-sample count (cadence basis)
        self._lat_ms = []
        self._fused_samples = 0
        self._fuse_hist = {}  # per-stream fused take -> dispatch count
        self.ticks = 0
        self.samples_in = 0
        self.samples_out = 0
        self.rejects = 0
        self._draining = False
        self._stopped = False
        self._log = MetricLogger(root)
        resumed = 0
        if resume and root is not None:
            resumed = self._try_resume()
        self._log.log("serve", kind="start", capacity=self.capacity,
                      streams=len(self.registry.sessions), resumed=resumed,
                      width=self.engine.width, mode=self.ladder.mode,
                      fuse=self.fuse,
                      precision_mode=self.engine.precision_mode,
                      model_class=type(model).__name__)

    # ------------------------------------------------------------ loading
    @classmethod
    def from_artifact(cls, path, **kw):
        """Serve a fitted checkpoint: ``path`` is a run dir or artifact file
        readable by eval/model_io (runtime/checkpoint.py readers)."""
        from redcliff_tpu.eval.model_io import load_model_for_eval

        loaded = load_model_for_eval(path)
        model, params = loaded[0], loaded[1]
        return cls(model, params, **kw)

    # ------------------------------------------------------------ admission
    def connect(self, sid=None, now=None):
        """Admit a new subscriber stream: lease a slot, reset its lane,
        mint its trace_id. Raises :class:`SlotsExhausted` (with the
        soonest-lease-expiry ETA) when the table is full."""
        now = time.time() if now is None else float(now)
        with self._mu:
            try:
                sess = self.registry.connect(sid=sid, now=now)
            except SlotsExhausted as e:
                self.rejects += 1
                self._log.log("serve", kind="reject", eta_s=e.eta_s,
                              capacity=self.capacity, reason=e.reason)
                raise
            self.engine.reset_slot(sess.slot)
            self.pending[sess.sid] = deque()
            self.out[sess.sid] = deque()
            self.drops[sess.sid] = 0
            self._answered[sess.sid] = 0
            self._log.log("session", kind="connect", sid=sess.sid,
                          slot=sess.slot, trace_id=sess.trace_id,
                          lease_s=self.registry.lease_s)
            return {"sid": sess.sid, "slot": sess.slot,
                    "trace_id": sess.trace_id}

    def disconnect(self, sid):
        """Close a stream and recycle its slot. Unknown sid is a no-op
        (double-disconnect races are normal under churn)."""
        with self._mu:
            sess = self.registry.disconnect(sid)
            if sess is None:
                return None
            self._recycle(sess, kind="disconnect")
            return sess.state

    def _recycle(self, sess, kind):
        """Free one lane after a terminal transition: reset exactly that
        lane, drop its queues, emit the lifecycle + recycle pair."""
        self.engine.reset_slot(sess.slot)
        self.pending.pop(sess.sid, None)
        undelivered = len(self.out.pop(sess.sid, ()) or ())
        self.drops.pop(sess.sid, None)
        self._answered.pop(sess.sid, None)
        self._log.log("session", kind=kind, sid=sess.sid, slot=sess.slot,
                      trace_id=sess.trace_id, samples_in=sess.samples_in,
                      samples_out=sess.samples_out, state=sess.state,
                      undelivered=undelivered)
        self._log.log("session", kind="recycle", sid=sess.sid,
                      slot=sess.slot, trace_id=sess.trace_id)

    # ------------------------------------------------------------ ingest/poll
    def ingest(self, sid, sample, now=None):
        """Offer one sample to a stream. Returns a structured verdict dict
        (``accepted`` plus reason/backlog on refusal) — NEVER raises for
        data problems; a contract violation quarantines the offending
        stream only."""
        now = time.time() if now is None else float(now)
        with self._mu:
            sess = self.registry.get(sid)
            if sess is None:
                return {"accepted": False, "reason": "unknown session"}
            self.registry.heartbeat(sid, now=now)
            if sess.state == _session.QUARANTINED:
                return {"accepted": False, "trace_id": sess.trace_id,
                        "reason": f"quarantined: {sess.quarantine_reason}"}
            arr = np.asarray(sample, dtype=np.float32)
            if arr.shape != (self.engine.num_chans,):
                self._quarantine(sess, f"shape violation: got "
                                 f"{tuple(arr.shape)}, want "
                                 f"({self.engine.num_chans},)", now)
                return {"accepted": False, "trace_id": sess.trace_id,
                        "reason": f"quarantined: "
                                  f"{sess.quarantine_reason}"}
            q = self.pending[sid]
            if len(q) >= self.ingest_cap:
                self._log.log("serve", kind="overflow", sid=sid,
                              trace_id=sess.trace_id, backlog=len(q))
                return {"accepted": False, "trace_id": sess.trace_id,
                        "reason": "backlog full", "backlog": len(q)}
            sess.samples_in += 1
            self.samples_in += 1
            q.append((arr, now))
            return {"accepted": True, "trace_id": sess.trace_id}

    def poll(self, sid, max_items=None, now=None):
        """Drain a stream's answered records (oldest first). Counts as a
        heartbeat. A quarantined stream's poll returns its structured error
        state as the final record."""
        now = time.time() if now is None else float(now)
        with self._mu:
            sess = self.registry.get(sid)
            if sess is None:
                return []
            self.registry.heartbeat(sid, now=now)
            q = self.out.get(sid)
            if q is None:
                return []
            n = len(q) if max_items is None else min(len(q), int(max_items))
            return [q.popleft() for _ in range(n)]

    # ------------------------------------------------------------ quarantine
    def _quarantine(self, sess, reason, now, extra=0):
        """ACTIVE -> QUARANTINED: structured error state replaces output.
        Pending samples are answered as error records (a drain must not
        strand them); ``extra`` covers samples already popped into the
        in-flight fused batch behind the poison — the lane's latch
        discarded them in-graph, accounting answers them here. The lane's
        device state is never consulted again."""
        self.registry.quarantine(sess.sid, reason)
        q = self.pending.get(sess.sid)
        err = {"sid": sess.sid, "trace_id": sess.trace_id,
               "error": sess.quarantine_reason}
        outq = self.out.get(sess.sid)
        for _ in range(int(extra)):
            self._push_out(sess, outq, dict(err))
        while q:
            q.popleft()
            self._push_out(sess, outq, dict(err))
        self._push_out(sess, outq, dict(err))
        self._log.log("session", kind="quarantine", sid=sess.sid,
                      slot=sess.slot, trace_id=sess.trace_id, reason=reason)

    def _push_out(self, sess, outq, record):
        """Append to a stream's out-queue under the slow-consumer cap:
        past it, ITS oldest record drops (counted) — containment, not
        global stall."""
        if outq is None:
            return
        if len(outq) >= self.out_cap:
            outq.popleft()
            self.drops[sess.sid] = self.drops.get(sess.sid, 0) + 1
        outq.append(record)

    # ------------------------------------------------------------ the tick
    def _assemble(self, now):
        """Pop pending samples per ACTIVE stream into the tick batch at
        FULL capacity (the dispatcher slices to the rung). Fusion engages
        only when some stream has real backlog — otherwise depth is 1 and
        the single-tick program runs (the PR-17 bit-path). Returns
        ``(samples (S, F, C), arrive (S, F), meta, depth)``; meta maps
        slot -> (sid, [t_enq, ...])."""
        S, C = self.capacity, self.engine.num_chans
        depth = 1
        if self.fuse > 1:
            for sess in self.registry.live():
                if sess.state == _session.ACTIVE and \
                        len(self.pending.get(sess.sid) or ()) > 1:
                    depth = self.fuse
                    break
        samples = np.zeros((S, depth, C), dtype=np.float32)
        arrive = np.zeros((S, depth), dtype=bool)
        meta = {}
        for sess in self.registry.live():
            if sess.state != _session.ACTIVE:
                continue
            q = self.pending.get(sess.sid)
            if not q:
                continue
            ts = []
            for f in range(min(len(q), depth)):
                sample, t_enq = q.popleft()
                samples[sess.slot, f] = sample
                arrive[sess.slot, f] = True
                ts.append(t_enq)
            meta[sess.slot] = (sess.sid, ts)
        return samples, arrive, meta, depth

    def _live_hi(self):
        """Live high-water mark: 1 + the highest leased slot (ACTIVE and
        QUARANTINED both hold lanes). The rung must cover every leased
        slot."""
        return 1 + max((s.slot for s in self.registry.sessions.values()),
                       default=-1)

    def _ladder_tick(self, now, floor=0):
        """Run the rung policy at the tick boundary; resize + emit on a
        decision. ``floor`` covers slots already assembled into the
        in-flight batch (a disconnect between assemble and dispatch must
        not shrink them out from under the distribute)."""
        hi = max(self._live_hi(), int(floor))
        cur = self.engine.width
        new, ev = self.ladder.decide(hi, cur, self.engine.is_cold,
                                     self.engine.platform)
        if new != cur:
            self.engine.resize(new)
        if ev is not None:
            self._log.log("serve_ladder", capacity=self.capacity,
                          mode=self.ladder.mode, ticks=self.ticks, **ev)

    def _distribute(self, out, meta, depth, now):
        """Turn one dispatch's lane outputs into per-stream records. A
        fused dispatch carries a leading F axis; element f of lane s is
        bit-equal to the f-th sequential single-tick dispatch, so the
        record stream is independent of fuse depth (the fusion identity
        pin). Graph cadence keys off the per-stream answered counter, so
        the QoS ladder composes with fusion unchanged."""
        fused = depth > 1
        for slot, (sid, t_enqs) in meta.items():
            sess = self.registry.get(sid)
            if sess is None:      # reaped between assemble and distribute
                continue
            for f, t_enq in enumerate(t_enqs):
                if fused:
                    poison_hit = out["poison_hit"][f, slot]
                    ready = out["ready"][f, slot]
                else:
                    poison_hit = out["poison_hit"][slot]
                    ready = out["ready"][slot]
                if poison_hit:
                    self._poison_ticks.append(self.ticks)
                    self._quarantine(sess, "non-finite sample", now,
                                     extra=len(t_enqs) - f - 1)
                    break
                if not ready:
                    # warmup: ring not yet full — the sample advanced state
                    # but no readout exists yet
                    continue
                self._answered[sid] = self._answered.get(sid, 0) + 1
                cadence = QOS_CADENCE[min(sess.qos_rung,
                                          len(QOS_CADENCE) - 1)]
                lat_ms = max(0.0, (now - t_enq) * 1e3)
                scores = out["scores"][f, slot] if fused \
                    else out["scores"][slot]
                rec = {"sid": sid, "trace_id": sess.trace_id,
                       "seq": self._answered[sid],
                       "scores": np.array(scores, copy=True),
                       "latency_ms": lat_ms}
                if (self._answered[sid] - 1) % cadence == 0:
                    graph = out["graph"][f, slot] if fused \
                        else out["graph"][slot]
                    rec["graph"] = np.array(graph, copy=True)
                self._push_out(sess, self.out.get(sid), rec)
                sess.samples_out += 1
                self.samples_out += 1
                if len(self._lat_ms) < _MAX_LAT_SAMPLES:
                    self._lat_ms.append(lat_ms)

    def _maybe_demote(self, now):
        """The poisoned-lane-storm sentinel: ``storm`` quarantines-by-NaN
        inside ``window`` ticks demote the whole table from mixed to f32
        (retrace only; rings are already f32). Persisted at drain, honored
        at resume — never silently re-promoted."""
        if self.engine.precision_mode != "mixed" or self.engine.demoted:
            return
        w = self._demote_window
        while self._poison_ticks and self._poison_ticks[0] <= self.ticks - w:
            self._poison_ticks.popleft()
        if len(self._poison_ticks) < self._demote_storm:
            return
        self.engine.demote()
        self._log.log("precision", kind="demote", scope="serve",
                      lanes_poisoned=len(self._poison_ticks),
                      window_ticks=w, ticks=self.ticks,
                      cause="poisoned-lane storm", mode_from="mixed",
                      mode_to="f32")

    def _update_qos(self, now):
        """Per-stream backlog ladder with hysteresis; emits only rung
        changes. One greedy subscriber demotes alone."""
        demote_at = self.ingest_cap * _QOS_DEMOTE_FRAC
        restore_at = self.ingest_cap * _QOS_RESTORE_FRAC
        top = len(QOS_CADENCE) - 1
        for sess in self.registry.live():
            if sess.state != _session.ACTIVE:
                continue
            backlog = len(self.pending.get(sess.sid, ()))
            if backlog >= demote_at and sess.qos_rung < top:
                frm = sess.qos_rung
                sess.qos_rung += 1
                self._log.log("serve", kind="qos", sid=sess.sid,
                              trace_id=sess.trace_id, rung=sess.qos_rung,
                              from_rung=frm, backlog=backlog,
                              cadence=QOS_CADENCE[sess.qos_rung],
                              reason="backlog")
            elif backlog <= restore_at and sess.qos_rung > 0:
                frm = sess.qos_rung
                sess.qos_rung = 0
                self._log.log("serve", kind="qos", sid=sess.sid,
                              trace_id=sess.trace_id, rung=0, from_rung=frm,
                              backlog=backlog, cadence=QOS_CADENCE[0],
                              reason="recovered")

    def _reap(self, now):
        for sess in self.registry.reap(now=now):
            self._recycle(sess, kind="expire")

    def _dispatch_tick(self, samples, arrive, meta, depth, now, wall):
        """The shared back half of a tick: ladder decision, ONE dispatch at
        the rung, distribute, sentinels, counters. ``samples``/``arrive``
        are full-capacity; the rung slice is a view."""
        with self._mu:
            floor = 1 + max(meta, default=-1)
            self._ladder_tick(now, floor=floor)
            W = self.engine.width
        answered = 0
        out = None
        if meta:
            cold = self.engine.is_cold(W, depth)
            t0 = time.perf_counter()
            with _obs.span("serve.dispatch", component="serve"):
                if depth > 1:
                    out = self.engine.step_fused(samples[:W], arrive[:W])
                else:
                    out = self.engine.step(samples[:W, 0], arrive[:W, 0])
            ms = (time.perf_counter() - t0) * 1e3
        with self._mu:
            if out is not None:
                self.ladder.observe(W, ms, cold)
                if depth > 1:
                    self._fused_samples += int(arrive[:W].sum())
                for _slot, (_sid, ts) in meta.items():
                    self._fuse_hist[len(ts)] = \
                        self._fuse_hist.get(len(ts), 0) + 1
                before = self.samples_out
                # on the real clock, latency must charge the dispatch that
                # just ran; an injected (virtual) clock stays as given so
                # replayed runs remain deterministic
                self._distribute(out, meta, depth,
                                 time.time() if wall else now)
                answered = self.samples_out - before
                self._maybe_demote(now)
            self._update_qos(now)
            self.ticks += 1
            if self.ticks % _TICK_EVERY == 0:
                self._emit_tick()
        return answered

    def pump(self, now=None):
        """One synchronous tick. Returns the number of samples answered."""
        wall = now is None
        now = time.time() if wall else float(now)
        with self._mu:
            self._reap(now)
            samples, arrive, meta, depth = self._assemble(now)
        return self._dispatch_tick(samples, arrive, meta, depth, now, wall)

    def _emit_tick(self):
        dist = {}
        if self._lat_ms:
            dist = {"p50_ms": _slo.percentile(self._lat_ms, 50.0),
                    "p99_ms": _slo.percentile(self._lat_ms, 99.0)}
        self._log.log("serve", kind="tick", ticks=self.ticks,
                      streams=len(self.registry.sessions),
                      free_slots=self.registry.free_slots(),
                      width=self.engine.width,
                      live=self._live_hi(),
                      samples_in=self.samples_in,
                      samples_out=self.samples_out,
                      fused_samples=self._fused_samples,
                      rejects=self.rejects,
                      dropped=sum(self.drops.values()),
                      n=len(self._lat_ms), **dist)
        if self.fuse > 1:
            self._log.log("serve_fuse", kind="stats", depth=self.fuse,
                          fused_samples=self._fused_samples,
                          hist={str(k): v for k, v
                                in sorted(self._fuse_hist.items())},
                          ticks=self.ticks)

    # ------------------------------------------------------------ the loop
    def run_loop(self, max_ticks=None, interval_s=0.0, depth=2):
        """Drive ticks through the double-buffered prefetch pipeline:
        assembly of tick t+1 (prefetch thread) overlaps the engine dispatch
        of tick t (this thread). Assembly stays full-capacity host work —
        the ladder decision and the rung slice happen on THIS thread, which
        owns device state. Runs until ``max_ticks`` or a drain request;
        prefetched-but-unstepped batches are consumed to exhaustion on
        drain — never dropped — then :meth:`drain` finishes the remaining
        backlog synchronously."""
        from redcliff_tpu.data.pipeline import prefetch_batches

        def assembly():
            n = 0
            while not self._draining:
                if max_ticks is not None and n >= max_ticks:
                    return
                now = time.time()
                with self._mu:
                    self._reap(now)
                    batch = self._assemble(now)
                yield batch + (now,)
                n += 1
                if interval_s:
                    time.sleep(interval_s)

        src = prefetch_batches(assembly(), depth=depth)
        # exhaust the stream — on drain the generator stops producing and
        # the loop below consumes every already-buffered batch (samples
        # popped from pending must be answered, not lost)
        for samples, arrive, meta, fdepth, t_asm in src:
            self._dispatch_tick(samples, arrive, meta, fdepth,
                                time.time(), True)
        src.close()
        if self._draining:
            self.drain()

    # ------------------------------------------------------------ drain/stop
    def drain(self, now=None):
        """Answer every in-flight sample, checkpoint every session, stop.
        Zero loss: live streams' pending queues pump to empty; undelivered
        out-queues persist into the drain checkpoint for the restarted
        server to hand back."""
        now = time.time() if now is None else float(now)
        self._draining = True
        # bounded by total backlog: each pump answers >= 1 sample while any
        # ACTIVE stream has pending work (warmup samples count as progress
        # via their state advance)
        guard = self.capacity * self.ingest_cap + len(self.registry.sessions)
        while guard >= 0 and any(
                self.pending.get(s.sid)
                for s in self.registry.live()
                if s.state == _session.ACTIVE):
            self.pump(now=now)
            guard -= 1
        path = self._checkpoint()
        dist = {}
        if self._lat_ms:
            dist = {"p50_ms": _slo.percentile(self._lat_ms, 50.0),
                    "p99_ms": _slo.percentile(self._lat_ms, 99.0),
                    "n": len(self._lat_ms)}
        self._log.log("serve", kind="drain", ticks=self.ticks,
                      streams=len(self.registry.sessions),
                      width=self.engine.width,
                      samples_in=self.samples_in,
                      samples_out=self.samples_out,
                      fused_samples=self._fused_samples,
                      rejects=self.rejects,
                      dropped=sum(self.drops.values()),
                      undelivered=sum(len(q) for q in self.out.values()),
                      checkpoint=path, **dist)
        self.stop()
        return path

    def stop(self):
        if self._stopped:
            return
        self._stopped = True
        self._fold_cost_store()
        self._log.log("serve", kind="stop", ticks=self.ticks,
                      samples_out=self.samples_out)
        self._log.close()

    def _fold_cost_store(self):
        """Fold this process's per-rung tick/compile observations into the
        persistent PR-8 store (when one is configured): the next server's
        first shrink decision prices against real evidence instead of
        falling back to always-max."""
        try:
            from redcliff_tpu.obs import costmodel as _costmodel
            if _costmodel.store_path(None) is None:
                return
            rows = self.ladder.rows()
            if rows:
                _costmodel.update_store(None, rows, self.engine.platform)
        except Exception:
            pass  # telemetry must never take down a drain

    def request_drain(self):
        """Async-signal-safe drain request: the running loop (or the next
        explicit ``drain()`` caller) completes it."""
        self._draining = True

    def install_signal_handlers(self):
        """SIGTERM/SIGINT -> graceful drain request (the preemption
        discipline runtime/preempt.py applies to fits, applied to serve)."""
        def _h(signum, frame):
            self.request_drain()
        signal.signal(signal.SIGTERM, _h)
        signal.signal(signal.SIGINT, _h)

    # ------------------------------------------------------------ durability
    def _state_path(self):
        return os.path.join(self.root, STATE_BASENAME) \
            if self.root is not None else None

    def _checkpoint(self):
        path = self._state_path()
        if path is None:
            return None
        with self._mu:
            payload = {
                "registry": self.registry.snapshot(),
                "engine": self.engine.export_state(),
                "ladder": {"width": self.engine.width,
                           "capacity": self.capacity,
                           "mode": self.ladder.mode},
                "precision": {"mode": self.engine.precision_mode,
                              "demoted": self.engine.demoted},
                "out": {sid: list(q) for sid, q in self.out.items()},
                "answered": dict(self._answered),
                "drops": dict(self.drops),
                "counters": {"ticks": self.ticks,
                             "samples_in": self.samples_in,
                             "samples_out": self.samples_out,
                             "fused_samples": self._fused_samples,
                             "rejects": self.rejects},
            }
        write_checkpoint(path, payload)
        return path

    def _try_resume(self):
        path = self._state_path()
        if path is None or not (os.path.exists(path)
                                or os.path.exists(path + ".prev")):
            return 0
        payload, _src = load_checkpoint(path)
        if payload is None:
            return 0
        now = time.time()
        prec = payload.get("precision") or {}
        if prec.get("demoted") and self.engine.precision_mode == "mixed" \
                and not self.engine.demoted:
            # a storm-demoted table NEVER silently re-promotes on restart
            self.engine.demote()
            self._log.log("precision", kind="resume_demoted", scope="serve",
                          cause="checkpoint recorded demotion",
                          mode_from="mixed", mode_to="f32")
        eng_snap = payload["engine"]
        ck_width = int(np.asarray(eng_snap["window"]).shape[0])
        snap_reg = payload["registry"]
        if int(snap_reg["capacity"]) == self.capacity:
            self.registry = _session.SessionRegistry.from_snapshot(
                snap_reg, now=now)
            # restore straight into the recorded rung; the ladder takes
            # over from there at the first pump
            self.engine.resize(min(max(ck_width, 1), self.capacity))
            self.engine.import_state(eng_snap)
        else:
            self._resume_repack(snap_reg, eng_snap, ck_width, now)
        self.out = {sid: deque(v) for sid, v in payload["out"].items()}
        self._answered = dict(payload.get("answered", {}))
        self.drops = dict(payload.get("drops", {}))
        c = payload.get("counters", {})
        self.ticks = int(c.get("ticks", 0))
        self.samples_in = int(c.get("samples_in", 0))
        self.samples_out = int(c.get("samples_out", 0))
        self._fused_samples = int(c.get("fused_samples", 0))
        self.rejects = int(c.get("rejects", 0))
        for sess in self.registry.live():
            self.pending.setdefault(sess.sid, deque())
            self.out.setdefault(sess.sid, deque())
            self.drops.setdefault(sess.sid, 0)
            self._answered.setdefault(sess.sid, 0)
            self._log.log("session", kind="resume", sid=sess.sid,
                          slot=sess.slot, trace_id=sess.trace_id,
                          state=sess.state,
                          samples_out=sess.samples_out)
        self._log.log("serve", kind="resume",
                      streams=len(self.registry.sessions),
                      width=self.engine.width,
                      ticks=self.ticks, checkpoint=path)
        return len(self.registry.sessions)

    def _resume_repack(self, snap_reg, eng_snap, ck_width, now):
        """Cross-geometry resume: re-pack live lanes into THIS table
        instead of failing the PR-17 shape check. Lanes pack dense from
        slot 0 in checkpoint-slot order (relative order preserved), the
        registry's LIFO pool is re-seeded above them, and the engine
        restores row-by-row through ``import_state(slot_map=...)``. Only a
        table too small for the live streams refuses — naming both
        geometries."""
        sess_dicts = sorted(snap_reg["sessions"], key=lambda d: d["slot"])
        n = len(sess_dicts)
        if n > self.capacity:
            raise ValueError(
                f"serve resume geometry mismatch: checkpoint capacity "
                f"{int(snap_reg['capacity'])} (rung {ck_width}, {n} live "
                f"streams) vs engine capacity {self.capacity} — {n} live "
                f"streams do not fit the new table; grow capacity or drain "
                f"sessions before resizing")
        reg = _session.SessionRegistry(self.capacity,
                                       lease_s=snap_reg.get("lease_s"))
        slot_map = {}
        for new, d in enumerate(sess_dicts):
            sess = _session.Session.from_dict(d)
            slot_map[int(d["slot"])] = new
            sess.slot = new
            sess.lease_expires_at = now + reg.lease_s
            reg.sessions[sess.sid] = sess
        reg._free = list(range(self.capacity - 1, n - 1, -1))
        self.registry = reg
        width = self.ladder.target(n)
        self.engine.resize(width)
        self.engine.import_state(eng_snap, slot_map=slot_map)
        self._log.log("serve_ladder", kind="repack", from_width=ck_width,
                      to_width=width, live=n, capacity=self.capacity,
                      from_capacity=int(snap_reg["capacity"]),
                      mode=self.ladder.mode, streams=n)
