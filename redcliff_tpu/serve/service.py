"""Streaming inference service: supervision + dispatch over the slot table.

The serving loop that ties the layers together: the
:class:`~redcliff_tpu.serve.engine.StreamEngine` slot table (device math),
the :class:`~redcliff_tpu.serve.session.SessionRegistry` (lease/heartbeat
supervision), the shared admission taxonomy (``SlotsExhausted``
reject-with-ETA), and the telemetry spine (schema-registered ``serve`` /
``session`` events, ``serve.dispatch`` spans, per-stream ``trace_id``).

**Tick discipline.** ``pump()`` is one tick: reap lapsed leases (recycled
lanes reset one-by-one, co-residents untouched), assemble at most one
pending sample per ACTIVE stream into the ``(S, C)`` arrival batch, ONE
engine dispatch, distribute outputs. ``run_loop`` rides the same tick
through :func:`data.pipeline.prefetch_batches` (depth=2), so host assembly
of tick t+1 overlaps device compute of tick t — the same double-buffered
discipline the training engines use.

**Input contracts (per stream, never per table).** A shape-violating sample
quarantines its stream HOST-side (it never reaches the device); a
non-finite sample is detected in-graph and quarantines the stream with its
lane's ring untouched (the poison sample is discarded, the ``poisoned``
flag latches). Either way the stream degrades to a structured error state —
its subscriber polls the verdict — while co-resident lanes' outputs stay
bit-identical to a run where the poisoner never existed (pinned,
tests/test_serve.py).

**Overload ladder.** Admission rejects with ETA when slots are exhausted
(``SlotsExhausted``); a stream whose backlog climbs sheds graph-readout
cadence through :data:`QOS_CADENCE` rungs (factor scores keep flowing at
full rate — the cheap output — while the ``C x C`` graph emission thins)
BEFORE any latency SLO breach; per-sample ingest past the backlog cap gets
a structured non-accept; a slow consumer's out-queue drops ITS oldest
results past :data:`ENV_OUT_CAP` (counted) instead of growing without
bound or stalling siblings. Demotion is per-stream: one greedy subscriber
degrades alone.

**Drain.** ``drain()`` (or SIGTERM via :meth:`ServeService.
install_signal_handlers`) answers every in-flight sample, converts nothing
to loss, checkpoints sessions + slot-table rings + undelivered outputs
through runtime/checkpoint.py (atomic, CRC, ``.prev``), and a restarted
server resumes every session — same ``trace_id``, same ring state, same
undelivered outputs — with a fresh lease so subscribers can re-attach.

jax stays out of module scope (LAZY_JAX_MODULES): constructing/driving a
service in tests pulls jax only when the engine spins up.
"""
from __future__ import annotations

import os
import signal
import threading
import time
from collections import deque

import numpy as np

from redcliff_tpu import obs as _obs
from redcliff_tpu.obs import slo as _slo
from redcliff_tpu.obs.logging import MetricLogger
from redcliff_tpu.runtime.admission import SlotsExhausted  # noqa: F401 (re-export)
from redcliff_tpu.runtime.checkpoint import (
    load_checkpoint,
    write_checkpoint,
)
from redcliff_tpu.serve import session as _session

__all__ = ["ServeService", "SlotsExhausted", "ENV_SLOTS", "DEFAULT_SLOTS",
           "ENV_INGEST_CAP", "ENV_OUT_CAP", "QOS_CADENCE", "STATE_BASENAME"]

ENV_SLOTS = "REDCLIFF_SERVE_SLOTS"
DEFAULT_SLOTS = 8
ENV_INGEST_CAP = "REDCLIFF_SERVE_INGEST_CAP"
DEFAULT_INGEST_CAP = 64
ENV_OUT_CAP = "REDCLIFF_SERVE_OUT_CAP"
DEFAULT_OUT_CAP = 256

# degraded-QoS ladder: graph-readout cadence per rung (emit the (C, C)
# combined graph on every Nth answered sample). Factor scores always flow
# at rung cadence 1 — they are the cheap per-sample product; the graph is
# the payload that thins under load. Mirrors the fleet ladder's
# demote-before-deadline philosophy (fleet/autoscale.py).
QOS_CADENCE = (1, 4, 16)
# backlog hysteresis (fractions of the ingest cap): demote above, restore
# below — the gap prevents rung flapping at a steady backlog
_QOS_DEMOTE_FRAC = 0.5
_QOS_RESTORE_FRAC = 0.25

STATE_BASENAME = "serve_state.bin"

# cumulative latency reservoir cap: p50/p99 over the run, bounded memory
_MAX_LAT_SAMPLES = 100_000
# tick-event cadence (every Nth pump emits the counters/latency record)
_TICK_EVERY = 25


def _int_env(name, default):
    try:
        v = int(os.environ.get(name, default))
        return v if v > 0 else default
    except ValueError:
        return default


class ServeService:
    """One serving process: slot table + sessions + queues + telemetry.

    All public methods accept an explicit ``now`` (tests and the chaos
    harness drive virtual clocks); wall time is only the default. Public
    methods are serialized on an internal lock; ``pump``/``run_loop`` must
    be driven from one thread (the engine owns device state).
    """

    def __init__(self, model, params, root=None, capacity=None,
                 lease_s=None, resume=True):
        from redcliff_tpu.serve.engine import StreamEngine

        self.capacity = int(capacity if capacity is not None
                            else _int_env(ENV_SLOTS, DEFAULT_SLOTS))
        self.ingest_cap = _int_env(ENV_INGEST_CAP, DEFAULT_INGEST_CAP)
        self.out_cap = _int_env(ENV_OUT_CAP, DEFAULT_OUT_CAP)
        self.root = root
        self._mu = threading.RLock()
        self.engine = StreamEngine(model, params, self.capacity)
        self.registry = _session.SessionRegistry(self.capacity,
                                                 lease_s=lease_s)
        self.pending = {}    # sid -> deque[(sample (C,), t_enq)]
        self.out = {}        # sid -> deque[record]
        self.drops = {}      # sid -> slow-consumer drops
        self._answered = {}  # sid -> answered-sample count (cadence basis)
        self._lat_ms = []
        self.ticks = 0
        self.samples_in = 0
        self.samples_out = 0
        self.rejects = 0
        self._draining = False
        self._stopped = False
        self._log = MetricLogger(root)
        resumed = 0
        if resume and root is not None:
            resumed = self._try_resume()
        self._log.log("serve", kind="start", capacity=self.capacity,
                      streams=len(self.registry.sessions), resumed=resumed,
                      model_class=type(model).__name__)

    # ------------------------------------------------------------ loading
    @classmethod
    def from_artifact(cls, path, **kw):
        """Serve a fitted checkpoint: ``path`` is a run dir or artifact file
        readable by eval/model_io (runtime/checkpoint.py readers)."""
        from redcliff_tpu.eval.model_io import load_model_for_eval

        loaded = load_model_for_eval(path)
        model, params = loaded[0], loaded[1]
        return cls(model, params, **kw)

    # ------------------------------------------------------------ admission
    def connect(self, sid=None, now=None):
        """Admit a new subscriber stream: lease a slot, reset its lane,
        mint its trace_id. Raises :class:`SlotsExhausted` (with the
        soonest-lease-expiry ETA) when the table is full."""
        now = time.time() if now is None else float(now)
        with self._mu:
            try:
                sess = self.registry.connect(sid=sid, now=now)
            except SlotsExhausted as e:
                self.rejects += 1
                self._log.log("serve", kind="reject", eta_s=e.eta_s,
                              capacity=self.capacity, reason=e.reason)
                raise
            self.engine.reset_slot(sess.slot)
            self.pending[sess.sid] = deque()
            self.out[sess.sid] = deque()
            self.drops[sess.sid] = 0
            self._answered[sess.sid] = 0
            self._log.log("session", kind="connect", sid=sess.sid,
                          slot=sess.slot, trace_id=sess.trace_id,
                          lease_s=self.registry.lease_s)
            return {"sid": sess.sid, "slot": sess.slot,
                    "trace_id": sess.trace_id}

    def disconnect(self, sid):
        """Close a stream and recycle its slot. Unknown sid is a no-op
        (double-disconnect races are normal under churn)."""
        with self._mu:
            sess = self.registry.disconnect(sid)
            if sess is None:
                return None
            self._recycle(sess, kind="disconnect")
            return sess.state

    def _recycle(self, sess, kind):
        """Free one lane after a terminal transition: reset exactly that
        lane, drop its queues, emit the lifecycle + recycle pair."""
        self.engine.reset_slot(sess.slot)
        self.pending.pop(sess.sid, None)
        undelivered = len(self.out.pop(sess.sid, ()) or ())
        self.drops.pop(sess.sid, None)
        self._answered.pop(sess.sid, None)
        self._log.log("session", kind=kind, sid=sess.sid, slot=sess.slot,
                      trace_id=sess.trace_id, samples_in=sess.samples_in,
                      samples_out=sess.samples_out, state=sess.state,
                      undelivered=undelivered)
        self._log.log("session", kind="recycle", sid=sess.sid,
                      slot=sess.slot, trace_id=sess.trace_id)

    # ------------------------------------------------------------ ingest/poll
    def ingest(self, sid, sample, now=None):
        """Offer one sample to a stream. Returns a structured verdict dict
        (``accepted`` plus reason/backlog on refusal) — NEVER raises for
        data problems; a contract violation quarantines the offending
        stream only."""
        now = time.time() if now is None else float(now)
        with self._mu:
            sess = self.registry.get(sid)
            if sess is None:
                return {"accepted": False, "reason": "unknown session"}
            self.registry.heartbeat(sid, now=now)
            if sess.state == _session.QUARANTINED:
                return {"accepted": False, "trace_id": sess.trace_id,
                        "reason": f"quarantined: {sess.quarantine_reason}"}
            arr = np.asarray(sample, dtype=np.float32)
            if arr.shape != (self.engine.num_chans,):
                self._quarantine(sess, f"shape violation: got "
                                 f"{tuple(arr.shape)}, want "
                                 f"({self.engine.num_chans},)", now)
                return {"accepted": False, "trace_id": sess.trace_id,
                        "reason": f"quarantined: "
                                  f"{sess.quarantine_reason}"}
            q = self.pending[sid]
            if len(q) >= self.ingest_cap:
                self._log.log("serve", kind="overflow", sid=sid,
                              trace_id=sess.trace_id, backlog=len(q))
                return {"accepted": False, "trace_id": sess.trace_id,
                        "reason": "backlog full", "backlog": len(q)}
            sess.samples_in += 1
            self.samples_in += 1
            q.append((arr, now))
            return {"accepted": True, "trace_id": sess.trace_id}

    def poll(self, sid, max_items=None, now=None):
        """Drain a stream's answered records (oldest first). Counts as a
        heartbeat. A quarantined stream's poll returns its structured error
        state as the final record."""
        now = time.time() if now is None else float(now)
        with self._mu:
            sess = self.registry.get(sid)
            if sess is None:
                return []
            self.registry.heartbeat(sid, now=now)
            q = self.out.get(sid)
            if q is None:
                return []
            n = len(q) if max_items is None else min(len(q), int(max_items))
            return [q.popleft() for _ in range(n)]

    # ------------------------------------------------------------ quarantine
    def _quarantine(self, sess, reason, now):
        """ACTIVE -> QUARANTINED: structured error state replaces output.
        Pending samples are answered as error records (a drain must not
        strand them); the lane's device state is never consulted again."""
        self.registry.quarantine(sess.sid, reason)
        q = self.pending.get(sess.sid)
        err = {"sid": sess.sid, "trace_id": sess.trace_id,
               "error": sess.quarantine_reason}
        outq = self.out.get(sess.sid)
        while q:
            q.popleft()
            self._push_out(sess, outq, dict(err))
        self._push_out(sess, outq, dict(err))
        self._log.log("session", kind="quarantine", sid=sess.sid,
                      slot=sess.slot, trace_id=sess.trace_id, reason=reason)

    def _push_out(self, sess, outq, record):
        """Append to a stream's out-queue under the slow-consumer cap:
        past it, ITS oldest record drops (counted) — containment, not
        global stall."""
        if outq is None:
            return
        if len(outq) >= self.out_cap:
            outq.popleft()
            self.drops[sess.sid] = self.drops.get(sess.sid, 0) + 1
        outq.append(record)

    # ------------------------------------------------------------ the tick
    def _assemble(self, now):
        """Pop at most one pending sample per ACTIVE stream into the
        ``(S, C)`` tick batch. Returns (samples, arrive, meta); meta maps
        slot -> (sid, t_enq)."""
        S, C = self.capacity, self.engine.num_chans
        samples = np.zeros((S, C), dtype=np.float32)
        arrive = np.zeros((S,), dtype=bool)
        meta = {}
        for sess in self.registry.live():
            if sess.state != _session.ACTIVE:
                continue
            q = self.pending.get(sess.sid)
            if not q:
                continue
            sample, t_enq = q.popleft()
            samples[sess.slot] = sample
            arrive[sess.slot] = True
            meta[sess.slot] = (sess.sid, t_enq)
        return samples, arrive, meta

    def _distribute(self, out, meta, now):
        """Turn one dispatch's lane outputs into per-stream records."""
        for slot, (sid, t_enq) in meta.items():
            sess = self.registry.get(sid)
            if sess is None:      # reaped between assemble and distribute
                continue
            if out["poison_hit"][slot]:
                self._quarantine(sess, "non-finite sample", now)
                continue
            if not out["ready"][slot]:
                # warmup: ring not yet full — the sample advanced state
                # but no readout exists yet
                continue
            self._answered[sid] = self._answered.get(sid, 0) + 1
            cadence = QOS_CADENCE[min(sess.qos_rung, len(QOS_CADENCE) - 1)]
            lat_ms = max(0.0, (now - t_enq) * 1e3)
            rec = {"sid": sid, "trace_id": sess.trace_id,
                   "seq": self._answered[sid],
                   "scores": np.array(out["scores"][slot], copy=True),
                   "latency_ms": lat_ms}
            if (self._answered[sid] - 1) % cadence == 0:
                rec["graph"] = np.array(out["graph"][slot], copy=True)
            self._push_out(sess, self.out.get(sid), rec)
            sess.samples_out += 1
            self.samples_out += 1
            if len(self._lat_ms) < _MAX_LAT_SAMPLES:
                self._lat_ms.append(lat_ms)

    def _update_qos(self, now):
        """Per-stream backlog ladder with hysteresis; emits only rung
        changes. One greedy subscriber demotes alone."""
        demote_at = self.ingest_cap * _QOS_DEMOTE_FRAC
        restore_at = self.ingest_cap * _QOS_RESTORE_FRAC
        top = len(QOS_CADENCE) - 1
        for sess in self.registry.live():
            if sess.state != _session.ACTIVE:
                continue
            backlog = len(self.pending.get(sess.sid, ()))
            if backlog >= demote_at and sess.qos_rung < top:
                frm = sess.qos_rung
                sess.qos_rung += 1
                self._log.log("serve", kind="qos", sid=sess.sid,
                              trace_id=sess.trace_id, rung=sess.qos_rung,
                              from_rung=frm, backlog=backlog,
                              cadence=QOS_CADENCE[sess.qos_rung],
                              reason="backlog")
            elif backlog <= restore_at and sess.qos_rung > 0:
                frm = sess.qos_rung
                sess.qos_rung = 0
                self._log.log("serve", kind="qos", sid=sess.sid,
                              trace_id=sess.trace_id, rung=0, from_rung=frm,
                              backlog=backlog, cadence=QOS_CADENCE[0],
                              reason="recovered")

    def _reap(self, now):
        for sess in self.registry.reap(now=now):
            self._recycle(sess, kind="expire")

    def pump(self, now=None):
        """One synchronous tick. Returns the number of samples answered."""
        wall = now is None
        now = time.time() if wall else float(now)
        with self._mu:
            self._reap(now)
            samples, arrive, meta = self._assemble(now)
        answered = 0
        if meta:
            with _obs.span("serve.dispatch", component="serve"):
                out = self.engine.step(samples, arrive)
        else:
            out = None
        with self._mu:
            if out is not None:
                before = self.samples_out
                # on the real clock, latency must charge the dispatch that
                # just ran; an injected (virtual) clock stays as given so
                # replayed runs remain deterministic
                self._distribute(out, meta, time.time() if wall else now)
                answered = self.samples_out - before
            self._update_qos(now)
            self.ticks += 1
            if self.ticks % _TICK_EVERY == 0:
                self._emit_tick()
        return answered

    def _emit_tick(self):
        dist = {}
        if self._lat_ms:
            dist = {"p50_ms": _slo.percentile(self._lat_ms, 50.0),
                    "p99_ms": _slo.percentile(self._lat_ms, 99.0)}
        self._log.log("serve", kind="tick", ticks=self.ticks,
                      streams=len(self.registry.sessions),
                      free_slots=self.registry.free_slots(),
                      samples_in=self.samples_in,
                      samples_out=self.samples_out,
                      rejects=self.rejects,
                      dropped=sum(self.drops.values()),
                      n=len(self._lat_ms), **dist)

    # ------------------------------------------------------------ the loop
    def run_loop(self, max_ticks=None, interval_s=0.0, depth=2):
        """Drive ticks through the double-buffered prefetch pipeline:
        assembly of tick t+1 (prefetch thread) overlaps the engine dispatch
        of tick t (this thread). Runs until ``max_ticks`` or a drain
        request; prefetched-but-unstepped batches are consumed to
        exhaustion on drain — never dropped — then :meth:`drain` finishes
        the remaining backlog synchronously."""
        from redcliff_tpu.data.pipeline import prefetch_batches

        def assembly():
            n = 0
            while not self._draining:
                if max_ticks is not None and n >= max_ticks:
                    return
                now = time.time()
                with self._mu:
                    self._reap(now)
                    samples, arrive, meta = self._assemble(now)
                yield samples, arrive, meta, now
                n += 1
                if interval_s:
                    time.sleep(interval_s)

        src = prefetch_batches(assembly(), depth=depth)
        # exhaust the stream — on drain the generator stops producing and
        # the loop below consumes every already-buffered batch (samples
        # popped from pending must be answered, not lost)
        for samples, arrive, meta, t_asm in src:
            now = time.time()
            if meta:
                with _obs.span("serve.dispatch", component="serve"):
                    out = self.engine.step(samples, arrive)
            else:
                out = None
            with self._mu:
                if out is not None:
                    self._distribute(out, meta, now)
                self._update_qos(now)
                self.ticks += 1
                if self.ticks % _TICK_EVERY == 0:
                    self._emit_tick()
        src.close()
        if self._draining:
            self.drain()

    # ------------------------------------------------------------ drain/stop
    def drain(self, now=None):
        """Answer every in-flight sample, checkpoint every session, stop.
        Zero loss: live streams' pending queues pump to empty; undelivered
        out-queues persist into the drain checkpoint for the restarted
        server to hand back."""
        now = time.time() if now is None else float(now)
        self._draining = True
        # bounded by total backlog: each pump answers >= 1 sample while any
        # ACTIVE stream has pending work (warmup samples count as progress
        # via their state advance)
        guard = self.capacity * self.ingest_cap + len(self.registry.sessions)
        while guard >= 0 and any(
                self.pending.get(s.sid)
                for s in self.registry.live()
                if s.state == _session.ACTIVE):
            self.pump(now=now)
            guard -= 1
        path = self._checkpoint()
        dist = {}
        if self._lat_ms:
            dist = {"p50_ms": _slo.percentile(self._lat_ms, 50.0),
                    "p99_ms": _slo.percentile(self._lat_ms, 99.0),
                    "n": len(self._lat_ms)}
        self._log.log("serve", kind="drain", ticks=self.ticks,
                      streams=len(self.registry.sessions),
                      samples_in=self.samples_in,
                      samples_out=self.samples_out,
                      rejects=self.rejects,
                      dropped=sum(self.drops.values()),
                      undelivered=sum(len(q) for q in self.out.values()),
                      checkpoint=path, **dist)
        self.stop()
        return path

    def stop(self):
        if self._stopped:
            return
        self._stopped = True
        self._log.log("serve", kind="stop", ticks=self.ticks,
                      samples_out=self.samples_out)
        self._log.close()

    def request_drain(self):
        """Async-signal-safe drain request: the running loop (or the next
        explicit ``drain()`` caller) completes it."""
        self._draining = True

    def install_signal_handlers(self):
        """SIGTERM/SIGINT -> graceful drain request (the preemption
        discipline runtime/preempt.py applies to fits, applied to serve)."""
        def _h(signum, frame):
            self.request_drain()
        signal.signal(signal.SIGTERM, _h)
        signal.signal(signal.SIGINT, _h)

    # ------------------------------------------------------------ durability
    def _state_path(self):
        return os.path.join(self.root, STATE_BASENAME) \
            if self.root is not None else None

    def _checkpoint(self):
        path = self._state_path()
        if path is None:
            return None
        with self._mu:
            payload = {
                "registry": self.registry.snapshot(),
                "engine": self.engine.export_state(),
                "out": {sid: list(q) for sid, q in self.out.items()},
                "answered": dict(self._answered),
                "drops": dict(self.drops),
                "counters": {"ticks": self.ticks,
                             "samples_in": self.samples_in,
                             "samples_out": self.samples_out,
                             "rejects": self.rejects},
            }
        write_checkpoint(path, payload)
        return path

    def _try_resume(self):
        path = self._state_path()
        if path is None or not (os.path.exists(path)
                                or os.path.exists(path + ".prev")):
            return 0
        payload, _src = load_checkpoint(path)
        if payload is None:
            return 0
        now = time.time()
        self.registry = _session.SessionRegistry.from_snapshot(
            payload["registry"], now=now)
        self.engine.import_state(payload["engine"])
        self.out = {sid: deque(v) for sid, v in payload["out"].items()}
        self._answered = dict(payload.get("answered", {}))
        self.drops = dict(payload.get("drops", {}))
        c = payload.get("counters", {})
        self.ticks = int(c.get("ticks", 0))
        self.samples_in = int(c.get("samples_in", 0))
        self.samples_out = int(c.get("samples_out", 0))
        self.rejects = int(c.get("rejects", 0))
        for sess in self.registry.live():
            self.pending.setdefault(sess.sid, deque())
            self.out.setdefault(sess.sid, deque())
            self.drops.setdefault(sess.sid, 0)
            self._answered.setdefault(sess.sid, 0)
            self._log.log("session", kind="resume", sid=sess.sid,
                          slot=sess.slot, trace_id=sess.trace_id,
                          state=sess.state,
                          samples_out=sess.samples_out)
        self._log.log("serve", kind="resume",
                      streams=len(self.registry.sessions),
                      ticks=self.ticks, checkpoint=path)
        return len(self.registry.sessions)
