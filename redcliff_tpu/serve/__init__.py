"""Streaming inference service: the platform's second production workload.

The paper's point is reading out *dynamic* causal graphs from live
multivariate signal; this package serves a fitted REDCLIFF-S checkpoint to
many concurrent subscriber streams per chip — per-sample factor scores plus
per-state Granger-graph readouts — with robustness designed in at every
layer (ISSUE 17):

- :mod:`~redcliff_tpu.serve.engine` — the fixed-capacity vmapped **slot
  table**: each stream owns one lane of cached embedder state (a
  device-resident ring buffer of its last ``embed_lag`` samples), a new
  sample advances that state in O(1), and every tick batches all ragged
  arrivals through ONE dispatch. Lane math is row-independent, so a poison
  neighbor can never perturb a co-resident stream (bit-identity pinned);
- :mod:`~redcliff_tpu.serve.session` — the lease/heartbeat session
  registry: connect/disconnect/quarantine/expire lifecycle, dead
  subscribers reaped and slots recycled without touching live lanes,
  admission via the shared :class:`~redcliff_tpu.runtime.admission`
  taxonomy (``SlotsExhausted`` reject-with-ETA);
- :mod:`~redcliff_tpu.serve.service` — the serving loop: per-sample input
  contracts (NaN / shape violations quarantine the offending stream into a
  structured error state), a per-stream degraded-QoS ladder (graph-readout
  cadence sheds before any latency SLO breach), SIGTERM drain (in-flight
  samples answered, sessions checkpointed, a restarted server resumes
  them — re-packing lanes across rung geometries), and per-stream
  ``trace_id`` end to end. ISSUE 20 makes the data plane *elastic*: the
  slot table rides pow2 occupancy rungs sized to live load (shrinks priced
  against cold-compile cost through the PR-8 store), backlogged streams
  advance up to ``REDCLIFF_SERVE_FUSE`` samples in one ``lax.scan``
  dispatch, and ``precision_mode="mixed"`` serves bf16 contractions over
  f32 ring state with a poisoned-lane-storm sentinel that auto-demotes the
  table to f32;
- :mod:`~redcliff_tpu.serve.chaos` — the seeded chaos harness:
  connect/disconnect storms, NaN streams, slow-consumer backpressure, and
  the churn-isolation comparison that pins co-resident outputs bit-identical
  to an interference-free run.

``python -m redcliff_tpu.serve smoke`` runs the self-contained smoke
(3 streams incl. a NaN poisoner -> quarantine + siblings answer + drain).
"""
from redcliff_tpu.serve.session import (  # noqa: F401
    Session,
    SessionRegistry,
)

__all__ = ["Session", "SessionRegistry"]
