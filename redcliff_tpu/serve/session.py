"""Lease/heartbeat session registry: the serve plane's supervision layer.

Every subscriber stream is a **session** owning exactly one slot-table lane
for its lifetime. The registry is the single authority on that ownership:
who holds which slot, whose lease is live, who is quarantined, and which
lanes are free. It holds NO device state — recycling a slot tells the
caller to reset that one lane (engine.reset_slot), never touching
co-residents — so supervision bugs cannot corrupt inference state.

Session state machine (one-way except the free-slot cycle)::

    connect -> ACTIVE -(poison sample)-> QUARANTINED -(disconnect)-+
                  |                           |                    |
                  |<-- heartbeat renews lease |-(lease expiry)----->  slot
                  |-(disconnect)-> CLOSED  ---------------------->  freed +
                  |-(lease expiry)-> EXPIRED -------------------->  recycled

ACTIVE and QUARANTINED sessions both hold a lease: a quarantined session
keeps its slot (its subscriber polls the structured error state) until it
disconnects or its lease lapses. Dead subscribers are reaped by lease
expiry exactly like fleet workers: a subscriber that stops heartbeating
(ingest and poll both count) is EXPIRED by the next ``reap`` sweep and its
slot recycled — no human in the loop, no perturbation of live lanes.

Admission raises the shared :class:`~redcliff_tpu.runtime.admission.
SlotsExhausted` taxonomy when every slot is leased, carrying the soonest
lease expiry as the retry ETA (the same structured reject-with-ETA contract
fleet submit uses).

Each session carries a durable ``trace_id`` (ISSUE 12 discipline, same
format fleet submit mints) — the identity every serve/session event and
every answered sample carries end to end.

stdlib only, no jax (obs/schema.py ``--check`` enforces it): session
supervision must run — and be testable — without a backend.
"""
from __future__ import annotations

import os
import time
import uuid

from redcliff_tpu.runtime.admission import SlotsExhausted

__all__ = ["Session", "SessionRegistry", "ENV_LEASE_S", "DEFAULT_LEASE_S",
           "ACTIVE", "QUARANTINED", "CLOSED", "EXPIRED", "STATES"]

ENV_LEASE_S = "REDCLIFF_SERVE_LEASE_S"
DEFAULT_LEASE_S = 30.0

ACTIVE = "active"
QUARANTINED = "quarantined"
CLOSED = "closed"
EXPIRED = "expired"
STATES = (ACTIVE, QUARANTINED, CLOSED, EXPIRED)

# lease states still holding a slot; CLOSED/EXPIRED sessions are terminal
# bookkeeping records whose slots are already back in the free pool
_LEASED = (ACTIVE, QUARANTINED)


def lease_s_from_env(default=DEFAULT_LEASE_S):
    try:
        v = float(os.environ.get(ENV_LEASE_S, default))
        return v if v > 0 else default
    except ValueError:
        return default


class Session:
    """One subscriber stream's supervision record."""

    __slots__ = ("sid", "slot", "trace_id", "state", "lease_expires_at",
                 "connected_at", "samples_in", "samples_out",
                 "quarantine_reason", "qos_rung")

    def __init__(self, sid, slot, trace_id, now, lease_s):
        self.sid = sid
        self.slot = int(slot)
        self.trace_id = trace_id
        self.state = ACTIVE
        self.connected_at = float(now)
        self.lease_expires_at = float(now) + float(lease_s)
        self.samples_in = 0
        self.samples_out = 0
        self.quarantine_reason = None
        self.qos_rung = 0

    def to_dict(self):
        return {k: getattr(self, k) for k in self.__slots__}

    @classmethod
    def from_dict(cls, d):
        s = cls(d["sid"], d["slot"], d["trace_id"], 0.0, 1.0)
        for k in cls.__slots__:
            setattr(s, k, d[k])
        return s


class SessionRegistry:
    """Slot ownership + lease supervision for a fixed-capacity slot table.

    All methods take ``now`` explicitly (tests and chaos drive virtual
    clocks); ``time.time()`` is only the default. Not thread-safe by
    itself — the service serializes access on its pump loop.
    """

    def __init__(self, capacity, lease_s=None):
        self.capacity = int(capacity)
        self.lease_s = float(lease_s if lease_s is not None
                             else lease_s_from_env())
        # LIFO free pool: recycled slots are re-leased most-recently-freed
        # first, keeping the live-lane set dense under churn
        self._free = list(range(self.capacity - 1, -1, -1))
        self.sessions = {}          # sid -> Session (live: ACTIVE/QUARANTINED)
        self.history = []           # terminal Session records, bounded
        self._max_history = 256

    # ------------------------------------------------------------ admission
    def connect(self, sid=None, now=None):
        """Lease a free slot to a new session; :class:`SlotsExhausted` with
        the soonest-lease-expiry ETA when the table is full."""
        now = time.time() if now is None else float(now)
        # duplicate sid is a caller bug, not a capacity condition — it must
        # not masquerade as a retryable SlotsExhausted on a full table
        if sid is not None and sid in self.sessions:
            raise ValueError(f"session id {sid!r} already connected")
        if not self._free:
            soonest = min((s.lease_expires_at for s in
                           self.sessions.values()), default=None)
            eta = max(0.0, soonest - now) if soonest is not None else None
            raise SlotsExhausted(self.capacity, eta_s=eta)
        sid = sid or f"sess-{uuid.uuid4().hex[:12]}"
        slot = self._free.pop()
        trace_id = f"tr-{uuid.uuid4().hex[:16]}"
        sess = Session(sid, slot, trace_id, now, self.lease_s)
        self.sessions[sid] = sess
        return sess

    # ------------------------------------------------------------ lifecycle
    def get(self, sid):
        return self.sessions.get(sid)

    def heartbeat(self, sid, now=None):
        """Renew a live session's lease (any subscriber activity counts)."""
        now = time.time() if now is None else float(now)
        sess = self.sessions.get(sid)
        if sess is None:
            return None
        sess.lease_expires_at = now + self.lease_s
        return sess

    def quarantine(self, sid, reason):
        """ACTIVE -> QUARANTINED: the stream degrades to a structured error
        state but keeps its slot/lease (the subscriber reads the verdict)."""
        sess = self.sessions.get(sid)
        if sess is None or sess.state != ACTIVE:
            return sess
        sess.state = QUARANTINED
        sess.quarantine_reason = str(reason)
        return sess

    def disconnect(self, sid):
        """Live -> CLOSED; slot back to the free pool. Returns the session
        (None if unknown — double-disconnect is a no-op, not an error)."""
        sess = self.sessions.pop(sid, None)
        if sess is None:
            return None
        sess.state = CLOSED
        self._retire(sess)
        return sess

    def reap(self, now=None):
        """Expire every live session whose lease has lapsed; returns the
        reaped sessions (their slots are already back in the pool — the
        caller resets exactly those lanes)."""
        now = time.time() if now is None else float(now)
        dead = [s for s in self.sessions.values()
                if s.lease_expires_at <= now]
        for sess in dead:
            del self.sessions[sess.sid]
            sess.state = EXPIRED
            self._retire(sess)
        return dead

    def _retire(self, sess):
        self._free.append(sess.slot)
        self.history.append(sess)
        if len(self.history) > self._max_history:
            del self.history[: len(self.history) - self._max_history]

    # ------------------------------------------------------------ introspection
    def live(self):
        """Live sessions (ACTIVE + QUARANTINED), slot-ordered."""
        return sorted(self.sessions.values(), key=lambda s: s.slot)

    def free_slots(self):
        return len(self._free)

    def snapshot(self):
        """JSON-able registry state: the drain checkpoint's session half."""
        return {"capacity": self.capacity, "lease_s": self.lease_s,
                "free": list(self._free),
                "sessions": [s.to_dict() for s in self.live()]}

    @classmethod
    def from_snapshot(cls, snap, now=None, lease_s=None):
        """Rebuild a registry from :meth:`snapshot`. Every resumed session's
        lease restarts at ``now`` (the old absolute expiries belong to the
        dead server's clock; a resume must give subscribers a full lease to
        re-attach before the reaper runs)."""
        now = time.time() if now is None else float(now)
        reg = cls(snap["capacity"],
                  lease_s=lease_s if lease_s is not None else snap["lease_s"])
        reg._free = list(snap["free"])
        for d in snap["sessions"]:
            sess = Session.from_dict(d)
            sess.lease_expires_at = now + reg.lease_s
            reg.sessions[sess.sid] = sess
        return reg
