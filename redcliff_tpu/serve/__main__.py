"""CLI for the streaming inference service.

``run`` serves a fitted artifact until SIGTERM (graceful drain); ``smoke``
is the self-contained CI leg: a seeded tiny model, three subscriber
streams — one streaming NaNs — and the full robustness story end to end
(poisoner quarantined, siblings answer with finite scores, graceful drain
writes a resumable checkpoint). ``ladder-smoke`` is the elastic-data-plane
CI leg (ISSUE 20): churn 3 -> 17 -> 2 streams through a capacity-32 table
under the forced occupancy ladder and assert the rung transitions
4 -> 32 -> 4, zero quarantines, and victim records byte-identical to a
ladder-off run. Exit 0 iff every assertion holds.

Usage::

    python -m redcliff_tpu.serve run --artifact RUN_DIR --root SERVE_DIR \
        [--slots N] [--interval-s S] [--precision-mode MODE] \
        [--ladder MODE] [--fuse N]
    python -m redcliff_tpu.serve smoke [--root DIR]
    python -m redcliff_tpu.serve ladder-smoke [--root DIR]
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile


def _build_tiny_artifact(root, seed=0):
    """Fit-free fitted artifact: a seeded tiny REDCLIFF-S model saved
    through the standard trainer writer, so the smoke exercises the real
    artifact load path."""
    import jax

    from redcliff_tpu.models.redcliff import (RedcliffSCMLP,
                                              RedcliffSCMLPConfig)
    from redcliff_tpu.train.trainer import save_model

    model = RedcliffSCMLP(RedcliffSCMLPConfig(
        num_chans=4, gen_lag=2, gen_hidden=(8,), embed_lag=4,
        embed_hidden_sizes=(8,), num_factors=2, num_supervised_factors=2,
        factor_weight_l1_coeff=0.01, adj_l1_reg_coeff=0.001,
        factor_cos_sim_coeff=0.01,
        factor_score_embedder_type="Vanilla_Embedder",
        primary_gc_est_mode="fixed_factor_exclusive", num_sims=1,
        training_mode="combined"))
    params = model.init(jax.random.PRNGKey(seed))
    save_model(root, model, params)
    return root


def _smoke(args):
    import numpy as np

    from redcliff_tpu.serve.service import ServeService

    root = args.root or tempfile.mkdtemp(prefix="redcliff-serve-smoke-")
    os.makedirs(root, exist_ok=True)
    artifact = _build_tiny_artifact(root)
    svc = ServeService.from_artifact(artifact, root=root, capacity=4)
    svc.install_signal_handlers()

    chans = svc.engine.num_chans
    warmup = svc.engine.window_len
    n = warmup + 8
    rng = np.random.default_rng(7)
    streams = {sid: rng.normal(size=(n, chans)).astype(np.float32)
               for sid in ("good-a", "good-b", "poisoner")}
    # the poisoner turns toxic mid-stream, after its ring has warmed up
    streams["poisoner"][warmup + 2, 1] = np.nan
    for sid in streams:
        svc.connect(sid=sid, now=0.0)

    now = 0.0
    for t in range(n):
        now += 0.01
        for sid, arr in streams.items():
            svc.ingest(sid, arr[t], now=now)
        svc.pump(now=now)

    failures = []
    polls = {sid: svc.poll(sid, now=now) for sid in streams}
    for sid in ("good-a", "good-b"):
        recs = [r for r in polls[sid] if "scores" in r]
        if len(recs) != n - warmup + 1:
            failures.append(f"{sid}: answered {len(recs)}, "
                            f"want {n - warmup + 1}")
        if any(not np.all(np.isfinite(np.asarray(r["scores"])))
               for r in recs):
            failures.append(f"{sid}: non-finite scores leaked")
    sess = svc.registry.get("poisoner")
    if sess is None or sess.state != "quarantined":
        failures.append(f"poisoner not quarantined "
                        f"(state={getattr(sess, 'state', 'gone')})")
    if not any("error" in r for r in polls["poisoner"]):
        failures.append("poisoner got no structured error record")

    ckpt = svc.drain(now=now)
    if ckpt is None or not os.path.exists(ckpt):
        failures.append(f"drain checkpoint missing: {ckpt!r}")

    if failures:
        print("serve smoke FAILED:")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(f"serve smoke OK: 2 siblings answered {n - warmup + 1} samples "
          f"each, poisoner quarantined, drain checkpoint at {ckpt}")
    return 0


def _ladder_smoke(args):
    """The elastic-data-plane CI leg: capacity-32 table, forced ladder,
    churn 3 -> 17 -> 2 streams, deterministic virtual clock. Asserts the
    rung rides 4 -> 32 -> 4, nobody is quarantined, and the two persistent
    victim streams' records are byte-identical to a ladder-off run."""
    import json
    import shutil

    import numpy as np

    from redcliff_tpu.serve import chaos
    from redcliff_tpu.serve.service import ServeService

    # tight hysteresis so the forced shrink lands inside the smoke's churn
    # phases (the decision logic is identical at any hold)
    os.environ.setdefault("REDCLIFF_SERVE_LADDER_HOLD", "2")
    base = args.root or tempfile.mkdtemp(prefix="redcliff-serve-ladder-")
    for sub in ("artifact", "forced", "off"):
        os.makedirs(os.path.join(base, sub), exist_ok=True)
    artifact = _build_tiny_artifact(os.path.join(base, "artifact"))

    capacity, chans, warmup = 32, 4, 4
    n = warmup + 20
    victims = {f"victim-{i}": chaos.stream_samples(100 + i, n, chans)
               for i in range(2)}
    # churn plan on the virtual tick clock: phase A runs 3 streams
    # (2 victims + 1 extra), phase B connects 14 more (17 live -> rung 32),
    # phase C disconnects all extras (2 live -> rung 4)
    phase_b, phase_c = 8, 16

    def churn(svc, t, now):
        if t == 0:
            svc.connect(sid="extra-0", now=now)
        if t == phase_b:
            for i in range(1, 15):
                svc.connect(sid=f"extra-{i}", now=now)
        if t == phase_c:
            for i in range(15):
                svc.disconnect(f"extra-{i}")
        # extras stream clean samples while connected (never poll: they
        # are load, not subscribers)
        rng = np.random.default_rng(1000 + t)
        for i in range(15):
            x = rng.normal(size=chans).astype(np.float32)
            svc.ingest(f"extra-{i}", x, now=now)

    def run(mode, root):
        svc = ServeService.from_artifact(artifact, root=root,
                                         capacity=capacity, ladder=mode,
                                         resume=False)
        for sid in victims:
            svc.connect(sid=sid, now=0.0)
        res = chaos.drive(svc, victims, ticks=n + 8, chaos_fn=churn)
        svc.stop()
        return res, svc

    forced_root = os.path.join(base, "forced")
    res_on, svc_on = run("force", forced_root)
    res_off, _svc_off = run("off", os.path.join(base, "off"))

    failures = []
    identical, compared, detail = chaos.outputs_identical(res_on, res_off)
    if not identical or compared == 0:
        failures.append(f"victim records diverge under the ladder "
                        f"({compared} compared): {detail}")
    quarantined = [s.sid for s in svc_on.registry.sessions.values()
                   if s.state != "active"]
    if quarantined:
        failures.append(f"unexpected quarantines: {quarantined}")

    rungs = []
    with open(os.path.join(forced_root, "metrics.jsonl")) as fh:
        for line in fh:
            rec = json.loads(line)
            if rec.get("event") == "serve_ladder" and \
                    rec.get("kind") in ("grow", "shrink"):
                rungs.append(int(rec["to_width"]))
    want = [4, 32, 4]
    if rungs != want:
        failures.append(f"rung transitions {rungs}, want {want}")

    if args.root is None:
        shutil.rmtree(base, ignore_errors=True)
    if failures:
        print("serve ladder smoke FAILED:")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(f"serve ladder smoke OK: rungs {rungs}, {compared} victim "
          f"records byte-identical across ladder on/off, 0 quarantines")
    return 0


def _run(args):
    from redcliff_tpu.serve.service import ServeService

    svc = ServeService.from_artifact(
        args.artifact, root=args.root, capacity=args.slots,
        precision_mode=args.precision_mode, ladder=args.ladder,
        fuse=args.fuse)
    svc.install_signal_handlers()
    svc.run_loop(interval_s=args.interval_s)
    if not svc._stopped:
        svc.drain()
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(prog="python -m redcliff_tpu.serve")
    sub = p.add_subparsers(dest="cmd", required=True)
    ps = sub.add_parser("smoke", help="self-contained robustness smoke")
    ps.add_argument("--root", default=None)
    ps.set_defaults(fn=_smoke)
    pl = sub.add_parser("ladder-smoke",
                        help="occupancy-ladder churn smoke (ISSUE 20)")
    pl.add_argument("--root", default=None)
    pl.set_defaults(fn=_ladder_smoke)
    pr = sub.add_parser("run", help="serve an artifact until SIGTERM")
    pr.add_argument("--artifact", required=True)
    pr.add_argument("--root", required=True)
    pr.add_argument("--slots", type=int, default=None)
    pr.add_argument("--interval-s", type=float, default=0.005)
    pr.add_argument("--precision-mode", default=None,
                    choices=("f32", "mixed"),
                    help="serve-table precision (default: "
                         "REDCLIFF_SERVE_PRECISION or f32)")
    pr.add_argument("--ladder", default=None,
                    choices=("off", "auto", "force"),
                    help="occupancy-ladder mode (default: "
                         "REDCLIFF_SERVE_LADDER or auto)")
    pr.add_argument("--fuse", type=int, default=None,
                    help="max samples fused per dispatch (default: "
                         "REDCLIFF_SERVE_FUSE or 1)")
    pr.set_defaults(fn=_run)
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
