"""Seeded chaos harness for the serve plane: churn, poison, slow consumers.

The serving twin of fleet/chaos.py: every storm is a deterministic function
of its seed, so a failure reproduces exactly. Three adversaries, composable
in one storm:

- **connect/disconnect churn** — short-lived sessions lease, stream, and
  vanish every few ticks (some by disconnect, some by silent lease expiry),
  exercising slot recycling under load;
- **NaN streams** — a fraction of chaos sessions stream non-finite samples,
  exercising per-stream quarantine;
- **slow consumers** — chaos sessions never poll, so their out-queues hit
  the cap and shed THEIR oldest records, exercising bounded-memory
  containment.

The headline check is :func:`churn_isolation_report`: run the same victim
streams twice — once interference-free, once inside a storm — and compare
every victim's answered records BYTE for byte. The slot-table engine's
row-independence makes this an equality, not a tolerance (the churn
isolation pin, tests/test_serve.py + the bench ``serve`` probe).

stdlib + numpy only, no jax (obs/schema.py ``--check`` enforces it): the
harness drives a service object; the service owns the backend.
"""
from __future__ import annotations

import numpy as np

from redcliff_tpu.runtime.admission import SlotsExhausted

__all__ = ["stream_samples", "drive", "make_churn_storm",
           "make_sawtooth_storm", "outputs_identical",
           "churn_isolation_report"]


def stream_samples(seed, n, chans):
    """Deterministic victim signal: ``(n, chans)`` float32."""
    rng = np.random.default_rng(seed)
    return rng.normal(size=(n, chans)).astype(np.float32)


def drive(svc, victim_samples, ticks, chaos_fn=None, now0=0.0, dt=0.01):
    """Drive a service on a virtual clock: each tick ingests one pending
    sample per victim (while any remain), runs the chaos actor, pumps once,
    and polls every victim. Victims must already be connected (so their
    slot assignment precedes any churn). Returns ``{sid: [records...]}``.
    """
    fed = {sid: 0 for sid in victim_samples}
    results = {sid: [] for sid in victim_samples}
    now = float(now0)
    for t in range(int(ticks)):
        now += dt
        for sid, arr in victim_samples.items():
            i = fed[sid]
            if i < len(arr):
                verdict = svc.ingest(sid, arr[i], now=now)
                if verdict.get("accepted"):
                    fed[sid] = i + 1
        if chaos_fn is not None:
            chaos_fn(svc, t, now)
        svc.pump(now=now)
        for sid in victim_samples:
            results[sid].extend(svc.poll(sid, now=now))
    return results


def make_churn_storm(seed, chans, connect_p=0.6, nan_p=0.4,
                     lifetime=(1, 5), expire_p=0.25):
    """Build a seeded per-tick chaos actor for :func:`drive`.

    Each tick it retires due chaos sessions (mostly by disconnect; with
    probability ``expire_p`` by going silent and letting the lease reaper
    recycle the slot), connects a new one with probability ``connect_p``
    (poisoned — streaming NaNs — with probability ``nan_p``), and feeds
    every live chaos session one sample. Chaos sessions never poll: they
    are the slow consumers. ``SlotsExhausted`` rejections are expected
    under storm pressure and counted on ``storm.rejects``.
    """
    rng = np.random.default_rng(seed)
    live = {}   # sid -> [retire_tick, poisoned, abandon]

    def storm(svc, t, now):
        for sid in [s for s, v in live.items() if v[0] <= t]:
            if not live[sid][2]:
                svc.disconnect(sid)
            # abandoned sessions just stop heartbeating; the reaper takes
            # the slot back at lease expiry
            del live[sid]
        if rng.random() < connect_p:
            sid = f"chaos-{t}-{rng.integers(1 << 20)}"
            poisoned = bool(rng.random() < nan_p)
            abandon = bool(rng.random() < expire_p)
            span = int(rng.integers(lifetime[0], lifetime[1] + 1))
            try:
                svc.connect(sid=sid, now=now)
            except SlotsExhausted:
                storm.rejects += 1
            else:
                live[sid] = [t + span, poisoned, abandon]
        for sid, (_r, poisoned, abandon) in live.items():
            x = rng.normal(size=chans).astype(np.float32)
            if poisoned:
                x[int(rng.integers(chans))] = np.nan
            # abandoned sessions are silent from birth: no ingest means no
            # heartbeat, so only the lease reaper can recycle their slots
            if not abandon:
                svc.ingest(sid, x, now=now)

    storm.rejects = 0
    return storm


def make_sawtooth_storm(seed, chans, lo=0, hi=6, period=12, nan_p=0.0):
    """Seeded sawtooth-occupancy actor: chaos-session count rides a
    deterministic triangle wave between ``lo`` and ``hi`` with the given
    ``period`` (ticks per half-cycle), connecting on the upstroke and
    disconnecting newest-first on the downstroke. The occupancy-ladder
    adversary: every sweep drags the live high-water mark through multiple
    rungs, forcing grow -> shrink -> grow cycles while victims stream
    (tests/test_serve_elastic.py pins their bytes across the whole ride).
    Sample payloads (and optional NaN poisoning at ``nan_p``) come from the
    seeded rng, so a failure reproduces exactly."""
    rng = np.random.default_rng(seed)
    live = []   # connected chaos sids, connect order

    def target(t):
        phase = t % (2 * period)
        up = phase if phase < period else 2 * period - phase
        return lo + round((hi - lo) * up / period)

    def storm(svc, t, now):
        want = target(t)
        while len(live) > want:
            svc.disconnect(live.pop())
        while len(live) < want:
            sid = f"saw-{t}-{len(live)}"
            try:
                svc.connect(sid=sid, now=now)
            except SlotsExhausted:
                storm.rejects += 1
                break
            else:
                live.append(sid)
        for sid in live:
            x = rng.normal(size=chans).astype(np.float32)
            if nan_p and rng.random() < nan_p:
                x[int(rng.integers(chans))] = np.nan
            svc.ingest(sid, x, now=now)

    storm.rejects = 0
    storm.target = target
    return storm


def outputs_identical(a, b):
    """Byte-for-byte comparison of two :func:`drive` result maps (scores,
    graphs, seq; latency excluded — it is clock, not math). Returns
    ``(identical, n_compared, detail)``."""
    n = 0
    for sid in a:
        ra, rb = a[sid], b.get(sid)
        if rb is None or len(ra) != len(rb):
            return False, n, f"{sid}: record count {len(ra)} vs " \
                             f"{len(rb) if rb is not None else 'missing'}"
        for x, y in zip(ra, rb):
            n += 1
            if x.get("seq") != y.get("seq"):
                return False, n, f"{sid}: seq {x.get('seq')} vs " \
                                 f"{y.get('seq')}"
            xs = np.asarray(x["scores"])
            ys = np.asarray(y["scores"])
            if xs.tobytes() != ys.tobytes():
                return False, n, f"{sid}: scores diverge at seq " \
                                 f"{x.get('seq')}"
            if ("graph" in x) != ("graph" in y):
                return False, n, f"{sid}: graph cadence diverges at seq " \
                                 f"{x.get('seq')}"
            if "graph" in x and (np.asarray(x["graph"]).tobytes()
                                 != np.asarray(y["graph"]).tobytes()):
                return False, n, f"{sid}: graph diverges at seq " \
                                 f"{x.get('seq')}"
    return True, n, ""


def churn_isolation_report(make_service, chans, n_victims=2, n_samples=24,
                           seed=0, extra_ticks=8):
    """THE isolation check: same victims, with and without a storm;
    verdict is byte equality of every victim output.

    ``make_service`` constructs a fresh service (fresh slot table) per run
    — the two runs must not share device state. Returns a dict with
    ``identical`` (the pin), ``compared`` (records checked), ``rejects``
    (storm admission pressure), and ``detail`` on mismatch.
    """
    victims = {f"victim-{i}": stream_samples(seed + i, n_samples, chans)
               for i in range(n_victims)}
    ticks = n_samples + int(extra_ticks)

    def run(with_storm):
        svc = make_service()
        for sid in victims:
            svc.connect(sid=sid, now=0.0)
        storm = make_churn_storm(seed + 1000, chans) if with_storm else None
        res = drive(svc, victims, ticks, chaos_fn=storm)
        svc.stop()
        return res, (storm.rejects if storm else 0)

    clean, _ = run(False)
    stormy, rejects = run(True)
    identical, compared, detail = outputs_identical(clean, stormy)
    return {"identical": identical, "compared": compared,
            "rejects": rejects, "victims": n_victims, "detail": detail}
