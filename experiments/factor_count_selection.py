"""Factor-count selection by cross-validated stopping criteria — the
notebook's model-selection flow, run on THIS build's own models.

The reference selects num_factors by comparing cross-validated
stopping-criteria minima across candidate factor counts (notebook cells
34-35, rebuilt as eval/analysis.factor_selection_table and pinned against
the notebook's hard-coded data by tests/test_analysis_notebook_parity.py) —
its answer to systems where the factor count is not known a priori.
VERDICT r4 flags the two worst Low-band systems of the banded study (3-1-2:
REDCLIFF-S 0.460 vs DGCNN 0.722; 6-4-2: 0.397 vs 0.408) as exactly the cases
this tool exists for, and notes it had never consumed a tree of this
framework's trained runs.

This experiment runs it end to end per system:
1. curate the banded-study folds (same generator, sample budget, seeds);
2. train REDCLIFF-S at num_factors K in {2..6} through the REAL driver
   (num_supervised_factors stays at the dataset's labeled-state count, as
   the reference holds it at TST's 3 states while sweeping K to 9);
3. feed the run tree to factor_selection_table; select K by summed criteria
   (forecast + factor minima, the notebook's comparison);
4. score every K with the off-diag optimal-F1 battery, so the artifact shows
   whether criteria-selected K improves on the banded table's K=2 default.

Writes experiments/FACTOR_COUNT_SELECTION.json.

Run:  python experiments/factor_count_selection.py <workdir> [--smoke]
      [--systems 3-1-2,6-4-2] [--folds N]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from accuracy_parity_synsys import REDCLIFF_ARGS  # noqa: E402
from redcliff_tpu.data.curation import curate_synthetic_fold  # noqa: E402
from redcliff_tpu.eval.analysis import factor_selection_table  # noqa: E402
from redcliff_tpu.eval.cross_alg import (  # noqa: E402
    evaluate_algorithm_on_fold, find_run_directory)
from redcliff_tpu.train.driver import set_up_and_run_experiments  # noqa: E402
from redcliff_tpu.utils.config import load_true_gc_factors  # noqa: E402

OFFDIAG = "key_stats_estGC_normOffDiag_vs_trueGC_normOffDiag"
K_CANDIDATES = (2, 3, 4, 5, 6)


def run_system(base, system, folds, smoke):
    num_nodes, num_edges, num_states = (int(v) for v in system.split("-"))
    n_train, n_val = (240, 96) if smoke else (1040, 240)
    data_args_by_fold = {}
    true_by_fold = {}
    for fold in range(folds):
        fold_dir, _ = curate_synthetic_fold(
            os.path.join(base, "data"), fold_id=fold, num_nodes=num_nodes,
            num_lags=2, num_factors=num_states,
            num_supervised_factors=num_states,
            num_edges_per_graph=num_edges, num_samples_in_train_set=n_train,
            num_samples_in_val_set=n_val, sample_recording_len=100,
            burnin_period=50, label_type_setting="OneHot",
            noise_type="gaussian", noise_level=1.0,
            folder_name=f"synSys{num_nodes}_{num_edges}_{num_states}")
        data_args_by_fold[fold] = os.path.join(
            fold_dir, f"data_fold{fold}_cached_args.txt")
        true_by_fold[fold] = load_true_gc_factors(data_args_by_fold[fold])

    run_dirs_by_k = {}
    science_by_k = {}
    for K in K_CANDIDATES:
        margs = dict(REDCLIFF_ARGS,
                     num_factors=str(K),
                     num_supervised_factors=str(num_states))
        if smoke:
            margs.update(max_iter="12", num_pretrain_epochs="4",
                         num_acclimation_epochs="4", check_every="2")
        margs_file = os.path.join(base, f"REDCLIFF_S_CMLP_K{K}_cached_args.txt")
        with open(margs_file, "w") as f:
            json.dump(margs, f)
        save_root = os.path.join(base, f"runs_K{K}")
        os.makedirs(save_root, exist_ok=True)
        run_dirs = []
        pooled = []
        for fold in range(folds):
            t0 = time.time()
            set_up_and_run_experiments(
                {"save_root_path": save_root}, [margs_file],
                [data_args_by_fold[fold]],
                possible_model_types=["REDCLIFF_S_CMLP"],
                possible_data_sets=[f"data_fold{fold}"], task_id=1)
            print(f"[{system} K={K}] fold {fold}: {time.time()-t0:.1f}s",
                  flush=True)
            run_dir = find_run_directory(save_root, "data", fold)
            run_dirs.append(run_dir)
            stats = evaluate_algorithm_on_fold(run_dir, "REDCLIFF_S_CMLP",
                                               true_by_fold[fold])
            pooled.extend(stats[OFFDIAG]["f1_vals_across_factors"])
        run_dirs_by_k[K] = run_dirs
        f1 = np.asarray(pooled, dtype=np.float64)
        science_by_k[K] = {
            "offdiag_optimal_f1_mean": float(f1.mean()),
            "offdiag_optimal_f1_sem": float(f1.std(ddof=1) / np.sqrt(len(f1)))
            if len(f1) > 1 else 0.0,
        }
        print(f"[{system} K={K}] optF1 "
              f"{science_by_k[K]['offdiag_optimal_f1_mean']:.3f} ± "
              f"{science_by_k[K]['offdiag_optimal_f1_sem']:.3f}", flush=True)

    table = factor_selection_table(run_dirs_by_k)
    # the notebook compares criteria minima across K; combine forecast +
    # factor criteria exactly as the training criteria weight them is not
    # defined there — select by the summed normalized minima, reporting both
    # components so the choice is auditable
    selectable = {K: (table[K].get("avg_forecasting_loss_mean", np.inf)
                      + table[K].get("avg_factor_loss_mean", np.inf))
                  for K in K_CANDIDATES}
    selected = min(selectable, key=selectable.get)
    print(f"[{system}] criteria-selected K = {selected} "
          f"(sums: { {k: round(v, 3) for k, v in selectable.items()} })",
          flush=True)
    return {
        "system": system,
        "num_labeled_states": num_states,
        "selection_table": table,
        "criteria_sum_by_k": {str(k): float(v)
                              for k, v in selectable.items()},
        "selected_num_factors": int(selected),
        "science_by_num_factors": {str(k): v
                                   for k, v in science_by_k.items()},
        "banded_study_default_k": 2,
        "banded_study_redcliff_optf1": {"3-1-2": 0.460, "6-4-2": 0.397}.get(
            system),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("workdir")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--systems", default="3-1-2,6-4-2")
    ap.add_argument("--folds", type=int, default=3)
    args = ap.parse_args()
    out = {"folds": args.folds, "smoke": bool(args.smoke), "systems": {}}
    for system in args.systems.split(","):
        base = (os.path.abspath(args.workdir) + f"_{system}"
                + ("_smoke" if args.smoke else ""))
        os.makedirs(base, exist_ok=True)
        out["systems"][system] = run_system(base, system, args.folds,
                                            args.smoke)
    dest = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "FACTOR_COUNT_SELECTION.json" if not args.smoke
                        else "FACTOR_COUNT_SELECTION_smoke.json")
    # merge with prior invocations' systems (separate --systems runs build
    # one artifact; a rerun of the same system replaces its entry)
    if os.path.isfile(dest):
        try:
            with open(dest) as f:
                prev = json.load(f)
            if prev.get("smoke") == out["smoke"]:
                merged = dict(prev.get("systems", {}))
                merged.update(out["systems"])
                out["systems"] = merged
        except (OSError, json.JSONDecodeError):
            pass
    with open(dest, "w") as f:
        json.dump(out, f, indent=2)
    print(f"[done] wrote {dest}", flush=True)


if __name__ == "__main__":
    main()
