"""Science-level A/B: the reference torch REDCLIFF_S_CMLP, trained by its OWN
pipeline on the same D4IC-analog folds, scored by the same battery.

The round-5 grid search (experiments/d4ic_grid_search.py) shows the BSCgs1
configuration plateauing at off-diag optF1 ~0.17-0.195 across the whole
gen_lr x ADJ_L1 x COS_SIM grid on the D4IC analog — far below the reference's
notebook 0.30-0.34 band for its real D4IC data. VERDICT round 4 poses the
decisive question: is ~0.18 the rebuild's fault, or what the reference itself
scores on this data? This experiment answers it by running the REFERENCE'S OWN
CODE end to end on the identical curated fold:

* data: the same `fold_<k>/train|validation/subset_*.pkl` shards our driver
  trains on, loaded by the reference's `NormalizedDREAM4Dataset` (its d4IC
  drivers use dataset_category="DREAM4", ref train/REDCLIFF_S_CMLP_d4IC_
  BSCgs1.py:44) with its own dataset-level z-scoring;
* args: the reference's `read_in_model_args`/`read_in_data_args` on the same
  transcribed BSCgs1 cached-args file, plus the driver's coefficient
  overwrite block (ref train/...BSCgs1.py:98-105);
* model + training: the reference's `create_model_instance` and
  `call_model_fit_method` (two torch Adams, the real 3-phase schedule, its
  own early stopping);
* scoring: the reference model's `GC("fixed_factor_exclusive", ...)` readout
  (the system-level eval override, ref eval_sysOptF1...py:172-175) against
  the same true graphs through our `three_view_optimal_f1_stats` — the exact
  statistic of the ACCURACY_D4IC tables.

The only reference dependency not in this environment is torcheeg; its DGCNN
is re-implemented here in torch from the public torcheeg formulation
(Chebynet over a learned adjacency — the same formulation our native
models/dgcnn.py rebuilds in JAX) and injected as the `torcheeg.models.DGCNN`
import the reference expects.

Writes experiments/D4IC_TORCH_AB.json.

Run:  python experiments/d4ic_torch_reference_ab.py <workdir> [--smoke]
      [--folds N] [--snr HSNR]
"""
import argparse
import json
import os
import sys
import time
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "experiments"))
sys.path.insert(0, os.path.join(REPO, "tests"))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import torch  # noqa: E402
import torch.nn as nn  # noqa: E402
import torch.nn.functional as F  # noqa: E402

from accuracy_parity_d4ic import REDCLIFF_ARGS  # noqa: E402
from d4ic_grid_search import OFFDIAG, curate_tier_fold  # noqa: E402
from redcliff_tpu.eval.stats import three_view_optimal_f1_stats  # noqa: E402
from redcliff_tpu.utils.config import load_true_gc_factors  # noqa: E402


# --------------------------------------------------------------------------
# torcheeg.models.DGCNN stand-in: the public torcheeg DGCNN formulation
# (trainable adjacency A -> relu + sym-normalized propagation operator ->
# Chebyshev-style support stack -> per-support graph convolutions summed ->
# relu -> 2-layer MLP head), constructor-compatible with
# DGCNN(in_channels, num_electrodes, num_layers, hid_channels, num_classes)
# as consumed by ref models/dgcnn.py:38-44. Same formulation as our JAX
# rebuild (redcliff_tpu/models/dgcnn.py).
# --------------------------------------------------------------------------
class _GraphConv(nn.Module):
    def __init__(self, in_channels, out_channels):
        super().__init__()
        self.weight = nn.Parameter(torch.empty(in_channels, out_channels))
        nn.init.xavier_normal_(self.weight)

    def forward(self, x, adj):
        return torch.matmul(adj, torch.matmul(x, self.weight))


class TorchegDGCNN(nn.Module):
    def __init__(self, in_channels=5, num_electrodes=62, num_layers=2,
                 hid_channels=32, num_classes=2):
        super().__init__()
        self.layer1 = nn.ModuleList(
            [_GraphConv(in_channels, hid_channels) for _ in range(num_layers)])
        self.BN1 = nn.BatchNorm1d(in_channels)
        self.fc1 = nn.Linear(num_electrodes * hid_channels, 64)
        self.fc2 = nn.Linear(64, num_classes)
        self.A = nn.Parameter(torch.empty(num_electrodes, num_electrodes))
        nn.init.xavier_normal_(self.A)

    @staticmethod
    def _normalize_A(A):
        A = F.relu(A)
        d = 1.0 / torch.sqrt(torch.sum(A, 1) + 1e-10)
        D = torch.diag_embed(d)
        return torch.matmul(torch.matmul(D, A), D)

    def forward(self, x):
        # x: (B, num_electrodes, in_channels); BN over the feature channels
        x = self.BN1(x.transpose(1, 2)).transpose(1, 2)
        L = self._normalize_A(self.A)
        supports = [torch.eye(L.shape[0], dtype=L.dtype, device=L.device)]
        for _ in range(len(self.layer1) - 1):
            supports.append(L if len(supports) == 1
                            else torch.matmul(supports[-1], L))
        out = None
        for conv, adj in zip(self.layer1, supports):
            h = conv(x, adj)
            out = h if out is None else out + h
        out = F.relu(out)
        out = out.reshape(x.shape[0], -1)
        return self.fc2(F.relu(self.fc1(out)))


def _install_reference(ref_root="/root/reference"):
    """Reference on sys.path with torcheeg/pywt satisfied (torcheeg by the
    real stand-in above, pywt by the conftest stub)."""
    eeg = types.ModuleType("torcheeg")
    eeg_models = types.ModuleType("torcheeg.models")
    eeg_models.DGCNN = TorchegDGCNN
    eeg.models = eeg_models
    sys.modules.setdefault("torcheeg", eeg)
    sys.modules.setdefault("torcheeg.models", eeg_models)
    from conftest import add_reference_to_path

    add_reference_to_path()
    return ref_root


def _create_reference_redcliff(args_dict):
    """The REDCLIFF_S_CMLP branch of the reference factory (ref
    general_utils/model_utils.py:354-392), constructed directly: the factory
    function itself imports reference modules that are not shipped
    (models.redcliff_s_clstm/redcliff_s_dgcnn) and third-party packages not
    in this environment (sklearn, causalnex), all unrelated to this model."""
    from models.redcliff_s_cmlp import REDCLIFF_S_CMLP

    if args_dict["X_train"] is not None:
        _, y0 = next(iter(args_dict["X_train"]))
        args_dict["num_supervised_factors"] = min(
            y0.size()[1], args_dict["num_supervised_factors"])
        args_dict["num_factors"] = max(args_dict["num_supervised_factors"],
                                       args_dict["num_factors"])
    return REDCLIFF_S_CMLP(
        args_dict["num_channels"], args_dict["gen_lag"],
        args_dict["gen_hidden"], args_dict["embed_lag"],
        args_dict["embed_hidden_sizes"], args_dict["input_length"],
        args_dict["output_length"], args_dict["num_factors"],
        args_dict["num_supervised_factors"], args_dict["coeff_dict"],
        args_dict["use_sigmoid_restriction"],
        args_dict["factor_score_embedder_type"],
        args_dict["factor_score_embedder_args"],
        args_dict["primary_gc_est_mode"], args_dict["forward_pass_mode"],
        num_sims=args_dict["num_sims"],
        wavelet_level=args_dict["wavelet_level"],
        save_path=args_dict["save_path"],
        training_mode=args_dict["training_mode"],
        num_pretrain_epochs=args_dict["num_pretrain_epochs"],
        num_acclimation_epochs=args_dict["num_acclimation_epochs"]).float()


def run_reference_fold(base, dargs, fold, margs_file, max_iter_override=None):
    """One reference training, the train-script choreography end to end
    (thin glue over the reference's own functions; ref
    train/REDCLIFF_S_CMLP_d4IC_BSCgs1.py:17-63,98-108,122-127)."""
    from general_utils import input_argument_utils as ref_iau
    from general_utils import model_utils as ref_mu
    import random as _random

    # the reference driver fixes every seed to 0 (ref :122-127)
    torch.manual_seed(0)
    np.random.seed(0)
    _random.seed(0)

    # tier- and cap-namespaced: the reference run-dir name encodes neither,
    # so a shared root would let one tier's run be reused for another's
    snr = os.path.basename(os.path.dirname(os.path.dirname(dargs)))
    save_root = os.path.join(
        base, f"runs_torch_ref_{snr}_mi{max_iter_override or 'ref'}")
    os.makedirs(save_root, exist_ok=True)
    args_dict = {"save_root_path": save_root,
                 "model_type": "REDCLIFF_S_CMLP",
                 "model_cached_args_file": margs_file,
                 "data_set_name": f"data_fold{fold}",
                 "data_cached_args_file": dargs}
    ref_iau.read_in_model_args(args_dict)
    ref_iau.read_in_data_args(args_dict)
    if max_iter_override is not None:
        args_dict["max_iter"] = max_iter_override

    # the driver's dataset-dependent coefficient overwrite (ref :98-105)
    K = args_dict["num_factors"]
    C = args_dict["num_channels"]
    cd = args_dict["coeff_dict"]
    cd["FACTOR_COS_SIM_COEFF"] = (cd["FACTOR_COS_SIM_COEFF"]
                                  / sum(1.0 * i for i in range(1, K)))
    cd["ADJ_L1_REG_COEFF"] = (cd["ADJ_L1_REG_COEFF"] * (1.0 / K)
                              * (1.0 / np.sqrt(C ** 2.0 - 1.0)))
    args_dict["stopping_criteria_forecast_coeff"] = cd["FORECAST_COEFF"]
    args_dict["stopping_criteria_factor_coeff"] = cd["FACTOR_SCORE_COEFF"]
    args_dict["stopping_criteria_cosSim_coeff"] = cd["FACTOR_COS_SIM_COEFF"]

    # run-dir naming as the reference script builds it (ref :22-31)
    save_dir = os.path.join(save_root, "_".join([
        args_dict["model_type"], args_dict["data_set_name"],
        "fc" + str(cd["FORECAST_COEFF"]).replace(".", "-"),
        "fsc" + str(cd["FACTOR_SCORE_COEFF"]).replace(".", "-"),
        "fcsc" + str(cd["FACTOR_COS_SIM_COEFF"]).replace(".", "-")[:8],
        "fwl1c" + str(cd["FACTOR_WEIGHT_L1_COEFF"]).replace(".", "-"),
        "al1c" + str(cd["ADJ_L1_REG_COEFF"]).replace(".", "-")[:8],
    ]))
    os.makedirs(save_dir, exist_ok=True)
    args_dict["save_path"] = save_dir

    final = os.path.join(save_dir, "final_best_model.bin")
    done_marker = os.path.join(save_dir, "TORCH_AB_FIT_COMPLETE")
    if os.path.isfile(final) and os.path.isfile(done_marker):
        # the reference's save_checkpoint writes final_best_model.bin DURING
        # training (ref models/redcliff_s_cmlp.py:902-903), so the file alone
        # does not imply completion; only a marker written after
        # call_model_fit_method returned marks a finished run
        print(f"[torch-ref] reusing completed run {save_dir}", flush=True)
        return torch.load(final, weights_only=False), True

    X_train, y_train, X_val, y_val = ref_mu.get_data_for_model_training(
        args_dict, grid_search=False, dataset_category="DREAM4")
    args_dict.update(X_train=X_train, y_train=y_train, X_val=X_val,
                     y_val=y_val)
    model = _create_reference_redcliff(args_dict)
    ref_mu.call_model_fit_method(model, args_dict)
    with open(done_marker, "w") as f:
        f.write("fit returned\n")

    if os.path.isfile(final):
        model = torch.load(final, weights_only=False)
    return model, False


def score_reference_model(model, true_gcs):
    """The system-level readout + statistic of the ACCURACY_D4IC tables:
    fixed_factor_exclusive GC per factor (the eval-layer override for
    conditional primary modes), three-view optimal-F1 vs the true graphs."""
    with torch.no_grad():
        ests_by_sample = model.GC(
            "fixed_factor_exclusive", X=None, threshold=False,
            ignore_lag=False, combine_wavelet_representations=True,
            rank_wavelets=False)
    ests = [np.asarray(t.detach().cpu().numpy(), dtype=np.float64)
            for t in ests_by_sample[0]]
    f1s, aucs = [], []
    for est, true in zip(ests, true_gcs):
        s = three_view_optimal_f1_stats(est, true)[OFFDIAG]
        f1s.append(s["f1"])
        if s.get("roc_auc") is not None:
            aucs.append(s["roc_auc"])
    return f1s, aucs


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("workdir")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--folds", type=int, default=3)
    ap.add_argument("--snr", default="HSNR", choices=["HSNR", "MSNR", "LSNR"])
    ap.add_argument("--max-iter", type=int, default=None)
    args = ap.parse_args()
    base = os.path.abspath(args.workdir) + ("_smoke" if args.smoke else "")
    os.makedirs(base, exist_ok=True)
    n_train, n_val = (24, 8) if args.smoke else (120, 30)

    margs = dict(REDCLIFF_ARGS)
    if args.smoke:
        margs.update(max_iter="8", num_pretrain_epochs="3",
                     num_acclimation_epochs="2", check_every="2")
    margs_file = os.path.join(base, "REDCLIFF_S_CMLP_torchab_cached_args.txt")
    with open(margs_file, "w") as f:
        json.dump(margs, f)

    _install_reference()

    # preserve trained wall-clocks across re-invocations (a resumed fold's
    # elapsed time is just the torch.load, not a measurement)
    dest_prev = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "D4IC_TORCH_AB.json")
    prev_train_s = {}
    if os.path.isfile(dest_prev) and not args.smoke:
        try:
            with open(dest_prev) as f:
                for pf in json.load(f).get("per_fold", []):
                    if not pf.get("reused"):
                        prev_train_s[pf["fold"]] = pf.get("train_s")
        except (OSError, json.JSONDecodeError, KeyError):
            pass

    all_f1, all_auc = [], []
    per_fold = []
    for fold in range(args.folds):
        dargs = curate_tier_fold(base, args.snr, fold, n_train, n_val)
        true_gcs = load_true_gc_factors(dargs)
        t0 = time.time()
        model, reused = run_reference_fold(base, dargs, fold, margs_file,
                                           max_iter_override=args.max_iter)
        wall = time.time() - t0
        f1s, aucs = score_reference_model(model, true_gcs)
        all_f1.extend(f1s)
        all_auc.extend(aucs)
        entry = {"fold": fold, "offdiag_optf1_by_factor": f1s,
                 "reused": bool(reused)}
        if reused:
            if prev_train_s.get(fold) is not None:
                entry["train_s"] = prev_train_s[fold]
                entry["train_s_carried_forward"] = True
        else:
            entry["train_s"] = round(wall, 1)
        per_fold.append(entry)
        print(f"[torch-ref] {args.snr} fold {fold}: "
              f"optF1/factor {[round(v, 3) for v in f1s]} ({wall:.0f}s)",
              flush=True)

    f1 = np.asarray(all_f1, dtype=np.float64)
    out = {
        "description": "reference torch REDCLIFF_S_CMLP (BSCgs1 transcribed "
                       "args, reference loaders/driver/fit) on the curated "
                       "D4IC-analog folds",
        "snr_tier": args.snr, "folds": args.folds, "smoke": bool(args.smoke),
        "offdiag_optimal_f1_mean": float(f1.mean()),
        "offdiag_optimal_f1_sem": float(f1.std(ddof=1) / np.sqrt(len(f1)))
        if len(f1) > 1 else 0.0,
        "offdiag_roc_auc_mean": float(np.mean(all_auc)) if all_auc else None,
        "per_fold": per_fold,
        "jax_build_same_config_round4": {"HSNR": 0.178, "MSNR": 0.177,
                                         "LSNR": 0.178},
        "jax_build_grid_best_fold0_round5": "see D4IC_GRID_SEARCH.json",
    }
    dest = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "D4IC_TORCH_AB.json" if not args.smoke
                        else "D4IC_TORCH_AB_smoke.json")
    with open(dest, "w") as f:
        json.dump(out, f, indent=2)
    print(f"[done] torch-ref {args.snr}: optF1 "
          f"{out['offdiag_optimal_f1_mean']:.3f} ± "
          f"{out['offdiag_optimal_f1_sem']:.3f}; wrote {dest}", flush=True)


if __name__ == "__main__":
    main()
