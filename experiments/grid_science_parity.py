"""Grid-engine science parity: the vmapped grid produces the same science
as the SLURM per-job pattern.

Round-3 established the grid engine's *speed* (bench.py) and its unit-level
criteria parity (tests/test_parallel_grid.py). This experiment closes the
remaining gap — demonstrating on real curated datasets that scale-out by
RedcliffGridRunner reaches the same scientific conclusion as the reference's
one-process-per-grid-point driver pattern
(/root/reference/train/REDCLIFF_S_CMLP_synSysInnovGauss1030_*.py:96-158,
whose grid axes include gen_lr and ADJ_L1_REG_COEFF), now with the
statistical treatment VERDICT round 4 asked for:

* N folds (default 3) of the system, each fold run both ways;
* per-fold Spearman rank correlation between the two engines' orderings of
  the grid points, plus the per-fold winner science delta;
* the per-point leg's wall-clock is preserved from the first TRAINED run —
  a resumed leg reports the recorded wall-clock with `resumed: true`
  instead of overwriting it with the no-op scan time;
* the resume guard requires a completed run (early-stopped or trained to
  max_iter) and evaluates the run dir it validated, not os.listdir()[0].

For each fold:
1. curate (or reuse) the fold of the chosen synSys system;
2. per-point leg: train the REDCLIFF-S reference config at each point of a
   gen_lr x ADJ_L1_REG_COEFF grid through the REAL array-task driver;
3. grid leg: all points at once through driver.run_coefficient_grid, seeded
   from the same weights and batch stream (the SLURM pattern fixes seeds);
4. select the best point both ways; score both winners' GC estimates with
   the off-diag optimal-F1 battery.

Writes experiments/GRID_SCIENCE_PARITY.json.

Run:  python experiments/grid_science_parity.py <workdir> [--smoke]
      [--folds N] [--system N-E-F]
"""
import argparse
import json
import os
import pickle
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from accuracy_parity_synsys import REDCLIFF_ARGS  # noqa: E402
from redcliff_tpu.data.curation import curate_synthetic_fold  # noqa: E402
from redcliff_tpu.eval.cross_alg import evaluate_algorithm_on_fold  # noqa: E402
from redcliff_tpu.eval.edge_dynamics import vector_spearman  # noqa: E402
from redcliff_tpu.eval.grid_selection import select_best_models  # noqa: E402
from redcliff_tpu.train.driver import (  # noqa: E402
    run_coefficient_grid, set_up_and_run_experiments)
from redcliff_tpu.utils.config import (  # noqa: E402
    load_true_gc_factors, read_in_data_args, read_in_model_args)

# the reference synSys gs drivers' axes include gen_lr and ADJ_L1_REG_COEFF
# (ref train/...tst100hzRerun1024AvgReg_gsSmooth1.py:103,108 and the synSys
# cached-args' values); 2x2 around the published setting
GEN_LR_AXIS = (0.0005, 0.002)
ADJ_L1_AXIS = (0.1, 0.01)
OFFDIAG = "key_stats_estGC_normOffDiag_vs_trueGC_normOffDiag"


def _grid_points():
    return [{"gen_lr": lr, "ADJ_L1_REG_COEFF": adj}
            for lr in GEN_LR_AXIS for adj in ADJ_L1_AXIS]


def spearman(a, b):
    """Spearman rank correlation of two score vectors (the repo's canonical
    tie-averaged implementation, one lane)."""
    rho, _ = vector_spearman(np.asarray(a).reshape(-1, 1),
                             np.asarray(b).reshape(-1, 1))
    # constant score vectors have zero rank variance (rho undefined); report
    # 0.0 rather than leaking NaN into the artifact
    return float(rho[0]) if np.isfinite(rho[0]) else 0.0


def _completed_run_dirs(save_root, min_epochs, expected_iters, lookback,
                        check_every):
    """Run dirs under save_root whose recorded schedule marks a COMPLETED
    training for this config: past pretrain+acclimation, and either trained
    to max_iter or stopped by the patience rule (epoch - best_it >=
    lookback*check_every). A mid-training interruption passes neither."""
    done = []
    for d in sorted(os.listdir(save_root)):
        meta_p = os.path.join(save_root, d,
                              "training_meta_data_and_hyper_parameters.pkl")
        if not os.path.isfile(meta_p):
            continue
        with open(meta_p, "rb") as f:
            meta = pickle.load(f)
        epoch = meta.get("epoch", -1)
        best_it = meta.get("best_it", None)
        if not (min_epochs < epoch + 1 <= expected_iters):
            continue
        finished = (epoch + 1 == expected_iters
                    or (best_it is not None
                        and epoch - best_it >= lookback * check_every))
        if finished:
            done.append(d)
    return done


def run_fold(base, fold, base_margs, args_smoke, system):
    num_nodes, num_edges, num_factors = (int(v) for v in system.split("-"))
    fold_dir, _ = curate_synthetic_fold(
        os.path.join(base, "data"), fold_id=fold, num_nodes=num_nodes,
        num_lags=2, num_factors=num_factors,
        num_supervised_factors=num_factors, num_edges_per_graph=num_edges,
        num_samples_in_train_set=240 if args_smoke else 1040,
        num_samples_in_val_set=96 if args_smoke else 240,
        sample_recording_len=100, burnin_period=50,
        label_type_setting="OneHot", noise_type="gaussian", noise_level=1.0,
        folder_name=f"synSys{num_nodes}_{num_edges}_{num_factors}")
    dargs_file = os.path.join(fold_dir, f"data_fold{fold}_cached_args.txt")
    true_gcs = load_true_gc_factors(dargs_file)

    # -------------------------------------------------- per-point (SLURM) leg
    points = _grid_points()
    pp_root = os.path.join(base, f"runs_per_point_f{fold}")
    pp_results = []
    pp_wall = 0.0
    pp_trained = 0
    expected_iters = int(base_margs["max_iter"])
    min_epochs = (int(base_margs["num_pretrain_epochs"])
                  + int(base_margs["num_acclimation_epochs"]))
    lookback = int(base_margs["lookback"])
    check_every = int(base_margs["check_every"])
    for i, pt in enumerate(points):
        margs = dict(base_margs)
        margs["gen_lr"] = repr(pt["gen_lr"])
        margs["ADJ_L1_REG_COEFF"] = repr(pt["ADJ_L1_REG_COEFF"])
        margs_file = os.path.join(
            base, f"REDCLIFF_S_CMLP_point{i}_cached_args.txt")
        with open(margs_file, "w") as f:
            json.dump(margs, f)
        # the run-folder name does not encode gen_lr (ref :19-30), so each
        # point gets its own save root to avoid collisions across lr values
        save_root = os.path.join(pp_root, f"point{i}")
        os.makedirs(save_root, exist_ok=True)
        t0 = time.time()
        done = _completed_run_dirs(save_root, min_epochs, expected_iters,
                                   lookback, check_every)
        resumed = bool(done)
        if not done:
            set_up_and_run_experiments(
                {"save_root_path": save_root}, [margs_file], [dargs_file],
                possible_model_types=["REDCLIFF_S_CMLP"],
                possible_data_sets=[f"data_fold{fold}"], task_id=1)
            pp_trained += 1
            pp_wall += time.time() - t0
            done = _completed_run_dirs(save_root, min_epochs, expected_iters,
                                       lookback, check_every)
            assert done, f"training left no completed run in {save_root}"
        run_dir = os.path.join(save_root, done[0])
        with open(os.path.join(
                run_dir, "training_meta_data_and_hyper_parameters.pkl"),
                "rb") as f:
            meta = pickle.load(f)
        pp_results.append({"point": pt, "run_dir": run_dir,
                           "best_loss": meta["best_loss"],
                           "best_it": meta["best_it"],
                           "resumed": resumed,
                           "train_s": round(time.time() - t0, 1)})
        print(f"[f{fold} per-point] {pt}: best_loss={meta['best_loss']:.5f} "
              f"best_it={meta['best_it']} resumed={resumed}", flush=True)

    # flat artifact tree (the eval_gs layout) for grid-selection ranking
    flat = os.path.join(base, f"runs_flat_f{fold}")
    os.makedirs(flat, exist_ok=True)
    for i, r in enumerate(pp_results):
        link = os.path.join(flat, f"point{i}_" + os.path.basename(r["run_dir"]))
        if not os.path.exists(link):
            os.symlink(r["run_dir"], link)
    gs_rankings = select_best_models(flat)

    # ------------------------------------------------------------- grid leg
    margs_file = os.path.join(base, "margs_base.txt")
    with open(margs_file, "w") as f:
        json.dump(base_margs, f)
    args_dict = {"save_root_path": os.path.join(base, f"runs_grid_f{fold}"),
                 "model_type": "REDCLIFF_S_CMLP",
                 "model_cached_args_file": margs_file,
                 "data_set_name": f"data_fold{fold}",
                 "data_cached_args_file": dargs_file}
    read_in_model_args(args_dict)
    read_in_data_args(args_dict)
    from redcliff_tpu.train.driver import (
        rescale_dataset_dependent_coefficients)
    rescale_dataset_dependent_coefficients(args_dict)
    from redcliff_tpu.train.orchestration import (
        create_model_instance, get_data_for_model_training)
    model = create_model_instance(args_dict)
    # grid_search=False: BOTH legs must train on the full fold — the default
    # True applies the reference's quarter-subsampling for cheap searches,
    # which silently handicapped the grid leg vs the per-point driver leg
    train_ds, val_ds = get_data_for_model_training(args_dict,
                                                   grid_search=False)

    from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig
    tc = RedcliffTrainConfig(
        embed_lr=args_dict["embed_lr"], embed_eps=args_dict["embed_eps"],
        embed_weight_decay=args_dict["embed_weight_decay"],
        gen_lr=args_dict["gen_lr"], gen_eps=args_dict["gen_eps"],
        gen_weight_decay=args_dict["gen_weight_decay"],
        max_iter=args_dict["max_iter"], lookback=args_dict["lookback"],
        check_every=args_dict["check_every"],
        batch_size=args_dict["batch_size"],
        stopping_criteria_forecast_coeff=args_dict[
            "stopping_criteria_forecast_coeff"],
        stopping_criteria_factor_coeff=args_dict[
            "stopping_criteria_factor_coeff"],
        stopping_criteria_cosSim_coeff=args_dict[
            "stopping_criteria_cosSim_coeff"])

    def rescaled_adj(raw):
        d = {"coeff_dict": {"ADJ_L1_REG_COEFF": raw},
             "num_factors": args_dict["num_factors"],
             "num_channels": args_dict["num_channels"]}
        rescale_dataset_dependent_coefficients(d)
        return d["coeff_dict"]["ADJ_L1_REG_COEFF"]

    grid_points = [{"gen_lr": pt["gen_lr"],
                    "adj_l1_reg_coeff": rescaled_adj(pt["ADJ_L1_REG_COEFF"])}
                   for pt in points]
    # the SLURM-array pattern seeds every per-point process identically
    # (ref :122-127 fixes all seeds to 0), so the grid starts from the SAME
    # weights as each per-point run
    t_grid = time.time()
    res = run_coefficient_grid(model, tc, grid_points, train_ds, val_ds,
                               key=jax.random.PRNGKey(0),
                               init_point_params=model.init(
                                   jax.random.PRNGKey(0)))
    grid_wall = time.time() - t_grid
    grid_criteria = np.asarray(res.best_criteria, dtype=np.float64)

    # ------------------------------------------------------------ selection
    pp_losses = [r["best_loss"] for r in pp_results]
    pp_best = int(np.argmin(pp_losses))
    grid_best = int(np.argmin(grid_criteria))
    rank_corr = spearman(np.asarray(pp_losses), grid_criteria)

    # ----------------------------------------------- per-config science table
    def offdiag_stats(stats):
        s = stats[OFFDIAG]
        return {"optimal_f1": s["f1_mean_across_factors"],
                "optimal_f1_sem": s["f1_mean_std_err_across_factors"],
                "roc_auc": s.get("roc_auc_mean_across_factors")}

    per_config = []
    for i, pt in enumerate(points):
        pp_stats = offdiag_stats(evaluate_algorithm_on_fold(
            pp_results[i]["run_dir"], "REDCLIFF_S_CMLP", true_gcs))
        grid_run = os.path.join(base, f"runs_grid_f{fold}", f"grid_point{i}")
        os.makedirs(grid_run, exist_ok=True)
        pt_params = jax.tree.map(lambda x: np.asarray(x)[i], res.best_params)
        with open(os.path.join(grid_run, "final_best_model.bin"), "wb") as f:
            pickle.dump({"model_class": "RedcliffSCMLP",
                         "config": model.config, "params": pt_params}, f)
        grid_stats = offdiag_stats(evaluate_algorithm_on_fold(
            grid_run, "REDCLIFF_S_CMLP", true_gcs))
        per_config.append({
            "point": pt,
            "per_point_driver": pp_stats,
            "grid_engine": grid_stats,
            "optf1_delta": grid_stats["optimal_f1"] - pp_stats["optimal_f1"],
        })
        print(f"[f{fold} science] {pt}: driver optF1 "
              f"{pp_stats['optimal_f1']:.3f}±{pp_stats['optimal_f1_sem']:.3f}"
              f" vs grid {grid_stats['optimal_f1']:.3f}±"
              f"{grid_stats['optimal_f1_sem']:.3f}", flush=True)

    winner_delta = (per_config[grid_best]["grid_engine"]["optimal_f1"]
                    - per_config[pp_best]["per_point_driver"]["optimal_f1"])
    # the wall-clock comparison is only a measurement when EVERY point
    # trained in this invocation; a partially-resumed leg would understate
    # the per-point cost by the number of resumed points
    pp_all_trained = pp_trained == len(points)
    print(f"[f{fold} done] same_winner={pp_best == grid_best} "
          f"rank_corr={rank_corr:.3f} winner_optf1_delta={winner_delta:.3f} "
          f"wall: pp {pp_wall:.0f}s ({pp_trained}/{len(points)} trained) "
          f"grid {grid_wall:.0f}s", flush=True)

    return {
        "fold": fold,
        "per_point": [{k: v for k, v in r.items() if k != "run_dir"}
                      for r in pp_results],
        "grid": [{"point": pt, "best_criteria": float(c),
                  "best_epoch": int(e)}
                 for pt, c, e in zip(points, grid_criteria, res.best_epoch)],
        "selected_point_per_point_driver": points[pp_best],
        "selected_point_grid_engine": points[grid_best],
        "same_winner": bool(pp_best == grid_best),
        "spearman_rank_correlation": rank_corr,
        "winner_science_delta_optf1": winner_delta,
        "per_config_science": per_config,
        "winner_stats_per_point_driver":
            per_config[pp_best]["per_point_driver"],
        "winner_stats_grid_engine": per_config[grid_best]["grid_engine"],
        "grid_selection_rankings": {
            crit: {"best_run": v["best_run"],
                   "ranking": [[n, float(x), int(e)]
                               for n, x, e in v["ranking"]]}
            for crit, v in gs_rankings.items()},
        "wall_clock_s": {
            "per_point_total": round(pp_wall, 1),
            "per_point_trained": pp_all_trained,
            "points_trained": pp_trained,
            "grid_total": round(grid_wall, 1)},
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("workdir")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--folds", type=int, default=3)
    ap.add_argument("--system", default="6-2-2")
    args = ap.parse_args()
    # smoke/full runs and different systems use disjoint workdirs: run-dir
    # names encode neither max_iter, sample counts, nor the system, so
    # sharing one tree would let the per-point resume guard reuse stale
    # artifacts (smoke inside full, or one system's models for another's)
    base = (os.path.abspath(args.workdir) + f"_{args.system}"
            + ("_smoke" if args.smoke else ""))
    os.makedirs(base, exist_ok=True)

    base_margs = dict(REDCLIFF_ARGS)
    nf = int(args.system.split("-")[2])
    if nf != 2:
        base_margs.update(num_factors=str(nf), num_supervised_factors=str(nf))
    if args.smoke:
        base_margs.update(max_iter="12", num_pretrain_epochs="4",
                          num_acclimation_epochs="4", check_every="2")

    folds = [run_fold(base, f, base_margs, args.smoke, args.system)
             for f in range(args.folds)]

    corr = [f["spearman_rank_correlation"] for f in folds]
    deltas = [f["winner_science_delta_optf1"] for f in folds]
    # preserve trained wall-clock across re-invocations: a resumed leg would
    # otherwise overwrite the measurement with the no-op resume scan time
    # default system keeps the canonical artifact name; other systems get
    # their own file so runs cannot overwrite each other
    tag = "" if args.system == "6-2-2" else "_" + args.system.replace("-", "_")
    dest = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        f"GRID_SCIENCE_PARITY{tag}.json" if not args.smoke
                        else f"GRID_SCIENCE_PARITY{tag}_smoke.json")
    prev = None
    if os.path.isfile(dest):
        try:
            with open(dest) as f:
                prev = json.load(f)
        except (OSError, json.JSONDecodeError):
            prev = None
    prev_same_system = (prev is not None
                        and prev.get("system", "").startswith(args.system))
    for fr in folds:
        if not fr["wall_clock_s"]["per_point_trained"] and prev_same_system:
            for pfr in prev.get("folds", []):
                if (pfr.get("fold") == fr["fold"]
                        and pfr.get("wall_clock_s", {}).get(
                            "per_point_trained")):
                    fr["wall_clock_s"]["per_point_total"] = \
                        pfr["wall_clock_s"]["per_point_total"]
                    fr["wall_clock_s"]["per_point_trained"] = True
                    fr["wall_clock_s"]["carried_forward"] = True

    out = {
        "system": f"{args.system} (reference synSys config)",
        "axes": {"gen_lr": list(GEN_LR_AXIS),
                 "ADJ_L1_REG_COEFF": list(ADJ_L1_AXIS)},
        "smoke": bool(args.smoke),
        "num_folds": args.folds,
        "folds": folds,
        "same_winner_by_fold": [f["same_winner"] for f in folds],
        "spearman_rank_correlation_by_fold": corr,
        "spearman_rank_correlation_mean": float(np.mean(corr)),
        "winner_science_delta_optf1_by_fold": deltas,
        "winner_science_delta_optf1_mean": float(np.mean(deltas)),
        "wall_clock_s_by_fold": [f["wall_clock_s"] for f in folds],
    }
    with open(dest, "w") as f:
        json.dump(out, f, indent=2)
    print(f"[done] folds={args.folds} "
          f"same_winner={out['same_winner_by_fold']} "
          f"rank_corr={['%.3f' % c for c in corr]} "
          f"winner_delta={['%.3f' % d for d in deltas]}; wrote {dest}",
          flush=True)


if __name__ == "__main__":
    main()
