"""Grid-engine science parity: the vmapped grid produces the same science
as the SLURM per-job pattern.

Round-3 established the grid engine's *speed* (bench.py) and its unit-level
criteria parity (tests/test_parallel_grid.py). This experiment closes the
remaining gap — demonstrating on a real curated dataset that scale-out by
RedcliffGridRunner reaches the same scientific conclusion as the reference's
one-process-per-grid-point driver pattern
(/root/reference/train/REDCLIFF_S_CMLP_synSysInnovGauss1030_*.py:96-158,
whose grid axes include gen_lr and ADJ_L1_REG_COEFF):

1. curate (or reuse) fold 0 of the 6-2-2 synSys system;
2. per-point leg: train the REDCLIFF-S reference config at each point of a
   gen_lr x ADJ_L1_REG_COEFF grid through the REAL array-task driver
   (set_up_and_run_experiments -> kick_off_model_training_experiment, with
   the driver's dataset-dependent coefficient rescaling), one process-like
   run per point, artifacts in the reference layout;
3. grid leg: train ALL points simultaneously through
   driver.run_coefficient_grid (RedcliffGridRunner) with identical rescaled
   coefficients;
4. select the best point both ways — the grid's best_criteria argmin vs the
   per-point artifacts' recorded best_loss (same stopping-criterion
   semantics; also recorded: eval/grid_selection.select_best_models rankings
   over the per-point artifact tree, the eval_gs script flow);
5. score both winners' GC estimates against the fold's true graphs
   (off-diag optimal-F1 / ROC-AUC) through the same cross-alg battery.

Writes experiments/GRID_SCIENCE_PARITY.json. The two legs share the
SLURM-array pattern's RNG contract — every per-point process seeds
identically (ref drivers fix all seeds to 0), so the grid starts from the
same weights (init_grid_from) and consumes the same shuffled batch stream
(both engines draw from default_rng(tc.seed)). "Parity" = both engines
select the same hyperparameter point with closely matching per-point
criteria, and the selected models' optF1/ROC-AUC agree (bit-level step
equality is pinned at unit level by test_grid_matches_single_point_training).

Run:  python experiments/grid_science_parity.py <workdir> [--smoke]
"""
import argparse
import json
import os
import pickle
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from accuracy_parity_synsys import REDCLIFF_ARGS  # noqa: E402
from redcliff_tpu.data.curation import curate_synthetic_fold  # noqa: E402
from redcliff_tpu.eval.cross_alg import evaluate_algorithm_on_fold  # noqa: E402
from redcliff_tpu.eval.grid_selection import select_best_models  # noqa: E402
from redcliff_tpu.train.driver import (  # noqa: E402
    run_coefficient_grid, set_up_and_run_experiments)
from redcliff_tpu.utils.config import (  # noqa: E402
    load_true_gc_factors, read_in_data_args, read_in_model_args)

# the reference synSys gs drivers' axes include gen_lr and ADJ_L1_REG_COEFF
# (ref train/...tst100hzRerun1024AvgReg_gsSmooth1.py:103,108 and the synSys
# cached-args' values); 2x2 around the published setting
GEN_LR_AXIS = (0.0005, 0.002)
ADJ_L1_AXIS = (0.1, 0.01)
OFFDIAG = "key_stats_estGC_normOffDiag_vs_trueGC_normOffDiag"


def _grid_points():
    return [{"gen_lr": lr, "ADJ_L1_REG_COEFF": adj}
            for lr in GEN_LR_AXIS for adj in ADJ_L1_AXIS]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("workdir")
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    # smoke and full runs use disjoint workdirs: run-dir names encode neither
    # max_iter nor sample counts, so sharing one tree would let the per-point
    # resume guard reuse smoke artifacts inside a full run (and vice versa)
    base = os.path.abspath(args.workdir) + ("_smoke" if args.smoke else "")
    os.makedirs(base, exist_ok=True)

    # ---------------------------------------------------------------- data
    fold_dir, _ = curate_synthetic_fold(
        os.path.join(base, "data"), fold_id=0, num_nodes=6, num_lags=2,
        num_factors=2, num_supervised_factors=2, num_edges_per_graph=2,
        num_samples_in_train_set=240 if args.smoke else 1040,
        num_samples_in_val_set=96 if args.smoke else 240,
        sample_recording_len=100, burnin_period=50,
        label_type_setting="OneHot", noise_type="gaussian", noise_level=1.0,
        folder_name="synSys6_2_2")
    dargs_file = os.path.join(fold_dir, "data_fold0_cached_args.txt")
    true_gcs = load_true_gc_factors(dargs_file)

    base_margs = dict(REDCLIFF_ARGS)
    if args.smoke:
        base_margs.update(max_iter="12", num_pretrain_epochs="4",
                          num_acclimation_epochs="4", check_every="2")

    # -------------------------------------------------- per-point (SLURM) leg
    points = _grid_points()
    pp_root = os.path.join(base, "runs_per_point")
    pp_results = []
    t_pp = time.time()
    for i, pt in enumerate(points):
        margs = dict(base_margs)
        margs["gen_lr"] = repr(pt["gen_lr"])
        margs["ADJ_L1_REG_COEFF"] = repr(pt["ADJ_L1_REG_COEFF"])
        margs_file = os.path.join(
            base, f"REDCLIFF_S_CMLP_point{i}_cached_args.txt")
        with open(margs_file, "w") as f:
            json.dump(margs, f)
        # the run-folder name does not encode gen_lr (ref :19-30), so each
        # point gets its own save root to avoid collisions across lr values
        save_root = os.path.join(pp_root, f"point{i}")
        os.makedirs(save_root, exist_ok=True)
        t0 = time.time()
        # reuse a finished per-point run only when its recorded schedule
        # matches this invocation: it must have trained past THIS config's
        # pretrain+acclimation and not beyond max_iter (a stale smoke
        # artifact, epoch ~11, can then never masquerade as a 300-epoch run)
        expected_iters = int(base_margs["max_iter"])
        min_epochs = (int(base_margs["num_pretrain_epochs"])
                      + int(base_margs["num_acclimation_epochs"]))
        done = []
        for d in os.listdir(save_root):
            meta_p = os.path.join(save_root, d,
                                  "training_meta_data_and_hyper_parameters.pkl")
            if os.path.isfile(meta_p):
                with open(meta_p, "rb") as f:
                    meta = pickle.load(f)
                if min_epochs < meta.get("epoch", -1) + 1 <= expected_iters:
                    done.append(d)
        if not done:
            set_up_and_run_experiments(
                {"save_root_path": save_root}, [margs_file], [dargs_file],
                possible_model_types=["REDCLIFF_S_CMLP"],
                possible_data_sets=["data_fold0"], task_id=1)
        run_dir = os.path.join(save_root, os.listdir(save_root)[0])
        with open(os.path.join(
                run_dir, "training_meta_data_and_hyper_parameters.pkl"),
                "rb") as f:
            meta = pickle.load(f)
        pp_results.append({"point": pt, "run_dir": run_dir,
                           "best_loss": meta["best_loss"],
                           "best_it": meta["best_it"],
                           "train_s": round(time.time() - t0, 1)})
        print(f"[per-point] {pt}: best_loss={meta['best_loss']:.5f} "
              f"best_it={meta['best_it']} ({pp_results[-1]['train_s']}s)",
              flush=True)
    pp_wall = time.time() - t_pp

    # flat artifact tree (the eval_gs layout) for grid-selection ranking
    flat = os.path.join(base, "runs_flat")
    os.makedirs(flat, exist_ok=True)
    for i, r in enumerate(pp_results):
        link = os.path.join(flat, f"point{i}_" + os.path.basename(r["run_dir"]))
        if not os.path.exists(link):
            os.symlink(r["run_dir"], link)
    gs_rankings = select_best_models(flat)

    # ------------------------------------------------------------- grid leg
    # identical args/coefficients via the driver's own read/rescale path
    margs_file = os.path.join(base, "margs_base.txt")
    with open(margs_file, "w") as f:
        json.dump(base_margs, f)
    args_dict = {"save_root_path": os.path.join(base, "runs_grid"),
                 "model_type": "REDCLIFF_S_CMLP",
                 "model_cached_args_file": margs_file,
                 "data_set_name": "data_fold0",
                 "data_cached_args_file": dargs_file}
    read_in_model_args(args_dict)
    read_in_data_args(args_dict)
    from redcliff_tpu.train.driver import (
        rescale_dataset_dependent_coefficients)
    rescale_dataset_dependent_coefficients(args_dict)
    from redcliff_tpu.train.orchestration import (
        create_model_instance, get_data_for_model_training)
    model = create_model_instance(args_dict)
    train_ds, val_ds = get_data_for_model_training(args_dict)

    from redcliff_tpu.train.redcliff_trainer import RedcliffTrainConfig
    tc = RedcliffTrainConfig(
        embed_lr=args_dict["embed_lr"], embed_eps=args_dict["embed_eps"],
        embed_weight_decay=args_dict["embed_weight_decay"],
        gen_lr=args_dict["gen_lr"], gen_eps=args_dict["gen_eps"],
        gen_weight_decay=args_dict["gen_weight_decay"],
        max_iter=args_dict["max_iter"], lookback=args_dict["lookback"],
        check_every=args_dict["check_every"],
        batch_size=args_dict["batch_size"],
        stopping_criteria_forecast_coeff=args_dict[
            "stopping_criteria_forecast_coeff"],
        stopping_criteria_factor_coeff=args_dict[
            "stopping_criteria_factor_coeff"],
        stopping_criteria_cosSim_coeff=args_dict[
            "stopping_criteria_cosSim_coeff"])

    # rescale each point's ADJ_L1 through the driver's own helper so both
    # legs share one formula by construction
    def rescaled_adj(raw):
        d = {"coeff_dict": {"ADJ_L1_REG_COEFF": raw},
             "num_factors": args_dict["num_factors"],
             "num_channels": args_dict["num_channels"]}
        rescale_dataset_dependent_coefficients(d)
        return d["coeff_dict"]["ADJ_L1_REG_COEFF"]

    grid_points = [{"gen_lr": pt["gen_lr"],
                    "adj_l1_reg_coeff": rescaled_adj(pt["ADJ_L1_REG_COEFF"])}
                   for pt in points]
    # the SLURM-array pattern seeds every per-point process identically
    # (ref :122-127 fixes all seeds to 0; call_model_fit_method inits from
    # PRNGKey(seed)), so the grid starts from the SAME weights as each
    # per-point run — isolating engine semantics from init-lottery noise
    t_grid = time.time()
    res = run_coefficient_grid(model, tc, grid_points, train_ds, val_ds,
                               key=jax.random.PRNGKey(0),
                               init_point_params=model.init(
                                   jax.random.PRNGKey(0)))
    grid_wall = time.time() - t_grid
    grid_criteria = np.asarray(res.best_criteria, dtype=np.float64)
    for pt, crit, ep in zip(points, grid_criteria, res.best_epoch):
        print(f"[grid] {pt}: best_criteria={float(crit):.5f} "
              f"best_epoch={int(ep)}", flush=True)

    # ------------------------------------------------------------ selection
    pp_best = int(np.argmin([r["best_loss"] for r in pp_results]))
    grid_best = int(np.argmin(grid_criteria))
    same_winner = pp_best == grid_best
    # selection is rank-consistent when both engines order the points the
    # same way; near-tied neighbors can still flip the argmin (300 epochs of
    # f32 training diverge chaotically between ANY two executions — two
    # SLURM jobs with different kernels included)
    pp_order = list(np.argsort([r["best_loss"] for r in pp_results]))
    grid_order = list(np.argsort(grid_criteria))

    # ----------------------------------------------- per-config science table
    # the core claim: AT EACH CONFIG, the grid-trained model and the
    # per-point-driver-trained model reach the same science (optF1/ROC-AUC
    # of the GC readout vs the fold's true graphs)
    def offdiag_stats(stats):
        s = stats[OFFDIAG]
        return {"optimal_f1": s["f1_mean_across_factors"],
                "optimal_f1_sem": s["f1_mean_std_err_across_factors"],
                "roc_auc": s.get("roc_auc_mean_across_factors")}

    per_config = []
    for i, pt in enumerate(points):
        pp_stats = offdiag_stats(evaluate_algorithm_on_fold(
            pp_results[i]["run_dir"], "REDCLIFF_S_CMLP", true_gcs))
        # materialize the grid point as a reference-layout artifact and score
        # it through the exact same battery
        grid_run = os.path.join(base, "runs_grid", f"grid_point{i}")
        os.makedirs(grid_run, exist_ok=True)
        pt_params = jax.tree.map(lambda x: np.asarray(x)[i], res.best_params)
        with open(os.path.join(grid_run, "final_best_model.bin"), "wb") as f:
            pickle.dump({"model_class": "RedcliffSCMLP",
                         "config": model.config, "params": pt_params}, f)
        grid_stats = offdiag_stats(evaluate_algorithm_on_fold(
            grid_run, "REDCLIFF_S_CMLP", true_gcs))
        per_config.append({
            "point": pt,
            "per_point_driver": pp_stats,
            "grid_engine": grid_stats,
            "optf1_delta": grid_stats["optimal_f1"] - pp_stats["optimal_f1"],
        })
        print(f"[science] {pt}: driver optF1 "
              f"{pp_stats['optimal_f1']:.3f}±{pp_stats['optimal_f1_sem']:.3f}"
              f" vs grid {grid_stats['optimal_f1']:.3f}±"
              f"{grid_stats['optimal_f1_sem']:.3f}", flush=True)

    out = {
        "system": "6-2-2 fold 0 (reference synSys config)",
        "axes": {"gen_lr": list(GEN_LR_AXIS),
                 "ADJ_L1_REG_COEFF": list(ADJ_L1_AXIS)},
        "smoke": bool(args.smoke),
        "per_point": [{**{k: v for k, v in r.items() if k != "run_dir"}}
                      for r in pp_results],
        "grid": [{"point": pt, "best_criteria": float(c),
                  "best_epoch": int(e)}
                 for pt, c, e in zip(points, grid_criteria, res.best_epoch)],
        "selected_point_per_point_driver": points[pp_best],
        "selected_point_grid_engine": points[grid_best],
        "same_winner": bool(same_winner),
        "rank_order_per_point_driver": [int(i) for i in pp_order],
        "rank_order_grid_engine": [int(i) for i in grid_order],
        "per_config_science": per_config,
        "winner_stats_per_point_driver":
            per_config[pp_best]["per_point_driver"],
        "winner_stats_grid_engine": per_config[grid_best]["grid_engine"],
        "grid_selection_rankings": {
            crit: {"best_run": v["best_run"],
                   "ranking": [[n, float(x), int(e)]
                               for n, x, e in v["ranking"]]}
            for crit, v in gs_rankings.items()},
        "wall_clock_s": {"per_point_total": round(pp_wall, 1),
                         "grid_total": round(grid_wall, 1)},
    }
    dest = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "GRID_SCIENCE_PARITY.json" if not args.smoke
                        else "GRID_SCIENCE_PARITY_smoke.json")
    with open(dest, "w") as f:
        json.dump(out, f, indent=2)
    print(f"[done] same_winner={same_winner} "
          f"pp={points[pp_best]} grid={points[grid_best]} "
          f"rank_pp={pp_order} rank_grid={grid_order}", flush=True)
    print(f"[done] wall: per-point {pp_wall:.0f}s vs grid {grid_wall:.0f}s; "
          f"wrote {dest}", flush=True)


if __name__ == "__main__":
    main()
